"""Generalized device query plans: And / Or / Not trees over ordered AND
unordered link patterns.

Round-1 compilation covered only conjunctions of ordered patterns
(query/compiler.py); everything else — `Or` (reference
pattern_matcher.py:633-687), unordered Set/Similarity matching (:158-262),
nested `And(Or(...))` — fell back to the single-threaded host algebra.
This module plans the full logical language:

  PTerm   — ordered Link / LinkTemplate (reuses compiler.TermPlan)
  PUTerm  — unordered Link / LinkTemplate (multiset semantics)
  PAnd    — reference And.matched semantics incl. the empty-accumulator
            reseed quirk and negated-term forbidden sets (:689-748)
  POr     — reference Or.matched semantics incl. the joint-negative
            de-Morgan branch (:633-687)
  PNot    — negation wrapper (:616-631)
  PConst  — plan-time-decidable terms (grounded links, bare nodes):
            a static matched flag with no assignments

Execution lives in query/tree.py (staged) and the fused tree executor.
Queries outside even this language (e.g. Links nesting LinkTemplates)
still raise NotCompilable and run on the host algebra.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

from das_tpu.core.hashing import ExpressionHasher, hex_to_i64
from das_tpu.core.schema import UNORDERED_LINK_TYPES
from das_tpu.query.ast import (
    And,
    Link,
    LinkTemplate,
    LogicalExpression,
    Node,
    Not,
    Or,
    TypedVariable,
    Variable,
)
from das_tpu.query.compiler import NotCompilable, TermPlan, UnknownAtom, _plan_term


@dataclass
class PUTermPlan:
    """An unordered link pattern: probe by multiset, values = the sorted
    remaining targets after removing the grounded multiset."""

    arity: int
    type_id: Optional[int]                 # None only for template probes
    required: Tuple[Tuple[int, int], ...]  # (global_row, count), sorted
    var_names: Tuple[str, ...]             # distinct pattern variables
    ctype: Optional[int] = None            # template probe key (int64)


@dataclass
class PTerm:
    plan: TermPlan


@dataclass
class PUTerm:
    plan: PUTermPlan


@dataclass
class PConst:
    matched: bool


@dataclass
class PNot:
    child: "PlanNode"


@dataclass
class PAnd:
    children: List["PlanNode"] = field(default_factory=list)


@dataclass
class POr:
    children: List["PlanNode"] = field(default_factory=list)


PlanNode = Union[PTerm, PUTerm, PConst, PNot, PAnd, POr]


def _plan_unordered_link(db, term: Link) -> Union[PUTerm, PConst]:
    if term.atom_type in db.data.pattern_black_list:
        raise NotCompilable("blacklisted link type")  # host algebra answers
    arity = len(term.targets)
    type_id = db._type_id(term.atom_type)
    if type_id is None:
        return PConst(False)  # unknown type: get_matched_links -> []
    var_names: List[str] = []
    grounded_counts = {}
    for target in term.targets:
        if isinstance(target, TypedVariable):
            raise NotCompilable("typed variable in unordered link")
        if isinstance(target, Variable):
            if target.name in var_names:
                # duplicate variable: UnorderedAssignment.assign rejects
                # every candidate (pattern_matcher.py:171-182) -> no matches
                return PConst(False)
            var_names.append(target.name)
        elif isinstance(target, Node):
            handle = target.get_handle(db)
            row = db.fin.row_of_hex.get(handle) if handle else None
            if row is None:
                return PConst(False)  # Node.matched false -> Link.matched false
        else:
            raise NotCompilable("unsupported unordered target")
    if not var_names:
        # fully grounded: Link.matched degenerates to link_exists
        # (pattern_matcher.py:536-538); handles exist per the loop above
        handles = [t.get_handle(db) for t in term.targets]
        return PConst(db.link_exists(term.atom_type, handles))
    for target in term.targets:
        if isinstance(target, Node):
            row = db.fin.row_of_hex[target.get_handle(db)]
            grounded_counts[row] = grounded_counts.get(row, 0) + 1
    return PUTerm(
        PUTermPlan(
            arity=arity,
            type_id=type_id,
            required=tuple(sorted(grounded_counts.items())),
            var_names=tuple(var_names),
        )
    )


def _plan_unordered_template(db, term: LinkTemplate) -> Union[PUTerm, PConst]:
    names: List[str] = []
    for tv in term.targets:
        if not isinstance(tv, TypedVariable):
            raise NotCompilable("template target")
        if tv.name in names:
            return PConst(False)  # duplicate var: assign rejects all
        names.append(tv.name)
    type_hashes = [
        db.data.table.get_named_type_hash(t)
        for t in [term.link_type, *[tv.type for tv in term.targets]]
    ]
    ctype_hex = ExpressionHasher.composite_hash(type_hashes)
    return PUTerm(
        PUTermPlan(
            arity=len(term.targets),
            type_id=None,
            required=(),
            var_names=tuple(names),
            ctype=int(hex_to_i64(ctype_hex)),
        )
    )


def _plan_leaf(db, term) -> PlanNode:
    if isinstance(term, LinkTemplate):
        if term.ordered:
            return PTerm(_plan_term(db, term, False))
        return _plan_unordered_template(db, term)
    if isinstance(term, Link):
        if any(isinstance(t, LinkTemplate) for t in term.targets):
            raise NotCompilable("nested template link")
        # get_matched_links keys the probe mode off the TYPE NAME
        # (db_interface.py UNORDERED_LINK_TYPES), the assignment class off
        # the ctor flag; compile only when the two agree.
        db_unordered = term.atom_type in UNORDERED_LINK_TYPES
        if term.ordered and db_unordered:
            raise NotCompilable("ordered pattern on unordered link type")
        if not term.ordered and not db_unordered:
            raise NotCompilable("unordered pattern on ordered link type")
        if not term.ordered:
            return _plan_unordered_link(db, term)
        has_var = any(
            isinstance(t, Variable) and not isinstance(t, TypedVariable)
            for t in term.targets
        )
        if not has_var:
            # fully grounded all-Node link: reference Link.matched
            # degenerates to node existence + link_exists
            # (pattern_matcher.py:502-538); nested grounded links recurse
            # through Link.matched and stay on the host
            if not all(isinstance(t, Node) for t in term.targets):
                raise NotCompilable("grounded link with non-node targets")
            handles = []
            for t in term.targets:
                if not db.node_exists(t.atom_type, t.name):
                    return PConst(False)
                handles.append(t.get_handle(db))
            return PConst(db.link_exists(term.atom_type, handles))
        try:
            return PTerm(_plan_term(db, term, False))
        except UnknownAtom:
            # unknown grounded node or unknown link type: the reference
            # answers no-match, not an error
            return PConst(False)
    if isinstance(term, Node):
        return PConst(db.node_exists(term.atom_type, term.name))
    if isinstance(term, Variable):  # includes TypedVariable
        return PConst(True)
    raise NotCompilable(f"unsupported leaf {type(term).__name__}")


def build_plan(db, query: LogicalExpression) -> PlanNode:
    """Plan an arbitrary And/Or/Not tree, or raise NotCompilable."""
    if isinstance(query, Not):
        return PNot(build_plan(db, query.term))
    if isinstance(query, And):
        return PAnd([build_plan(db, t) for t in query.terms])
    if isinstance(query, Or):
        return POr([build_plan(db, t) for t in query.terms])
    return _plan_leaf(db, query)
