"""Closed-form counting for star-shaped conjunctions (miner joints).

The pattern miner's composite queries (mining/miner.py `_composite`) are
STAR joins: every positive term shares exactly one variable (V0) and every
other variable is free and appears in exactly one term.  For that shape
the match count has a closed form that needs NO pair expansion:

    count = Σ_v  Π_t  deg_t(v)

where deg_t(v) is the number of links matching term t with the shared
variable bound to atom row v.  Each composite assignment is determined by
one link choice per term (free variables are bijective with a term's
matching rows), so the product over independent per-v choices is exact —
the same number the reference's nested-loop And join (pattern_matcher.py
:732-738) and the fused pair-expansion path produce.

Why this matters: the general fused path materializes the join output
(24M-row capacity buffers at FlyBase scale — r03's joint phase ran
33.5 ms/link against a <20 target, execution-bound).  Here a whole-table
term costs one cached degree VECTOR (a bincount over its target column)
and a probed term one searchsorted per other term — buffers scale with
the smallest term, never the join output.

Degree-vector cache: dense [atom_count] int32 vectors per
(arity, type_id, position), keyed against the live DeviceBucket identity
so an incremental commit (which swaps in merged buckets) naturally
invalidates.  A handful of (type, position) pairs recur across the
miner's hundreds of joints, so the bincounts amortize to nothing.

Routing: `plan_star` recognizes the shape (ordered terms only, no
negation, no eq_pairs, no templates); everything else falls through to
the general executors.  Known tolerance (shared with the fused path):
dangling (-1) element rows never join here, while the host algebra would
join two danglings with identical hex — impossible in converter output.

**The reseed quirk makes zeros ambiguous.**  The reference And.matched
re-seeds an emptied accumulator from the next positive term
(pattern_matcher.py:725-728; ast.py keeps parity), so a conjunction of
DISJOINT terms does not answer 0.  Star prefix totals are monotone
(T_{i+1} > 0 ⇒ T_i > 0), so a NONZERO star total proves every prefix
join was nonempty — the quirk never fired and the closed form equals
the reference count exactly.  A zero star total is therefore the only
ambiguous outcome: callers MUST recount zeros through the general
(quirk-faithful) path.  `star_count_many` returns None for those lanes.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

FETCHES = {"n": 0}


def _enabled() -> bool:
    return os.environ.get("DAS_TPU_STAR", "1") != "0"


# ---------------------------------------------------------------------------
# shape detection
# ---------------------------------------------------------------------------


class StarLane:
    """One star-shaped count query, decomposed into whole-table terms
    (dense degree vectors) and probed terms (row sets)."""

    __slots__ = ("w_specs", "f_specs", "sig")

    def __init__(self, w_specs, f_specs, sig):
        self.w_specs = w_specs  # [(arity, type_id, v0_pos)]
        self.f_specs = f_specs  # [(arity, type_id, fixed, v0_pos)]
        self.sig = sig


def plan_star(db, plans) -> Optional[StarLane]:
    """Recognize a star conjunction in a list of compiler.TermPlan.
    Returns None when the shape doesn't apply (caller falls back)."""
    if not _enabled() or plans is None or not isinstance(plans, list):
        return None
    if len(plans) < 2:
        return None
    var_seen: Dict[str, int] = {}
    for p in plans:
        if p.negated or p.ctype is not None or p.type_id is None:
            return None
        if p.eq_pairs:
            return None
        for name in p.var_names:
            var_seen[name] = var_seen.get(name, 0) + 1
    shared = [name for name, n in var_seen.items() if n == len(plans)]
    if len(shared) != 1:
        return None
    if any(n != 1 for name, n in var_seen.items() if name != shared[0]):
        return None
    s = shared[0]
    w_specs, f_specs = [], []
    for p in plans:
        v0_pos = p.var_cols[p.var_names.index(s)]
        if p.fixed:
            f_specs.append((p.arity, p.type_id, tuple(p.fixed), v0_pos))
        else:
            w_specs.append((p.arity, p.type_id, v0_pos))
    sig = (tuple(sorted(w_specs)), tuple((a, t, len(f), v) for a, t, f, v in f_specs))
    return StarLane(w_specs, f_specs, sig)


# ---------------------------------------------------------------------------
# degree vectors
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("atom_count",))
def _deg_vector(type_ids, targets_col, type_id, atom_count: int):
    """Dense degree vector: deg[v] = |{links of type_id with column == v}|."""
    mask = type_ids == type_id
    safe = jnp.clip(targets_col, 0, atom_count - 1)
    contrib = (mask & (targets_col >= 0)).astype(jnp.int32)
    return jnp.zeros(atom_count, dtype=jnp.int32).at[safe].add(contrib)


def _get_deg(db, arity: int, type_id: int, pos: int):
    """Cached dense degree vector, invalidated when the bucket object is
    replaced (incremental merge / full rebuild both swap buckets)."""
    cache = getattr(db, "_star_deg_cache", None)
    if cache is None:
        cache = db._star_deg_cache = {}
    bucket = db.dev.buckets.get(arity)
    if bucket is None or bucket.size == 0:
        return None
    key = (arity, type_id, pos)
    hit = cache.get(key)
    if hit is not None and hit[0] is bucket:
        return hit[1]
    deg = _deg_vector(
        bucket.type_id, bucket.targets[:, pos], np.int32(type_id),
        int(db.fin.atom_count),
    )
    if len(cache) > 32:
        cache.clear()
    cache[key] = (bucket, deg)
    return deg


# ---------------------------------------------------------------------------
# count programs
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_w",))
def _star_dense(degs, n_w: int):
    prod = degs[0].astype(jnp.int64)
    for i in range(1, n_w):
        prod = prod * degs[i].astype(jnp.int64)
    return prod.sum()


@partial(jax.jit, static_argnames=("n_w", "n_f"))
def _star_from_base(base_vals, base_mask, degs, f_sorted, n_w: int, n_f: int):
    """Σ over base rows of Π other-term degrees at the row's shared value."""
    ok = base_mask & (base_vals >= 0)
    prod = ok.astype(jnp.int64)
    if n_w:
        safe = jnp.clip(base_vals, 0, degs[0].shape[0] - 1)
        for i in range(n_w):
            prod = prod * degs[i][safe].astype(jnp.int64)
    for i in range(n_f):
        s = f_sorted[i]
        lo = jnp.searchsorted(s, base_vals, side="left")
        hi = jnp.searchsorted(s, base_vals, side="right")
        prod = prod * (hi - lo).astype(jnp.int64)
    return jnp.where(ok, prod, 0).sum()


@jax.jit
def _sorted_vals(vals, mask):
    """Valid values sorted ascending; padding (int32 max) sorts past every
    real row id so searchsorted ranges exclude it."""
    return jnp.sort(jnp.where(mask, vals, jnp.int32(2**31 - 1)))


def _probe_vals(db, arity, type_id, fixed, v0_pos):
    """Padded (vals, mask) of a probed term's shared-variable column."""
    padded = db.probe_ordered_padded(arity, type_id, fixed)
    if padded is None:
        return None
    local, mask = padded
    bucket = db.dev.buckets[arity]
    vals = _gather_col(bucket.targets, local, v0_pos)
    return vals, mask


@partial(jax.jit, static_argnames=("pos",))
def _gather_col(targets, local, pos: int):
    safe = jnp.clip(local, 0, targets.shape[0] - 1)
    return targets[safe, pos]


def _dispatch(db, lane: StarLane):
    """Queue one lane's count on the device; returns a device scalar (no
    host sync — the caller fetches every lane in one transfer)."""
    degs = []
    for arity, type_id, pos in lane.w_specs:
        deg = _get_deg(db, arity, type_id, pos)
        if deg is None:
            return jnp.int64(0)
        degs.append(deg)
    if not lane.f_specs:
        return _star_dense(tuple(degs), len(degs))
    probed = []
    for arity, type_id, fixed, v0_pos in lane.f_specs:
        pv = _probe_vals(db, arity, type_id, fixed, v0_pos)
        if pv is None:
            return jnp.int64(0)
        probed.append(pv)
    # base = the probed term with the smallest padded capacity (probe
    # capacities grow with the result range, so this tracks selectivity)
    base_idx = min(range(len(probed)), key=lambda i: probed[i][0].shape[0])
    base_vals, base_mask = probed[base_idx]
    f_sorted = tuple(
        _sorted_vals(v, m)
        for i, (v, m) in enumerate(probed)
        if i != base_idx
    )
    return _star_from_base(
        base_vals, base_mask, tuple(degs), f_sorted, len(degs), len(f_sorted)
    )


def star_count_many(db, lanes: Sequence[StarLane]) -> List[Optional[int]]:
    """Count every lane with ONE host fetch: dispatches are async, the
    stack transfer at the end is the only round trip.  Zero totals come
    back as None — the reseed quirk makes them ambiguous (see module
    docstring) and the caller must recount them on the general path."""
    scalars = [_dispatch(db, lane) for lane in lanes]
    FETCHES["n"] += 1
    return [
        int(x) if int(x) > 0 else None
        for x in np.asarray(jnp.stack(scalars))
    ]


def try_star_count(db, plans) -> Optional[int]:
    """Single-query surface for compiler.count_matches; None = not star,
    or an ambiguous zero (caller falls through either way)."""
    lane = plan_star(db, plans)
    if lane is None:
        return None
    return star_count_many(db, [lane])[0]
