"""Closed-form counting for star-shaped conjunctions (miner joints).

The pattern miner's composite queries (mining/miner.py `_composite`) are
STAR joins: every positive term shares exactly one variable (V0) and every
other variable is free and appears in exactly one term.  For that shape
the match count has a closed form that needs NO pair expansion:

    count = Σ_v  Π_t  deg_t(v)

where deg_t(v) is the number of links matching term t with the shared
variable bound to atom row v.  Each composite assignment is determined by
one link choice per term (free variables are bijective with a term's
matching rows), so the product over independent per-v choices is exact —
the same number the reference's nested-loop And join (pattern_matcher.py
:732-738) and the fused pair-expansion path produce.

Why this matters: the general fused path materializes the join output
(24M-row capacity buffers at FlyBase scale — r03's joint phase ran
33.5 ms/link against a <20 target, execution-bound).  Here a probed
term contributes its sparse support (unique shared-variable values +
multiplicities) and a whole-table term stays SYMBOLIC: its degree at
any support point is a searchsorted range length on the existing
(type<<32|target) sorted index, so a lane containing any probed term is
a few thousand binary searches and multiply-adds — no join buffers, no
per-shape capacity learning.  A table ⊙ table product (the rare
all-whole-table prefix) extracts the smaller side's support by
run-length over its contiguous sorted-key slice and proceeds sparse —
no dense [atom_count] vector exists anywhere in the host edition.

**The reseed quirk is computed in-program, not dodged.**  The reference
And re-seeds an emptied accumulator from the next positive term
(pattern_matcher.py:725-728; ast.py keeps parity): the accumulator
evolves as E_1 = t_1, E_i = (t_i if E_{i-1} = ∅ else E_{i-1} ⋈ t_i),
and the answer is |E_n|.  On degree vectors that IS the fold

    R ← deg_1 ;  R ← (deg_i  if Σ R = 0  else  R ⊙ deg_i) ;  count = Σ R

because a reseeded accumulator holds exactly term i's assignments —
whose degree vector over the shared variable is deg_i — and every
subsequent join multiplies pointwise.  One special case dominates: an
EMPTY TERM (S_i = Σ deg_i = 0) makes the reference's And return
no-match outright (Link.matched is False before any join), so any
S_i = 0 answers 0 regardless of the fold.  With that guard the star
route is TOTAL for its shape: every lane gets an exact reference-equal
count, zeros included — no general-path fallback, which at FlyBase
scale would mean compiling whole-table join programs just to re-derive
quirk verdicts.

Caches (host edition: keyed on segment identities; device edition: on
the live DeviceBucket identity, so an incremental commit naturally
invalidates): sparse probe supports per (arity, type, fixed) and
whole-table run-length supports per (arity, type, position).  A handful
of terms recur across the miner's hundreds of joints, so everything
amortizes.

Routing: `plan_star` recognizes the shape (ordered terms only, no
negation, no eq_pairs, no templates); everything else falls through to
the general executors.  Known tolerance (shared with the fused path):
dangling (-1) element rows never join here, while the host algebra would
join two danglings with identical hex — impossible in converter output.

**Two executions of the same algebra** (`DAS_TPU_STAR_FOLD`, default
`host`; count-identical, differentially asserted in
tests/test_starcount.py):

* `host` — sparse supports from a host searchsorted probe, whole-table
  degrees as range lengths at the support points; table ⊙ table extracts
  the smaller side's support by run-length over its sorted key slice —
  NO dense [atom_count] vector exists anywhere; zero device work.
  Rationale: a mixed lane's arithmetic is a few thousand
  multiply-adds, while the device edition pays per-lane dispatch +
  probe round trips (through the TPU tunnel, ~10-100 ms each) AND its
  whole-table degree bincounts lower to TPU scatter-adds at ~5 s per
  24M-element vector — at r04 those made the joint phase run 21-40 s
  for 374 lanes.  The only edition available on dev-less backends (the
  mesh store folds here regardless of the env).
* `device` — every lane through the jitted degree-vector fold with
  lane-grouped fetches; kept for differential testing and as the
  pattern for a future multi-chip fold."""

from __future__ import annotations

import os
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

FETCHES = {"n": 0}


def _enabled() -> bool:
    return os.environ.get("DAS_TPU_STAR", "1") != "0"


# ---------------------------------------------------------------------------
# shape detection
# ---------------------------------------------------------------------------


class StarLane:
    """One star-shaped count query: per-term degree specs in REFERENCE
    order (the prefix verdict is order-sensitive)."""

    __slots__ = ("specs",)

    def __init__(self, specs):
        # spec: (arity, type_id, v0_pos, fixed) — fixed == () ⇒ whole-table
        self.specs = specs


def plan_star(db, plans) -> Optional[StarLane]:
    """Recognize a star conjunction in a list of compiler.TermPlan.
    Returns None when the shape doesn't apply (caller falls back)."""
    if not _enabled() or plans is None or not isinstance(plans, list):
        return None
    if len(plans) < 2:
        return None
    var_seen: Dict[str, int] = {}
    for p in plans:
        if p.negated or p.ctype is not None or p.type_id is None:
            return None
        if p.eq_pairs:
            return None
        for name in p.var_names:
            var_seen[name] = var_seen.get(name, 0) + 1
    shared = [name for name, n in var_seen.items() if n == len(plans)]
    if len(shared) != 1:
        return None
    if any(n != 1 for name, n in var_seen.items() if name != shared[0]):
        return None
    s = shared[0]
    specs = []
    for p in plans:
        v0_pos = p.var_cols[p.var_names.index(s)]
        specs.append((p.arity, p.type_id, v0_pos, tuple(p.fixed)))
    return StarLane(tuple(specs))


# ---------------------------------------------------------------------------
# degree vectors
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("atom_count",))
def _deg_vector(type_ids, targets_col, type_id, atom_count: int):
    """Dense degree vector: deg[v] = |{links of type_id with column == v}|."""
    mask = type_ids == type_id
    safe = jnp.clip(targets_col, 0, atom_count - 1)
    contrib = (mask & (targets_col >= 0)).astype(jnp.int32)
    return jnp.zeros(atom_count, dtype=jnp.int32).at[safe].add(contrib)


@partial(jax.jit, static_argnames=("atom_count",))
def _scatter_deg(vals, mask, atom_count: int):
    """Degree vector of a probed term's (padded) shared-variable column."""
    ok = mask & (vals >= 0)
    safe = jnp.clip(vals, 0, atom_count - 1)
    return jnp.zeros(atom_count, dtype=jnp.int32).at[safe].add(
        ok.astype(jnp.int32)
    )


def _get_deg(db, arity: int, type_id: int, pos: int):
    """Cached whole-table degree vector.  Validity is (bucket identity,
    atom_count): a commit swaps the buckets it touches, but an UNTOUCHED
    arity keeps its bucket object while fin.atom_count grows — a
    bucket-only check would then serve a stale-length vector into the
    fold (shape mismatch or silent undercount of new atoms)."""
    cache = getattr(db, "_star_deg_cache", None)
    if cache is None:
        cache = db._star_deg_cache = {}
    bucket = db.dev.buckets.get(arity)
    if bucket is None or bucket.size == 0:
        return None
    atom_count = int(db.fin.atom_count)
    key = (arity, type_id, pos)
    hit = cache.get(key)
    if hit is not None and hit[0] is bucket and hit[1] == atom_count:
        return hit[2]
    deg = _deg_vector(
        bucket.type_id, bucket.targets[:, pos], np.int32(type_id), atom_count
    )
    # dense vectors are [atom_count] int32 (~120 MB each at reference
    # scale): bound THEM by count separately from the cheap probe-column
    # entries, or a few dozen distinct whole-table terms would exhaust
    # HBM alongside the store
    # dense keys end in a position INT; probe-column keys end in the
    # fixed tuple
    if sum(isinstance(k[2], int) for k in cache) >= 16:
        _evict_oldest(cache, lambda k: isinstance(k[2], int), 12)
    cache.pop(key, None)  # refresh moves the entry to the FIFO back
    cache[key] = (bucket, atom_count, deg)
    return deg


@partial(jax.jit, static_argnames=("pos",))
def _gather_col(targets, local, pos: int):
    safe = jnp.clip(local, 0, targets.shape[0] - 1)
    return targets[safe, pos]


def _term_deg(db, spec):
    """Degree vector of one term; None when the bucket is missing (the
    term is empty — count 0).  Probed terms are cached like whole-table
    ones: the miner reuses the same ~100 candidate terms across hundreds
    of composites, and each probe pays a capacity-check fetch (a full
    tunnel RTT) that the cache amortizes away."""
    arity, type_id, v0_pos, fixed = spec
    if not fixed:
        return _get_deg(db, arity, type_id, v0_pos)
    cache = getattr(db, "_star_deg_cache", None)
    if cache is None:
        cache = db._star_deg_cache = {}
    bucket = db.dev.buckets.get(arity)
    if bucket is None or bucket.size == 0:
        return None
    # keyed WITHOUT the shared-variable position: the blocking
    # capacity-check fetch belongs to the probe, and the same probe can
    # appear with the shared variable at different positions — only the
    # cheap jitted gather differs per position
    key = (arity, type_id, fixed)
    hit = cache.get(key)
    if hit is not None and hit[0] is bucket:
        local, mask = hit[2]
    else:
        padded = db.probe_ordered_padded(arity, type_id, fixed)
        local, mask = padded
        # cache SMALL probe columns only: an overflow-grown probe is
        # padded to its learned capacity, and hundreds of multi-MB
        # cached rows would silently compete with the store for HBM
        if local.shape[0] <= (1 << 20):
            if len(cache) > 256:
                _evict_oldest(
                    cache, lambda k: not isinstance(k[2], int), 192
                )
            cache.pop(key, None)  # refresh -> FIFO back
            cache[key] = (bucket, None, (local, mask))
    vals = _gather_col(bucket.targets, local, v0_pos)
    return _scatter_deg(vals, mask, int(db.fin.atom_count))


# ---------------------------------------------------------------------------
# the prefix cascade
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n",))
def _star_fold(degs, n: int):
    """(per-term row counts S[n], reference-fold count) — the reseeding
    accumulator computed on degree vectors (module docstring)."""
    term_totals = jnp.stack([d.sum(dtype=jnp.int64) for d in degs])
    acc = degs[0].astype(jnp.int64)
    for i in range(1, n):
        d = degs[i].astype(jnp.int64)
        # E_{i-1} empty ⇒ this term RESEEDS the accumulator
        acc = jnp.where(acc.sum() == 0, d, acc * d)
    return term_totals, acc.sum()


def _dispatch(db, lane: StarLane):
    """Queue one lane's fold (async); returns device (S, count) or an
    immediate exact 0 (int) when a term's bucket is absent."""
    degs = []
    for spec in lane.specs:
        deg = _term_deg(db, spec)
        if deg is None:
            return 0
        degs.append(deg)
    return _star_fold(tuple(degs), len(degs))


#: lanes dispatched between fetches — each PROBED term materializes a
#: transient dense [atom_count] vector (~120 MB at reference scale), so
#: unbounded batches would queue tens of GB ahead of one transfer; 12
#: bounds transients to ~4.3 GB worst case (3 probed terms per lane)
#: while keeping the fetch count (each a tunnel RTT) low
GROUP = 12


# ---------------------------------------------------------------------------
# host edition: sparse supports, zero device round trips
# ---------------------------------------------------------------------------


def _host_cache(db) -> Dict:
    cache = getattr(db, "_star_host_cache", None)
    if cache is None:
        cache = db._star_host_cache = {}
    return cache


def _evict_oldest(cache, pred, keep: int) -> None:
    """FIFO-evict entries matching ``pred`` down to ``keep`` (dict
    preserves insertion order, so the front of the iteration is the
    oldest).  A miner cycling >256 distinct terms keeps its working set
    instead of rebuilding the whole key class from scratch."""
    matching = [k for k in cache if pred(k)]
    for k in matching[: max(0, len(matching) - keep)]:
        del cache[k]


def _host_sparse_deg(db, spec):
    """((sorted unique shared-variable values, int64 multiplicities),
    total) of a probed term — the shared host probe
    (storage/atom_table.py host_probe_locals: the same algorithm and the
    same index copies in both editions).  Cached: the miner reuses ~100
    candidate terms across hundreds of composites."""
    from das_tpu.storage.atom_table import host_probe_locals, host_segments

    arity, type_id, v0_pos, fixed = spec
    segments = host_segments(db, arity)
    if not segments:
        return None
    cache = _host_cache(db)
    key = ("sparse", arity, type_id, v0_pos, fixed)
    hit = cache.get(key)
    if (
        hit is not None
        and len(hit[0]) == len(segments)
        and all(a is b for a, b in zip(hit[0], segments))
    ):
        return hit[1]
    chunks = []
    for b in segments:
        local = host_probe_locals(b, type_id, fixed)
        if local.size == 0:
            continue
        v0 = b.targets[local, v0_pos]
        v0 = v0[v0 >= 0]  # device parity: dangling rows never scatter
        if v0.size:
            chunks.append(v0)
    if chunks:
        idx, cnt = np.unique(np.concatenate(chunks), return_counts=True)
        cnt = cnt.astype(np.int64)
        ent = ((idx.astype(np.int64), cnt), int(cnt.sum()))
    else:
        e = np.empty(0, dtype=np.int64)
        ent = ((e, e), 0)
    if len(cache) > 256:
        _evict_oldest(cache, lambda k: k[0] in ("sparse", "tsparse"), 192)
    cache.pop(key, None)  # refresh -> FIFO back
    cache[key] = (tuple(segments), ent)
    return ent


def _mul(acc, d):
    """Pointwise product of two sparse degree representations
    (sorted unique idx, cnt) — intersection of supports."""
    ai, ac = acc
    di, dc = d
    common, ia, ib = np.intersect1d(
        ai, di, assume_unique=True, return_indices=True
    )
    return common, ac[ia] * dc[ib]


def _rep_sum(d) -> int:
    return int(d[1].sum())


def _table_total(db, arity: int, type_id: int, v0_pos: int) -> int:
    """Exact DEGREE-SUM of a whole-table term: rows of the type whose
    shared-variable position holds a REAL atom.  Computed as the
    [tid<<32, tid<<32 + 2^31) range on the (type<<32|target) sorted key
    — a dangling (-1) target ORs to key -1 and falls outside, so this
    equals the dense edition's `col >= 0` bincount sum exactly (a raw
    key_type range would count dangling rows the dense sum excludes,
    corrupting the empty-term guard and any reseed that lands on a
    symbolic table term)."""
    from das_tpu.storage.atom_table import host_segments

    base = np.int64(type_id) << 32
    total = 0
    for b in host_segments(db, arity):
        keys = b.key_type_pos[v0_pos]
        total += int(
            np.searchsorted(keys, base + (np.int64(1) << 31), side="left")
        ) - int(np.searchsorted(keys, base, side="left"))
    return total


def _table_deg_at(db, spec, idx: np.ndarray) -> np.ndarray:
    """deg_t(v) for a WHOLE-TABLE term at the given atom rows only:
    per-segment searchsorted range lengths on the (type<<32|target) sorted
    key — identical numbers to the dense bincount's entries at `idx`,
    without ever materializing a [atom_count] vector (the dense build is
    a ~1 s gather+bincount pass per (type, position) at reference scale;
    a mixed lane only ever needs the degrees on its sparse support)."""
    from das_tpu.storage.atom_table import host_segments

    arity, type_id, v0_pos, _ = spec
    out = np.zeros(idx.shape[0], dtype=np.int64)
    base = np.int64(type_id) << 32
    for b in host_segments(db, arity):
        keys = b.key_type_pos[v0_pos]
        q = base | idx.astype(np.int64)
        lo = np.searchsorted(keys, q, side="left")
        hi = np.searchsorted(keys, q, side="right")
        out += hi - lo
    return out


def _table_sparse(db, spec):
    """((sorted unique shared-variable values, int64 multiplicities),
    total) of a WHOLE-TABLE term, extracted by run-length over the
    CONTIGUOUS (type<<32|target) sorted-key slice — the slice is already
    sorted, so uniques are np.diff boundaries: one linear pass, no
    bincount, no [atom_count] vector.  Cached like the probe supports."""
    arity, type_id, v0_pos, _ = spec
    from das_tpu.storage.atom_table import host_segments

    segments = host_segments(db, arity)
    if not segments:
        return None
    cache = _host_cache(db)
    key = ("tsparse", arity, type_id, v0_pos)
    hit = cache.get(key)
    if (
        hit is not None
        and len(hit[0]) == len(segments)
        and all(a is b for a, b in zip(hit[0], segments))
    ):
        return hit[1]
    base = np.int64(type_id) << 32
    parts = []  # (idx, cnt) per segment
    for b in segments:
        keys = b.key_type_pos[v0_pos]
        lo = int(np.searchsorted(keys, base, side="left"))
        hi = int(np.searchsorted(keys, base + (np.int64(1) << 31), side="left"))
        if hi <= lo:
            continue
        vals = keys[lo:hi] - base  # sorted, dangling-free by construction
        starts = np.r_[0, np.flatnonzero(np.diff(vals)) + 1]
        parts.append((vals[starts], np.diff(np.r_[starts, vals.size])))
    if not parts:
        ent = ((np.empty(0, np.int64), np.empty(0, np.int64)), 0)
    elif len(parts) == 1:
        idx, cnt = parts[0]
        ent = ((idx, cnt.astype(np.int64)), int(cnt.sum()))
    else:
        # overlay segments: merge run-length pairs (same value can appear
        # in several segments)
        allv = np.concatenate([p[0] for p in parts])
        allc = np.concatenate([p[1] for p in parts]).astype(np.int64)
        order = np.argsort(allv, kind="stable")
        sv, sc = allv[order], allc[order]
        starts = np.r_[0, np.flatnonzero(np.diff(sv)) + 1]
        csum = np.r_[0, np.cumsum(sc)]
        bounds = np.r_[starts, sv.size]
        cnt = csum[bounds[1:]] - csum[bounds[:-1]]
        ent = ((sv[starts], cnt), int(cnt.sum()))
    if len(cache) > 256:
        _evict_oldest(cache, lambda k: k[0] in ("sparse", "tsparse"), 192)
    cache.pop(key, None)  # refresh -> FIFO back
    cache[key] = (tuple(segments), ent)
    return ent


def _host_count(db, lane: StarLane) -> int:
    """One lane, exact, entirely host-side: the module-docstring fold on
    (representation, total) degree entries.

    Representations: ``("table", spec)`` — a whole-table term held
    SYMBOLIC; sparse ``(idx, cnt)`` — a support with multiplicities.
    The fold multiplies symbolically where it can: sparse ⊙ table is a
    vectorized searchsorted at the support points.  table ⊙ table
    extracts the SMALLER side's support by run-length over its sorted
    key slice (one linear pass) and proceeds sparse — no [atom_count]
    dense vector exists anywhere in this edition."""
    reps = []  # (rep, total)
    for spec in lane.specs:
        arity, type_id, v0_pos, fixed = spec
        if not fixed:
            total = _table_total(db, arity, type_id, v0_pos)
            ent = (("table", spec), total)
        else:
            ent = _host_sparse_deg(db, spec)
        if ent is None or ent[1] == 0:
            return 0  # empty positive term: And fails outright
        reps.append(ent)

    def is_table(r):
        return isinstance(r, tuple) and isinstance(r[0], str)

    def mul(a, a_total, b, b_total):
        a_tab, b_tab = is_table(a), is_table(b)
        if a_tab and b_tab:
            # materialize the smaller table sparsely, keep the other
            # symbolic — the product then rides the sparse ⊙ table path
            if b_total < a_total:
                a, b = b, a
            ent = _table_sparse(db, a[1])
            a = ent[0] if ent is not None else (
                np.empty(0, np.int64), np.empty(0, np.int64)
            )
            a_tab = False
        if a_tab or b_tab:
            rep, tab = (b, a) if a_tab else (a, b)
            idx, cnt = rep  # sparse ⊙ table: degrees at the support
            out = cnt * _table_deg_at(db, tab[1], idx)
            keep = out != 0
            return idx[keep], out[keep]
        return _mul(a, b)

    acc, acc_total = reps[0]
    for d, d_total in reps[1:]:
        if acc_total == 0:
            acc, acc_total = d, d_total  # reference reseed quirk
        else:
            acc = mul(acc, acc_total, d, d_total)  # never symbolic after
            acc_total = _rep_sum(acc)
    return acc_total


def _device_count_group(db, lanes: Sequence[StarLane]) -> List[int]:
    """The device fold over a lane list: async dispatches, one host fetch
    per GROUP of lanes."""
    results: List[int] = []
    for g in range(0, len(lanes), GROUP):
        outs = [_dispatch(db, lane) for lane in lanes[g : g + GROUP]]
        FETCHES["n"] += 1
        fetched = jax.device_get([o for o in outs if not isinstance(o, int)])
        it = iter(fetched)
        for o in outs:
            if isinstance(o, int):
                results.append(o)
                continue
            term_totals, count = next(it)
            if (term_totals == 0).any():
                results.append(0)  # empty positive term: And fails outright
            else:
                results.append(int(count))
    return results


def star_count_many(db, lanes: Sequence[StarLane]) -> List[int]:
    """Count every lane exactly.  Host edition (default): zero device
    work, zero fetches — sparse supports for probed terms, symbolic
    whole-table terms, run-length extraction of the smaller side for
    table ⊙ table products.  Device edition (`DAS_TPU_STAR_FOLD=device`, single-chip
    buffers required — the mesh store always folds host-side): every
    lane through the jitted degree-vector fold, one host fetch per GROUP
    of lanes.  A dense-lane DEVICE batch was tried and reverted: XLA
    lowers the degree bincount as a scatter-add, which at 24M elements
    runs ~5 s/vector on TPU vs ~0.7 s for the host bincount — the
    measured r04 device-fold joint times (21-40 s) were these scatters,
    not dispatch alone.  Every edition computes the reseed semantics
    exactly."""
    if os.environ.get("DAS_TPU_STAR_FOLD", "host") != "device" or not hasattr(
        db, "dev"
    ):
        return [_host_count(db, lane) for lane in lanes]
    return list(_device_count_group(db, lanes))


def try_star_count(db, plans) -> Optional[int]:
    """Single-query surface for compiler.count_matches; None = not star."""
    lane = plan_star(db, plans)
    if lane is None:
        return None
    return star_count_many(db, [lane])[0]
