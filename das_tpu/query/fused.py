"""Fused single-dispatch execution of compiled conjunctive queries.

The staged pipeline in query/compiler.py launches one jitted kernel per
stage (term probe, term-table build, dedup, each join, each anti-join) and
syncs an exact count to the host between stages — ~2T+J dispatches and
device->host round-trips per query.  That is the dominant cost at
query-serving latency scale (the reference's analogue is one Redis
round-trip per probe, redis_mongo_db.py:235-252).

Here the *entire* plan — every probe, term table, dedup, join and
anti-join — is traced into ONE jitted program.  Grounded constants
(probe keys, fixed target rows) enter as dynamic scalar/vector arguments,
so a single compiled executable serves every grounding of the same query
shape: the benchmark loop, the pattern miner's count queries and the
service edge all hit a warm cache after the first call.

Static-shape discipline: per-term and per-join capacities are static
(cache key includes them); the program reports exact per-stage counts so
the host can detect overflow and re-lower with doubled capacities
(powers of two => bounded recompiles).  One reference quirk cannot be
expressed shape-statically: an *empty* intermediate accumulator is
re-seeded by the next positive term (ast.py And.matched, mirroring
pattern_matcher.py:726-738).  The fused program detects that condition
(any intermediate join count of zero with positive terms remaining) and
the caller falls back to the staged path — answers stay exactly
reference-identical.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from das_tpu.ops.join import (
    _anti_join_impl,
    _build_term_table_impl,
    _join_tables_impl,
)

# probe index routes (static per term).  Every compiler.TermPlan pins
# either a link type (type_id) or a composite type (ctype) — plan_query
# rejects anything else — so these three routes are exhaustive.
ROUTE_CTYPE = "ctype"        # template probe: composite-type key
ROUTE_TYPE_POS = "type_pos"  # (type_id<<32|target) at first grounded position
ROUTE_TYPE = "type"          # type-only probe


@dataclass(frozen=True)
class FusedTermSig:
    """Shape-static description of one term (no grounded values)."""

    arity: int
    route: str
    p0: int                        # probe position for *_pos routes, else -1
    extra_fixed: Tuple[int, ...]   # verified positions beyond the probe key
    var_cols: Tuple[int, ...]
    eq_pairs: Tuple[Tuple[int, int], ...]
    var_names: Tuple[str, ...]
    negated: bool


@dataclass(frozen=True)
class FusedPlanSig:
    terms: Tuple[FusedTermSig, ...]
    term_caps: Tuple[int, ...]
    join_caps: Tuple[int, ...]


@dataclass
class FusedResult:
    var_names: Tuple[str, ...]
    vals: jax.Array          # [cap, k] int32
    valid: jax.Array         # [cap]
    count: int
    reseed_needed: bool      # host must fall back to the staged path
    overflow: bool           # some capacity too small; caller re-lowers


def _pow2_at_least(n: int, lo: int = 16) -> int:
    c = lo
    while c < n:
        c *= 2
    return c


def _probe(sig: FusedTermSig, arrays, key, fixed_vals, cap: int):
    """Trace one term probe + verification + term-table build.

    arrays = (sorted_keys, perm, targets, type_id) device arrays for the
    term's bucket/route; key is a traced scalar; fixed_vals a traced
    int32[len(extra_fixed)] vector.
    """
    sorted_keys, perm, targets, type_id = arrays
    lo = jnp.searchsorted(sorted_keys, key, side="left")
    hi = jnp.searchsorted(sorted_keys, key, side="right")
    range_count = (hi - lo).astype(jnp.int32)
    offs = jnp.arange(cap, dtype=jnp.int32)
    valid = offs < range_count
    idx = jnp.clip(lo.astype(jnp.int32) + offs, 0, sorted_keys.shape[0] - 1)
    local = jnp.where(valid, perm[idx], jnp.int32(2**31 - 1))
    safe = jnp.clip(local, 0, targets.shape[0] - 1)
    mask = valid
    for i, pos in enumerate(sig.extra_fixed):
        mask = mask & (targets[safe, pos] == fixed_vals[i])
    vals, mask = _build_term_table_impl(targets, local, mask, sig.var_cols, sig.eq_pairs)
    return vals, mask, range_count


def build_fused(sig: FusedPlanSig, count_only: bool = False):
    """Lower one plan signature to a single jitted callable.

    Call convention: fn(bucket_arrays, keys, fixed_vals) where
      bucket_arrays — tuple of per-term (sorted_keys, perm, targets, type_id)
      keys          — tuple of per-term traced probe keys
      fixed_vals    — tuple of per-term int32 vectors (extra grounded rows)
    Returns (vals, valid, count, term_ranges, join_counts, reseed_flag).
    """
    positives = [i for i, t in enumerate(sig.terms) if not t.negated]
    negatives = [i for i, t in enumerate(sig.terms) if t.negated]

    # static fold of output var names, mirroring compiler._join ordering
    names: Tuple[str, ...] = ()
    join_meta = []  # (pairs, extra, left_k) per join, static
    for n, i in enumerate(positives):
        t = sig.terms[i]
        if n == 0:
            names = t.var_names
            continue
        pairs = tuple(
            (names.index(v), t.var_names.index(v))
            for v in names
            if v in t.var_names
        )
        extra = tuple(
            j for j, v in enumerate(t.var_names) if v not in names
        )
        join_meta.append((pairs, extra))
        names = names + tuple(v for v in t.var_names if v not in names)
    # which tabu tables filter (static: var-set coverage, NO_COVERING rule)
    anti_meta = []
    for i in negatives:
        t = sig.terms[i]
        if set(t.var_names) <= set(names):
            anti_meta.append(
                (i, tuple((names.index(v), t.var_names.index(v)) for v in t.var_names))
            )

    def fn(bucket_arrays, keys, fixed_vals):
        tables = {}
        term_ranges = []
        for i, t in enumerate(sig.terms):
            vals, mask, rng = _probe(
                t, bucket_arrays[i], keys[i], fixed_vals[i], sig.term_caps[i]
            )
            # no per-term dedup: every route pins the link type (type_id or
            # ctype), so the full target vector is a function of (fixed
            # values, var tuple) and distinct candidate links always yield
            # distinct variable tuples
            tables[i] = (vals, mask)
            term_ranges.append(rng)

        acc_vals, acc_valid = tables[positives[0]]
        join_counts = []
        # the reseed quirk needs a *next* positive term; a single-term plan
        # with zero matches is just an empty answer — no fallback needed
        if len(positives) > 1:
            reseed = acc_valid.sum(dtype=jnp.int32) == 0
        else:
            reseed = jnp.bool_(False)
        for n, i in enumerate(positives[1:]):
            rv, rm = tables[i]
            pairs, extra = join_meta[n]
            # no post-join dedup: a join of duplicate-free tables is
            # duplicate-free (output row <-> (left row, right row) is a
            # bijection: shared columns agree, extras come from exactly one
            # side, and each side's rows are unique)
            acc_vals, acc_valid, total = _join_tables_impl(
                acc_vals, acc_valid, rv, rm, pairs, extra, sig.join_caps[n]
            )
            join_counts.append(total)
            if n < len(positives) - 2:
                reseed = reseed | (acc_valid.sum(dtype=jnp.int32) == 0)

        for i, pairs in anti_meta:
            rv, rm = tables[i]
            acc_valid = _anti_join_impl(acc_vals, acc_valid, rv, rm, pairs)

        count = acc_valid.sum(dtype=jnp.int32)
        # ONE small stats vector => the host fetches everything it needs to
        # decide overflow/reseed in a single device->host transfer (the
        # tunnel RTT dominates per-query latency, ~tens of ms per fetch)
        stats = jnp.stack(
            [count, reseed.astype(jnp.int32), *term_ranges, *join_counts]
        )
        if count_only:
            # XLA dead-code-eliminates every value gather feeding only the
            # discarded binding table — counts need keys and masks alone
            return stats
        return acc_vals, acc_valid, stats

    return jax.jit(fn), names


def get_executor(db) -> "FusedExecutor":
    """The per-database executor, cached on the device tables so a
    `refresh()` (which rebuilds them) naturally drops stale programs."""
    ex = getattr(db.dev, "_fused_executor", None)
    if ex is None or ex.db is not db:
        ex = FusedExecutor(db)
        db.dev._fused_executor = ex
    return ex


class FusedExecutor:
    """Per-database cache: plan signature -> compiled fused executable."""

    def __init__(self, db):
        self.db = db
        self._cache: Dict[Tuple, Tuple] = {}          # (plan_sig, count_only)
        self._batch_cache: Dict[FusedPlanSig, object] = {}
        # overflow-corrected capacities learned per plan shape, so later
        # calls start right-sized instead of re-running the overflowing
        # program every time
        self._caps: Dict[Tuple, Tuple[Tuple[int, ...], Tuple[int, ...]]] = {}

    def _remember_caps(self, sigs, term_caps, join_caps) -> None:
        """Record learned capacities and evict superseded smaller-capacity
        executables for this signature, so long-running services don't
        accumulate one compiled program per retry tier."""
        if self._caps.get(sigs) == (term_caps, join_caps):
            return
        self._caps[sigs] = (term_caps, join_caps)
        keep = (term_caps, join_caps)
        for key in list(self._cache):
            ps = key[0]
            if ps.terms == sigs and (ps.term_caps, ps.join_caps) != keep:
                del self._cache[key]
        for ps in list(self._batch_cache):
            if ps.terms == sigs and (ps.term_caps, ps.join_caps) != keep:
                del self._batch_cache[ps]

    # -- plan -> signature + dynamic arguments ----------------------------

    def _term_args(self, plan) -> Optional[Tuple[FusedTermSig, Tuple, object, np.ndarray]]:
        """Map a compiler.TermPlan to (sig, bucket_arrays, key, fixed_vals)."""
        db = self.db
        bucket = db.dev.buckets.get(plan.arity)
        if bucket is None or bucket.size == 0:
            return None
        if plan.ctype is not None:
            sig_route, p0, extra = ROUTE_CTYPE, -1, ()
            arrays = (bucket.key_ctype, bucket.order_by_ctype, bucket.targets, bucket.type_id)
            key = np.int64(plan.ctype)
        elif plan.type_id is not None and plan.fixed:
            p0, v0 = plan.fixed[0]
            sig_route, extra = ROUTE_TYPE_POS, tuple(p for p, _ in plan.fixed[1:])
            arrays = (
                bucket.key_type_pos[p0],
                bucket.order_by_type_pos[p0],
                bucket.targets,
                bucket.type_id,
            )
            key = (np.int64(plan.type_id) << 32) | np.int64(v0)
        else:
            # plan_query guarantees type_id or ctype is set (TermPlan
            # invariant) — an untyped plan cannot reach the fused path
            assert plan.type_id is not None, "TermPlan without type or ctype"
            sig_route, p0, extra = ROUTE_TYPE, -1, ()
            arrays = (bucket.key_type, bucket.order_by_type, bucket.targets, bucket.type_id)
            key = np.int32(plan.type_id)
        fixed_vals = np.asarray(
            [v for _, v in plan.fixed[1:]] if sig_route == ROUTE_TYPE_POS else [],
            dtype=np.int32,
        )
        sig = FusedTermSig(
            arity=plan.arity,
            route=sig_route,
            p0=p0,
            extra_fixed=extra,
            var_cols=plan.var_cols,
            eq_pairs=plan.eq_pairs,
            var_names=plan.var_names,
            negated=plan.negated,
        )
        return sig, arrays, key, fixed_vals

    def _estimate(self, plan) -> int:
        """Exact candidate-range count for a term, computed host-side: the
        same sorted key arrays the device probes live in `fin` (numpy), so
        two binary searches give the range size with no device round trip."""
        b = self.db.fin.buckets.get(plan.arity)
        if b is None or b.size == 0:
            return 0
        if plan.ctype is not None:
            keys, key = b.key_ctype, np.int64(plan.ctype)
        elif plan.type_id is not None and plan.fixed:
            p0, v0 = plan.fixed[0]
            keys, key = b.key_type_pos[p0], (np.int64(plan.type_id) << 32) | np.int64(v0)
        else:
            assert plan.type_id is not None, "TermPlan without type or ctype"
            keys, key = b.key_type, np.int32(plan.type_id)
        lo = int(np.searchsorted(keys, key, side="left"))
        hi = int(np.searchsorted(keys, key, side="right"))
        return hi - lo

    def _order(self, plans) -> List:
        """Greedy join ordering: seed with the smallest positive term, then
        repeatedly take the smallest term sharing a variable with the bound
        set (avoiding cross products); negated terms filter at the end
        regardless of order.  Safe because the caller falls back to the
        staged (reference-order) path whenever the final result is empty —
        and a non-empty full conjunction makes every sub-join non-empty, so
        the reference's empty-accumulator reseed quirk provably cannot fire.
        """
        pos = [(p, self._estimate(p)) for p in plans if not p.negated]
        neg = [p for p in plans if p.negated]
        if len(pos) <= 1:
            return [p for p, _ in pos] + neg
        ordered = []
        bound: set = set()
        remaining = list(pos)
        while remaining:
            connected = [
                (p, e) for p, e in remaining
                if not bound or (set(p.var_names) & bound)
            ] or remaining
            pick = min(connected, key=lambda pe: pe[1])
            remaining.remove(pick)
            ordered.append(pick[0])
            bound |= set(pick[0].var_names)
        return ordered + neg

    def execute(self, plans, count_only: bool = False) -> Optional[FusedResult]:
        """Run the whole plan in one dispatch.

        With count_only the compiled program returns just the stats vector
        (binding-table materialization is dead-code-eliminated) — the shape
        `count_matches` and the miner want.

        Returns None when a term's bucket is missing: an unmatched positive
        term means "no match" and an unmatched negated term never filters,
        both of which the staged path already handles — the caller decides.
        """
        plans = self._order(plans)
        mapped = []
        for plan in plans:
            m = self._term_args(plan)
            if m is None:
                return None
            mapped.append(m)
        sigs = tuple(m[0] for m in mapped)
        arrays = tuple(m[1] for m in mapped)
        keys = tuple(m[2] for m in mapped)
        fvals = tuple(m[3] for m in mapped)

        cfg = self.db.config
        # exact host-side range counts => term capacities never overflow;
        # shapes past the configured ceiling go to the staged path, which
        # clamps (and owns the overflow error policy)
        term_caps = tuple(_pow2_at_least(self._estimate(plan)) for plan in plans)
        if max(term_caps) > cfg.max_result_capacity:
            return None
        n_joins = max(0, sum(1 for s in sigs if not s.negated) - 1)
        # joins tend to stay near the larger input's size once the greedy
        # order avoids cross products; seed capacity there to spare retries
        # (each retry recompiles), and let overflow doubling correct upward
        join_cap0 = _pow2_at_least(
            max([cfg.initial_result_capacity, *term_caps])
        )
        join_caps = tuple([join_cap0] * n_joins)
        learned = self._caps.get(sigs)
        if learned is not None:
            term_caps = tuple(max(a, b) for a, b in zip(term_caps, learned[0]))
            join_caps = tuple(max(a, b) for a, b in zip(join_caps, learned[1]))

        while True:
            plan_sig = FusedPlanSig(sigs, term_caps, join_caps)
            entry = self._cache.get((plan_sig, count_only))
            if entry is None:
                entry = build_fused(plan_sig, count_only)
                self._cache[(plan_sig, count_only)] = entry
            fn, names = entry
            if count_only:
                vals = valid = None
                stats_dev = fn(arrays, keys, fvals)
            else:
                vals, valid, stats_dev = fn(arrays, keys, fvals)
            stats = np.asarray(stats_dev)
            count, reseed = int(stats[0]), bool(stats[1])
            ranges = stats[2 : 2 + len(sigs)]
            jcounts = stats[2 + len(sigs) :]
            new_tc = tuple(
                _pow2_at_least(int(r)) if int(r) > c else c
                for r, c in zip(ranges, term_caps)
            ) if ranges.size else term_caps
            new_jc = tuple(
                _pow2_at_least(int(t)) if int(t) > c else c
                for t, c in zip(jcounts, join_caps)
            ) if jcounts.size else join_caps
            if new_tc == term_caps and new_jc == join_caps:
                break
            if max(new_tc + new_jc, default=0) > cfg.max_result_capacity:
                return None  # staged path clamps and owns overflow policy
            term_caps, join_caps = new_tc, new_jc

        self._remember_caps(sigs, term_caps, join_caps)
        n_positive = sum(1 for s in sigs if not s.negated)
        return FusedResult(
            var_names=names,
            vals=vals,
            valid=valid,
            count=count,
            # an empty result under a reordered multi-term join could mask
            # the reference's reseed quirk in its original order — redo it
            # on the staged (reference-order) path to stay answer-exact
            reseed_needed=reseed or (count == 0 and n_positive > 1),
            overflow=False,
        )

    # -- batched counting --------------------------------------------------

    def count_batch(self, plans_list) -> List[Optional[int]]:
        """Count many same-or-mixed-shape queries in as few dispatches as
        possible: plans are grouped by shape signature, each group runs as
        ONE vmapped fused program over the stacked grounded keys, and the
        whole group's counts come back in a single stats transfer.  This is
        the pattern-miner hot loop (SimplePatternMiner.ipynb cell 9: one
        Redis round trip per candidate in the reference; here ~one device
        round trip per *shape*).

        Entries that can't run fused (missing bucket) or that need the
        reference reseed quirk come back as None — the caller falls back to
        the staged/host path for those.
        """
        prepared = []  # (index, sigs, arrays, keys, fvals, ests)
        out: List[Optional[int]] = [None] * len(plans_list)
        groups: Dict[Tuple, List[int]] = {}
        for idx, plans in enumerate(plans_list):
            plans = self._order(plans)
            mapped = [self._term_args(p) for p in plans]
            if any(m is None for m in mapped):
                continue
            sigs = tuple(m[0] for m in mapped)
            prepared.append(
                (
                    idx,
                    sigs,
                    tuple(m[1] for m in mapped),
                    tuple(m[2] for m in mapped),
                    tuple(m[3] for m in mapped),
                    tuple(self._estimate(p) for p in plans),
                )
            )
            groups.setdefault(sigs, []).append(len(prepared) - 1)

        cfg = self.db.config
        for sigs, members in groups.items():
            term_caps = tuple(
                _pow2_at_least(max(prepared[m][5][t] for m in members))
                for t in range(len(sigs))
            )
            if max(term_caps) > cfg.max_result_capacity:
                continue  # caller's fallback handles the giant probes
            n_joins = max(0, sum(1 for s in sigs if not s.negated) - 1)
            join_cap0 = _pow2_at_least(max([cfg.initial_result_capacity, *term_caps]))
            join_caps = tuple([join_cap0] * n_joins)
            learned = self._caps.get(sigs)
            if learned is not None:
                term_caps = tuple(max(a, b) for a, b in zip(term_caps, learned[0]))
                join_caps = tuple(max(a, b) for a, b in zip(join_caps, learned[1]))
            keys_stacked = tuple(
                np.stack([prepared[m][3][t] for m in members])
                for t in range(len(sigs))
            )
            fvals_stacked = tuple(
                np.stack([prepared[m][4][t] for m in members])
                for t in range(len(sigs))
            )
            arrays = prepared[members[0]][2]
            while True:
                plan_sig = FusedPlanSig(sigs, term_caps, join_caps)
                entry = self._batch_cache.get(plan_sig)
                if entry is None:
                    fn, _names = build_fused(plan_sig, count_only=True)
                    entry = jax.jit(
                        jax.vmap(
                            lambda keys, fvals, _fn=fn, _arrays=arrays: _fn(
                                _arrays, keys, fvals
                            )
                        )
                    )
                    self._batch_cache[plan_sig] = entry
                stats = np.asarray(entry(keys_stacked, fvals_stacked))
                ranges = stats[:, 2 : 2 + len(sigs)]
                jcounts = stats[:, 2 + len(sigs) :]
                new_tc = tuple(
                    _pow2_at_least(int(ranges[:, t].max())) if ranges[:, t].max() > c else c
                    for t, c in enumerate(term_caps)
                )
                new_jc = tuple(
                    _pow2_at_least(int(jcounts[:, j].max())) if jcounts.size and jcounts[:, j].max() > c else c
                    for j, c in enumerate(join_caps)
                )
                if new_tc == term_caps and new_jc == join_caps:
                    break
                if max(new_tc + new_jc) > cfg.max_result_capacity:
                    stats = None
                    break
                term_caps, join_caps = new_tc, new_jc
            if stats is None:
                continue
            self._remember_caps(sigs, term_caps, join_caps)
            n_positive = sum(1 for s in sigs if not s.negated)
            for row, m in zip(stats, members):
                count, reseed = int(row[0]), bool(row[1])
                if reseed or (count == 0 and n_positive > 1):
                    continue  # needs the exact-quirk staged path
                out[prepared[m][0]] = count
        return out
