"""Fused single-dispatch execution of compiled conjunctive queries.

The staged pipeline in query/compiler.py launches one jitted kernel per
stage (term probe, term-table build, dedup, each join, each anti-join) and
syncs an exact count to the host between stages — ~2T+J dispatches and
device->host round-trips per query.  That is the dominant cost at
query-serving latency scale (the reference's analogue is one Redis
round-trip per probe, redis_mongo_db.py:235-252).

Here the *entire* plan — every probe, term table, dedup, join and
anti-join — is traced into ONE jitted program.  Grounded constants
(probe keys, fixed target rows) enter as dynamic scalar/vector arguments,
so a single compiled executable serves every grounding of the same query
shape: the benchmark loop, the pattern miner's count queries and the
service edge all hit a warm cache after the first call.

Static-shape discipline: per-term and per-join capacities are static
(cache key includes them); the program reports exact per-stage counts so
the host can detect overflow and re-lower with doubled capacities
(powers of two => bounded recompiles).  One reference quirk cannot be
expressed shape-statically: an *empty* intermediate accumulator is
re-seeded by the next positive term (ast.py And.matched, mirroring
pattern_matcher.py:726-738).  The fused program detects that condition
(any intermediate join count of zero with positive terms remaining) and
the caller falls back to the staged path — answers stay exactly
reference-identical.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from das_tpu import obs
from das_tpu.ops.join import (
    _anti_join_impl,
    _build_term_table_impl,
    _dedup_table_impl,
    _index_join_impl,
    _join_tables_impl,
)

# probe index routes (static per term).  Every compiler.TermPlan pins
# either a link type (type_id) or a composite type (ctype) — plan_query
# rejects anything else — so these three routes are exhaustive.
ROUTE_CTYPE = "ctype"        # template probe: composite-type key
ROUTE_TYPE_POS = "type_pos"  # (type_id<<32|target) at first grounded position
ROUTE_TYPE = "type"          # type-only probe


@dataclass(frozen=True)
class FusedTermSig:
    """Shape-static description of one term (no grounded values)."""

    arity: int
    route: str
    p0: int                        # probe position for *_pos routes, else -1
    extra_fixed: Tuple[int, ...]   # verified positions beyond the probe key
    var_cols: Tuple[int, ...]
    eq_pairs: Tuple[Tuple[int, int], ...]
    var_names: Tuple[str, ...]
    negated: bool


@dataclass(frozen=True)
class FusedPlanSig:
    terms: Tuple[FusedTermSig, ...]
    term_caps: Tuple[int, ...]
    join_caps: Tuple[int, ...]
    #: per join: -1 = sort-merge against the materialized right table;
    #: else the posting-index position for an INDEX JOIN — the right side
    #: stays implicit (whole-type term probed through key_type_pos[p]), so
    #: buffers scale with join output, never with the table (FlyBase-scale
    #: whole-table terms would otherwise force 33M-row buffers and
    #: minutes-long compiles)
    index_joins: Tuple[int, ...] = ()
    #: route term probes and joins through the Pallas fused kernels
    #: (das_tpu/kernels/) instead of the lowered op chains.  Part of the
    #: signature so kernel and lowered executables cache side by side
    #: (the bench A/B flips DasConfig.use_pallas_kernels per call).
    use_kernels: bool = False
    #: the bytes planner's program verdict was GRID-CHUNKED for at least
    #: one stage (kernels/budget.py).  The traced bodies re-derive their
    #: own layout from the same byte model at trace time — this flag is
    #: the cache-key/telemetry mirror (kernel_tiled route counters)
    tiled: bool = False
    #: budget.vmem_budget() snapshot at dispatch (0 when kernels are
    #: off).  Part of the cache key because the traced LAYOUT — which
    #: stages tile and at what chunk_rows — is a function of the budget
    #: beyond the single tiled bit: a budget change must compile a fresh
    #: executable, not replay one whose chunks the old budget sized
    vmem_budget: int = 0
    #: the cost-based planner (das_tpu/planner) ordered this plan and
    #: seeded its capacities.  Part of the signature for cache-key
    #: honesty (the vmem_budget rationale): the planner A/B flips
    #: DasConfig.use_planner per arm, and when both arms happen to pick
    #: the same order/caps the arms must still compile-and-count their
    #: own executables instead of silently replaying each other's
    planned: bool = False
    #: leading positives fused into ONE k-way multiway intersection
    #: step (kernels/multiway.py) instead of a binary-join chain prefix
    #: (0 = pure chain).  Changes the traced program AND the meaning of
    #: join_caps/index_joins (join_caps[0] is then the multiway output
    #: buffer; index_joins cover only the tail binary joins), so it
    #: must be part of the cache key (DL002's tiled lesson).
    multiway: int = 0


def plan_index_joins(sigs: Tuple[FusedTermSig, ...], start: int = 0):
    """Static per-join index-join eligibility: right side must be an
    ordered whole-type probe (ROUTE_TYPE, no extra verification, no
    repeated variables), positive, and actually share a variable.

    `start` skips the first `start` joins entirely (the multiway
    prefix's internal joins — its clauses ground through materialized
    term tables, never the posting index): the returned tuple covers
    joins start..P-2 and `right_terms` maps term index to the join's
    RELATIVE position in that tuple."""
    positives, _neg, _names, join_meta, _anti = fold_join_meta(sigs)
    index_joins = []
    right_terms = {}
    for n in range(start, max(0, len(positives) - 1)):
        i = positives[n + 1]
        t = sigs[i]
        pairs, _extra = join_meta[n]
        if (
            t.route == ROUTE_TYPE
            and not t.negated
            and not t.eq_pairs
            and not t.extra_fixed
            and pairs
        ):
            p = t.var_cols[pairs[0][1]]
            index_joins.append(p)
            right_terms[i] = n - start
        else:
            index_joins.append(-1)
    return tuple(index_joins), right_terms


@dataclass
class FusedResult:
    var_names: Tuple[str, ...]
    vals: jax.Array          # [cap, k] int32 (device)
    valid: jax.Array         # [cap] (device)
    count: int
    reseed_needed: bool      # host must fall back to the staged path
    overflow: bool           # some capacity too small; caller re-lowers
    host_vals: Optional[np.ndarray] = None   # prefetched host copies —
    host_valid: Optional[np.ndarray] = None  # free for materialization
    multiway: bool = False   # answered by a k-way multiway program


class _ExecJob:
    """One execute()'s mutable state, split into dispatch / settle halves
    so execute_many can interleave many queries' dispatches before paying
    a single host transfer (each fetch is a full RTT on a tunneled TPU).
    Semantics are exactly execute()'s: same program cache, same capacity
    retry, same reseed verdict, same cap learning."""

    __slots__ = (
        "ex", "count_only", "same_order", "sigs", "arrays", "keys", "fvals",
        "term_caps", "join_caps", "index_joins", "use_kernels", "names",
        "result", "planned", "rounds", "last_ranges", "last_join_rows",
        "multiway", "count_route",
    )

    def __init__(
        self, ex, count_only, same_order, sigs, arrays, keys, fvals,
        term_caps, join_caps, index_joins, use_kernels=False, planned=None,
        multiway=0,
    ):
        self.ex = ex
        self.count_only = count_only
        self.same_order = same_order
        self.sigs = sigs
        self.arrays = arrays
        self.keys = keys
        self.fvals = fvals
        self.term_caps = term_caps
        self.join_caps = join_caps
        self.index_joins = index_joins
        self.use_kernels = use_kernels
        self.names = None
        self.result: Optional[FusedResult] = None
        #: the PlannedProgram that ordered/seeded this job (None =
        #: legacy heuristics); settle feeds its estimates back to the
        #: planner counters so estimator error is observable
        self.planned = planned
        #: leading positives fused into one k-way intersection step
        #: (planner/search.py PlannedProgram.multiway; 0 = binary chain)
        self.multiway = multiway
        self.rounds = 0
        self.last_ranges = None      # final-round per-term exact ranges
        self.last_join_rows = None   # final-round per-step exact totals
        #: False when this job is a SITE inside a whole-tree program
        #: (_TreeExecJob): the tree job owns the per-answer route
        #: telemetry — a 3-site tree must count ONE answer, not three
        self.count_route = True

    def plan_sig(self) -> FusedPlanSig:
        """The plan signature at the CURRENT capacities.  Kernel
        eligibility is re-derived per round by the BYTES planner
        (kernels/budget.py, replacing the old per-dimension fits()): a
        capacity retry can grow the combined footprint past the VMEM
        budget, in which case the re-dispatch picks the grid-chunked
        layout — or, past even the tiled resident set, falls back to
        the lowered program.  Shared by dispatch() and the whole-tree
        job (_TreeExecJob), whose tree signature nests one of these per
        site."""
        from das_tpu.kernels import budget

        route = budget.ROUTE_LOWERED
        if self.use_kernels:
            route = kernel_program_plan(
                self.sigs,
                tuple((a[0].shape[0], a[2].shape[0]) for a in self.arrays),
                self.term_caps, self.join_caps, self.index_joins,
                multiway=self.multiway,
            )
        use_k = route != budget.ROUTE_LOWERED
        tiled = route == budget.ROUTE_TILED
        return FusedPlanSig(
            self.sigs, self.term_caps, self.join_caps, self.index_joins,
            use_k, tiled, budget.vmem_budget() if use_k else 0,
            self.planned is not None, self.multiway,
        )

    def dispatch(self):
        """Queue the program at the current capacities (async, no sync)."""
        from das_tpu.kernels import record_dispatch

        plan_sig = self.plan_sig()
        use_k, tiled = plan_sig.use_kernels, plan_sig.tiled
        entry = self.ex._cache.get((plan_sig, self.count_only))
        if entry is None:
            entry = build_fused(plan_sig, self.count_only)
            self.ex._cache[(plan_sig, self.count_only)] = entry
        fn, self.names = entry
        self.rounds += 1
        if plan_sig.planned:
            from das_tpu.planner import PLANNER_COUNTS

            PLANNER_COUNTS["programs"] += 1
        record_dispatch("fused")
        if use_k:
            record_dispatch("fused_kernel")
            if tiled:
                record_dispatch("fused_kernel_tiled")
        if self.multiway:
            record_dispatch("fused_multiway")
        # trace span + optional jax.profiler scope around the enqueue
        # (ISSUE 12): host-monotonic timestamps only — the dispatch half
        # stays sync-free (DL001/DL010); attrs carry the route and the
        # planner's estimated rows so settle's actuals line up against
        # them in one Perfetto lane.  Guarded: the disabled path packs
        # no attribute dict.
        sp = obs.NOOP_SPAN
        if obs.enabled():
            route = "fused"
            if self.multiway:
                route = "fused_multiway"
            elif use_k:
                route = "fused_kernel"
            sp = obs.span(
                "exec.dispatch", route=route, round=self.rounds,
                count_only=self.count_only,
                est_join_rows=(
                    list(self.planned.est_join_rows)
                    if self.planned is not None else None
                ),
            )
        with sp, obs.annotation("exec.dispatch"):
            return fn(self.arrays, self.keys, self.fvals)

    def settle(self, host_out, dev_out) -> bool:
        """Consume one round's fetched stats.  True = finished (result is
        set; None result = capacity ceiling, caller falls back as before);
        False = capacities grew, dispatch again."""
        if self.count_only:
            vals = valid = host_vals = host_valid = None
            stats = np.asarray(host_out)
        else:
            # ONE host transfer carried result + stats: fetching stats
            # first and the binding table later would triple the per-query
            # latency floor.  Device refs are kept alongside for callers
            # that keep joining on device (tree executor).
            host_vals, host_valid, stats = host_out
            vals, valid, _ = dev_out
        count, reseed = int(stats[0]), bool(stats[1])
        pos_empty = bool(stats[2])
        ranges = stats[3 : 3 + len(self.sigs)]
        jcounts = stats[3 + len(self.sigs) :]
        new_tc = tuple(
            _pow2_at_least(int(r)) if int(r) > c else c
            for r, c in zip(ranges, self.term_caps)
        ) if ranges.size else self.term_caps
        new_jc = tuple(
            _pow2_at_least(int(t)) if int(t) > c else c
            for t, c in zip(jcounts, self.join_caps)
        ) if jcounts.size else self.join_caps
        if new_tc != self.term_caps or new_jc != self.join_caps:
            if (
                max(new_tc + new_jc, default=0)
                > self.ex.db.config.max_result_capacity
            ):
                return True  # staged path clamps and owns overflow policy
            self.term_caps, self.join_caps = new_tc, new_jc
            return False
        self.ex._remember_caps(self.sigs, self.term_caps, self.join_caps)
        self.last_ranges = [int(r) for r in ranges]
        self.last_join_rows = [int(t) for t in jcounts]
        if self.planned is not None:
            from das_tpu.planner import observe_settle

            observe_settle(self.planned, self.last_join_rows, self.rounds)
        n_positive = sum(1 for s in self.sigs if not s.negated)
        self.result = FusedResult(
            var_names=self.names,
            vals=vals,
            valid=valid,
            count=count,
            # an empty result under a REORDERED multi-term join could mask
            # the reference's reseed quirk in its original order — redo it
            # on the exact path; in reference order the in-program flag is
            # authoritative, and an empty POSITIVE TERM is always definitive
            reseed_needed=reseed
            or (
                count == 0
                and n_positive > 1
                and not pos_empty
                and not self.same_order
            ),
            overflow=False,
            host_vals=host_vals,
            host_valid=host_valid,
            multiway=bool(self.multiway),
        )
        if self.multiway and self.count_route:
            # per-ANSWER route telemetry (dispatch counts live above):
            # settle fires once per executed job, after every retry
            # round; tree SITE jobs stay silent (count_route False) —
            # their tree job counts the one fused_tree answer
            from das_tpu.query.compiler import ROUTE_COUNTS

            ROUTE_COUNTS["fused_multiway"] += 1
        return True


class _PendingMany:
    """One dispatched-but-unsettled batch: cache-prefilled results, the
    in-flight jobs with their cache keys, the device refs of the enqueued
    round, and the delta version the round was dispatched against (guards
    the settle-time cache insert against a racing commit)."""

    __slots__ = ("results", "jobs", "outs", "version", "fetch_ms")

    def __init__(self, results, jobs, outs, version):
        self.results = results
        self.jobs = jobs
        self.outs = outs
        self.version = version
        # wall-ms of each settle round's host transfer, timed where it
        # happens (settle_pending_iter) — fetch_ms[0] IS the settle
        # round-trip the coalescer's adaptive window sizes from; an
        # all-hit or declined round leaves it empty, so host-side work
        # can never masquerade as the wire
        self.fetch_ms: List[float] = []


def dispatch_pending(results_cache, exec_job, plans_lists, count_only,
                     cache_only=False):
    """Phase-1 shared loop (pendant of settle_pending): resolve
    result-cache hits, dedup identical in-batch queries, prepare and
    ENQUEUE the remaining jobs' first round — all asynchronous.
    `exec_job(plans, count_only)` returns a dispatchable job or None.
    Shared by the single-device and sharded executors so the dedup
    invariant (duplicates alias ONE shared index list, and never record
    their own cache miss) lives in exactly one place."""
    results: List = [None] * len(plans_lists)
    version = results_cache.version()
    jobs = []
    by_key: Dict[Tuple, List[int]] = {}
    for i, plans in enumerate(plans_lists):
        key = results_cache.key(plans, count_only)
        dup = by_key.get(key)
        if dup is not None:
            # in-batch dedup BEFORE the cache lookup: concurrent
            # identical queries (the hot serving case) share ONE
            # program and must not each record a cache miss — the
            # hit-rate figure would under-report exactly this
            # workload.  The others alias the result at settle time.
            dup.append(i)
            continue
        hit = results_cache.get(key)
        if hit is not None:
            results[i] = hit
            continue
        if cache_only:
            # degraded-mode serving (ISSUE 13 breaker): answer from the
            # delta-versioned cache ONLY — a miss stays a dispatch-time
            # decline (results[i] None, no device program enqueued)
            continue
        job = exec_job(plans, count_only)
        if job is not None:
            idxs = [i]
            by_key[key] = idxs
            jobs.append((idxs, job, key))
    outs = [job.dispatch() for _, job, _ in jobs]
    return _PendingMany(results, jobs, outs, version)


def settle_pending_iter(results_cache, pending):
    """Streaming settle of a _PendingMany (ISSUE 6 early-settle): yields
    `(index, result)` as each query's answer becomes FINAL — cache hits
    first (they were answered at dispatch with zero transfer), then, per
    retry round, every job whose verdict landed in that round's ONE host
    transfer.  A query that settled in round 1 streams to its caller
    while its batch-mates' capacity retries are still re-dispatching —
    its first rows arrive one RTT after its own dispatch, not after the
    whole group settles.  Settle-time cache inserts stay guarded by the
    dispatch-time delta version (daslint DL007).  Indices the dispatch
    phase declined (no job, no cache hit) are never yielded — drain the
    iterator and read `pending.results` (None = declined), or use
    settle_pending.  Shared by the single-device and sharded executors —
    their jobs expose the same dispatch()/settle() halves, so the
    serving pipeline's second phase is ONE implementation."""
    for i, hit in enumerate(pending.results):
        if hit is not None:
            yield i, hit
    jobs, outs = pending.jobs, pending.outs
    from das_tpu import fault

    retry = fault.fetch_retry()
    while jobs:
        t0 = time.perf_counter()
        with obs.annotation("exec.settle_fetch"):
            # the shared RetryPolicy (das_tpu/fault, ISSUE 13) replaces
            # the old bare fetch: a transient tunnel drop (or an
            # injected settle_fetch fault) retries with deterministic
            # backoff instead of failing the whole group, and EVERY
            # attempt tallies FETCH_COUNTS — the fetches-per-query
            # telemetry must count real wire trips, not logical rounds
            # (DL013's tally leg)
            def _fetch_round():
                FETCH_COUNTS["n"] += 1
                fault.maybe_fail("settle_fetch")
                return jax.device_get(tuple(outs))

            fetched = retry.run(_fetch_round)
        fetch_s = time.perf_counter() - t0
        pending.fetch_ms.append(fetch_s * 1e3)
        if obs.enabled():
            # the wire, where it happens: one span per settle round's
            # host transfer, one histogram sample (the RTT distribution
            # the adaptive window must hide), one fetch counter tick
            obs.counter("exec.fetches").inc()
            obs.histogram("exec.settle_fetch_ms").observe(fetch_s * 1e3)
            obs.REC.record(
                "exec.settle_fetch", "X", t0, fetch_s, 0,
                {"jobs": len(jobs)},
            )
        nxt = []
        for (idxs, job, key), host, out in zip(jobs, fetched, outs):
            if job.settle(host, out):
                results_cache.put(key, job.result, pending.version)
                for i in idxs:
                    pending.results[i] = job.result
                    yield i, job.result
            else:
                nxt.append((idxs, job, key))
        jobs = nxt
        outs = [job.dispatch() for _, job, _ in jobs]
    pending.jobs, pending.outs = [], []


def settle_pending(results_cache, pending) -> List:
    """Drive a _PendingMany to completion (the non-streaming form of
    settle_pending_iter): one host transfer per retry round, per-job
    settle verdicts, version-guarded cache inserts.  Returns the full
    results list (None = the dispatch phase declined that entry)."""
    for _ in settle_pending_iter(results_cache, pending):
        pass
    return pending.results


#: largest per-term candidate window the exact (reference-order) variant
#: will materialize; beyond this the staged path answers instead
EXACT_TERM_CAP_LIMIT = 1 << 20

#: host fetches of device results — each one is a full RTT on a tunneled
#: TPU, so bench.py reports fetches-per-query alongside the transport RTT
#: to decompose host-visible latency honestly (VERDICT r02 item 3)
FETCH_COUNTS = {"n": 0}

#: the CLOSED set of scopes allowed to call jax.device_get (daslint
#: DL013, the COLLECTIVE_SITES idiom applied to host transfers): calls
#: attribute to their outermost enclosing function, qualified by module
#: stem (package name for __init__ modules).  Every entry must both
#: contain a device_get AND tally FETCH_COUNTS (starcount tallies its
#: own FETCHES, folded into bench the same way) — "one transfer per
#: settle round" is only a checkable contract if the transfer sites are
#: enumerable and the telemetry cannot undercount.  Adding a fetch site
#: means adding it here, under review, with its RTT story.
FETCH_SITES = (
    #: the serving pipeline's ONE transfer per settle round (§10)
    "fused.settle_pending_iter",
    #: whole-tree retry loop — one transfer per tree round (ISSUE 10)
    "fused.run_tree_job",
    #: single-query execute()'s settle fetch
    "fused.FusedExecutor.execute",
    #: reference-order exact variant's settle fetch
    "fused.FusedExecutor.execute_exact",
    #: planner explain(execute=True) driving a real job to settle
    "planner._explain_plans",
    #: star-count device fold: one fetch per GROUP of lanes
    "starcount._device_count_group",
    #: materialization fallbacks when no prefetched host copy exists —
    #: one transfer per table/batch, never on the cache-hit path
    "compiler.materialize",
    "tree.materialize_tables",
    "tree._tree_entry",
    "sharded_db.ShardedDB.materialize",
    #: sharded execute()'s settle fetch (mesh twin of execute)
    "fused_sharded.ShardedFusedExecutor.execute",
)


def _pow2_at_least(n: int, lo: int = 16) -> int:
    c = lo
    while c < n:
        c *= 2
    return c


def _probe(sig: FusedTermSig, arrays, key, fixed_vals, cap: int,
           use_kernels: bool = False):
    """Trace one term probe + verification + term-table build.

    arrays = (sorted_keys, perm, targets, type_id) device arrays for the
    term's bucket/route; key is a traced scalar; fixed_vals a traced
    int32[len(extra_fixed)] vector.  With use_kernels the whole chain
    traces as ONE Pallas kernel (das_tpu/kernels/probe.py) instead of the
    lowered searchsorted/gather/verify op sequence.
    """
    sorted_keys, perm, targets, type_id = arrays
    if use_kernels:
        from das_tpu import kernels

        return kernels.probe_term_table_impl(
            sorted_keys, perm, targets, key, fixed_vals, cap,
            var_cols=sig.var_cols, eq_pairs=sig.eq_pairs,
            extra_fixed=sig.extra_fixed,
            interpret=kernels.interpret_mode(),
        )
    lo = jnp.searchsorted(sorted_keys, key, side="left")
    hi = jnp.searchsorted(sorted_keys, key, side="right")
    range_count = (hi - lo).astype(jnp.int32)
    offs = jnp.arange(cap, dtype=jnp.int32)
    valid = offs < range_count
    idx = jnp.clip(lo.astype(jnp.int32) + offs, 0, sorted_keys.shape[0] - 1)
    local = jnp.where(valid, perm[idx], jnp.int32(2**31 - 1))
    safe = jnp.clip(local, 0, targets.shape[0] - 1)
    mask = valid
    for i, pos in enumerate(sig.extra_fixed):
        mask = mask & (targets[safe, pos] == fixed_vals[i])
    vals, mask = _build_term_table_impl(targets, local, mask, sig.var_cols, sig.eq_pairs)
    return vals, mask, range_count


def fold_join_meta(terms: Tuple[FusedTermSig, ...]):
    """Static join metadata for a positive-term fold: output name order,
    per-join (pairs, extra) column maps, and which negated terms filter
    (NO_COVERING rule: a tabu with variables outside the output never
    excludes).  Shared by the single-device and sharded program builders —
    this derivation is load-bearing for answer correctness."""
    positives = [i for i, t in enumerate(terms) if not t.negated]
    negatives = [i for i, t in enumerate(terms) if t.negated]
    names: Tuple[str, ...] = ()
    join_meta = []
    for n, i in enumerate(positives):
        t = terms[i]
        if n == 0:
            names = t.var_names
            continue
        pairs = tuple(
            (names.index(v), t.var_names.index(v))
            for v in names
            if v in t.var_names
        )
        extra = tuple(j for j, v in enumerate(t.var_names) if v not in names)
        join_meta.append((pairs, extra))
        names = names + tuple(v for v in t.var_names if v not in names)
    anti_meta = []
    for i in negatives:
        t = terms[i]
        if set(t.var_names) <= set(names):
            anti_meta.append(
                (i, tuple((names.index(v), t.var_names.index(v)) for v in t.var_names))
            )
    return positives, negatives, names, join_meta, anti_meta


def multiway_meta(join_meta, mw: int):
    """Static k-way step metadata for a multiway prefix of `mw` clauses:
    (per-tail (v column, extra columns), clause-0's v column).  ONE
    derivation shared by build_fused and build_fused_sharded — like
    fold_join_meta, this is load-bearing for answer correctness, and the
    star-prefix invariant (every prefix join shares exactly one
    variable, at the same accumulated column) is enforced here for both
    program builders."""
    assert all(len(join_meta[j][0]) == 1 for j in range(mw - 1)), (
        "multiway prefix joins must share exactly one variable"
    )
    meta = tuple(
        (join_meta[j][0][0][1], join_meta[j][1]) for j in range(mw - 1)
    )
    return meta, join_meta[0][0][0][0]


def kernel_program_plan(
    sigs, term_shapes, term_caps, join_caps, index_joins,
    *, n_shards: int = 1, exch_caps=None, multiway: int = 0,
) -> str:
    """Bytes-based kernel route for ONE fused program (single-device,
    shard-local, or vmapped count-batch lane) — the planner call that
    replaced the per-dimension `fits()` gate.

    term_shapes[i] = (n_keys, n_rows) of term i's probe index arrays (for
    the sharded executor: PER-SHARD slab sizes — the kernel boundary is
    the shard).  Every stage the program will trace gets a byte plan from
    kernels/budget.py with its COMBINED buffer footprint:

      * probes — all materialized terms (negated included);
      * joins — the left side at its accumulated capacity and the right
        side at the size the kernel ACTUALLY holds: inside shard_map a
        broadcast right is S×cap rows, a hash-partitioned join holds
        S×q on both sides, and an index join gathers the small LEFT to
        S×cap (the old per-dimension check under-accounted exactly these
        concurrent-buffer shapes);
      * anti joins — the final accumulator against each gathered tabu.

    Returns budget.ROUTE_LOWERED / ROUTE_SINGLE / ROUTE_TILED for the
    whole program (one over-budget stage kicks the program to the
    lowered bodies — the all-or-nothing use_kernels contract).  Callers
    re-derive per capacity-retry round; the kernel impls re-derive the
    same model per stage at trace time, so verdict and traced program
    agree."""
    from das_tpu.kernels import budget

    return budget.combine(*_kernel_stage_plans(
        sigs, term_shapes, term_caps, join_caps, index_joins,
        n_shards=n_shards, exch_caps=exch_caps, multiway=multiway,
    ))


def _kernel_stage_plans(
    sigs, term_shapes, term_caps, join_caps, index_joins,
    *, n_shards: int = 1, exch_caps=None, multiway: int = 0,
):
    """The per-stage byte plans behind kernel_program_plan — exposed so
    the program ledger (das_tpu/obs/proflog.py) can report the SAME
    modeled footprint the route gate decided on next to what XLA's
    memory_analysis actually allocated (the §15 calibration contract)."""
    from das_tpu.kernels import budget

    positives, _negatives, _names, join_meta, anti_meta = fold_join_meta(sigs)
    start = multiway if multiway else 1
    index_joins = (
        tuple(index_joins) if index_joins
        else tuple([-1] * max(0, len(positives) - start))
    )
    index_right = {
        positives[start + t]: t for t, p in enumerate(index_joins) if p >= 0
    }
    plans = []
    for i, t in enumerate(sigs):
        if i in index_right:
            continue  # never materialized; budgeted at its join below
        n_keys, n_rows = term_shapes[i]
        plans.append(budget.probe_plan(
            n_keys, n_rows, t.arity, len(t.var_cols), term_caps[i]
        ))
    width = len(sigs[positives[0]].var_cols) if positives else 0
    left_rows = term_caps[positives[0]] if positives else 0
    if multiway:
        # k-way stage: the tails arrive width-padded and — inside
        # shard_map — broadcast-gathered to S×cap rows each, all
        # CONCURRENTLY resident next to the local accumulator and the
        # output block (the S×cap accounting rule of the binary joins)
        tails = [positives[j] for j in range(1, multiway)]
        kpad = max(len(sigs[i].var_cols) for i in tails)
        k_out = width + sum(
            len(join_meta[j][1]) for j in range(multiway - 1)
        )
        plans.append(budget.multiway_plan(
            left_rows, width,
            tuple((n_shards * term_caps[i], kpad) for i in tails),
            k_out, join_caps[0],
        ))
        width = k_out
        left_rows = join_caps[0]
    for t, i in enumerate(positives[start:]):
        pairs, extra = join_meta[start - 1 + t]
        jc = join_caps[(1 if multiway else 0) + t]
        k_out = width + len(extra)
        if index_joins[t] >= 0:
            n_keys, n_rows = term_shapes[i]
            plans.append(budget.index_join_plan(
                n_shards * left_rows, width, n_keys, n_rows,
                sigs[i].arity, k_out, jc,
            ))
        else:
            q = exch_caps[(1 if multiway else 0) + t] if exch_caps else 0
            if q:  # hash-partitioned: S×q rows land on the joining shard
                l_rows, r_rows = n_shards * q, n_shards * q
            else:  # broadcast-right: the gathered right is S×cap rows
                l_rows, r_rows = left_rows, n_shards * term_caps[i]
            plans.append(budget.join_plan(
                l_rows, width, r_rows, len(sigs[i].var_cols),
                len(pairs), k_out, jc,
            ))
        width = k_out
        left_rows = jc
    for i, _pairs in anti_meta:
        plans.append(budget.anti_join_plan(
            left_rows, width, n_shards * term_caps[i], len(sigs[i].var_cols)
        ))
    return plans


def program_model_bytes(sig, bucket_arrays, *_rest) -> int:
    """Modeled peak kernel footprint of ONE fused program — the largest
    per-stage combined (resident + streamed block) byte figure the
    budget planner gated the kernel route on (stages run sequentially,
    so the max is the modeled live-at-once peak).  0 when the program
    runs the lowered bodies (no kernel stages to calibrate).  Called by
    the program ledger at AOT-compile time with the program's actual
    call arguments, so the table shapes are exactly what the trace saw
    (ShardedPlanSigs carry exch_caps; their bucket arrays are [S, m]
    slabs and the per-shard axis-1 sizes are the kernel boundary)."""
    if not getattr(sig, "use_kernels", False):
        return 0
    sharded = hasattr(sig, "exch_caps")
    ax = 1 if sharded else 0
    shapes = tuple(
        (a[0].shape[ax], a[2].shape[ax]) for a in bucket_arrays
    )
    plans = _kernel_stage_plans(
        sig.terms, shapes, sig.term_caps, sig.join_caps, sig.index_joins,
        n_shards=getattr(sig, "n_shards", 1),
        exch_caps=getattr(sig, "exch_caps", None),
        multiway=getattr(sig, "multiway", 0),
    )
    if not plans:
        return 0
    return max(p.resident_bytes + p.block_bytes for p in plans)


def tree_model_bytes(sig, *site_inputs) -> int:
    """Whole-tree twin of program_model_bytes: the max modeled stage
    footprint over every conjunction site of the fused tree program
    (sites trace sequentially into one program)."""
    ssigs = sig.sites + ((sig.neg,) if sig.neg is not None else ())
    return max(
        (program_model_bytes(ssig, inputs[0])
         for ssig, inputs in zip(ssigs, site_inputs)),
        default=0,
    )


def remember_caps(caps_dict, caches, sigs, new_caps, caps_of) -> None:
    """Record learned capacities for a signature and evict superseded
    smaller-capacity executables from the given caches (whose keys all lead
    with the plan signature), so long-running services don't accumulate one
    compiled program per retry tier.  `caps_of` extracts the signature's
    capacity tuple (shape differs between executors)."""
    if caps_dict.get(sigs) == new_caps:
        return
    caps_dict[sigs] = new_caps
    for cache in caches:
        for key in list(cache):
            ps = key[0]
            if ps.terms == sigs and caps_of(ps) != new_caps:
                del cache[key]


class CapStore:
    """Cross-process persistence of learned capacities, keyed by a stable
    hash of the plan signature.  Every capacity-retry tier compiles a new
    XLA executable (minutes at FlyBase scale), so starting a fresh process
    at the last learned tier — alongside the persistent XLA cache — turns
    repeat benchmarks and service restarts from re-learning into cache
    hits.  Capacities are perf hints only: a stale entry merely costs a
    retry, never correctness."""

    def __init__(self, tag: str):
        import os

        base = os.environ.get(
            "DAS_TPU_XLA_CACHE",
            os.path.join(
                os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
                "das_tpu", "xla",
            ),
        )
        self.path = None if base == "0" else os.path.join(
            os.path.dirname(base) or ".", f"caps_{tag}.json"
        )
        self._data = {}
        if self.path and os.path.exists(self.path):
            try:
                import json

                with open(self.path) as fh:
                    self._data = json.load(fh)
            except Exception:
                self._data = {}

    @staticmethod
    def _key(sigs, salt: str) -> str:
        import hashlib

        return hashlib.md5((repr(sigs) + "|" + salt).encode()).hexdigest()

    def load(self, sigs, salt: str = ""):
        caps = self._data.get(self._key(sigs, salt))
        return None if caps is None else tuple(tuple(c) for c in caps)

    def save(self, sigs, caps, salt: str = "") -> None:
        key = self._key(sigs, salt)
        as_lists = [list(c) for c in caps]
        if self._data.get(key) == as_lists:
            return
        self._data[key] = as_lists
        if self.path is None:
            return
        try:
            import json
            import os

            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            tmp = self.path + f".tmp{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(self._data, fh)
            os.replace(tmp, self.path)
        except Exception:
            pass  # persistence is best-effort


def _trace_conj(sig: FusedPlanSig, bucket_arrays, keys, fixed_vals):
    """Trace ONE conjunction — every probe, term table, join and
    anti-join — into the caller's program.  Returns
    (acc_vals, acc_valid, stats_list) where stats_list =
    [count, reseed, any_pos_empty, *term_ranges, *join_counts] as traced
    scalars.  This is build_fused's whole body, extracted so the
    whole-tree program (build_fused_tree, ISSUE 10) can trace several
    conjunction sites side by side in one executable — probes and term
    tables shared by XLA CSE where branches coincide, and all sites
    settling in one transfer."""
    positives, _negatives, names, join_meta, anti_meta = fold_join_meta(sig.terms)
    mw = sig.multiway
    # first positive the tail binary fold starts from (the accumulator
    # is the multiway output when mw, else the first term table)
    start = mw if mw else 1
    index_joins = sig.index_joins or tuple(
        [-1] * max(0, len(positives) - start)
    )
    index_right = {
        positives[start + t]: t for t, p in enumerate(index_joins) if p >= 0
    }
    if mw:
        mw_meta, mw_vcol0 = multiway_meta(join_meta, mw)
    use_k = sig.use_kernels
    if use_k or mw:
        from das_tpu import kernels as _kernels

        _interp = _kernels.interpret_mode()
        # the multiway step has no separate lowered chain: with the
        # kernel route off its body still traces — by direct discharge
        # to ordinary XLA ops (interpret=True works on ANY backend; the
        # pallas_call lowering is reserved for the kernel route)
        _mw_interp = _interp if use_k else True

    tables = {}
    term_ranges = []
    pos_count = {}
    for i, t in enumerate(sig.terms):
        if i in index_right:
            # index-join right side: never materialized.  Its arrays
            # are the (type<<32|target) positional index; the term's
            # candidate count (for the empty-positive-term rule) is the
            # type's key range, and it exerts no capacity pressure.
            keys_sorted = bucket_arrays[i][0]
            tid = jnp.asarray(keys[i], jnp.int64)
            lo = jnp.searchsorted(keys_sorted, tid << 32, side="left")
            hi = jnp.searchsorted(keys_sorted, (tid + 1) << 32, side="left")
            pos_count[i] = (hi - lo).astype(jnp.int32)
            tables[i] = None
            term_ranges.append(jnp.int32(0))
            continue
        vals, mask, rng = _probe(
            t, bucket_arrays[i], keys[i], fixed_vals[i], sig.term_caps[i],
            use_kernels=use_k,
        )
        # no per-term dedup: every route pins the link type (type_id or
        # ctype), so the full target vector is a function of (fixed
        # values, var tuple) and distinct candidate links always yield
        # distinct variable tuples
        tables[i] = (vals, mask)
        pos_count[i] = mask.sum(dtype=jnp.int32)
        term_ranges.append(rng)

    # a positive term with zero verified candidates fails the whole And
    # in the reference (term.matched False -> return False, ast.py
    # And.matched) — a DEFINITIVE empty answer, distinct from the
    # reseed quirk, which fires only when a *join* empties a non-empty
    # accumulator with positive terms remaining
    any_pos_empty = jnp.bool_(False)
    for i in positives:
        any_pos_empty = any_pos_empty | (pos_count[i] == 0)

    acc_vals, acc_valid = tables[positives[0]]
    join_counts = []
    # the reseed quirk needs a *next* positive term; a single-term plan
    # with zero matches is just an empty answer — no fallback needed
    if len(positives) > 1:
        reseed = acc_valid.sum(dtype=jnp.int32) == 0
    else:
        reseed = jnp.bool_(False)
    if mw:
        # k-way multiway step: ALL prefix clauses ground in one
        # leapfrog-intersection pass — no intermediate tables, one
        # output buffer (sig.join_caps[0]).  The kernel's partial
        # totals are the would-be binary intermediates' exact pair
        # counts, so the reference's empty-accumulator reseed
        # verdict is reproduced without materializing them: the
        # t-th internal join triggers iff its absolute position is
        # before the LAST join of the whole program (the chain's
        # `n < len(positives) - 2` rule).
        acc_vals, acc_valid, mw_totals = _kernels.multiway_join_impl(
            acc_vals, acc_valid,
            [tables[i] for i in positives[1:mw]],
            mw_vcol0, mw_meta, sig.join_caps[0],
            interpret=_mw_interp,
        )
        join_counts.append(mw_totals[mw - 2])
        for t in range(max(0, min(mw - 1, len(positives) - 2))):
            reseed = reseed | (mw_totals[t] == 0)
    for t, i in enumerate(positives[start:]):
        n = start - 1 + t          # absolute join position
        pairs, extra = join_meta[n]
        jc = sig.join_caps[(1 if mw else 0) + t]
        # no post-join dedup: a join of duplicate-free tables is
        # duplicate-free (output row <-> (left row, right row) is a
        # bijection: shared columns agree, extras come from exactly one
        # side, and each side's rows are unique)
        if index_joins[t] >= 0:
            ks, perm, targets, _tid = bucket_arrays[i]
            if use_k:
                acc_vals, acc_valid, total = _kernels.index_join_impl(
                    acc_vals, acc_valid, ks, perm, targets, keys[i],
                    pairs, sig.terms[i].var_cols, extra,
                    jc, interpret=_interp,
                )
            else:
                acc_vals, acc_valid, total = _index_join_impl(
                    acc_vals, acc_valid, ks, perm, targets, keys[i],
                    pairs, sig.terms[i].var_cols, extra, jc,
                )
        else:
            rv, rm = tables[i]
            if use_k:
                acc_vals, acc_valid, total = _kernels.join_tables_impl(
                    acc_vals, acc_valid, rv, rm, pairs, extra,
                    jc, interpret=_interp,
                )
            else:
                acc_vals, acc_valid, total = _join_tables_impl(
                    acc_vals, acc_valid, rv, rm, pairs, extra, jc
                )
        join_counts.append(total)
        if n < len(positives) - 2:
            reseed = reseed | (acc_valid.sum(dtype=jnp.int32) == 0)

    for i, pairs in anti_meta:
        rv, rm = tables[i]
        if use_k:
            acc_valid = _kernels.anti_join_impl(
                acc_vals, acc_valid, rv, rm, pairs, interpret=_interp
            )
        else:
            acc_valid = _anti_join_impl(acc_vals, acc_valid, rv, rm, pairs)

    count = acc_valid.sum(dtype=jnp.int32)
    reseed = reseed & ~any_pos_empty
    stats_list = [
        count,
        reseed.astype(jnp.int32),
        any_pos_empty.astype(jnp.int32),
        *term_ranges,
        *join_counts,
    ]
    return acc_vals, acc_valid, stats_list


def build_fused(sig: FusedPlanSig, count_only: bool = False):
    """Lower one plan signature to a single jitted callable.

    Call convention: fn(bucket_arrays, keys, fixed_vals) where
      bucket_arrays — tuple of per-term (sorted_keys, perm, targets, type_id)
      keys          — tuple of per-term traced probe keys
      fixed_vals    — tuple of per-term int32 vectors (extra grounded rows)
    Returns (vals, valid, stats); stats = [count, reseed, any_pos_empty,
    *term_ranges, *join_counts] — ONE small vector so the host fetches
    everything it needs to decide overflow/reseed in a single
    device->host transfer (the tunnel RTT dominates per-query latency).
    The conjunction body itself lives in _trace_conj (shared with the
    whole-tree program builder).
    """
    _positives, _negatives, names, _jm, _am = fold_join_meta(sig.terms)

    def fn(bucket_arrays, keys, fixed_vals):
        acc_vals, acc_valid, stats_list = _trace_conj(
            sig, bucket_arrays, keys, fixed_vals
        )
        stats = jnp.stack(stats_list)
        if count_only:
            # XLA dead-code-eliminates every value gather feeding only the
            # discarded binding table — counts need keys and masks alone
            return stats
        return acc_vals, acc_valid, stats

    # program ledger (ISSUE 14): identity when DAS_TPU_PROFLOG is off;
    # on, the first call per shape AOT-compiles and records wall time +
    # cost/memory analysis under this signature's digest
    return obs.proflog.instrument(
        "fused", obs.proflog.sig_digest(sig, count_only), jax.jit(fn),
        model_bytes=partial(program_model_bytes, sig),
    ), names


def conj_stats_len(n_terms: int, n_steps: int) -> int:
    """Length of one conjunction's stats block inside a stacked
    whole-tree stats vector: [count, reseed, any_pos_empty,
    *term_ranges, *join_counts] — the settle halves parse by this (the
    sharded blocks append their exchange occupancies on top)."""
    return 3 + n_terms + n_steps


def canonical_tree_names(terms) -> Tuple[str, ...]:
    """Canonical output layout of a whole-tree program: the site's bound
    variables in SORTED name order — the same canonical column order the
    tree executor's union path projects to (query/tree.py
    _canonicalize), so in-program dedup/anti row equality matches the
    host assignment-set identity exactly."""
    _pos, _neg, names, _jm, _am = fold_join_meta(terms)
    return tuple(sorted(names))


@dataclass(frozen=True)
class FusedTreeSig:
    """Shape-static description of ONE whole-tree fused program (ISSUE
    10): every positive Or branch as a full per-site plan signature,
    plus the joint negative conjunction for the de-Morgan difference
    branch.  Nested FusedPlanSigs carry the per-site capacities, kernel
    routing and planner provenance, so the tree signature inherits
    their cache-key honesty (daslint DL002)."""

    sites: Tuple[FusedPlanSig, ...]
    neg: Optional[FusedPlanSig] = None


def build_fused_tree(sig: FusedTreeSig, count_only: bool = False):
    """Lower a whole Or/negation plan tree to ONE jitted program: every
    conjunction site traces via _trace_conj, the positive branches
    union in-program (projection to the canonical sorted-name column
    order, concat, exact lexsort dedup — the tree executor's
    union_ctables machinery, fused), and the optional negative branch
    anti-joins the union on ALL columns (the de-Morgan difference,
    query/tree.py difference()).  An N-branch Or settles in one
    dispatch and one transfer where the tree executor pays >= N.

    Call convention: fn(*site_inputs) where site_inputs has one
    (bucket_arrays, keys, fixed_vals) triple per positive site, then
    one for the negative site when sig.neg is set.  Stats layout:
      [final_count, *site_0_block, ..., *neg_block]
    with each block = [count, reseed, any_pos_empty, *term_ranges,
    *join_counts] (conj_stats_len per site) — the host parses per-site
    verdicts for capacity retry and the reseed contract out of ONE
    transfer."""
    out_names = canonical_tree_names(sig.sites[0].terms)
    K = len(out_names)
    perms = []
    for ssig in sig.sites + ((sig.neg,) if sig.neg is not None else ()):
        _p, _n, names, _jm, _am = fold_join_meta(ssig.terms)
        assert tuple(sorted(names)) == out_names, (
            "tree fusion requires one shared variable universe"
        )
        perms.append(tuple(names.index(v) for v in out_names))

    def fn(*site_inputs):
        blocks = []
        parts = []
        for i, ssig in enumerate(sig.sites):
            ba, ks, fv = site_inputs[i]
            v, m, sl = _trace_conj(ssig, ba, ks, fv)
            blocks.append(sl)
            parts.append((v[:, jnp.asarray(perms[i], dtype=jnp.int32)], m))
        union_vals = jnp.concatenate([v for v, _ in parts], axis=0)
        union_valid = jnp.concatenate([m for _, m in parts], axis=0)
        if sig.neg is not None:
            ba, ks, fv = site_inputs[len(sig.sites)]
            nv, nm, nsl = _trace_conj(sig.neg, ba, ks, fv)
            blocks.append(nsl)
            nv = nv[:, jnp.asarray(perms[-1], dtype=jnp.int32)]
            # de-Morgan difference: joint negative answers minus the
            # positive union — plain full-row equality removal against
            # the RAW concat (the union is only a membership set here;
            # duplicates are harmless, so no dedup sort is paid)
            all_pairs = tuple((c, c) for c in range(K))
            nm = _anti_join_impl(nv, nm, union_vals, union_valid, all_pairs)
            out_vals, out_valid = nv, nm
            count = nm.sum(dtype=jnp.int32)
        else:
            # exact union dedup (ops/join.py): all sites are ordered
            # tables over one variable set, so positional row equality
            # over the canonical columns IS the reference assignment
            # identity
            out_vals, out_valid, count = _dedup_table_impl(
                union_vals, union_valid
            )
        stats = jnp.stack(
            [count] + [s for block in blocks for s in block]
        )
        if count_only:
            return stats
        return out_vals, out_valid, stats

    return obs.proflog.instrument(
        "fused_tree", obs.proflog.sig_digest(sig, count_only),
        jax.jit(fn), model_bytes=partial(tree_model_bytes, sig),
    ), out_names


class _TreeExecJob:
    """One whole-tree execution's mutable state (ISSUE 10), split into
    the dispatch/settle halves like _ExecJob.  Wraps one count_only
    per-site _ExecJob per conjunction site: the site jobs own ordering,
    planner seeds, capacity math and the reseed verdict (their settle
    halves parse this job's per-site stats blocks), while THIS job owns
    the single fused tree program — one dispatch, one transfer, where
    the tree executor pays one per site.

    Decline semantics: a site hitting the capacity ceiling, or any
    site's reseed verdict firing, abandons the fused tree (result None,
    needs_fallback) and the tree executor re-answers — bit-identical,
    exactly like the conjunction path's staged fallback.

    The sharded twin (_ShardedTreeExecJob, parallel/fused_sharded.py)
    subclasses this and overrides ONLY the executor-specific hooks —
    tree_sig / _build / _blk_len / _make_result plus the literal
    counter keys (daslint DL004 pins counting sites as declared-key
    literals, so the dispatch/settle wrappers stay per-class) — the
    settle_pending_iter sharing idiom applied to tree jobs."""

    __slots__ = (
        "ex", "site_jobs", "neg_job", "names", "rounds", "result",
        "needs_fallback", "matched_any", "_done",
    )

    def __init__(self, ex, site_jobs, neg_job):
        self.ex = ex
        self.site_jobs = site_jobs
        self.neg_job = neg_job
        self.names = None
        self.rounds = 0
        self.result = None
        #: True once settle decided the tree executor must re-answer
        #: (per-site reseed verdict or capacity ceiling)
        self.needs_fallback = False
        #: the reference Or.matched verdict source: any POSITIVE site
        #: matched (site count > 0) — independent of the difference
        #: branch's final count
        self.matched_any = False
        self._done = set()

    def _all_jobs(self):
        return self.site_jobs + (
            [self.neg_job] if self.neg_job is not None else []
        )

    # -- executor-specific hooks (the sharded twin overrides these) ------

    def tree_sig(self) -> FusedTreeSig:
        return FusedTreeSig(
            tuple(j.plan_sig() for j in self.site_jobs),
            self.neg_job.plan_sig() if self.neg_job is not None else None,
        )

    def _build(self, tree_sig):
        return build_fused_tree(tree_sig)

    def _blk_len(self, j) -> int:
        return conj_stats_len(len(j.sigs), len(j.join_caps))

    def _make_result(self, vals, valid, count, host_vals, host_valid):
        return FusedResult(
            var_names=self.names,
            vals=vals,
            valid=valid,
            count=count,
            reseed_needed=False,
            overflow=False,
            host_vals=host_vals,
            host_valid=host_valid,
        )

    def dispatch(self):
        """Queue the whole-tree program at every site's current
        capacities (async, no sync)."""
        from das_tpu.kernels import record_dispatch

        record_dispatch("fused_tree")
        sp = obs.NOOP_SPAN
        if obs.enabled():
            sp = obs.span("exec.dispatch", route="fused_tree",
                          sites=len(self.site_jobs))
        with sp, obs.annotation("exec.dispatch"):
            return self._dispatch_common()

    def settle(self, host_out, dev_out) -> bool:
        done = self._settle_common(host_out, dev_out)
        if done and self.result is not None:
            from das_tpu.query.compiler import ROUTE_COUNTS

            ROUTE_COUNTS["fused_tree"] += 1
        return done

    # -- shared machinery ------------------------------------------------

    def _dispatch_common(self):
        tree_sig = self.tree_sig()
        cache = self.ex._tree_progs
        entry = cache.get(tree_sig)
        if entry is None:
            entry = self._build(tree_sig)
            if len(cache) > 64:
                # superseded-capacity entries have no per-site eviction
                # hook (remember_caps keys on conjunction sigs): bound
                # the program cache instead of leaking one executable
                # per retry tier across long-running services
                cache.clear()
            cache[tree_sig] = entry
        fn, self.names = entry
        self.rounds += 1
        for j in self._all_jobs():
            j.rounds += 1
        if any(j.planned is not None for j in self._all_jobs()):
            from das_tpu.planner import PLANNER_COUNTS

            # ONE program carried every planned site this round — the
            # "programs" counter tracks dispatched device programs, and
            # fewer of them is exactly the fused tree's point
            PLANNER_COUNTS["programs"] += 1
        return fn(*(
            (j.arrays, j.keys, j.fvals) for j in self._all_jobs()
        ))

    def _settle_common(self, host_out, dev_out) -> bool:
        """Consume one round's fetched stats: slice the per-site blocks
        out of the ONE stats vector and run each site job's own settle
        verdict on its block.  True = finished (result set, or decline:
        result None + needs_fallback); False = some site's capacities
        grew — dispatch the whole tree again (still one program)."""
        host_vals, host_valid, stats = host_out
        vals, valid, _ = dev_out
        stats = np.asarray(stats)
        off = 1
        grew = False
        for idx, j in enumerate(self._all_jobs()):
            blk_len = self._blk_len(j)
            blk = stats[off : off + blk_len]
            off += blk_len
            if idx in self._done:
                continue  # its caps fit earlier; the block is stable
            if j.settle(blk, None):
                if j.result is None:
                    # capacity ceiling: the tree executor owns the
                    # overflow policy (exactly the conjunction decline)
                    self.result = None
                    self.needs_fallback = True
                    return True
                self._done.add(idx)
            else:
                grew = True
        if grew:
            return False
        if any(j.result.reseed_needed for j in self._all_jobs()):
            # a site's reseed quirk fired: its in-program answer is not
            # trustworthy under reordering — the tree executor re-runs
            # the whole tree (its conj leaves resolve reseeds on the
            # exact variant), answers stay reference-identical
            self.result = None
            self.needs_fallback = True
            return True
        self.matched_any = any(j.result.count > 0 for j in self.site_jobs)
        self.result = self._make_result(
            vals, valid, int(stats[0]), host_vals, host_valid
        )
        return True


def run_tree_job(job):
    """Drive a tree job's dispatch/settle retry loop to completion (the
    execute() idiom) — ONE implementation for both executors."""
    while True:
        out = job.dispatch()
        FETCH_COUNTS["n"] += 1
        if obs.enabled():
            obs.counter("exec.fetches").inc()
        t0 = time.perf_counter()
        with obs.annotation("exec.settle_fetch"):
            fetched = jax.device_get(out)
        if obs.enabled():
            fetch_s = time.perf_counter() - t0
            obs.histogram("exec.settle_fetch_ms").observe(fetch_s * 1e3)
            obs.REC.record("exec.settle_fetch", "X", t0, fetch_s, 0,
                           {"tree": True})
        if job.settle(fetched, out):
            return job


def prepare_tree_job(ex, pos_sites, neg_plans, job_cls):
    """Build one whole-tree job (ISSUE 10) on executor `ex`: one
    count_only site job per positive Or branch (each rides the full
    _exec_job machinery — planner ordering and seeds, learned caps,
    index-join routing, multiway prefixes), plus one for the joint
    negative conjunction.  None when ANY site declines (missing bucket,
    capacity ceiling) — the tree executor answers, bit-identical.
    Site jobs don't count per-answer route telemetry (count_route):
    the tree job reports the ONE fused answer.  Shared by both
    executors — `job_cls` is their only difference."""
    site_jobs = []
    for site in pos_sites:
        j = ex._exec_job(list(site), True)
        if j is None:
            return None
        j.count_route = False
        site_jobs.append(j)
    neg_job = None
    if neg_plans:
        neg_job = ex._exec_job(list(neg_plans), True)
        if neg_job is None:
            return None
        neg_job.count_route = False
    return job_cls(ex, site_jobs, neg_job)


@dataclass(frozen=True)
class FusedExactSig:
    """Shape-static description of a REFERENCE-ORDER plan for the exact
    (in-program reseed) variant.  chain_caps holds one capacity per suffix
    chain join (s, i), s < i, in _chain_order() order."""

    terms: Tuple[FusedTermSig, ...]
    term_caps: Tuple[int, ...]
    chain_caps: Tuple[int, ...]


def _chain_order(P: int):
    return [(s, i) for s in range(P) for i in range(s + 1, P)]


def _fold_names(var_names_seq):
    """Static fold of output variable names along a join chain; returns the
    final name tuple and per-step (pairs, extra) join metadata (mirrors
    compiler._join ordering)."""
    names: Tuple[str, ...] = ()
    metas = []
    for n, vn in enumerate(var_names_seq):
        if n == 0:
            names = tuple(vn)
            continue
        pairs = tuple((names.index(v), vn.index(v)) for v in names if v in vn)
        extra = tuple(j for j, v in enumerate(vn) if v not in names)
        metas.append((pairs, extra))
        names = names + tuple(v for v in vn if v not in names)
    return names, metas


def build_fused_exact(sig: FusedExactSig, count_only: bool = False):
    """Lower a reference-order plan to ONE program that implements the
    And fold EXACTLY — including the empty-accumulator reseed quirk
    (ast.py And.matched, mirroring pattern_matcher.py:725-738) — so no
    query shape ever needs the staged/host fallback for reseed reasons.

    The reseed makes the accumulator's variable set data-dependent (it can
    restart at any term), which XLA's static shapes can't express directly.
    Trick: every possible reseed point s yields a STATIC suffix chain
    J(s,i) = A_s ⋈ ... ⋈ A_i, so the program computes all P(P-1)/2 chain
    joins with static column metadata, runs the reference fold as a tiny
    automaton over the chains' exact counts (state = latest reseed point),
    and selects the final table of the active state.  Chain totals are
    masked to the ACTIVE path so the host never grows capacity for
    never-taken cross-product chains.

    Returns (fn, names_per_state, cols_per_state): names_per_state[s] is
    the static bound variable tuple of final state s and cols_per_state[s]
    their column indices in the full-K output table — the host picks by
    the returned state.  Call convention matches build_fused; stats layout:
      [count, s_active, any_pos_empty, *term_ranges, *masked_chain_totals]
    """
    positives = [i for i, t in enumerate(sig.terms) if not t.negated]
    negatives = [i for i, t in enumerate(sig.terms) if t.negated]
    P = len(positives)
    chain_pairs = _chain_order(P)
    cap_of = dict(zip(chain_pairs, sig.chain_caps))

    # static metadata per suffix chain
    chain_names: Dict[Tuple[int, int], Tuple[str, ...]] = {}
    chain_meta: Dict[Tuple[int, int], Tuple] = {}
    for s in range(P):
        seq = [sig.terms[positives[i]].var_names for i in range(s, P)]
        names, metas = _fold_names(seq)
        running = tuple(seq[0])
        chain_names[(s, s)] = running
        for off, meta in enumerate(metas):
            i = s + 1 + off
            vn = seq[off + 1]
            running = running + tuple(v for v in vn if v not in running)
            chain_names[(s, i)] = running
            chain_meta[(s, i)] = meta

    # full output layout: all positive variables, first-appearance order
    all_names, _ = _fold_names([sig.terms[i].var_names for i in positives])
    K = len(all_names)
    names_per_state = tuple(chain_names[(s, P - 1)] for s in range(P))
    cols_per_state = tuple(
        tuple(all_names.index(n) for n in names) for names in names_per_state
    )
    cap_final = max(
        cap_of[(s, P - 1)] if s < P - 1 else sig.term_caps[positives[s]]
        for s in range(P)
    )

    def fn(bucket_arrays, keys, fixed_vals):
        tables = {}
        term_ranges = []
        for i, t in enumerate(sig.terms):
            vals, mask, rng = _probe(
                t, bucket_arrays[i], keys[i], fixed_vals[i], sig.term_caps[i]
            )
            tables[i] = (vals, mask)
            term_ranges.append(rng)

        pos_counts = [tables[i][1].sum(dtype=jnp.int32) for i in positives]
        any_pos_empty = jnp.bool_(False)
        for c in pos_counts:
            any_pos_empty = any_pos_empty | (c == 0)

        # all suffix-chain joins (static shapes per chain)
        chain: Dict[Tuple[int, int], Tuple] = {}
        totals: Dict[Tuple[int, int], jax.Array] = {}
        C = jnp.zeros((P, P), dtype=jnp.int32)
        for s in range(P):
            v, m = tables[positives[s]]
            chain[(s, s)] = (v, m)
            C = C.at[s, s].set(pos_counts[s])
            for i in range(s + 1, P):
                rv, rm = tables[positives[i]]
                pairs, extra = chain_meta[(s, i)]
                v, m, tot = _join_tables_impl(
                    chain[(s, i - 1)][0], chain[(s, i - 1)][1],
                    rv, rm, pairs, extra, cap_of[(s, i)],
                )
                chain[(s, i)] = (v, m)
                totals[(s, i)] = tot
                # explicit downcast: tot is an int64 row count; scattering
                # it into the int32 count matrix without astype is a
                # FutureWarning today and an error in future JAX
                C = C.at[s, i].set(
                    jnp.minimum(tot, 2**31 - 1).astype(jnp.int32)
                )

        # the reference fold as an automaton over chain counts:
        # state = latest reseed point; transition BEFORE joining term i
        s_act = jnp.int32(0)
        used: Dict[Tuple[int, int], jax.Array] = {}
        for i in range(1, P):
            prev_empty = C[s_act, i - 1] == 0
            for s in range(i):
                used[(s, i)] = (~prev_empty) & (s_act == s)
            s_act = jnp.where(prev_empty, jnp.int32(i), s_act)

        masked_totals = [
            jnp.where(used[(s, i)], totals[(s, i)], jnp.int32(0))
            for (s, i) in chain_pairs
        ]

        # final state tables: project to the full-K layout, apply negation
        # filters whose variable set the state covers, pad to cap_final
        final_vals = jnp.zeros((cap_final, K), dtype=jnp.int32)
        final_valid = jnp.zeros((cap_final,), dtype=bool)
        count = jnp.int32(0)
        for s in range(P):
            v, m = chain[(s, P - 1)]
            names_s = chain_names[(s, P - 1)]
            for ni in negatives:
                t = sig.terms[ni]
                if set(t.var_names) <= set(names_s):
                    pairs = tuple(
                        (names_s.index(x), t.var_names.index(x))
                        for x in t.var_names
                    )
                    rv, rm = tables[ni]
                    m = _anti_join_impl(v, m, rv, rm, pairs)
            proj = jnp.zeros((v.shape[0], K), dtype=jnp.int32)
            for ci, name in enumerate(names_s):
                proj = proj.at[:, all_names.index(name)].set(v[:, ci])
            pad = cap_final - v.shape[0]
            if pad:
                proj = jnp.concatenate(
                    [proj, jnp.zeros((pad, K), dtype=jnp.int32)]
                )
                m = jnp.concatenate([m, jnp.zeros((pad,), dtype=bool)])
            sel = s_act == s
            final_vals = jnp.where(sel, proj, final_vals)
            final_valid = jnp.where(sel, m, final_valid)
            count = jnp.where(sel, m.sum(dtype=jnp.int32), count)

        count = jnp.where(any_pos_empty, jnp.int32(0), count)
        final_valid = final_valid & ~any_pos_empty
        stats = jnp.stack(
            [
                count,
                s_act,
                any_pos_empty.astype(jnp.int32),
                *term_ranges,
                *masked_totals,
            ]
        )
        if count_only:
            return stats
        return final_vals, final_valid, stats

    # exact variant stays off the kernel route (no byte model to
    # calibrate) but its compiles are ledger-visible like every program
    return obs.proflog.instrument(
        "fused_exact", obs.proflog.sig_digest(sig, count_only),
        jax.jit(fn),
    ), names_per_state, cols_per_state


#: token capacity for index-joined terms — never materialized
INDEX_TERM_TOKEN_CAP = 16


def apply_index_joins(buckets, sigs, arrays, term_caps, start_join: int = 0):
    """Decide per-join index-join routing and rewrite the affected terms'
    inputs: positional posting-index arrays instead of the type-sorted
    window, and a token capacity (the term is never materialized, so it
    exerts no buffer or compile-size pressure).  `buckets` maps arity to
    the executor's bucket objects (single-device DeviceBucket or sharded
    ShardedBucket — both carry key_type_pos/order_by_type_pos/targets/
    type_id), so both executors share one routing convention.
    `start_join` excludes the multiway prefix's internal joins
    (plan_index_joins) — the returned index_joins cover the TAIL binary
    joins only."""
    index_joins, index_right = plan_index_joins(sigs, start_join)
    if index_right:
        arrays = list(arrays)
        term_caps = list(term_caps)
        for i, n in index_right.items():
            p = index_joins[n]
            b = buckets[sigs[i].arity]
            arrays[i] = (
                b.key_type_pos[p], b.order_by_type_pos[p],
                b.targets, b.type_id,
            )
            term_caps[i] = INDEX_TERM_TOKEN_CAP
        arrays = tuple(arrays)
        term_caps = tuple(term_caps)
    return index_joins, frozenset(index_right), arrays, term_caps


def clamp_index_terms(term_caps, index_right):
    """Learned/stored capacities may predate index-join routing for this
    signature; index-joined terms never materialize, so their token
    capacity must survive the merge."""
    return tuple(
        INDEX_TERM_TOKEN_CAP if i in index_right else c
        for i, c in enumerate(term_caps)
    )


#: batching ceiling for one member's largest term capacity: a vmapped
#: group multiplies every padded buffer by the lane count, so a whole-type
#: term at reference scale (tens of millions of rows) must run single-lane
#: (the staged/single-dispatch paths handle it in one ~quarter-GB buffer)
LARGE_TERM_BATCH_LIMIT = 1 << 23


def trivial_plan_count(db, plans) -> Optional[int]:
    """Exact count for a single positive term with distinct variables —
    entirely host-side, zero device work.

    Unconstrained shape (whole-type / whole-template): every row in the
    term's key range yields one distinct assignment (links are
    content-addressed, so no two rows bind identical targets), so the
    host-side range size IS the answer — no materialized multi-GB padded
    table.  This is the pattern miner's all-wildcard candidate shape
    (reference emits a `[*, *targets]` key per link and counts the Redis
    set).

    Grounded shape (type + fixed positions): the most selective fixed
    position's sorted range is gathered from the SAME host copies of the
    probe indexes the device uses, the remaining fixed positions verified
    with numpy compares.  Each surviving row is one distinct assignment
    for the same content-addressing reason — every non-fixed position is
    a distinct variable, so two surviving rows that bound identical
    targets would be the same link.  This is the miner's wildcard-variant
    candidate shape (notebook cell 9): the reference answers each with a
    Redis `patterns` set cardinality; the fused path would compile one
    vmapped program per variant shape (the r04 counting phase spent ~54 s
    there at FlyBase scale).  The one shape whose count the host cannot
    decide locally is a dangling (-1) target in a variable position —
    two distinct links could then bind identical tuples and the device
    path would dedup them — so those rows (nonexistent in converter
    output) fall back to the device (None)."""
    if plans is None or len(plans) != 1:
        return None
    p = plans[0]
    if p.negated or p.eq_pairs:
        return None
    if not p.fixed:
        return estimate_plan_rows(db, p)
    if p.ctype is not None or p.type_id is None:
        return None
    if os.environ.get("DAS_TPU_HOST_COUNT", "1") == "0":
        return None  # test hook: force the device path for grounded terms
    from das_tpu.storage.atom_table import host_probe_locals, host_segments

    # a non-None EMPTY dangling set proves no -1 target exists in any
    # segment (finalize records every unresolved element; the delta path
    # keeps the set current and a restored store without one rebuilds on
    # first commit) — the per-row scan below can then never fire, so skip
    # gathering var columns entirely on the common converter-output path
    dangling = db.fin.dangling_hexes
    scan_dangling = dangling is None or len(dangling) > 0
    total = 0
    for b in host_segments(db, p.arity):
        local = host_probe_locals(b, p.type_id, p.fixed)
        if local.size == 0:
            continue
        if scan_dangling and p.var_cols and b.has_dangling:
            sub = b.targets[np.ix_(local, p.var_cols)]
            if (sub < 0).any():
                return None  # dangling rows: device dedup semantics decide
        total += int(local.size)
    return total


def estimate_plan_rows(db, plan) -> int:
    """EXACT candidate count for one term with zero device work: the same
    sorted key arrays the device probes live in host memory, so binary
    searches give the range size with no device round trip.  Sums over the
    base bucket and any incremental-delta overlay segment
    (`db.host_bucket_segments`, provided by both device backends) —
    together they exactly mirror the merged device index.  Shared by the
    single-device and sharded executors."""
    from das_tpu.storage.atom_table import host_segments

    total = 0
    for b in host_segments(db, plan.arity):
        if plan.ctype is not None:
            keys, key = b.key_ctype, np.int64(plan.ctype)
        elif plan.type_id is not None and plan.fixed:
            p0, v0 = plan.fixed[0]
            keys, key = b.key_type_pos[p0], (np.int64(plan.type_id) << 32) | np.int64(v0)
        else:
            assert plan.type_id is not None, "TermPlan without type or ctype"
            keys, key = b.key_type, np.int32(plan.type_id)
        lo = int(np.searchsorted(keys, key, side="left"))
        hi = int(np.searchsorted(keys, key, side="right"))
        total += hi - lo
    return total


def reference_order_authoritative(positives) -> bool:
    """THE predicate behind the keep-reference-order rule, shared by
    order_plans and the cost-based planner (das_tpu/planner/search.py —
    one copy, so the two paths cannot drift on WHICH queries pay the
    reseed fallback): the positive terms are CONNECTED in reference
    order (every term shares a variable with the terms before it) AND
    at least one is grounded (selective — its candidate set is a
    specific-target probe, so intermediates stay small by construction).
    The compiled program is then the reference fold itself and its
    in-program reseed flag is authoritative: zero-count answers are
    definitive, no exact-variant re-run."""
    if len(positives) <= 1:
        return True
    bound = set(positives[0].var_names)
    for p in positives[1:]:
        if not (set(p.var_names) & bound):
            return False
        bound |= set(p.var_names)
    return any(p.fixed and p.ctype is None for p in positives)


def order_plans(plans, estimate) -> List:
    """Join ordering policy (shared by the single-device and sharded
    executors).  When `reference_order_authoritative` holds, keep the
    reference order (reseed verdicts then need no exact-variant re-run).
    All-wildcard analytic plans and disconnected plans use greedy
    smallest-first ordering, which avoids huge x huge first joins (e.g.
    the ungrounded 3-var bio query: Member x Member in reference order
    materializes sum-of-degree-squared rows; greedy starts from the
    small Interacts table instead).  Negated terms filter at the end
    regardless of order."""
    pos = [(p, estimate(p)) for p in plans if not p.negated]
    neg = [p for p in plans if p.negated]
    if len(pos) <= 1:
        return [p for p, _ in pos] + neg
    if reference_order_authoritative([p for p, _ in pos]):
        return [p for p, _ in pos] + neg
    ordered = []
    bound = set()
    remaining = list(pos)
    while remaining:
        connected = [
            (p, e) for p, e in remaining
            if not bound or (set(p.var_names) & bound)
        ] or remaining
        pick = min(connected, key=lambda pe: pe[1])
        remaining.remove(pick)
        ordered.append(pick[0])
        bound |= set(pick[0].var_names)
    return ordered + neg


def same_positive_order(ordered, plans) -> bool:
    """Reseed semantics depend only on the POSITIVE term order (negated
    terms filter at the end either way)."""
    po = [p for p in ordered if not p.negated]
    pp = [p for p in plans if not p.negated]
    return len(po) == len(pp) and all(a is b for a, b in zip(po, pp))


class ResultCache:
    """Device-resident query result cache, guarded by the backend's
    incremental-commit counter (storage/delta.py delta_version).

    Key = (per-term plan digest, count_only): the TermPlan tuple carries
    the plan SHAPE and every grounded value (type ids, fixed global rows,
    ctype keys), and global rows are stable within one delta version — so
    shape + grounded values + version pin the answer exactly.  A hit
    returns the cached FusedResult (device refs plus the prefetched host
    copies): zero device programs, zero host transfers.  Any commit bumps
    delta_version, which drops the whole cache — every entry was written
    against the pre-commit tables, so that is exactly the stale set.

    Reseed-flagged results are never cached (the exact variant re-answers
    them); entries are LRU-bounded by config.result_cache_size, and a
    non-count result wider than MAX_ENTRY_ROWS is not cached at all —
    each such entry pins cap-sized device AND host buffers, so a
    count-bounded LRU alone could pin (entries x max_result_capacity)
    bytes of HBM.  Serving-shaped (grounded) answers are far below the
    bound; giant analytic tables just stay uncached."""

    #: widest binding table one cache entry may pin (rows x columns);
    #: at int32 this bounds an entry near 4 MB device + 4 MB host
    MAX_ENTRY_ROWS = 1 << 20

    def __init__(self, db):
        import threading
        from collections import OrderedDict

        self.db = db
        self._data: "OrderedDict" = OrderedDict()
        self._version = None
        self._lock = threading.Lock()
        self.stats = {"hits": 0, "misses": 0, "invalidations": 0}

    @staticmethod
    def key(plans, count_only: bool):
        return (
            tuple(
                (
                    p.arity, p.type_id, p.ctype, p.fixed, p.var_names,
                    p.var_cols, p.eq_pairs, p.negated,
                )
                for p in plans
            ),
            count_only,
        )

    def limit(self) -> int:
        return int(getattr(self.db.config, "result_cache_size", 0))

    def version(self):
        return getattr(self.db, "delta_version", None)

    def _sync_version(self) -> None:
        """Caller holds the lock."""
        v = self.version()
        if v != self._version:
            if self._data:
                self.stats["invalidations"] += 1
                if obs.enabled():
                    # a commit just made every entry stale — the event
                    # the trace needs to explain a post-commit latency
                    # step (hits turning into device dispatches)
                    obs.event("cache.invalidate", entries=len(self._data),
                              version=v)
                    obs.counter("cache.invalidations").inc()
            self._data.clear()
            self._version = v

    def get(self, key):
        if self.limit() <= 0:
            return None
        with self._lock:
            self._sync_version()
            hit = self._data.get(key)
            if hit is None:
                self.stats["misses"] += 1
                if obs.enabled():
                    obs.event("cache.miss")
                    obs.counter("cache.misses").inc()
                return None
            self._data.move_to_end(key)
            self.stats["hits"] += 1
            if obs.enabled():
                # zero-dispatch answer: the "materialize-or-cache-hit"
                # arm of the traced lifecycle
                obs.event("cache.hit", count=getattr(hit, "count", None))
                obs.counter("cache.hits").inc()
            return hit

    def put(self, key, result, version) -> None:
        """`version` is the delta version the caller DISPATCHED against:
        a commit that landed between dispatch and settle must not smuggle
        a pre-commit answer under the post-commit version."""
        from das_tpu import fault
        from das_tpu.core.exceptions import InjectedFault

        try:
            fault.maybe_fail("cache_insert")
        except InjectedFault:
            # a failed cache insert degrades to "not cached" — the
            # answer was already computed and delivered, so the query
            # must never see this failure (chaos-parity: the only
            # observable effect is a later cache miss)
            return
        limit = self.limit()
        if limit <= 0 or result is None or getattr(
            result, "reseed_needed", False
        ):
            return
        vals = getattr(result, "vals", None)
        # total elements, covering both the 2-D [cap, k] single-device
        # table and the 3-D [S, cap, k] sharded layout
        if vals is not None and vals.size > self.MAX_ENTRY_ROWS:
            return  # too wide to pin: see MAX_ENTRY_ROWS
        with self._lock:
            self._sync_version()
            if version != self._version:
                return
            self._data[key] = result
            self._data.move_to_end(key)
            while len(self._data) > limit:
                self._data.popitem(last=False)


def result_cache_stats(db) -> Dict[str, int]:
    """Aggregate hit/miss counters of the db's live executor caches (the
    single-device fused executor and/or the sharded mirror) — serving
    observability without reaching into executor internals."""
    out = {"hits": 0, "misses": 0, "invalidations": 0}
    executors = []
    dev = getattr(db, "dev", None)
    if dev is not None:
        executors.append(getattr(dev, "_fused_executor", None))
    tables = getattr(db, "tables", None)
    if tables is not None:
        executors.append(getattr(tables, "_fused_executor", None))
    for ex in executors:
        for attr in ("results", "tree_results"):
            cache = getattr(ex, attr, None)
            if cache is not None:
                for k in out:
                    out[k] += cache.stats[k]
    return out


def get_executor(db) -> "FusedExecutor":
    """The per-database executor, cached on the device tables so a
    `refresh()` (which rebuilds them) naturally drops stale programs."""
    ex = getattr(db.dev, "_fused_executor", None)
    if ex is None or ex.db is not db:
        ex = FusedExecutor(db)
        db.dev._fused_executor = ex
    return ex


# -- warm-state bundle (ISSUE 15, storage/durable.py) ------------------------
#
# The state a fresh replica would otherwise RE-LEARN: CapStore learned
# capacities (each re-learned tier is an XLA recompile), the planner's
# exact degree statistics (host searchsorted passes), and the answered
# count-cache entries (the miner's hot loop).  All of it is a perf hint
# — a stale or absent bundle costs retries/recomputation, never
# correctness — so export/apply are best-effort and keyed by
# delta_version exactly like the result caches.


def _warm_executor(db):
    dev = getattr(db, "dev", None)
    if dev is not None:
        return get_executor(db)
    if getattr(db, "tables", None) is not None:
        from das_tpu.parallel.fused_sharded import get_sharded_executor

        return get_sharded_executor(db)
    return None


def _jsonable(obj):
    """Nested tuples -> lists for msgpack (keys round-trip via
    _tuplize)."""
    if isinstance(obj, tuple):
        return [_jsonable(x) for x in obj]
    return obj


def _tuplize(obj):
    if isinstance(obj, list):
        return tuple(_tuplize(x) for x in obj)
    return obj


def export_warm_state(db) -> Optional[Dict]:
    """The warm bundle persisted beside a snapshot (durable.
    write_snapshot): cross-process CapStore dicts (already stable-hash
    keyed), count-only result-cache entries (host ints — the wide
    binding tables stay device-resident and are NOT persisted), and
    the planner estimator's memoized degree statistics.

    Scope: learned CAPACITIES cover the single-device executor only —
    ShardedFusedExecutor keeps its `_caps` keyed by raw sig tuples
    with no stable-hash store, so the mesh bundle carries counts +
    planner stats (giving it a CapStore is the named remainder); a
    mesh replica's planner-seeded capacities are margin-free where the
    statistics are exact, so the retry tier this leaves on the table
    is the estimator-miss residue only."""
    ex = _warm_executor(db)
    if ex is None:
        return None
    out: Dict = {"delta_version": int(getattr(db, "delta_version", 0))}
    caps = {}
    for tag in ("_cap_store", "_exact_cap_store"):
        store = getattr(ex, tag, None)
        if store is not None and store._data:
            caps[tag] = dict(store._data)
    out["caps"] = caps
    counts = []
    results = getattr(ex, "results", None)
    if results is not None:
        with results._lock:
            for key, entry in results._data.items():
                if getattr(entry, "vals", None) is None and isinstance(
                    getattr(entry, "count", None), int
                ):
                    counts.append([_jsonable(key), entry.count])
    out["counts"] = counts
    est = getattr(db, "_planner_estimator", None)
    if est is not None and est.version == getattr(db, "delta_version", None):
        out["planner"] = {
            "rows": [[_jsonable(k), v] for k, v in est._rows.items()],
            "distinct": [
                [_jsonable(k), v] for k, v in est._distinct.items()
            ],
        }
    return out


def apply_warm_state(db, state: Dict) -> bool:
    """Apply a restored warm bundle onto a freshly restored backend.
    The delta_version guard is the SAME staleness rule the result
    caches live by: a bundle recorded at a version the store is no
    longer at (WAL replayed past the snapshot) is discarded whole."""
    if int(state.get("delta_version", -1)) != int(
        getattr(db, "delta_version", 0)
    ):
        return False
    ex = _warm_executor(db)
    if ex is None:
        return False
    for tag, data in (state.get("caps") or {}).items():
        store = getattr(ex, tag, None)
        if store is not None:
            store._data.update(data)
    version = getattr(db, "delta_version", None)
    results = getattr(ex, "results", None)
    if results is not None:
        for key, n in state.get("counts") or ():
            results.put(
                _tuplize(key),
                FusedResult((), None, None, int(n), False, False),
                version,
            )
    planner = state.get("planner")
    if planner:
        from das_tpu.planner.stats import estimator_for

        est = estimator_for(db)
        if est is not None:
            est._rows.update(
                (_tuplize(k), int(v)) for k, v in planner.get("rows", ())
            )
            est._distinct.update(
                (_tuplize(k), int(v))
                for k, v in planner.get("distinct", ())
            )
    return True


class FusedExecutor:
    """Per-database cache: plan signature -> compiled fused executable."""

    def __init__(self, db):
        self.db = db
        self._cache: Dict[Tuple, Tuple] = {}          # (plan_sig, count_only)
        #: answered-result cache (delta-version guarded).  Consulted by
        #: the serving/batched paths (execute_many / dispatch_many /
        #: count_batch) and by execute(use_cache=True); the bare execute()
        #: stays uncached so per-dispatch regression pins keep measuring
        #: the device.
        self.results = ResultCache(db)
        #: tree-composite cache (query/tree.py): whole evaluated plan
        #: trees keyed by plan-tree digest, same version guard
        self.tree_results = ResultCache(db)
        self._batch_cache: Dict[FusedPlanSig, object] = {}
        #: whole-tree fused programs (ISSUE 10): FusedTreeSig -> (fn,
        #: names).  Bounded in _TreeExecJob.dispatch (no per-site
        #: remember_caps eviction hook — tree sigs nest many term sigs)
        self._tree_progs: Dict[FusedTreeSig, Tuple] = {}
        self._exact_cache: Dict[Tuple, Tuple] = {}    # (exact_sig, count_only)
        self._exact_batch_cache: Dict[FusedExactSig, Tuple] = {}
        # overflow-corrected capacities learned per plan shape, so later
        # calls start right-sized instead of re-running the overflowing
        # program every time; the CapStores carry them across processes
        self._caps: Dict[Tuple, Tuple[Tuple[int, ...], Tuple[int, ...]]] = {}
        self._exact_caps: Dict[Tuple, Tuple[int, ...]] = {}
        self._cap_store = CapStore("greedy")
        self._exact_cap_store = CapStore("exact")

    def _cap_salt(self) -> str:
        """Capacities are KB-size dependent: key the cross-process store by
        store shape so flybase-scale caps never seed a toy KB (or vice
        versa — undersized seeds merely retry)."""
        fin = self.db.fin
        return f"{fin.atom_count}:{fin.node_count}"

    def _learned_caps(self, mem, store, sigs, shape_lens):
        """In-memory learned caps, else the cross-process store — BOTH
        validated against the expected per-stage lengths: the same term
        signature carries per-JOIN buffers on the binary chain but
        per-STEP buffers on the multiway route (one output buffer for
        the whole star prefix), so caps learned on one route must not
        zip-truncate into the other's seed merge."""
        def _valid(caps):
            return caps is not None and len(caps) == len(shape_lens) and all(
                len(c) == n for c, n in zip(caps, shape_lens)
            )

        caps = mem.get(sigs)
        if _valid(caps):
            return caps
        caps = store.load(sigs, self._cap_salt())
        return caps if _valid(caps) else None

    _same_positive_order = staticmethod(same_positive_order)

    @staticmethod
    def _stack_or_const(rows):
        """One vmap input slot from per-member values: (stacked, axis 0)
        when members differ, (shared value, axis None) when identical —
        None axes let XLA compute constant terms (e.g. an ungrounded probe
        shared by the whole batch) ONCE instead of per member."""
        first = rows[0]
        if all(np.array_equal(r, first) for r in rows[1:]):
            return first, None
        return np.stack(rows), 0

    @staticmethod
    def _sig_caps(ps) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        second = ps.join_caps if isinstance(ps, FusedPlanSig) else ps.chain_caps
        return (ps.term_caps, second)

    def _remember_caps(self, sigs, term_caps, join_caps) -> None:
        remember_caps(
            self._caps, (self._cache, self._batch_cache), sigs,
            (term_caps, join_caps), self._sig_caps,
        )
        self._cap_store.save(sigs, (term_caps, join_caps), self._cap_salt())

    # -- plan -> signature + dynamic arguments ----------------------------

    def _term_args(self, plan) -> Optional[Tuple[FusedTermSig, Tuple, object, np.ndarray]]:
        """Map a compiler.TermPlan to (sig, bucket_arrays, key, fixed_vals)."""
        db = self.db
        bucket = db.dev.buckets.get(plan.arity)
        if bucket is None or bucket.size == 0:
            return None
        if plan.ctype is not None:
            sig_route, p0, extra = ROUTE_CTYPE, -1, ()
            arrays = (bucket.key_ctype, bucket.order_by_ctype, bucket.targets, bucket.type_id)
            key = np.int64(plan.ctype)
        elif plan.type_id is not None and plan.fixed:
            p0, v0 = plan.fixed[0]
            sig_route, extra = ROUTE_TYPE_POS, tuple(p for p, _ in plan.fixed[1:])
            arrays = (
                bucket.key_type_pos[p0],
                bucket.order_by_type_pos[p0],
                bucket.targets,
                bucket.type_id,
            )
            key = (np.int64(plan.type_id) << 32) | np.int64(v0)
        else:
            # plan_query guarantees type_id or ctype is set (TermPlan
            # invariant) — an untyped plan cannot reach the fused path
            assert plan.type_id is not None, "TermPlan without type or ctype"
            sig_route, p0, extra = ROUTE_TYPE, -1, ()
            arrays = (bucket.key_type, bucket.order_by_type, bucket.targets, bucket.type_id)
            key = np.int32(plan.type_id)
        fixed_vals = np.asarray(
            [v for _, v in plan.fixed[1:]] if sig_route == ROUTE_TYPE_POS else [],
            dtype=np.int32,
        )
        sig = FusedTermSig(
            arity=plan.arity,
            route=sig_route,
            p0=p0,
            extra_fixed=extra,
            var_cols=plan.var_cols,
            eq_pairs=plan.eq_pairs,
            var_names=plan.var_names,
            negated=plan.negated,
        )
        return sig, arrays, key, fixed_vals

    def _estimate(self, plan) -> int:
        return estimate_plan_rows(self.db, plan)

    def _apply_index_joins(self, sigs, arrays, term_caps, start_join=0):
        return apply_index_joins(
            self.db.dev.buckets, sigs, arrays, term_caps, start_join
        )

    _clamp_index_terms = staticmethod(clamp_index_terms)

    def _join_cap_seed(self, plans, term_caps) -> int:
        """First-call join/chain capacity seed.  When the plan has grounded
        (fixed-target) positive terms, real join outputs are near those
        small candidate sets — seeding from the biggest UNGROUNDED term
        (the old policy) made every join pay full-table capacity, which is
        the difference between ~5 ms and ~5 s for a vmapped batch.  Retries
        double capacity on overflow and the result is memoized per shape,
        so a low seed costs at most a few extra compiles on first contact.

        The per-term estimates bound the clamp from BELOW too (ISSUE 8
        satellite): `min(initial_result_capacity, ...)` honors an
        operator-shrunk seed, but an accumulator that starts as a
        grounded term's table already holds max(grounded) exact rows —
        clamping the join capacity under that forces a guaranteed retry
        round (one wasted XLA compile per shape) that no configuration
        can be trying to buy."""
        cfg = self.db.config
        grounded = [
            self._estimate(p)
            for p in plans
            if p.fixed and p.ctype is None and not p.negated
        ]
        if grounded:
            mg = max(grounded)
            return _pow2_at_least(
                max(64, min(cfg.initial_result_capacity, 4 * mg), mg)
            )
        return _pow2_at_least(max([cfg.initial_result_capacity, *term_caps]))

    def _group_cap_seed(self, sigs, est_rows) -> int:
        """_join_cap_seed for a batch group: sigs are shape-static, so
        grounded-ness comes from the route; estimates vary per member."""
        cfg = self.db.config
        grounded_idx = [
            t for t, s in enumerate(sigs)
            if s.route == ROUTE_TYPE_POS and not s.negated
        ]
        if grounded_idx:
            m = max(max(e[t] for t in grounded_idx) for e in est_rows)
            # same lower bound as _join_cap_seed: a shrunk configured
            # seed must not clamp under the exact grounded row counts
            return _pow2_at_least(
                max(64, min(cfg.initial_result_capacity, 4 * m), m)
            )
        term_cap_max = max(
            _pow2_at_least(max(e[t] for e in est_rows))
            for t in range(len(sigs))
        )
        return _pow2_at_least(max(cfg.initial_result_capacity, term_cap_max))

    def _order(self, plans) -> List:
        return order_plans(plans, self._estimate)

    # _ExecJob drives the dispatch/settle halves of execute(); defined
    # after the class (it needs build_fused and FusedResult)

    def _exec_job(self, plans, count_only: bool) -> Optional["_ExecJob"]:
        """Prepare one execution's state (ordering, term args, capacity
        seeds).  None when a bucket is missing or the merged caps exceed
        the configured ceiling — the caller falls back, as before.

        Behind DasConfig.use_planner the cost-based planner
        (das_tpu/planner) fixes the join order and the per-intermediate
        capacity seeds from cardinality estimates; when it declines (or
        is off) the legacy greedy ordering and blind seeds apply —
        answers are identical either way, only compile/retry traffic
        differs."""
        from das_tpu import planner as _planner

        planned = (
            _planner.plan_conjunction(self.db, plans)
            if _planner.enabled(self.db.config) else None
        )
        # leading positives fused into one k-way multiway step — changes
        # the step-buffer layout below (join_caps[0] is the multiway
        # output; index_joins cover only the tail binary joins)
        mw = planned.multiway if planned is not None else 0
        if planned is not None:
            ordered = [plans[i] for i in planned.order]
        else:
            ordered = self._order(plans)
        # when ordering preserved the positive fold the program IS the
        # reference fold: its in-program reseed flag is then exact, so a
        # zero count with no flag (final join empty) is definitively empty
        same_order = self._same_positive_order(ordered, plans)
        plans = ordered
        mapped = []
        for plan in plans:
            m = self._term_args(plan)
            if m is None:
                return None
            mapped.append(m)
        sigs = tuple(m[0] for m in mapped)
        arrays = tuple(m[1] for m in mapped)
        keys = tuple(m[2] for m in mapped)
        fvals = tuple(m[3] for m in mapped)

        cfg = self.db.config
        # exact host-side range counts => term capacities never overflow;
        # shapes past the configured ceiling go to the staged path, which
        # clamps (and owns the overflow error policy)
        term_caps = tuple(_pow2_at_least(self._estimate(plan)) for plan in plans)
        index_joins, index_right, arrays, term_caps = self._apply_index_joins(
            sigs, arrays, term_caps, start_join=max(0, mw - 1)
        )
        n_positive = sum(1 for s in sigs if not s.negated)
        # one buffer per STEP: the multiway step plus the tail binary
        # joins, or the pure chain's P-1 joins
        n_steps = (n_positive - mw + 1) if mw else max(0, n_positive - 1)
        if planned is not None and len(planned.join_cap_seeds) == n_steps:
            # the costed seeds: margin × estimated rows per intermediate
            # instead of one blind seed for every join — overflow retry
            # still owns estimate error, the ladder just starts on the
            # right rung for the common case (and margin-FREE for the
            # multiway step, whose seed is the exact k-way intersection
            # product — no configured clamp can shrink it back under
            # the exact row count)
            join_caps = planned.join_cap_seeds
        else:
            join_caps = tuple(
                [self._join_cap_seed(plans, term_caps)] * n_steps
            )
        learned = self._learned_caps(
            self._caps, self._cap_store, sigs,
            (len(term_caps), len(join_caps)),
        )
        if learned is not None:
            term_caps = self._clamp_index_terms(
                tuple(max(a, b) for a, b in zip(term_caps, learned[0])),
                index_right,
            )
            join_caps = tuple(max(a, b) for a, b in zip(join_caps, learned[1]))
        # ceiling applies to the MERGED caps: stale/foreign CapStore
        # entries must not smuggle buffers past the configured maximum
        if max(term_caps + join_caps, default=0) > cfg.max_result_capacity:
            return None
        from das_tpu import kernels

        # counted only once the job EXISTS: a decline above (missing
        # bucket, capacity ceiling) runs the legacy fallback, and the
        # planned/greedy decomposition must cover executor traffic the
        # settle observation will actually complete
        if planned is not None:
            _planner.record_planned(planned)
        else:
            _planner.PLANNER_COUNTS["greedy"] += 1
        return _ExecJob(
            self, count_only, same_order, sigs, arrays, keys, fvals,
            term_caps, join_caps, index_joins,
            use_kernels=kernels.enabled(cfg), planned=planned,
            multiway=mw,
        )

    def execute(
        self, plans, count_only: bool = False, use_cache: bool = False
    ) -> Optional[FusedResult]:
        """Run the whole plan in one dispatch.

        With count_only the compiled program returns just the stats vector
        (binding-table materialization is dead-code-eliminated) — the shape
        `count_matches` and the miner want.

        With use_cache, an answered-result hit (same plan digest, same
        delta version) returns with ZERO device work; off by default so
        per-dispatch measurements and regression pins keep timing the
        device, not a dict lookup.

        Returns None when a term's bucket is missing: an unmatched positive
        term means "no match" and an unmatched negated term never filters,
        both of which the staged path already handles — the caller decides.
        """
        if use_cache:
            key = self.results.key(plans, count_only)
            hit = self.results.get(key)
            if hit is not None:
                return hit
            version = self.results.version()
        job = self._exec_job(plans, count_only)
        if job is None:
            return None
        while True:
            out = job.dispatch()
            FETCH_COUNTS["n"] += 1
            if job.settle(jax.device_get(out), out):
                if use_cache:
                    self.results.put(key, job.result, version)
                return job.result

    def tree_exec_job(self, pos_sites, neg_plans=None) -> Optional[_TreeExecJob]:
        """Prepare one whole-tree execution (ISSUE 10) — see
        prepare_tree_job."""
        return prepare_tree_job(self, pos_sites, neg_plans, _TreeExecJob)

    def execute_tree(self, pos_sites, neg_plans=None) -> Optional[_TreeExecJob]:
        """Run a whole Or/negation tree as ONE fused program (retry loop
        included).  Returns the settled job — result None with
        needs_fallback means the tree executor must re-answer (reseed
        verdict or capacity ceiling) — or None when no job could form."""
        job = self.tree_exec_job(pos_sites, neg_plans)
        if job is None:
            return None
        return run_tree_job(job)

    def dispatch_many(self, plans_lists, count_only: bool = False,
                      cache_only: bool = False):
        """First half of the serving pipeline: resolve result-cache hits,
        prepare the remaining jobs, and ENQUEUE their first dispatch round
        — all asynchronous, no host transfer.  The device starts executing
        this batch while the caller is still settling the previous one
        (settle_many); that overlap is the cross-request pipelining the
        coalescer drives (service/coalesce.py).  Returns an opaque pending
        handle for settle_many.  With cache_only (degraded-mode serving,
        ISSUE 13 breaker) NO device program is enqueued: cache hits
        answer, misses stay dispatch-time declines."""
        return dispatch_pending(
            self.results, self._exec_job, plans_lists, count_only,
            cache_only=cache_only,
        )

    def settle_many(self, pending) -> List[Optional[FusedResult]]:
        """Second half: pay the host transfer for the dispatched round and
        run each job's settle verdict.  Jobs that overflowed a capacity
        re-dispatch HERE, serially with their fetch — the graceful
        fallback: a retry round cannot overlap the next batch (its caps
        just changed), so it degrades to execute_many's serial loop."""
        return settle_pending(self.results, pending)

    def settle_many_iter(self, pending):
        """Streaming second half (ISSUE 6): yields (index, FusedResult)
        as each query's verdict lands — see settle_pending_iter."""
        return settle_pending_iter(self.results, pending)

    def execute_many(
        self, plans_lists, count_only: bool = False
    ) -> List[Optional[FusedResult]]:
        """Serving-path coalescing (VERDICT r03 item 5): every query in the
        batch dispatches asynchronously, then ONE host transfer fetches all
        results — N concurrent singles pay one tunnel RTT per retry round
        instead of one each.  Per-query semantics (capacity retry, reseed
        verdicts, cap learning) are identical to execute(): the same job
        object drives both halves (dispatch_many / settle_many)."""
        return self.settle_many(self.dispatch_many(plans_lists, count_only))

    def _remember_exact_caps(self, sigs, term_caps, chain_caps) -> None:
        remember_caps(
            self._exact_caps, (self._exact_cache, self._exact_batch_cache),
            sigs, (term_caps, chain_caps), self._sig_caps,
        )
        self._exact_cap_store.save(
            sigs, (term_caps, chain_caps), self._cap_salt()
        )

    def execute_exact(self, plans, count_only: bool = False) -> Optional[FusedResult]:
        """Reference-order single-dispatch execution with the reseed quirk
        implemented in-program (build_fused_exact).  `plans` must be in the
        original (reference) term order — NO greedy reordering here, the
        fold is order-sensitive.  Never needs a reseed fallback; returns
        None only on missing buckets or capacity ceiling."""
        mapped = []
        for plan in plans:
            m = self._term_args(plan)
            if m is None:
                return None
            mapped.append(m)
        sigs = tuple(m[0] for m in mapped)
        arrays = tuple(m[1] for m in mapped)
        keys = tuple(m[2] for m in mapped)
        fvals = tuple(m[3] for m in mapped)

        cfg = self.db.config
        term_caps = tuple(_pow2_at_least(self._estimate(plan)) for plan in plans)
        P = sum(1 for s in sigs if not s.negated)
        n_chain = len(_chain_order(P))
        chain_caps = tuple([self._join_cap_seed(plans, term_caps)] * n_chain)
        learned = self._learned_caps(
            self._exact_caps, self._exact_cap_store, sigs,
            (len(term_caps), len(chain_caps)),
        )
        if learned is not None:
            term_caps = tuple(max(a, b) for a, b in zip(term_caps, learned[0]))
            chain_caps = tuple(max(a, b) for a, b in zip(chain_caps, learned[1]))
        # the exact variant materializes every term (its suffix chains have
        # no index-join form); past ~1M-row terms the compile alone costs
        # minutes — the staged reference-order path owns that regime.  The
        # ceilings apply to MERGED caps (CapStore must not bypass them).
        if max(term_caps) > min(cfg.max_result_capacity, EXACT_TERM_CAP_LIMIT):
            return None
        if max(chain_caps, default=0) > cfg.max_result_capacity:
            return None

        while True:
            plan_sig = FusedExactSig(sigs, term_caps, chain_caps)
            entry = self._exact_cache.get((plan_sig, count_only))
            if entry is None:
                entry = build_fused_exact(plan_sig, count_only)
                self._exact_cache[(plan_sig, count_only)] = entry
            fn, names_per_state, cols_per_state = entry
            FETCH_COUNTS["n"] += 1
            if count_only:
                host_vals = host_valid = vals = valid = None
                stats = np.asarray(fn(arrays, keys, fvals))
            else:
                out = fn(arrays, keys, fvals)
                vals, valid, _ = out
                host_vals, host_valid, stats = jax.device_get(out)
            count, s_act = int(stats[0]), int(stats[1])
            ranges = stats[3 : 3 + len(sigs)]
            mtotals = stats[3 + len(sigs) :]
            new_tc = tuple(
                _pow2_at_least(int(r)) if int(r) > c else c
                for r, c in zip(ranges, term_caps)
            ) if ranges.size else term_caps
            new_cc = tuple(
                _pow2_at_least(int(t)) if int(t) > c else c
                for t, c in zip(mtotals, chain_caps)
            ) if mtotals.size else chain_caps
            if new_tc == term_caps and new_cc == chain_caps:
                break
            if max(new_tc + new_cc, default=0) > cfg.max_result_capacity:
                return None  # staged path clamps and owns overflow policy
            term_caps, chain_caps = new_tc, new_cc

        self._remember_exact_caps(sigs, term_caps, chain_caps)
        # project the full-K table onto the active state's bound columns so
        # var_names and value columns line up for materialization
        cols = list(cols_per_state[s_act])
        if vals is not None and cols != list(range(vals.shape[1])):
            vals = vals[:, np.asarray(cols)]
            host_vals = host_vals[:, cols]
        return FusedResult(
            var_names=names_per_state[s_act],
            vals=vals,
            valid=valid,
            count=count,
            reseed_needed=False,
            overflow=False,
            host_vals=host_vals,
            host_valid=host_valid,
        )

    # -- batched counting --------------------------------------------------

    def _run_batch_group(
        self, make_sig, cache, build, arrays,
        key_rows, fval_rows, n_terms, term_caps, caps,
    ):
        """Shared machinery for one vmapped batch group: stack-or-hoist the
        per-member inputs, compile/cache the (sig, axes) entry, and retry
        with doubled capacities until no stage overflows.  Returns
        (stats or None, term_caps, caps); stats rows follow the common
        layout [count, flag, flag, *term_ranges, *stage_totals]."""
        cfg = self.db.config
        n_members = len(key_rows)
        # dedup identical lanes: the miner's stochastic sampler redraws the
        # same grounded keys constantly — each unique row computes once and
        # fans back out below
        seen: Dict[Tuple, int] = {}
        back: List[int] = []
        uniq_keys, uniq_fvals = [], []
        for kr, fr in zip(key_rows, fval_rows):
            h = (
                tuple(np.asarray(k).tobytes() for k in kr),
                tuple(np.asarray(f).tobytes() for f in fr),
            )
            i = seen.get(h)
            if i is None:
                i = len(uniq_keys)
                seen[h] = i
                uniq_keys.append(kr)
                uniq_fvals.append(fr)
            back.append(i)
        key_rows, fval_rows = uniq_keys, uniq_fvals
        n_unique = len(key_rows)
        # pad the lane count to a power of two: jit re-traces per stacked
        # shape, so without padding every distinct member count compiles a
        # fresh program (the miner's joint phase produced dozens) — padded
        # lanes duplicate the last member and their stats rows are dropped
        lanes = _pow2_at_least(n_unique, lo=1)
        if lanes != n_unique:
            key_rows = list(key_rows) + [key_rows[-1]] * (lanes - n_unique)
            fval_rows = list(fval_rows) + [fval_rows[-1]] * (lanes - n_unique)
        keys_stacked, key_axes = zip(*(
            self._stack_or_const([kr[t] for kr in key_rows])
            for t in range(n_terms)
        ))
        fvals_stacked, fval_axes = zip(*(
            self._stack_or_const([fr[t] for fr in fval_rows])
            for t in range(n_terms)
        ))
        all_const = all(a is None for a in key_axes + fval_axes)
        from das_tpu.kernels import record_dispatch

        while True:
            plan_sig = make_sig(term_caps, caps)
            cache_key = (plan_sig, key_axes, fval_axes)
            record_dispatch("count")
            if getattr(plan_sig, "use_kernels", False):
                record_dispatch("count_kernel")
                if getattr(plan_sig, "tiled", False):
                    record_dispatch("count_kernel_tiled")
            entry = cache.get(cache_key)
            if entry is None:
                fn = build(plan_sig)
                # bucket arrays are an ARGUMENT (vmap-broadcast with
                # in_axes=None), never a closure: a closed-over array is a
                # baked constant — the whole store would be serialized into
                # every compile payload (multi-GB at reference scale; a
                # remote-compile tunnel rejects it outright), and a cached
                # entry would keep reading PRE-COMMIT arrays after an
                # incremental delta merge replaced them
                entry = obs.proflog.instrument(
                    "count_batch",
                    obs.proflog.sig_digest(plan_sig, key_axes, fval_axes),
                    jax.jit(
                        fn if all_const
                        else jax.vmap(
                            fn,
                            in_axes=(None, tuple(key_axes), tuple(fval_axes)),
                        )
                    ),
                    model_bytes=partial(program_model_bytes, plan_sig),
                )
                cache[cache_key] = entry
            # the shared RetryPolicy (das_tpu/fault, ISSUE 13) replaces
            # the old hard-coded retry-once for transient backend/
            # transport failures (remote-compile tunnels drop large
            # payloads occasionally): bounded attempts, exponential
            # backoff with deterministic jitter — and every attempt is a
            # real device fetch, so each tallies FETCH_COUNTS (the
            # DL013-pinned per-attempt accounting)
            from das_tpu import fault

            def _count_fetch():
                FETCH_COUNTS["n"] += 1
                fault.maybe_fail("settle_fetch")
                return np.asarray(
                    entry(arrays, keys_stacked, fvals_stacked)
                )

            stats = fault.fetch_retry().run(_count_fetch)
            stats = np.atleast_2d(stats)  # all_const programs return one row
            ranges = stats[:, 3 : 3 + n_terms]
            totals = stats[:, 3 + n_terms :]
            new_tc = tuple(
                _pow2_at_least(int(ranges[:, t].max())) if ranges[:, t].max() > c else c
                for t, c in enumerate(term_caps)
            )
            new_cc = tuple(
                _pow2_at_least(int(totals[:, j].max())) if totals.size and totals[:, j].max() > c else c
                for j, c in enumerate(caps)
            )
            if new_tc == term_caps and new_cc == caps:
                # fan unique-lane rows back out to the original members
                # (all_const programs produce one row for everybody)
                idx = np.zeros(len(back), dtype=int) if all_const else np.asarray(back)
                return stats[idx], term_caps, caps
            if max(new_tc + new_cc) > cfg.max_result_capacity:
                return None, term_caps, caps
            term_caps, caps = new_tc, new_cc

    def build_count_loop(self, plans_list):
        """ONE device program that runs the given same-shape count queries
        SEQUENTIALLY (`lax.fori_loop`) and returns every count — a single
        dispatch and a single host fetch regardless of the loop width.

        This is the honest device-latency probe for tunneled TPUs
        (VERDICT r02 item 3): `block_until_ready` does not wait through a
        remote-execution tunnel and every host fetch is a full RTT, so a
        host-visible per-query timing measures the NETWORK.  Here the wall
        time of two different loop widths differs only by device compute:
        (t_W2 - t_W1) / (W2 - W1) is per-query device latency with
        transport excluded.  A loop-carried zero (`counts.sum() & 0`) is
        mixed into constant probe keys so XLA cannot hoist iterations of
        identical queries out of the loop.

        Returns (run, W): run() dispatches once and fetches (counts[W],
        stats_max) as host arrays; stats_max lets the caller verify no
        in-loop capacity overflow or reseed flag invalidated the counts.
        Raises ValueError when the queries do not share one fused shape.
        """
        prepared = []
        same_order = []
        for plans in plans_list:
            ordered = self._count_order(plans)
            mapped = [self._term_args(p) for p in self._canonical_plans(ordered)]
            if any(m is None for m in mapped):
                raise ValueError("plan not fused-executable")
            same_order.append(self._same_positive_order(ordered, plans))
            prepared.append((
                tuple(m[0] for m in mapped),
                tuple(m[1] for m in mapped),
                tuple(m[2] for m in mapped),
                tuple(m[3] for m in mapped),
                tuple(self._estimate(p) for p in ordered),
            ))
        sigs = prepared[0][0]
        if any(p[0] != sigs for p in prepared):
            raise ValueError("queries must share one fused shape")
        n_terms = len(sigs)
        term_caps = tuple(
            _pow2_at_least(max(p[4][t] for p in prepared))
            for t in range(n_terms)
        )
        index_joins, index_right, arrays, term_caps = self._apply_index_joins(
            sigs, prepared[0][1], term_caps
        )
        n_joins = max(0, sum(1 for s in sigs if not s.negated) - 1)
        cap0 = self._group_cap_seed(sigs, [p[4] for p in prepared])
        join_caps = tuple([cap0] * n_joins)
        learned = self._learned_caps(
            self._caps, self._cap_store, sigs,
            (len(term_caps), len(join_caps)),
        )
        if learned is not None:
            term_caps = self._clamp_index_terms(
                tuple(max(a, b) for a, b in zip(term_caps, learned[0])),
                index_right,
            )
            join_caps = tuple(max(a, b) for a, b in zip(join_caps, learned[1]))
        # same ceiling rule as execute(): merged caps (incl. CapStore
        # imports from a process with a larger configured maximum) must
        # not build an oversized program
        if max(term_caps + join_caps, default=0) > self.db.config.max_result_capacity:
            raise ValueError("count loop exceeds max_result_capacity")
        W = len(prepared)
        keys_stacked, key_axes = zip(*(
            self._stack_or_const([p[2][t] for p in prepared])
            for t in range(n_terms)
        ))
        fvals_stacked, fval_axes = zip(*(
            self._stack_or_const([p[3][t] for p in prepared])
            for t in range(n_terms)
        ))
        keys_elem = tuple(
            k if ax is None else k[:1][0]
            for k, ax in zip(keys_stacked, key_axes)
        )
        fvals_elem = tuple(
            f if ax is None else f[:1][0]
            for f, ax in zip(fvals_stacked, fval_axes)
        )

        def make_run(term_caps, join_caps, barrier=False):
            plan_sig = FusedPlanSig(sigs, term_caps, join_caps, index_joins)
            fn, _ = build_fused(plan_sig, count_only=True)
            if barrier:
                # explicit optimization barriers split the loop body's
                # fused cluster: the TPU compiler's scoped-vmem budget can
                # overflow when the whole count body fuses INSIDE a
                # fori_loop even though the identical body compiles
                # standalone.  (jax.checkpoint is a no-op here — remat
                # emits its barrier only under differentiation.)
                inner = fn

                def fn(arrays_, keys_, fvals_):
                    keys_ = jax.lax.optimization_barrier(keys_)
                    fvals_ = jax.lax.optimization_barrier(fvals_)
                    return jax.lax.optimization_barrier(
                        inner(arrays_, keys_, fvals_)
                    )

            n_stats = int(
                jax.eval_shape(fn, arrays, keys_elem, fvals_elem).shape[0]
            )

            @jax.jit
            def looped(arrays, keys_stacked, fvals_stacked):
                def body(i, carry):
                    counts, flags, mx = carry
                    dep = counts.sum() & jnp.int64(0)  # loop-carried zero
                    keys_i = tuple(
                        k[i] if ax is not None
                        else jnp.asarray(k) + dep.astype(jnp.asarray(k).dtype)
                        for k, ax in zip(keys_stacked, key_axes)
                    )
                    fv_i = tuple(
                        f[i] if ax is not None else f
                        for f, ax in zip(fvals_stacked, fval_axes)
                    )
                    stats = fn(arrays, keys_i, fv_i)
                    counts = counts.at[i].set(stats[0].astype(jnp.int64))
                    flags = flags.at[i].set(
                        (stats[1] + 2 * stats[2]).astype(jnp.int32)
                    )
                    mx = jnp.maximum(mx, stats.astype(jnp.int64))
                    return counts, flags, mx

                init = (
                    jnp.zeros(W, dtype=jnp.int64),
                    jnp.zeros(W, dtype=jnp.int32),
                    jnp.zeros(n_stats, dtype=jnp.int64),
                )
                return jax.lax.fori_loop(0, W, body, init)

            looped = obs.proflog.instrument(
                "count_loop",
                obs.proflog.sig_digest(plan_sig, W, barrier),
                looped,
                model_bytes=partial(program_model_bytes, plan_sig),
            )

            def run():
                FETCH_COUNTS["n"] += 1
                counts, flags, mx = looped(arrays, keys_stacked, fvals_stacked)
                return np.asarray(counts), np.asarray(flags), np.asarray(mx)

            return run

        # settle capacities like execute()'s retry loop — but ACROSS the
        # whole width, so the timed runs never truncate a join silently
        barrier = os.environ.get("DAS_TPU_LOOP_BARRIER", "0") == "1"
        while True:
            runner = make_run(term_caps, join_caps, barrier=barrier)
            try:
                counts, flags, mx = runner()
            except jax.errors.JaxRuntimeError as exc:
                # any AOT compile failure of the un-barriered loop gets ONE
                # barrier retry: the v5e scoped-vmem overflow surfaces
                # through a remote-compile tunnel as an opaque
                # "tpu_compile_helper subprocess exit code 1" with no
                # "vmem" substring to match on
                if not barrier:
                    barrier = True
                    continue
                raise
            ranges = mx[3 : 3 + n_terms]
            totals = mx[3 + n_terms :]
            new_tc = tuple(
                _pow2_at_least(int(r)) if int(r) > c else c
                for r, c in zip(ranges, term_caps)
            ) if ranges.size else term_caps
            new_jc = tuple(
                _pow2_at_least(int(t)) if int(t) > c else c
                for t, c in zip(totals, join_caps)
            ) if totals.size else join_caps
            if new_tc == term_caps and new_jc == join_caps:
                break
            if max(new_tc + new_jc, default=0) > self.db.config.max_result_capacity:
                raise ValueError("count loop exceeds max_result_capacity")
            term_caps, join_caps = new_tc, new_jc
        # reference-semantics guard — the same per-row verdicts
        # count_batch honors: a raised reseed flag, or a zero count the
        # greedy reordering cannot certify (no empty positive term and not
        # reference order), means the loop would time a program computing
        # WRONG numbers — refuse instead
        n_positive = sum(1 for s in sigs if not s.negated)
        for i in range(W):
            reseed, pos_empty = bool(flags[i] & 1), bool(flags[i] & 2)
            if reseed:
                raise ValueError("count loop hit the reseed quirk; not loopable")
            if (
                int(counts[i]) == 0
                and n_positive > 1
                and not pos_empty
                and not same_order[i]
            ):
                raise ValueError("count loop has an ambiguous zero; not loopable")

        def run():
            counts, _flags, mx = runner()
            return counts, mx

        self._remember_caps(sigs, term_caps, join_caps)
        return run, W

    @staticmethod
    def _structural_key(p):
        return (
            p.negated, p.arity, p.ctype is not None, p.type_id is None,
            tuple(pos for pos, _ in p.fixed), p.var_cols, p.eq_pairs,
        )

    def _count_order(self, plans):
        """Ordering for count-only batches.  When every positive term
        shares a common variable (the miner's composites all share V0),
        ANY order is join-connected, so sort by (SIZE CLASS, STRUCTURE)
        instead of the raw greedy estimate: lanes whose greedy orders
        differ would otherwise compile one program per permutation, but a
        purely structural sort can put a whole-table term before a
        grounded one — at FlyBase scale that turned the miner's joint
        phase into huge×huge first joins.  The size class is a coarse
        log16 bucket: selective terms still come first, and same-shape
        lanes whose estimates land in the same bucket share one compile
        (lanes straddling a fixed bucket boundary can still split).
        Queries without a common variable keep the greedy order."""
        pos = [p for p in plans if not p.negated]
        if len(pos) > 1:
            common = set(pos[0].var_names)
            for p in pos[1:]:
                common &= set(p.var_names)
            if common:
                neg = [p for p in plans if p.negated]
                return sorted(
                    pos,
                    key=lambda p: (
                        max(0, int(self._estimate(p)).bit_length() - 1) // 4,
                        self._structural_key(p),
                    ),
                ) + neg
        return self._order(plans)

    @staticmethod
    def _canonical_plans(plans):
        """Rename variables by first occurrence (X0, X1, …) so the batch
        signature depends on join STRUCTURE alone.  A match COUNT is
        invariant under variable renaming, but FusedTermSig.var_names is
        part of the compile key — without this the miner's generated names
        (V0, T0_V2, T1_V2, …) fragment otherwise-identical shapes into
        one compile each.  Count-only paths may use this; result-set paths
        must not (var_names reach the materialized assignments)."""
        import copy as _copy

        mapping: Dict[str, str] = {}
        out = []
        for p in plans:
            names = []
            for n in p.var_names:
                if n not in mapping:
                    mapping[n] = f"X{len(mapping)}"
                names.append(mapping[n])
            q = _copy.copy(p)
            q.var_names = tuple(names)
            out.append(q)
        return out

    def count_batch(self, plans_list) -> List[Optional[int]]:
        """Count many same-or-mixed-shape queries in as few dispatches as
        possible: plans are grouped by shape signature, each group runs as
        ONE vmapped fused program over the stacked grounded keys, and the
        whole group's counts come back in a single stats transfer.  This is
        the pattern-miner hot loop (SimplePatternMiner.ipynb cell 9: one
        Redis round trip per candidate in the reference; here ~one device
        round trip per *shape*).

        Entries that can't run fused (missing bucket) or that need the
        reference reseed quirk come back as None — the caller falls back to
        the staged/host path for those.
        """
        prepared = []  # (index, sigs, arrays, keys, fvals, ests)
        out: List[Optional[int]] = [None] * len(plans_list)
        groups: Dict[Tuple, List[int]] = {}
        # count-batch result cache (ROADMAP "result-cache scope"): the
        # miner's stochastic loop redraws the same joints across calls —
        # an answered (plan digest, count_only=True) entry under the same
        # delta version costs zero device work.  Keys use the ORIGINAL
        # plan tuples (grounded values included); the version captured
        # here guards the put against a commit racing the batch.
        cache_keys: Dict[int, Tuple] = {}
        cache_version = self.results.version()
        for idx, plans in enumerate(plans_list):
            n = trivial_plan_count(self.db, plans)
            if n is not None:
                out[idx] = n
                continue
            cache_keys[idx] = self.results.key(plans, True)
            hit = self.results.get(cache_keys[idx])
            if hit is not None:
                out[idx] = hit.count
                continue
            ordered = self._count_order(plans)
            same_order = self._same_positive_order(ordered, plans)
            mapped = [self._term_args(p) for p in self._canonical_plans(ordered)]
            if any(m is None for m in mapped):
                continue
            sigs = tuple(m[0] for m in mapped)
            prepared.append(
                (
                    idx,
                    sigs,
                    tuple(m[1] for m in mapped),
                    tuple(m[2] for m in mapped),
                    tuple(m[3] for m in mapped),
                    tuple(self._estimate(p) for p in ordered),
                    same_order,
                )
            )
            groups.setdefault(sigs, []).append(len(prepared) - 1)

        def _cache_count(idx: int, n: int) -> None:
            key = cache_keys.get(idx)
            if key is not None:
                self.results.put(
                    key,
                    FusedResult((), None, None, n, False, False),
                    cache_version,
                )

        cfg = self.db.config
        from das_tpu import kernels as _kernels

        use_k_cfg = _kernels.enabled(cfg)
        for sigs, members in groups.items():
            term_caps = tuple(
                _pow2_at_least(max(prepared[m][5][t] for m in members))
                for t in range(len(sigs))
            )
            index_joins, index_right, group_arrays, term_caps = (
                self._apply_index_joins(
                    sigs, prepared[members[0]][2], term_caps
                )
            )
            n_joins = max(0, sum(1 for s in sigs if not s.negated) - 1)
            join_cap0 = self._group_cap_seed(
                sigs, [prepared[m][5] for m in members]
            )
            join_caps = tuple([join_cap0] * n_joins)
            learned = self._learned_caps(
                self._caps, self._cap_store, sigs,
                (len(term_caps), len(join_caps)),
            )
            if learned is not None:
                term_caps = self._clamp_index_terms(
                    tuple(max(a, b) for a, b in zip(term_caps, learned[0])),
                    index_right,
                )
                join_caps = tuple(max(a, b) for a, b in zip(join_caps, learned[1]))
            # ceiling on MERGED caps (CapStore must not bypass it)
            if max(term_caps + join_caps, default=0) > cfg.max_result_capacity:
                continue  # caller's fallback handles the giant probes
            if max(term_caps, default=0) > LARGE_TERM_BATCH_LIMIT:
                # a vmapped group multiplies every padded buffer by the
                # lane count: whole-table terms run single-lane instead
                continue
            # kernel routing for the vmapped group (use_pallas_kernels):
            # the bytes planner re-derives the route per retry round from
            # the caps the make_sig call sees — a capacity doubling past
            # the VMEM budget re-plans grid-chunked, and past the tiled
            # resident set falls back to the lowered bodies, exactly like
            # the single-query dispatch
            group_shapes = tuple(
                (a[0].shape[0], a[2].shape[0]) for a in group_arrays
            )

            def _group_sig(
                tc, jc, _s=sigs, _ij=index_joins, _shapes=group_shapes
            ):
                route = (
                    kernel_program_plan(_s, _shapes, tc, jc, _ij)
                    if use_k_cfg else _kernels.budget.ROUTE_LOWERED
                )
                use_k = route != _kernels.budget.ROUTE_LOWERED
                return FusedPlanSig(
                    _s, tc, jc, _ij, use_k,
                    route == _kernels.budget.ROUTE_TILED,
                    _kernels.budget.vmem_budget() if use_k else 0,
                )

            stats, term_caps, join_caps = self._run_batch_group(
                _group_sig,
                self._batch_cache,
                lambda ps: build_fused(ps, count_only=True)[0],
                group_arrays,
                [prepared[m][3] for m in members],
                [prepared[m][4] for m in members],
                len(sigs), term_caps, join_caps,
            )
            if stats is None:
                continue
            self._remember_caps(sigs, term_caps, join_caps)
            if use_k_cfg and kernel_program_plan(
                sigs, group_shapes, term_caps, join_caps, index_joins
            ) != _kernels.budget.ROUTE_LOWERED:
                # route telemetry mirrors fused_kernel: one count per query
                # whose group program ran kernel-routed at the final caps
                from das_tpu.query import compiler as _qc

                _qc.ROUTE_COUNTS["count_kernel"] += len(members)
            n_positive = sum(1 for s in sigs if not s.negated)
            for row, m in zip(stats, members):
                count, reseed, pos_empty = int(row[0]), bool(row[1]), bool(row[2])
                same_order = prepared[m][6]
                if reseed or (
                    count == 0 and n_positive > 1 and not pos_empty and not same_order
                ):
                    continue  # greedy order can't decide — exact pass below
                out[prepared[m][0]] = count
                _cache_count(prepared[m][0], count)

        # exact second pass: entries the greedy program declined (possible
        # reseed) re-run as vmapped REFERENCE-ORDER programs with the
        # in-program reseed automaton — still ~one dispatch per shape group
        exact_groups: Dict[Tuple, List[Tuple]] = {}
        for idx, plans in enumerate(plans_list):
            if out[idx] is not None:
                continue
            mapped = [self._term_args(p) for p in self._canonical_plans(plans)]
            if any(m is None for m in mapped):
                continue  # missing bucket: host fallback handles
            sigs = tuple(m[0] for m in mapped)
            exact_groups.setdefault(sigs, []).append(
                (
                    idx,
                    tuple(m[1] for m in mapped),
                    tuple(m[2] for m in mapped),
                    tuple(m[3] for m in mapped),
                    tuple(self._estimate(p) for p in plans),
                )
            )
        for sigs, members in exact_groups.items():
            term_caps = tuple(
                _pow2_at_least(max(mm[4][t] for mm in members))
                for t in range(len(sigs))
            )
            P = sum(1 for s in sigs if not s.negated)
            cap0 = self._group_cap_seed(sigs, [mm[4] for mm in members])
            chain_caps = tuple([cap0] * len(_chain_order(P)))
            learned = self._learned_caps(
                self._exact_caps, self._exact_cap_store, sigs,
                (len(term_caps), len(chain_caps)),
            )
            if learned is not None:
                term_caps = tuple(max(a, b) for a, b in zip(term_caps, learned[0]))
                chain_caps = tuple(max(a, b) for a, b in zip(chain_caps, learned[1]))
            # ceilings on MERGED caps: whole-table terms (and CapStore
            # imports) stay out of the exact regime — staged path owns it
            if max(term_caps) > min(cfg.max_result_capacity, EXACT_TERM_CAP_LIMIT):
                continue
            if max(chain_caps, default=0) > cfg.max_result_capacity:
                continue
            stats, term_caps, chain_caps = self._run_batch_group(
                lambda tc, cc, _s=sigs: FusedExactSig(_s, tc, cc),
                self._exact_batch_cache,
                lambda ps: build_fused_exact(ps, count_only=True)[0],
                members[0][1],
                [mm[2] for mm in members],
                [mm[3] for mm in members],
                len(sigs), term_caps, chain_caps,
            )
            if stats is None:
                continue
            self._remember_exact_caps(sigs, term_caps, chain_caps)
            for row, mm in zip(stats, members):
                out[mm[0]] = int(row[0])
                _cache_count(mm[0], int(row[0]))
        return out
