"""Compiled conjunctive queries: the device fast path.

Reference behavior being replaced: `And.matched` retrieves each term's
candidate links, builds one Python Assignment object per candidate, and
joins assignment *sets* with an O(|A|×|B|) nested loop
(pattern_matcher.py:705-748).  Here a conjunctive query over ordered link
patterns compiles to a pipeline of device kernels:

    per term:  searchsorted range probe  → binding table (int32 matrix)
               + intra-term equality + lexsort dedup
    fold:      sort-merge equi-joins over shared variable columns
    negation:  anti-joins for each forbidden table whose variable set is
               covered by the output (exact reference semantics — tabu
               assignments with extra variables never exclude anything)
    output:    one padded (vals, valid) table + exact count

Join/anti-join/dedup kernels: das_tpu/ops/join.py.  The host orchestrates
stage boundaries (exact counts drive capacity-doubling retries and the
reference's empty-accumulator-reseed quirk) but touches no per-candidate
data until final materialization.

Compilable subset: `And`/bare patterns of *ordered* `Link`s (targets:
Node | grounded | Variable) and *ordered* `LinkTemplate`s, plus `Not` of
those; everything else (unordered multiset semantics, Or, nesting) falls
back to the host algebra, which is answer-identical.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from das_tpu.core.hashing import hex_to_i64
from das_tpu.ops.counters import ROUTE_KEYS
from das_tpu.ops.join import anti_join, build_term_table, dedup_table, join_tables
from das_tpu.query import assignment as asn_mod
from das_tpu.query.assignment import OrderedAssignment
from das_tpu.query.ast import (
    And,
    Link,
    LinkTemplate,
    LogicalExpression,
    Node,
    Not,
    PatternMatchingAnswer,
    TypedVariable,
    Variable,
)
from das_tpu.storage.tensor_db import TensorDB


@dataclass
class TermPlan:
    arity: int
    type_id: Optional[int]          # None only for template probes
    fixed: Tuple[Tuple[int, int], ...]   # (position, global_row)
    var_names: Tuple[str, ...]           # one per output column
    var_cols: Tuple[int, ...]            # first position of each var
    eq_pairs: Tuple[Tuple[int, int], ...]  # same-var repeated positions
    ctype: Optional[int] = None          # template probe key (int64)
    negated: bool = False


@dataclass
class BindingTable:
    var_names: Tuple[str, ...]
    vals: jax.Array      # [cap, k] int32
    valid: jax.Array     # [cap]
    count: int
    host_vals: Optional[np.ndarray] = None   # prefetched host copies (one
    host_valid: Optional[np.ndarray] = None  # transfer with the stats)


class NotCompilable(Exception):
    pass


class UnknownAtom(NotCompilable):
    """A grounded node or link type that doesn't exist in the KB: the
    reference answers no-match for these, not an error — planners convert
    this (and only this) into a static False, never a host fallback."""


#: How queries were executed, for benchmark reporting and tests.  "fused" =
#: single-dispatch jitted program, "staged" = per-stage device kernels,
#: "tree" = generalized device tree executor, "host" = Python algebra
#: fallback (incremented by the API dispatcher, not here); "*_kernel" =
#: the subset whose probes/joins traced through the Pallas kernels
#: (das_tpu/kernels/ — shard-local bodies for "sharded_kernel", vmapped
#: count-batch groups for "count_kernel", the staged negation membership
#: filter for "anti_kernel").  Keys are DECLARED in ops/counters.py —
#: the one registry daslint rule DL004 pins every counting literal
#: against — and the dict is built from it so the two cannot drift.
ROUTE_COUNTS = {k: 0 for k in ROUTE_KEYS}


def reset_route_counts() -> None:
    for k in ROUTE_COUNTS:
        ROUTE_COUNTS[k] = 0


def _plan_term(db: TensorDB, term, negated: bool) -> TermPlan:
    if isinstance(term, LinkTemplate):
        if not term.ordered:
            raise NotCompilable("unordered template")
        arity = len(term.targets)
        names, cols, eq = [], [], []
        for p, tv in enumerate(term.targets):
            if not isinstance(tv, TypedVariable):
                raise NotCompilable("template target")
            if tv.name in names:
                eq.append((cols[names.index(tv.name)], p))
            else:
                names.append(tv.name)
                cols.append(p)
        from das_tpu.core.hashing import ExpressionHasher

        type_hashes = [
            db.data.table.get_named_type_hash(t)
            for t in [term.link_type, *[tv.type for tv in term.targets]]
        ]
        ctype_hex = ExpressionHasher.composite_hash(type_hashes)
        return TermPlan(
            arity=arity,
            type_id=None,
            fixed=(),
            var_names=tuple(names),
            var_cols=tuple(cols),
            eq_pairs=tuple(eq),
            ctype=int(hex_to_i64(ctype_hex)),
            negated=negated,
        )
    if not isinstance(term, Link) or not term.ordered:
        raise NotCompilable("not an ordered link")
    if term.atom_type in db.data.pattern_black_list:
        # no pattern index exists for blacklisted types; the host algebra
        # (whose get_matched_links consults the same blacklist) answers
        raise NotCompilable("blacklisted link type")
    arity = len(term.targets)
    fixed, names, cols, eq = [], [], [], []
    for p, target in enumerate(term.targets):
        if isinstance(target, TypedVariable):
            raise NotCompilable("typed variable in link")
        if isinstance(target, Variable):
            if target.name in names:
                eq.append((cols[names.index(target.name)], p))
            else:
                names.append(target.name)
                cols.append(p)
        elif isinstance(target, Node):
            handle = target.get_handle(db)
            row = db.fin.row_of_hex.get(handle)
            if row is None:
                raise UnknownAtom("unknown grounded node")  # term can't match
            fixed.append((p, row))
        else:
            raise NotCompilable("unsupported target kind")
    if not names:
        raise NotCompilable("fully grounded term")
    type_id = db._type_id(term.atom_type)
    if type_id is None:
        raise UnknownAtom("unknown link type")
    return TermPlan(
        arity=arity,
        type_id=type_id,
        fixed=tuple(fixed),
        var_names=tuple(names),
        var_cols=tuple(cols),
        eq_pairs=tuple(eq),
        negated=negated,
    )


#: sentinel for a statically-empty plan (a positive grounded atom that
#: doesn't exist): the reference answers no-match, not an error.  Opaque
#: (neither truthy-iterable nor None) so a caller that forgets the
#: `plans is EMPTY_PLAN` identity check fails fast instead of iterating it.
EMPTY_PLAN = object()


def plan_query(
    db: TensorDB, query: LogicalExpression, unknown_atom_empty: bool = False
) -> "Union[List[TermPlan], None, object]":
    """Return term plans, or None when the query isn't compilable.  With
    unknown_atom_empty, a POSITIVE term grounded on an atom absent from
    the store returns EMPTY_PLAN instead of None — callers composing plans
    (the sharded Or decomposition) can then skip the branch as a static
    no-match instead of abandoning device execution."""
    if asn_mod.CONFIG.get("no_overload"):
        return None
    if isinstance(query, (Link, LinkTemplate)):
        terms = [query]
    elif isinstance(query, And):
        terms = query.terms
    else:
        return None
    if not terms:
        return None
    plans = []
    try:
        for term in terms:
            if isinstance(term, Not):
                try:
                    plans.append(_plan_term(db, term.term, True))
                except UnknownAtom:
                    continue  # tabu on a nonexistent atom never excludes
            else:
                plans.append(_plan_term(db, term, False))
    except UnknownAtom:
        return EMPTY_PLAN if unknown_atom_empty else None
    except NotCompilable:
        return None
    if not plans or all(p.negated for p in plans):
        return None
    return plans


#: _run_term_kernel verdict: the probe outgrew the kernel size bound
#: mid-retry — the caller must answer on the lowered path instead
_KERNEL_DECLINED = object()


def _run_term(db: TensorDB, plan: TermPlan) -> Optional[BindingTable]:
    from das_tpu import kernels

    bucket = db.dev.buckets.get(plan.arity)
    if kernels.enabled(db.config) and bucket is not None:
        # eligibility (single-block / grid-chunked / lowered) is the
        # bytes planner's per-round call inside _run_term_kernel — no
        # row-count pre-gate here: a FlyBase-scale bucket with a small
        # probe window is exactly the shape the tiled route serves
        table = _run_term_kernel(db, plan)
        if table is not _KERNEL_DECLINED:
            return table
    if plan.ctype is not None:
        padded = db.probe_ctype_padded(plan.arity, plan.ctype)
    else:
        padded = db.probe_ordered_padded(plan.arity, plan.type_id, plan.fixed)
    if padded is None:
        return None
    local, mask = padded
    bucket = db.dev.buckets[plan.arity]
    vals, mask = build_term_table(
        bucket.targets, local, mask, plan.var_cols, plan.eq_pairs
    )
    vals, keep, count = dedup_table(vals, mask)
    n = int(count)
    if n == 0:
        return None
    return BindingTable(plan.var_names, vals, keep, n)


def _run_term_kernel(db: TensorDB, plan: TermPlan) -> Optional[BindingTable]:
    """Staged term probe through the fused Pallas kernel: the probe →
    gather → verify → term-table chain is ONE dispatch instead of three
    (range_probe, verify_positions, build_term_table), with the same
    capacity-overflow retry contract as probe_ordered_padded."""
    from das_tpu import kernels
    from das_tpu.query.fused import get_executor
    from das_tpu.storage.tensor_db import _next_capacity

    m = get_executor(db)._term_args(plan)
    if m is None:
        return None
    sig, arrays, key, fvals = m
    bucket = db.dev.buckets[plan.arity]
    cap = min(db.config.initial_result_capacity, max(bucket.size, 16))
    while True:
        if not kernels.budget.probe_plan(
            arrays[0].shape[0], arrays[2].shape[0], arrays[2].shape[1],
            len(sig.var_cols), cap,
        ).kernel:
            # a retry can double the capacity past the byte budget (cap
            # ends < 2*range, so up to 2x the bucket size) — same
            # per-round re-derivation as the fused dispatch()
            return _KERNEL_DECLINED
        vals, mask, rng = kernels.probe_term_table(
            arrays[0], arrays[1], arrays[2], key, fvals, cap,
            var_cols=sig.var_cols, eq_pairs=sig.eq_pairs,
            extra_fixed=sig.extra_fixed,
        )
        if int(rng) <= cap:
            break
        cap = _next_capacity(int(rng), cap, db.config.max_result_capacity)
    vals, keep, count = dedup_table(vals, mask)
    n = int(count)
    if n == 0:
        return None
    return BindingTable(plan.var_names, vals, keep, n)


def _join(db: TensorDB, left: BindingTable, right: BindingTable) -> BindingTable:
    shared = [
        (left.var_names.index(v), right.var_names.index(v))
        for v in left.var_names
        if v in right.var_names
    ]
    extra = tuple(
        i for i, v in enumerate(right.var_names) if v not in left.var_names
    )
    out_names = left.var_names + tuple(
        v for v in right.var_names if v not in left.var_names
    )
    from das_tpu import kernels

    use_kernel = kernels.enabled(db.config)
    cap = max(64, min(left.count * right.count, db.config.initial_result_capacity))
    while True:
        join_op = (
            kernels.join_tables
            if use_kernel and kernels.budget.join_plan(
                left.vals.shape[0], left.vals.shape[1],
                right.vals.shape[0], right.vals.shape[1],
                len(shared), left.vals.shape[1] + len(extra), cap,
            ).kernel
            else join_tables
        )
        vals, valid, total = join_op(
            left.vals, left.valid, right.vals, right.valid,
            tuple(shared), extra, cap,
        )
        t = int(total)
        if t <= cap:
            break
        if cap >= db.config.max_result_capacity:
            from das_tpu.core.exceptions import CapacityOverflowError

            raise CapacityOverflowError(
                f"join needs {t} rows > max_result_capacity "
                f"{db.config.max_result_capacity}"
            )
        cap = min(max(cap * 2, t), db.config.max_result_capacity)
    vals, keep, count = dedup_table(vals, valid)
    return BindingTable(out_names, vals, keep, int(count))


def _execute_fused(
    db: TensorDB, plans: List[TermPlan], count_only: bool = False
) -> Optional[BindingTable]:
    """Single-dispatch fast path (query/fused.py): the whole plan runs as
    one jitted program, cached per plan shape on the device tables so every
    re-grounding of the same query skips tracing entirely.  When the
    greedy-order program detects the empty-accumulator reseed condition,
    the exact reference-order variant (in-program reseed automaton) runs
    instead — still one dispatch.  Returns None only when a term's bucket
    is absent or a capacity ceiling is hit — caller runs the staged path,
    which is answer-identical."""
    from das_tpu.query.fused import get_executor

    ex = get_executor(db)
    res = ex.execute(plans, count_only=count_only)
    if res is not None and res.reseed_needed:
        res = ex.execute_exact(plans, count_only=count_only)
    if res is None or res.reseed_needed:
        return None
    return BindingTable(
        res.var_names, res.vals, res.valid, res.count,
        host_vals=res.host_vals, host_valid=res.host_valid,
    )


def execute_fused_many_dispatch(db: TensorDB, plans_lists: List[List[TermPlan]],
                                cache_only: bool = False):
    """Pipeline phase 1 for the serving coalescer: resolve result-cache
    hits and ENQUEUE the batch's fused programs on the device — purely
    asynchronous, no host transfer.  Returns the pending handle for
    execute_fused_many_settle; between the two calls the device executes
    this batch while the host settles/materializes the previous one.
    cache_only (degraded-mode serving, ISSUE 13 breaker) answers from
    the delta-versioned cache only — no device program is enqueued."""
    from das_tpu.query.fused import get_executor

    return get_executor(db).dispatch_many(plans_lists, cache_only=cache_only)


def execute_fused_many_settle_iter(
    db: TensorDB, plans_lists: List[List[TermPlan]], pending
):
    """Streaming pipeline phase 2 (ISSUE 6 early-settle): yields
    `(index, BindingTable-or-None)` as each query's verdict becomes
    final.  Settled entries stream in retry-round order — a query whose
    first round fit arrives one RTT after its own dispatch, while its
    batch-mates' capacity retries are still re-dispatching.
    Reseed-flagged entries resolve on the exact reference-order variant
    in place.  Declines yield None for the caller to replay on the
    staged/host path: a settle-time decline (capacity ceiling,
    unresolved reseed) yields IN VERDICT ORDER as its round lands,
    while dispatch-time declines (no job, no cache hit) are never seen
    by the settle stream and yield last."""
    from das_tpu.query.fused import get_executor

    ex = get_executor(db)
    seen = [False] * len(plans_lists)
    for i, res in ex.settle_many_iter(pending):
        seen[i] = True
        if res is not None and res.reseed_needed:
            res = ex.execute_exact(plans_lists[i])
        if res is None or res.reseed_needed:
            yield i, None
            continue
        yield i, BindingTable(
            res.var_names, res.vals, res.valid, res.count,
            host_vals=res.host_vals, host_valid=res.host_valid,
        )
    for i, done in enumerate(seen):
        if not done:
            yield i, None


def execute_fused_many_settle(
    db: TensorDB, plans_lists: List[List[TermPlan]], pending
) -> List[Optional[BindingTable]]:
    """Pipeline phase 2: pay the host transfer, run per-query settle
    verdicts (capacity retries re-dispatch serially inside — the graceful
    fallback), and resolve reseed-flagged entries on the exact
    reference-order variant.  Queries the fused path declines come back
    None — the caller falls through to the staged/host path, exactly like
    the single-query route.  (The non-streaming form of
    execute_fused_many_settle_iter.)"""
    out: List[Optional[BindingTable]] = [None] * len(plans_lists)
    for i, table in execute_fused_many_settle_iter(db, plans_lists, pending):
        out[i] = table
    return out


def execute_sharded_many_dispatch(db, plans_lists: List[List[TermPlan]],
                                  cache_only: bool = False):
    """Mesh pendant of execute_fused_many_dispatch: resolve result-cache
    hits and ENQUEUE the batch's shard_map programs on the mesh — purely
    asynchronous.  The sharded serving path always opts into the
    delta-versioned result cache (same contract as _run_conjunctive);
    cache_only answers from it alone (degraded-mode serving)."""
    from das_tpu.parallel.fused_sharded import get_sharded_executor

    return get_sharded_executor(db).dispatch_many(
        plans_lists, cache_only=cache_only
    )


def execute_sharded_many_settle_iter(db, plans_lists, pending):
    """Mesh pendant of execute_fused_many_settle_iter: yields
    `(index, ShardedFusedResult-or-None)` as each query's verdict lands.
    Declines yield None for the caller to replay on the staged mesh
    pipeline (db.sharded_execute, answer-identical) — settle-time
    declines (capacity ceiling, reseed) in verdict order, dispatch-time
    declines last."""
    from das_tpu.parallel.fused_sharded import get_sharded_executor

    seen = [False] * len(plans_lists)
    for i, res in get_sharded_executor(db).settle_many_iter(pending):
        seen[i] = True
        yield i, (None if res is None or res.reseed_needed else res)
    for i, done in enumerate(seen):
        if not done:
            yield i, None


def execute_sharded_many_settle(db, plans_lists, pending) -> List:
    """Mesh pendant of execute_fused_many_settle: pay the host transfer,
    run per-query verdicts (capacity retries re-dispatch serially inside).
    Entries the fused mesh program declines — capacity ceiling or the
    reseed condition — come back None; the caller replays them on the
    staged mesh pipeline (db.sharded_execute), which is answer-identical."""
    out = [None] * len(plans_lists)
    for i, res in execute_sharded_many_settle_iter(db, plans_lists, pending):
        out[i] = res
    return out


def execute_fused_many(
    db: TensorDB, plans_lists: List[List[TermPlan]]
) -> List[Optional[BindingTable]]:
    """Batched `_execute_fused` for the serving coalescer: every query
    dispatches before ONE host transfer fetches all results (per retry
    round).  Queries the fused path declines (None) or that need the
    reseed fallback are resolved individually, exactly like the single
    path would."""
    pending = execute_fused_many_dispatch(db, plans_lists)
    return execute_fused_many_settle(db, plans_lists, pending)


def execute_plan(db: TensorDB, plans: List[TermPlan]) -> Optional[BindingTable]:
    """Run the pipeline; returns the final table or None for no match."""
    tabu_tables: List[BindingTable] = []
    accumulated: Optional[BindingTable] = None
    for plan in plans:
        table = _run_term(db, plan)
        if plan.negated:
            if table is not None:
                tabu_tables.append(table)
            continue
        if table is None:
            return None  # positive term unmatched -> whole And fails
        if accumulated is None or accumulated.count == 0:
            # reference quirk: an empty accumulator is re-seeded by the
            # next positive term (see das_tpu/query/ast.py And.matched)
            accumulated = table
        else:
            accumulated = _join(db, accumulated, table)
    if accumulated is None:
        return None
    from das_tpu import kernels

    use_kernel = kernels.enabled(db.config)
    valid = accumulated.valid
    for tabu in tabu_tables:
        if not set(tabu.var_names) <= set(accumulated.var_names):
            continue  # tabu with extra vars never excludes (NO_COVERING)
        pairs = tuple(
            (accumulated.var_names.index(v), tabu.var_names.index(v))
            for v in tabu.var_names
        )
        if use_kernel and kernels.budget.anti_join_plan(
            accumulated.vals.shape[0], accumulated.vals.shape[1],
            tabu.vals.shape[0], tabu.vals.shape[1],
        ).kernel:
            valid = kernels.anti_join(
                accumulated.vals, valid, tabu.vals, tabu.valid, pairs
            )
            ROUTE_COUNTS["anti_kernel"] += 1
        else:
            valid = anti_join(
                accumulated.vals, valid, tabu.vals, tabu.valid, pairs
            )
    count = int(valid.sum())
    return BindingTable(accumulated.var_names, accumulated.vals, valid, count)


def materialize(db: TensorDB, table: Optional[BindingTable], answer: PatternMatchingAnswer) -> bool:
    """Convert a device binding table into frozen OrderedAssignments."""
    from das_tpu import obs

    if table is None or table.count == 0:
        return False
    with obs.span("exec.materialize", rows=table.count,
                  prefetched=table.host_vals is not None):
        if table.host_vals is not None:
            vals, valid = table.host_vals, table.host_valid
        else:
            # one transfer for both arrays (each separate fetch is a
            # tunnel RTT)
            from das_tpu.query.fused import FETCH_COUNTS

            FETCH_COUNTS["n"] += 1
            vals, valid = jax.device_get((table.vals, table.valid))
        hexes = db.fin.hex_of_row
        for row in vals[valid]:
            a = OrderedAssignment()
            ok = True
            for name, val in zip(table.var_names, row):
                if not a.assign(name, hexes[int(val)]):
                    ok = False
                    break
            if ok and a.freeze():
                answer.assignments.add(a)
    return bool(answer.assignments)


def query_on_device(db: TensorDB, query: LogicalExpression, answer: PatternMatchingAnswer) -> Optional[bool]:
    """Full compiled execution; returns None when not compilable (caller
    falls back to the host algebra).  Pure ordered conjunctions take the
    fused single-dispatch path; everything else in the logical language
    (Or, unordered links, nested And/Or, negation trees) runs through the
    generalized tree executor (query/tree.py)."""
    plans = plan_query(db, query)
    if plans is not None:
        from das_tpu import kernels

        kernel_route = kernels.enabled(db.config)
        table = _execute_fused(db, plans)
        if table is None:
            table = execute_plan(db, plans)
            ROUTE_COUNTS["staged"] += 1
            if kernel_route:
                ROUTE_COUNTS["staged_kernel"] += 1
        else:
            ROUTE_COUNTS["fused"] += 1
            if kernel_route:
                ROUTE_COUNTS["fused_kernel"] += 1
        return materialize(db, table, answer)
    from das_tpu.query.tree import query_tree

    matched = query_tree(db, query, answer)
    if matched is not None:
        ROUTE_COUNTS["tree"] += 1
    return matched


def dispatch(db, query: LogicalExpression, answer: PatternMatchingAnswer, host=None) -> bool:
    """Route one query against any backend: sharded mesh program →
    single-device compiled path → host algebra, with an overflow fallback.
    This is the single routing point used by the API facade
    (das_tpu/api/atomspace.py) and the reference-compat shim (compat/das),
    so `expr.matched(db, answer)`-style call sites get the same device
    execution as `DistributedAtomSpace.query`.

    `host` overrides the host-algebra fallback callable (db, answer) ->
    bool.  A query object may also advertise `host_matched` (the compat
    shim's routing wrappers do) so that ANY dispatch call site — not just
    the wrapper itself — falls back to the pure host evaluator instead of
    re-entering the wrapper's `matched` and paying the device attempt
    twice."""
    from das_tpu.core.exceptions import CapacityOverflowError
    from das_tpu.utils.logger import logger

    matched = None
    try:
        if hasattr(db, "query_sharded"):
            matched = db.query_sharded(query, answer)
            if matched is not None:
                ROUTE_COUNTS["sharded"] += 1
                from das_tpu import kernels

                if kernels.enabled(getattr(db, "config", None)):
                    ROUTE_COUNTS["sharded_kernel"] += 1
        elif isinstance(db, TensorDB):
            matched = query_on_device(db, query, answer)
    except CapacityOverflowError as exc:
        logger().warning(f"device query overflowed, host fallback: {exc}")
        answer.assignments.clear()
        answer.negation = False
        matched = None
    if matched is None:
        ROUTE_COUNTS["host"] += 1
        fallback = host or getattr(query, "host_matched", None) or query.matched
        matched = fallback(db, answer)
    return matched


def explain(db, query: LogicalExpression, execute: bool = False,
            compile: bool = False) -> dict:
    """Costed-plan explain surface (das_tpu/planner): what the planner
    decided for `query` — join order, expected route, estimated rows,
    capacity seeds — and with execute=True the actual per-stage rows and
    retry rounds next to the estimates (compile=True adds the program
    ledger's compile/cost/memory record, ISSUE 14).  Lives here so the
    API facade and the reference-compat shim share one entry point,
    mirroring `dispatch`."""
    from das_tpu import planner

    return planner.explain(db, query, execute=execute, compile=compile)


def count_matches_staged(db: TensorDB, plans: List[TermPlan]) -> int:
    """Staged-pipeline count for plans the fused path already declined —
    skips re-trying the fused executor (it would just rediscover the same
    reseed/overflow verdict at the cost of an extra device dispatch)."""
    table = execute_plan(db, plans)
    return 0 if table is None else table.count


def count_matches(db: TensorDB, query: LogicalExpression) -> Optional[int]:
    """Benchmark surface: exact match count without host materialization."""
    plans = plan_query(db, query)
    if plans is not None:
        from das_tpu.query.fused import trivial_plan_count

        n = trivial_plan_count(db, plans)
        if n is not None:
            # single unconstrained term: the host-side range size is exact
            # (no device dispatch, no whole-table materialization)
            return n
        from das_tpu.query import starcount

        n = starcount.try_star_count(db, plans)
        if n is not None:
            # star conjunction (one shared variable, the miner's joint
            # shape): closed-form Σ_v Π deg_t(v), no join materialization
            ROUTE_COUNTS["star"] += 1
            return n
        table = _execute_fused(db, plans, count_only=True)
        if table is None:
            table = execute_plan(db, plans)
        return 0 if table is None else table.count
    # generalized tree: counts are exact only after host-set identity
    # (constraint-permutation and hash-XOR quirks), so materialize
    from das_tpu.query.tree import query_tree

    answer = PatternMatchingAnswer()
    matched = query_tree(db, query, answer)
    if matched is None:
        return None
    return len(answer.assignments) if matched else 0
