"""Staged device execution of generalized query plans (query/plan.py).

Evaluates And/Or/Not trees over ordered AND unordered patterns with the
candidate probes, term tables, joins, unions and negation filters all on
device; the host orchestrates node boundaries (counts drive capacity
retries and the reference's empty-accumulator reseed quirk,
pattern_matcher.py:726-738) and converts surviving rows to assignment
objects only at the API boundary.

Intermediate results are *disjunctions of composite tables* (`CTable`):
each table has ordered variable columns plus sorted value blocks for
unordered constraints, grouped by (kind, variable structure) — mirroring
how a reference answer set mixes OrderedAssignment / UnorderedAssignment /
CompositeAssignment objects with heterogeneous variable sets
(pattern_matcher.py:633-687 Or-union, :689-748 And-join).  The join
condition matrix reproduces the Assignment.join dispatch exactly
(pattern_matcher.py:121-140, 184-188, 292-303); see join_ctables.

Final set identity is established on the host: rows become reference
assignment objects added to a Python set, so dedup semantics (hash
equality) match the reference bit-for-bit even where the device-side
canonical dedup is conservative (e.g. same-variable-set constraint
permutations).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from das_tpu.core.exceptions import CapacityOverflowError
from das_tpu.ops import composite as comp_ops
from das_tpu.ops.join import anti_join, dedup_table, join_tables
from das_tpu.query import assignment as asn_mod
from das_tpu.query import compiler as qc
from das_tpu.query.assignment import (
    CompositeAssignment,
    OrderedAssignment,
    UnorderedAssignment,
)
from das_tpu.query.ast import PatternMatchingAnswer
from das_tpu.query.plan import (
    NotCompilable,
    PAnd,
    PConst,
    PNot,
    POr,
    PTerm,
    PUTerm,
    PUTermPlan,
    PlanNode,
    build_plan,
)


@dataclass
class CTable:
    """One homogeneous group of candidate assignments on device.

    kind    — "O" (ordered map), "U" (single unordered constraint),
              "C" (composite: optional ordered map + constraints)
    onames  — ordered variable names; ocols[i] holds onames[i]'s value
    ugroups — per unordered constraint: (sorted var names, value columns);
              each block holds the constraint's k distinct values sorted
    """

    kind: str
    onames: Tuple[str, ...]
    ocols: Tuple[int, ...]
    ugroups: Tuple[Tuple[Tuple[str, ...], Tuple[int, ...]], ...]
    vals: jnp.ndarray
    valid: jnp.ndarray
    count: int
    host_vals: Optional[np.ndarray] = None   # prefetched host copies
    host_valid: Optional[np.ndarray] = None

    @property
    def group_key(self):
        return (self.kind, tuple(sorted(self.onames)),
                tuple(sorted(n for n, _ in self.ugroups)))


@dataclass
class NodeResult:
    tables: List[CTable]
    negation: bool
    matched: bool


def _total(tables: List[CTable]) -> int:
    return sum(t.count for t in tables)


# ---------------------------------------------------------------------------
# leaf execution
# ---------------------------------------------------------------------------

def _from_binding_table(bt) -> CTable:
    return CTable(
        kind="O",
        onames=bt.var_names,
        ocols=tuple(range(len(bt.var_names))),
        ugroups=(),
        vals=bt.vals,
        valid=bt.valid,
        count=bt.count,
        host_vals=getattr(bt, "host_vals", None),
        host_valid=getattr(bt, "host_valid", None),
    )


class TreeOps:
    """Single-device op layer for the tree evaluator.

    The evaluator logic (join condition matrix, union/difference/negation
    semantics, the reseed quirk) is representation-agnostic: every CTable
    holds (vals, valid) arrays this layer produces and combines.  A backend
    exposing a `tree_ops` attribute (ShardedDB → parallel/sharded_tree.
    ShardedTreeOps) substitutes row-sharded global arrays and collective
    implementations; the evaluator above is unchanged — that is how
    unordered and negated query classes run on the mesh (VERDICT r02
    item 5) without a second evaluator."""

    def __init__(self, db):
        self.db = db

    # -- leaves ------------------------------------------------------------

    def run_term(self, plan) -> Optional[CTable]:
        bt = qc._run_term(self.db, plan)
        return None if bt is None else _from_binding_table(bt)

    def run_uterm(self, plan: PUTermPlan) -> Optional[CTable]:
        db = self.db
        bucket = db.dev.buckets.get(plan.arity)
        if bucket is None or bucket.size == 0:
            return None
        if plan.ctype is not None:
            padded = db.probe_ctype_padded(plan.arity, plan.ctype)
        elif plan.required:
            padded = db.probe_unordered_padded(plan.arity, plan.type_id, plan.required)
        else:
            padded = db.probe_ordered_padded(plan.arity, plan.type_id, ())
        if padded is None:
            return None
        local, mask = padded
        req_vals = np.asarray(
            [v for v, c in plan.required for _ in range(c)], dtype=np.int32
        )
        k = len(plan.var_names)
        vals, mask = comp_ops.build_uterm_table(
            bucket.targets_sorted, local, mask, req_vals, int(req_vals.size), k
        )
        return _finish_uterm(self, plan, vals, mask)

    def conj(self, plans) -> Optional[CTable]:
        """Ordered-conjunction fast path (fused, else staged)."""
        bt = qc._execute_fused(self.db, plans)
        if bt is None:
            bt = qc.execute_plan(self.db, plans)
        if bt is None or bt.count == 0:
            return None
        return _from_binding_table(bt)

    # -- table combinators -------------------------------------------------

    def join_tables(self, av, am, bv, bm, pairs, extra, cap, counts=None):
        # `counts` is a (left_rows, right_rows) hint; the mesh op layer
        # uses it for broadcast side selection, single-device ignores it
        return join_tables(av, am, bv, bm, pairs, extra, cap)

    def dedup(self, vals, valid):
        return dedup_table(vals, valid)

    anti_join = staticmethod(anti_join)

    def concat(self, parts):
        vals = jnp.concatenate([v for v, _ in parts], axis=0)
        valid = jnp.concatenate([m for _, m in parts], axis=0)
        return vals, valid

    def replicate(self, t: CTable) -> CTable:
        """Full copy of a table on every shard (identity off-mesh); pairwise
        negation/difference predicates need the tabu side whole."""
        return t


def _ops(db) -> TreeOps:
    return getattr(db, "tree_ops", None) or TreeOps(db)


def _finish_uterm(ops, plan, vals, mask) -> Optional[CTable]:
    k = len(plan.var_names)
    vals, keep, count = ops.dedup(vals, mask)
    n = int(count)
    if n == 0:
        return None
    return CTable(
        kind="U",
        onames=(),
        ocols=(),
        ugroups=((tuple(sorted(plan.var_names)), tuple(range(k))),),
        vals=vals,
        valid=keep,
        count=n,
    )


# ---------------------------------------------------------------------------
# generalized join (the Assignment.join dispatch as one device program)
# ---------------------------------------------------------------------------

def join_ctables(db, a: CTable, b: CTable) -> Optional[CTable]:
    """Join two candidate groups; `a` plays the accumulated (self) role in
    the reference's `a.join(b)` dispatch — the condition set is asymmetric
    for composite×composite (CompositeAssignment.join,
    pattern_matcher.py:292-303)."""
    shared = [v for v in a.onames if v in b.onames]
    pairs = tuple(
        (a.ocols[a.onames.index(v)], b.ocols[b.onames.index(v)]) for v in shared
    )
    extra_onames = tuple(v for v in b.onames if v not in a.onames)
    extra_cols = [b.ocols[b.onames.index(v)] for v in extra_onames]
    for _, cols in b.ugroups:
        extra_cols.extend(cols)
    ncols_a = a.vals.shape[1]
    out_onames = a.onames + extra_onames
    out_ocols = a.ocols + tuple(ncols_a + i for i in range(len(extra_onames)))
    b_groups_out = []
    off = ncols_a + len(extra_onames)
    for names, cols in b.ugroups:
        b_groups_out.append((names, tuple(off + i for i in range(len(cols)))))
        off += len(cols)

    ops = _ops(db)
    cap = max(64, min(max(a.count, 1) * max(b.count, 1),
                      db.config.initial_result_capacity))
    while True:
        vals, valid, total = ops.join_tables(
            a.vals, a.valid, b.vals, b.valid, pairs, tuple(extra_cols), cap,
            counts=(a.count, b.count),
        )
        t = int(total)
        if t <= cap:
            break
        if cap >= db.config.max_result_capacity:
            raise CapacityOverflowError(
                f"join needs {t} rows > max_result_capacity "
                f"{db.config.max_result_capacity}"
            )
        cap = min(max(cap * 2, t), db.config.max_result_capacity)

    om = (out_onames, out_ocols)
    a_g = list(a.ugroups)
    b_g = b_groups_out
    conds = []

    def viability(g):
        return comp_ops.viability_mask(vals, g[0], g[1], om[0], om[1])

    def strict(g):
        return comp_ops.contains_ordered_mask(vals, g[0], g[1], om[0], om[1])

    def compat(g1, g2):
        return comp_ops.compatible_mask(vals, g1[0], g1[1], g2[0], g2[1])

    if a.kind == "O":
        if b.kind == "U":
            conds.append(viability(b_g[0]))          # C([u])._add_ordered
        elif b.kind == "C":
            for g in b_g:                            # C_b.join(O_a) viability
                conds.append(viability(g))
    elif a.kind == "U":
        if b.kind == "O":
            conds.append(viability(a_g[0]))          # C([u])._add_ordered
        elif b.kind == "U":
            conds.append(compat(a_g[0], b_g[0]))     # C([uA])._add_unordered
        elif b.kind == "C":
            if b.onames:                             # C_b._add_unordered(uA)
                conds.append(strict(a_g[0]))
            for g in b_g:
                conds.append(compat(g, a_g[0]))
    else:  # a.kind == "C"
        if b.kind == "O":
            for g in a_g:                            # _add_ordered viability
                conds.append(viability(g))
        elif b.kind == "U":
            if a.onames:                             # _add_unordered strict
                conds.append(strict(b_g[0]))
            for g in a_g:
                conds.append(compat(g, b_g[0]))
        elif b.kind == "C":
            if b.onames:                             # om changed: re-check self
                for g in a_g:
                    conds.append(viability(g))
            if out_onames:
                # _add_unordered re-checks strict contains against the
                # merged om at join time — b's constraints may have been
                # kept by the weaker viability disjunction at construction
                for g in b_g:
                    conds.append(strict(g))
            for ga in a_g:
                for gb in b_g:
                    conds.append(compat(ga, gb))

    for c in conds:
        valid = valid & c
    vals, keep, count = ops.dedup(vals, valid)
    n = int(count)
    if n == 0:
        return None
    # group order mirrors the reference's append order: the composite whose
    # join method ran keeps its constraints first (U,C -> b's groups first)
    if a.kind == "U" and b.kind == "C":
        out_groups = tuple(b_g) + tuple(a_g)
    else:
        out_groups = tuple(a_g) + tuple(b_g)
    return CTable(
        kind="O" if not out_groups else "C",
        onames=out_onames,
        ocols=out_ocols,
        ugroups=out_groups,
        vals=vals,
        valid=keep,
        count=n,
    )


# ---------------------------------------------------------------------------
# union / difference over disjunction groups
# ---------------------------------------------------------------------------

def _sort_equal_blocks(vals, groups):
    """Per-row lexicographic ordering of constraint blocks that share the
    same variable set, so positional row equality matches the reference's
    order-insensitive composite identity (hash XOR over constraints)."""
    runs = []
    i = 0
    while i < len(groups):
        j = i
        while j + 1 < len(groups) and groups[j + 1][0] == groups[i][0]:
            j += 1
        if j > i:
            runs.append([groups[x][1] for x in range(i, j + 1)])
        i = j + 1
    for run in runs:
        blocks = [vals[:, jnp.asarray(cols, dtype=jnp.int32)] for cols in run]
        # bubble compare-swap network (runs are tiny)
        for a in range(len(blocks)):
            for b in range(len(blocks) - 1 - a):
                x, y = blocks[b], blocks[b + 1]
                gt = jnp.zeros(vals.shape[0], dtype=bool)
                eq = jnp.ones(vals.shape[0], dtype=bool)
                for c in range(x.shape[1]):
                    gt = gt | (eq & (x[:, c] > y[:, c]))
                    eq = eq & (x[:, c] == y[:, c])
                swap = gt[:, None]
                blocks[b] = jnp.where(swap, y, x)
                blocks[b + 1] = jnp.where(swap, x, y)
        for cols, block in zip(run, blocks):
            vals = vals.at[:, jnp.asarray(cols, dtype=jnp.int32)].set(block)
    return vals


def _canonicalize(t: CTable) -> CTable:
    """Project to the canonical column layout: ordered columns in sorted
    name order, then constraint blocks in sorted group-name order (blocks
    with identical variable sets additionally sorted per row)."""
    o_order = sorted(range(len(t.onames)), key=lambda i: t.onames[i])
    g_order = sorted(range(len(t.ugroups)), key=lambda i: t.ugroups[i][0])
    idx: List[int] = [t.ocols[i] for i in o_order]
    onames = tuple(t.onames[i] for i in o_order)
    groups = []
    pos = len(idx)
    for gi in g_order:
        names, cols = t.ugroups[gi]
        idx.extend(cols)
        groups.append((names, tuple(range(pos, pos + len(cols)))))
        pos += len(cols)
    if idx == list(range(t.vals.shape[1])):
        vals = t.vals
    else:
        vals = t.vals[:, jnp.asarray(idx, dtype=jnp.int32)]
    vals = _sort_equal_blocks(vals, groups)
    return CTable(t.kind, onames, tuple(range(len(onames))), tuple(groups),
                  vals, t.valid, t.count)


def union_ctables(ops: TreeOps, tables: List[CTable]) -> List[CTable]:
    """Set-union of candidate groups (reference Or union semantics,
    pattern_matcher.py:660-671): same-structure groups concatenate and
    dedup on device; different structures stay separate groups."""
    groups: Dict[Tuple, List[CTable]] = {}
    for t in tables:
        if t.count == 0:
            continue
        groups.setdefault(t.group_key, []).append(_canonicalize(t))
    out = []
    for members in groups.values():
        if len(members) == 1:
            out.append(members[0])
            continue
        vals, valid = ops.concat([(m.vals, m.valid) for m in members])
        vals, keep, count = ops.dedup(vals, valid)
        n = int(count)
        if n == 0:
            continue
        m0 = members[0]
        out.append(CTable(m0.kind, m0.onames, m0.ocols, m0.ugroups,
                          vals, keep, n))
    return out


def difference(ops: TreeOps, tables: List[CTable], minus: List[CTable]) -> List[CTable]:
    """Exact set difference (reference Or de-Morgan branch,
    pattern_matcher.py:674-684: joint negative answers minus the positive
    union — plain equality removal, not covering semantics).  The minus
    side is replicated first: a row must be removed on whichever shard it
    lives, not only where its minus twin happens to live."""
    minus_by_key: Dict[Tuple, List[CTable]] = {}
    for m in minus:
        if m.count:
            minus_by_key.setdefault(m.group_key, []).append(
                ops.replicate(_canonicalize(m))
            )
    out = []
    for t in tables:
        if t.count == 0:
            continue
        tc = _canonicalize(t)
        valid = tc.valid
        for m in minus_by_key.get(tc.group_key, []):
            all_cols = tuple((c, c) for c in range(tc.vals.shape[1]))
            valid = ops.anti_join(tc.vals, valid, m.vals, m.valid, all_cols)
        n = int(valid.sum())
        if n:
            out.append(CTable(tc.kind, tc.onames, tc.ocols, tc.ugroups,
                              tc.vals, valid, n))
    return out


# ---------------------------------------------------------------------------
# negation filtering (And forbidden sets)
# ---------------------------------------------------------------------------

def _excluded_pairs(t: CTable, tabu: CTable):
    """bool[rowsA, rowsT] — pred(a, t) per the check_negation dispatch;
    None when the tabu can statically never exclude this group."""
    va, vt = t.vals, tabu.vals
    if t.kind == "O":
        if tabu.kind == "O":
            return comp_ops.pair_ordered_covers(
                va, t.onames, t.ocols, vt, tabu.onames, tabu.ocols
            )
        if tabu.kind == "U":
            names, cols = tabu.ugroups[0]
            return comp_ops.pair_u_covered_by_ordered(
                va, t.onames, t.ocols, vt, names, cols
            )
        parts = []  # tabu composite: om sub-map AND every constraint covered
        if tabu.onames:
            p = comp_ops.pair_ordered_covers(
                va, t.onames, t.ocols, vt, tabu.onames, tabu.ocols
            )
            if p is None:
                return None
            parts.append(p)
        for names, cols in tabu.ugroups:
            p = comp_ops.pair_u_covered_by_ordered(
                va, t.onames, t.ocols, vt, names, cols
            )
            if p is None:
                return None
            parts.append(p)
        out = parts[0]
        for p in parts[1:]:
            out = out & p
        return out
    if t.kind == "U":
        names, cols = t.ugroups[0]
        if tabu.kind == "O":
            return comp_ops.pair_u_contains_ordered(
                va, names, cols, vt, tabu.onames, tabu.ocols
            )
        if tabu.kind == "U":
            tn, tc = tabu.ugroups[0]
            return comp_ops.pair_u_contains_unordered(va, names, cols, vt, tn, tc)
        out = None  # tabu composite: excluded iff SOME constraint contained
        for tn, tc in tabu.ugroups:
            p = comp_ops.pair_u_contains_unordered(va, names, cols, vt, tn, tc)
            if p is not None:
                out = p if out is None else (out | p)
        return out
    # t composite: the ordered part is IGNORED by the reference dispatch
    # (CompositeAssignment.check_negation, pattern_matcher.py:305-317)
    out = None
    for names, cols in t.ugroups:
        if tabu.kind == "O":
            p = comp_ops.pair_u_contains_ordered(
                va, names, cols, vt, tabu.onames, tabu.ocols
            )
        elif tabu.kind == "U":
            tn, tc = tabu.ugroups[0]
            p = comp_ops.pair_u_contains_unordered(va, names, cols, vt, tn, tc)
        else:
            p = None  # AND over tabu constraints
            ok = True
            for tn, tc in tabu.ugroups:
                q = comp_ops.pair_u_contains_unordered(va, names, cols, vt, tn, tc)
                if q is None:
                    ok = False
                    break
                p = q if p is None else (p & q)
            if not ok:
                p = None
        if p is not None:
            out = p if out is None else (out | p)
    return out


def apply_forbidden(ops: TreeOps, t: CTable, forbidden: List[CTable]) -> CTable:
    valid = t.valid
    for tabu in forbidden:
        if tabu.count == 0:
            continue
        if t.kind == "O" and tabu.kind == "O":
            if not set(tabu.onames) <= set(t.onames):
                continue  # NO_COVERING: never excludes
            pairs = tuple(
                (t.ocols[t.onames.index(v)], tabu.ocols[tabu.onames.index(v)])
                for v in tabu.onames
            )
            tabu_r = ops.replicate(tabu)
            valid = ops.anti_join(t.vals, valid, tabu_r.vals, tabu_r.valid, pairs)
            continue
        tabu_r = ops.replicate(tabu)
        pred = _excluded_pairs(t, tabu_r)
        if pred is None:
            continue
        excl = (pred & tabu_r.valid[None, :]).any(axis=1)
        valid = valid & ~excl
    n = int(valid.sum())
    return CTable(t.kind, t.onames, t.ocols, t.ugroups, t.vals, valid, n)


# ---------------------------------------------------------------------------
# tree evaluation (reference control-flow semantics)
# ---------------------------------------------------------------------------

def _ordered_conj_plans(node: PAnd):
    """TermPlans when every child is an ordered term (possibly negated or a
    static True const) — the fused single-dispatch fast path applies."""
    import copy as _copy

    plans = []
    for ch in node.children:
        if isinstance(ch, PConst):
            if not ch.matched:
                return "fail"
            continue
        if isinstance(ch, PTerm):
            plans.append(ch.plan)
        elif isinstance(ch, PNot) and isinstance(ch.child, PTerm):
            p = _copy.copy(ch.child.plan)
            p.negated = True
            plans.append(p)
        else:
            return None
    if not plans or all(p.negated for p in plans):
        return None
    return plans


def conj_sites(node: PlanNode) -> List[List]:
    """The ordered-conjunction leaf sites of a plan tree — every PAnd
    whose children compile to one TermPlan list, i.e. exactly the sites
    the cost-based planner (das_tpu/planner) orders and seeds when the
    tree evaluator's `conj()` leaves execute.  Used by the explain
    surface to render per-site costed plans for Or/negation composites;
    mixed And nodes recurse into their children instead."""
    sites: List[List] = []

    def walk(n: PlanNode) -> None:
        if isinstance(n, PAnd):
            plans = _ordered_conj_plans(n)
            if plans not in (None, "fail"):
                sites.append(plans)
                return
            for ch in n.children:
                walk(ch)
        elif isinstance(n, POr):
            for ch in n.children:
                walk(ch)
        elif isinstance(n, PNot):
            walk(n.child)

    walk(node)
    return sites


# ---------------------------------------------------------------------------
# whole-tree fusion (ISSUE 10): one program for the homogeneous Or subset
# ---------------------------------------------------------------------------


def tree_fusion_enabled(config=None) -> bool:
    """Resolve whole-tree fusion routing.  Env DAS_TPU_TREE_FUSION beats
    the config (the DAS_TPU_PALLAS idiom, so the bench A/B can flip arms
    without code changes); "auto" = on — ineligible shapes fall back to
    the tree executor, answers bit-identical either way."""
    mode = os.environ.get("DAS_TPU_TREE_FUSION")
    if mode is None and config is not None:
        mode = getattr(config, "use_tree_fusion", "auto")
    mode = str("auto" if mode is None else mode).lower()
    if mode in ("off", "0", "false"):
        return False
    return True


def tree_fusion_sites(node: PlanNode):
    """The homogeneous fusable subset (ISSUE 10): a POr whose every
    branch is an ordered conjunction over ONE shared variable universe.
    Returns (pos_sites, neg_plans, const_matched) — per-branch TermPlan
    lists, the joint negative conjunction's plans (the de-Morgan
    difference branch, reference pattern_matcher.py:674-684), and
    whether a statically-matched PConst branch forces the Or verdict —
    or None when the tree is outside the subset (unordered/composite
    shapes, mixed And nodes, heterogeneous variable sets): the staged
    tree executor keeps those, answer-identical.

    Nested positive-only POr children flatten (a union of unions is the
    same set); nested negation stays with the tree executor — its
    difference runs against the INNER union, not the root's."""
    if not isinstance(node, POr):
        return None
    pos_sites: List[List] = []
    neg_children: List[PlanNode] = []
    const_matched = False

    def flatten(n: POr, root: bool) -> bool:
        nonlocal const_matched
        for ch in n.children:
            if isinstance(ch, PNot):
                if not root:
                    return False
                neg_children.append(ch.child)
            elif isinstance(ch, PConst):
                if ch.matched:
                    const_matched = True
            elif isinstance(ch, PTerm):
                pos_sites.append([ch.plan])
            elif isinstance(ch, PAnd):
                plans = _ordered_conj_plans(ch)
                if plans == "fail":
                    continue  # statically unmatched branch: no rows
                if plans is None:
                    return False
                pos_sites.append(plans)
            elif isinstance(ch, POr):
                if not flatten(ch, False):
                    return False
            else:
                return False  # PUTerm etc.: composite shapes stay staged
        return True

    if not flatten(node, True):
        return None
    neg_plans = None
    if neg_children:
        # the reference's joint negative is And([n.child, ...]) — PAnd
        # children nest one level when a Not wraps a whole And.  Flatten
        # them: joining the groups' ordered tables equals the flattened
        # conjunction whenever no group-level reseed fires, and every
        # group-level reseed case raises the flattened program's
        # in-program reseed flag (an empty intermediate with positive
        # terms remaining) or the count==0/!same_order verdict — both
        # decline to the tree executor, which owns the quirk exactly.
        flat: List[PlanNode] = []
        for ch in neg_children:
            if isinstance(ch, PAnd):
                flat.extend(ch.children)
            else:
                flat.append(ch)
        joint = _ordered_conj_plans(PAnd(flat))
        if joint in (None, "fail"):
            # "fail" = a statically-false negative: the joint negative
            # answer set is empty and the whole difference result is
            # empty — rare and static, the tree executor handles it
            return None
        neg_plans = joint
    if not pos_sites:
        return None  # pure-negative Or: one site, nothing to fuse
    if len(pos_sites) + (1 if neg_plans else 0) < 2:
        return None  # a single conjunction IS the fused path already
    universe = {
        v for p in pos_sites[0] if not p.negated for v in p.var_names
    }
    if not universe:
        return None
    for site in pos_sites[1:]:
        if {v for p in site if not p.negated for v in p.var_names} != universe:
            return None  # heterogeneous var sets: separate CTable groups
    if neg_plans is not None:
        if {
            v for p in neg_plans if not p.negated for v in p.var_names
        } != universe:
            return None  # difference only removes within one group key
    return pos_sites, neg_plans, const_matched


class _TreeFusedEntry:
    """Cached whole-tree fused answer: the FusedResult/ShardedFusedResult
    (host copies prefetched — a hit issues zero device programs AND zero
    transfers) plus the negation/matched verdicts.  `vals` is exposed so
    ResultCache.put's size bound applies; reseed_needed is never set
    (reseed-flagged trees decline before caching)."""

    __slots__ = ("result", "negation", "matched")

    def __init__(self, result, negation, matched):
        self.result = result
        self.negation = negation
        self.matched = matched

    @property
    def vals(self):
        return self.result.vals


class _TreeFusedDecline:
    """Cached DECLINE verdict for one tree at one delta version (a
    per-site reseed fired, or a site hit the capacity ceiling): the next
    identical query skips straight to the staged tree executor — whose
    own `(digest,)` cache then answers with zero dispatches — instead of
    re-executing and re-discarding the whole fused program every time.
    Version-guarded like any entry: a commit can change the verdict
    (capacities, estimates), so the attempt re-runs after one."""

    __slots__ = ()


_TREE_FUSED_DECLINED = _TreeFusedDecline()


def _materialize_fused_tree(db, result, answer: PatternMatchingAnswer) -> bool:
    """Rows of a settled whole-tree program into reference assignment
    objects: the result is one ordered table over the canonical
    variable layout, so it materializes through materialize_tables
    verbatim (host-set identity establishes final dedup semantics, and
    removes the cross-shard duplicates the sharded union's local dedup
    leaves by design).  The boolean-mask row iteration flattens the
    sharded [S, cap] layout the same as the flat one."""
    t = CTable(
        kind="O",
        onames=result.var_names,
        ocols=tuple(range(len(result.var_names))),
        ugroups=(),
        vals=result.vals,
        valid=result.valid,
        count=result.count,
        host_vals=result.host_vals,
        host_valid=result.host_valid,
    )
    return materialize_tables(db, [t], answer)


def _tree_fused_executor(db):
    """The backend's fused executor exposing execute_tree, or None."""
    if hasattr(db, "dev"):
        from das_tpu.query.fused import get_executor

        return get_executor(db)
    if hasattr(db, "tables") and hasattr(db, "mesh"):
        from das_tpu.parallel.fused_sharded import get_sharded_executor

        return get_sharded_executor(db)
    return None


def query_tree_fused(db, plan: PlanNode, answer: PatternMatchingAnswer,
                     cache=None) -> Optional[bool]:
    """Answer an eligible Or/negation plan tree as ONE fused program
    (ISSUE 10): every conjunction site plus the in-program union/anti
    settles in a single dispatch and a single transfer, where the tree
    executor pays one dispatch/settle round trip per site.  Returns the
    matched verdict, or None when the tree is ineligible or the fused
    attempt declined (capacity ceiling, per-site reseed verdict) — the
    caller falls through to the staged tree executor, bit-identical."""
    sites = tree_fusion_sites(plan)
    if sites is None:
        return None
    pos_sites, neg_plans, const_matched = sites
    ex = _tree_fused_executor(db)
    if ex is None:
        return None
    key = version = None
    if cache is not None:
        digest = _plan_digest(plan)
        if digest is not None:
            key = (digest, "tree_fused")
            hit = cache.get(key)
            if isinstance(hit, _TreeFusedDecline):
                return None  # memoized decline: staged cache answers
            if hit is not None:
                answer.negation = hit.negation
                _materialize_fused_tree(db, hit.result, answer)
                return hit.matched
            version = cache.version()
    job = ex.execute_tree(pos_sites, neg_plans)
    if job is None or job.result is None:
        if key is not None:
            cache.put(key, _TREE_FUSED_DECLINED, version)
        return None
    negation = neg_plans is not None
    matched = const_matched or job.matched_any
    if key is not None:
        cache.put(key, _TreeFusedEntry(job.result, negation, matched),
                  version)
    answer.negation = negation
    _materialize_fused_tree(db, job.result, answer)
    return matched


def eval_plan(db, node: PlanNode) -> NodeResult:
    if isinstance(node, PConst):
        return NodeResult([], False, node.matched)
    if isinstance(node, PTerm):
        t = _ops(db).run_term(node.plan)
        return NodeResult([t] if t else [], False, t is not None and t.count > 0)
    if isinstance(node, PUTerm):
        t = _ops(db).run_uterm(node.plan)
        return NodeResult([t] if t else [], False, t is not None and t.count > 0)
    if isinstance(node, PNot):
        r = eval_plan(db, node.child)
        return NodeResult(r.tables, not r.negation, True)
    if isinstance(node, POr):
        return _eval_or(db, node)
    if isinstance(node, PAnd):
        return _eval_and(db, node)
    raise NotCompilable(f"unknown plan node {type(node).__name__}")


def _eval_or(db, node: POr) -> NodeResult:
    if not node.children:
        return NodeResult([], False, False)
    union_src: List[CTable] = []
    or_matched = False
    negatives: List[PNot] = []
    for ch in node.children:
        if isinstance(ch, PNot):
            negatives.append(ch)  # syntactic Not only (reference :651-653)
            continue
        r = eval_plan(db, ch)
        if not r.matched:
            continue
        or_matched = True
        # reference ignores a positive sub-answer's negation flag (:660-663)
        union_src.extend(r.tables)
    utables = union_ctables(_ops(db), union_src)
    if negatives:
        joint = PAnd([n.child for n in negatives])
        jr = eval_plan(db, joint)
        return NodeResult(difference(_ops(db), jr.tables, utables), True, or_matched)
    return NodeResult(utables, False, or_matched)


def _eval_and(db, node: PAnd) -> NodeResult:
    if not node.children:
        return NodeResult([], False, False)
    plans = _ordered_conj_plans(node)
    if plans == "fail":
        return NodeResult([], False, False)
    if plans is not None:
        t = _ops(db).conj(plans)
        if t is None or t.count == 0:
            return NodeResult([], False, False)
        return NodeResult([t], False, True)

    accumulated: Optional[List[CTable]] = None
    forbidden: List[CTable] = []
    for ch in node.children:
        r = eval_plan(db, ch)
        if not r.matched:
            return NodeResult([], False, False)
        if _total(r.tables) == 0:
            continue
        if r.negation:
            forbidden.extend(r.tables)
            continue
        if accumulated is None or _total(accumulated) == 0:
            # reference reseed quirk: an empty accumulator is replaced by
            # the next positive term's answers (pattern_matcher.py:726-738)
            accumulated = r.tables
        else:
            joined: List[CTable] = []
            for ta in accumulated:
                for tb in r.tables:
                    j = join_ctables(db, ta, tb)
                    if j is not None:
                        joined.append(j)
            accumulated = union_ctables(_ops(db), joined)
    result: List[CTable] = []
    for t in accumulated or []:
        t2 = apply_forbidden(_ops(db), t, forbidden)
        if t2.count:
            result.append(t2)
    return NodeResult(result, False, _total(result) > 0)


# ---------------------------------------------------------------------------
# materialization + entry point
# ---------------------------------------------------------------------------

def _row_to_assignment(t: CTable, row, hexes):
    if t.kind == "O":
        a = OrderedAssignment()
        for name, col in zip(t.onames, t.ocols):
            if not a.assign(name, hexes[int(row[col])]):
                return None
        return a if a.freeze() else None
    u_objs = []
    for names, cols in t.ugroups:
        u = UnorderedAssignment()
        for name, col in zip(names, cols):
            if not u.assign(name, hexes[int(row[col])]):
                return None
        if not u.freeze():
            return None
        u_objs.append(u)
    if t.kind == "U":
        return u_objs[0]
    om = None
    if t.onames:
        om = OrderedAssignment()
        for name, col in zip(t.onames, t.ocols):
            if not om.assign(name, hexes[int(row[col])]):
                return None
        om.freeze()
    comp = CompositeAssignment(u_objs[0])
    comp.unordered_mappings = u_objs
    comp.ordered_mapping = om
    comp._recompute_hash()
    return comp


def materialize_tables(db, tables: List[CTable], answer: PatternMatchingAnswer) -> bool:
    hexes = db.fin.hex_of_row
    for t in tables:
        if t.host_vals is not None:
            vals, valid = t.host_vals, t.host_valid
        else:
            # one transfer per table instead of one per array
            from das_tpu.query.fused import FETCH_COUNTS

            FETCH_COUNTS["n"] += 1
            vals, valid = jax.device_get((t.vals, t.valid))
        for row in vals[valid]:
            a = _row_to_assignment(t, row, hexes)
            if a is not None:
                answer.assignments.add(a)
    return bool(answer.assignments)


# ---------------------------------------------------------------------------
# composite-table result cache (ROADMAP "result-cache scope")
# ---------------------------------------------------------------------------


class _TreeEntry:
    """Cached root NodeResult of one evaluated plan tree: the composite
    tables (with prefetched host copies — a hit issues zero device
    programs AND zero host transfers), plus the negation/matched verdicts.
    reseed_needed/vals are absent so ResultCache.put's FusedResult-shaped
    guards pass it through; the size bound is enforced at build time."""

    __slots__ = ("tables", "negation", "matched")

    def __init__(self, tables, negation, matched):
        self.tables = tables
        self.negation = negation
        self.matched = matched


def _plan_digest(node: PlanNode):
    """Stable hashable digest of a plan tree — the tree pendant of
    ResultCache.key's per-term plan digest: node structure plus every
    grounded value (type ids, ctype keys, fixed/required global rows).
    Global rows are stable within one delta version, and the cache's
    version guard completes the key."""
    if isinstance(node, PConst):
        return ("const", node.matched)
    if isinstance(node, PTerm):
        p = node.plan
        return (
            "t", p.arity, p.type_id, p.ctype, p.fixed, p.var_names,
            p.var_cols, p.eq_pairs, p.negated,
        )
    if isinstance(node, PUTerm):
        u = node.plan
        return ("u", u.arity, u.type_id, u.ctype, u.required, u.var_names)
    if isinstance(node, PNot):
        return ("not", _plan_digest(node.child))
    if isinstance(node, (PAnd, POr)):
        tag = "and" if isinstance(node, PAnd) else "or"
        return (tag, tuple(_plan_digest(ch) for ch in node.children))
    return None  # unknown node kind: stay uncached, never mis-key


def _tree_cache(db):
    """The backend's delta-versioned tree-composite cache, living on the
    same executor object as the conjunctive ResultCache so a FULL refresh
    (which replaces the device tables and with them the executor) drops
    both wholesale."""
    if hasattr(db, "dev"):
        from das_tpu.query.fused import get_executor

        return get_executor(db).tree_results
    if hasattr(db, "tables") and hasattr(db, "mesh"):
        from das_tpu.parallel.fused_sharded import get_sharded_executor

        return get_sharded_executor(db).tree_results
    return None


def _tree_entry(r: NodeResult) -> Optional[_TreeEntry]:
    """Build a cacheable entry: bounded total width (each entry pins its
    tables' device buffers), host copies prefetched in ONE transfer so
    every later hit is transfer-free."""
    from das_tpu.query.fused import ResultCache

    total = sum(int(np.prod(t.vals.shape)) for t in r.tables)
    if total > ResultCache.MAX_ENTRY_ROWS:
        return None
    need = [t for t in r.tables if t.host_vals is None]
    if need:
        from das_tpu.query.fused import FETCH_COUNTS

        FETCH_COUNTS["n"] += 1  # ONE prefetch transfer per cached entry
        fetched = jax.device_get(tuple((t.vals, t.valid) for t in need))
        for t, (hv, hm) in zip(need, fetched):
            t.host_vals, t.host_valid = np.asarray(hv), np.asarray(hm)
    return _TreeEntry(list(r.tables), r.negation, r.matched)


def query_tree(db, query, answer: PatternMatchingAnswer) -> Optional[bool]:
    """Generalized device execution; None when the query is outside the
    compilable language (caller falls back to the host algebra)."""
    if asn_mod.CONFIG.get("no_overload"):
        return None
    try:
        plan = build_plan(db, query)
    except NotCompilable:
        return None
    cache = _tree_cache(db)
    # whole-tree fusion (ISSUE 10): the homogeneous Or/negation subset
    # settles as ONE fused program — in-program union + anti, one
    # transfer.  A decline (ineligible shape, capacity ceiling, reseed
    # verdict) falls through to the staged evaluator below,
    # answer-identical by the bit-parity contract (tests/test_ztreefuse)
    if tree_fusion_enabled(getattr(db, "config", None)):
        matched = query_tree_fused(db, plan, answer, cache)
        if matched is not None:
            return matched
    key = version = None
    if cache is not None:
        digest = _plan_digest(plan)
        if digest is not None:
            key = (digest,)
            hit = cache.get(key)
            if hit is not None:
                answer.negation = hit.negation
                materialize_tables(db, hit.tables, answer)
                return hit.matched
            version = cache.version()
    r = eval_plan(db, plan)
    if key is not None:
        entry = _tree_entry(r)
        if entry is not None:
            cache.put(key, entry, version)
    answer.negation = r.negation
    materialize_tables(db, r.tables, answer)
    return r.matched
