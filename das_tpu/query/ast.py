"""Pattern-matching query AST and host evaluator.

Same logical language as the reference query engine
(/root/reference/das/pattern_matcher/pattern_matcher.py:370-748):
`Node`, `Link`, `Variable`, `TypedVariable`, `LinkTemplate` atoms combined
with `And` / `Or` / `Not`.  `matched(db, answer)` evaluates recursively
against any `DBInterface` backend and fills a `PatternMatchingAnswer` with a
set of frozen assignments (plus a negation flag).

This module is the *host* evaluator: the per-candidate loops mirror the
reference's (pattern_matcher.py:524-531, :732-738) and work against any
`DBInterface` backend.  Device execution does not hook into these classes —
routing happens above them, in `DistributedAtomSpace._dispatch_query`
(das_tpu/api/atomspace.py), which hands compilable queries to
das_tpu/query/compiler.py / tree.py and falls back to `matched()` here for
anything outside the compilable language.  Either path fills the same
`PatternMatchingAnswer` with identical assignment sets.
"""

from __future__ import annotations

from functools import cmp_to_key
from typing import List, Optional, Set

from das_tpu.core.schema import WILDCARD
from das_tpu.query.assignment import (
    Assignment,
    OrderedAssignment,
    UnorderedAssignment,
)


class PatternMatchingAnswer:
    def __init__(self):
        self.assignments: Set[Assignment] = set()
        self.negation: bool = False

    def __repr__(self):
        s = "NOT\n" if self.negation else ""
        for assignment in self.assignments:
            s += f"{assignment}\n"
        return s


class LogicalExpression:
    def matched(self, db, answer: PatternMatchingAnswer) -> bool:
        raise NotImplementedError

    def __repr__(self):
        return "<LogicalExpression>"


class Atom(LogicalExpression):
    def __init__(self, atom_type: str):
        self.atom_type = atom_type
        self.handle = None

    def __repr__(self):
        return f"{self.atom_type}"

    def get_handle(self, db) -> Optional[str]:
        raise NotImplementedError


class Node(Atom):
    def __init__(self, node_type: str, node_name: str):
        super().__init__(node_type)
        self.name = node_name

    def __repr__(self):
        return f"<{self.atom_type}: {self.name}>"

    def get_handle(self, db) -> Optional[str]:
        if not self.handle:
            self.handle = db.get_node_handle(self.atom_type, self.name)
        return self.handle

    def matched(self, db, answer: PatternMatchingAnswer) -> bool:
        return db.node_exists(self.atom_type, self.name)


class Variable(Atom):
    def __init__(self, variable_name: str):
        super().__init__("ANY")
        self.name = variable_name

    def __repr__(self):
        return f"{self.name}"

    def get_handle(self, db) -> str:
        return WILDCARD

    def matched(self, db, answer: PatternMatchingAnswer) -> bool:
        return True


class TypedVariable(Variable):
    def __init__(self, variable_name: str, variable_type: str):
        super().__init__(variable_name)
        self.type = variable_type

    def __repr__(self):
        return f"{self.name}: {self.type}"


class Link(Atom):
    """A link pattern whose targets are grounded atoms or (untyped)
    variables.  Unordered links keep grounded targets first, variables last
    (reference Link ctor comparator, pattern_matcher.py:442-453)."""

    def __init__(self, link_type: str, targets: List[Atom], ordered: bool):
        assert not any(isinstance(t, TypedVariable) for t in targets)
        super().__init__(link_type)
        self.ordered = ordered
        if ordered:
            self.targets = targets
        else:
            def comparator(t1, t2):
                if isinstance(t1, Variable):
                    return 1
                if isinstance(t2, Variable):
                    return -1
                return 0

            self.targets = sorted(targets, key=cmp_to_key(comparator))

    def __repr__(self):
        return f"<{self.atom_type}: {self.targets}>"

    def get_handle(self, db) -> Optional[str]:
        if not self.handle:
            target_handles = [t.get_handle(db) for t in self.targets]
            if any(h is None for h in target_handles):
                return None
            self.handle = db.get_link_handle(self.atom_type, target_handles)
        return self.handle

    def _assign_variables(self, db, link_targets: List[str]) -> Optional[Assignment]:
        assert len(link_targets) == len(self.targets)
        if self.ordered:
            answer = OrderedAssignment()
            for atom, handle in zip(self.targets, link_targets):
                if isinstance(atom, Variable):
                    if not answer.assign(atom.name, handle):
                        return None
            return answer if answer.freeze() else None
        answer = UnorderedAssignment()
        remaining = list(link_targets)
        variables = []
        for atom in self.targets:
            if isinstance(atom, Variable):
                variables.append(atom)
            else:
                grounded = atom.get_handle(db)
                if grounded in remaining:
                    remaining.remove(grounded)
        if len(variables) != len(remaining):
            return None
        for atom, handle in zip(variables, remaining):
            if not answer.assign(atom.name, handle):
                return None
        return answer if answer.freeze() else None

    def _typed_variable_matched(self, db, answer) -> bool:
        first = True
        for target in self.targets:
            if isinstance(target, Variable) and not isinstance(target, TypedVariable):
                return False
            if isinstance(target, TypedVariable):
                if not first:
                    return False
                first = False
        return all(t.matched(db, answer) for t in self.targets)

    def matched(self, db, answer: PatternMatchingAnswer) -> bool:
        if any(isinstance(t, LinkTemplate) for t in self.targets):
            return self._typed_variable_matched(db, answer)
        if not all(t.matched(db, answer) for t in self.targets):
            return False
        target_handles = [t.get_handle(db) for t in self.targets]
        if any(h == WILDCARD for h in target_handles):
            matched = db.get_matched_links(self.atom_type, target_handles)
            answer.assignments = set()
            for link, targets in matched:
                asn = self._assign_variables(db, list(targets))
                if asn:
                    answer.assignments.add(asn)
            return bool(answer.assignments)
        return db.link_exists(self.atom_type, target_handles)


class LinkTemplate(LogicalExpression):
    """All-variable link pattern probing the type-template index."""

    def __init__(self, link_type: str, targets: List[TypedVariable], ordered: bool):
        assert all(isinstance(t, TypedVariable) for t in targets)
        self.link_type = link_type
        self.targets = targets
        self.ordered = ordered
        self.handle = None

    def __repr__(self):
        return f"<{self.link_type}: {self.targets}>"

    def _assign_variables(self, db, link_targets: List[str]) -> Optional[Assignment]:
        assert len(link_targets) == len(self.targets)
        answer = OrderedAssignment() if self.ordered else UnorderedAssignment()
        for variable, handle in zip(self.targets, link_targets):
            if not answer.assign(variable.name, handle):
                return None
        return answer if answer.freeze() else None

    def matched(self, db, answer: PatternMatchingAnswer) -> bool:
        matched = db.get_matched_type_template(
            [self.link_type, *[v.type for v in self.targets]]
        )
        answer.assignments = set()
        for link, targets in matched:
            asn = self._assign_variables(db, list(targets))
            if asn:
                answer.assignments.add(asn)
        return bool(answer.assignments)


class Not(LogicalExpression):
    def __init__(self, term: LogicalExpression):
        self.term = term

    def __repr__(self):
        return f"NOT({self.term})"

    def matched(self, db, answer: PatternMatchingAnswer) -> bool:
        self.term.matched(db, answer)
        answer.negation = not answer.negation
        return True


class Or(LogicalExpression):
    def __init__(self, terms: List[LogicalExpression]):
        self.terms = terms

    def __repr__(self):
        return f"OR({self.terms})"

    def matched(self, db, answer: PatternMatchingAnswer) -> bool:
        if not self.terms:
            return False
        assert not answer.assignments
        union: Set[Assignment] = set()
        or_matched = False
        negative_terms = []
        for term in self.terms:
            if isinstance(term, Not):
                negative_terms.append(term)
                continue
            term_answer = PatternMatchingAnswer()
            if not term.matched(db, term_answer):
                continue
            or_matched = True
            if term_answer.assignments:
                union |= term_answer.assignments
        if negative_terms:
            # de-Morgan: OR of NOTs == NOT(AND); answers are the joint
            # negative matches not already covered positively
            joint = And([t.term for t in negative_terms])
            term_answer = PatternMatchingAnswer()
            joint.matched(db, term_answer)
            answer.assignments = term_answer.assignments - union
            answer.negation = True
        else:
            answer.assignments = union
        return or_matched


class And(LogicalExpression):
    def __init__(self, terms: List[LogicalExpression]):
        self.terms = terms

    def __repr__(self):
        return f"AND({self.terms})"

    def _join_assignment_sets(self, db, left: Set[Assignment], right: Set[Assignment]):
        """Pairwise join of two assignment sets.  Overridden by the device
        compiler for ordered-only workloads; this host fallback is the
        reference nested loop (pattern_matcher.py:732-738)."""
        joined = []
        for a in left:
            for b in right:
                j = a.join(b)
                if j is not None:
                    joined.append(j)
        return joined

    def matched(self, db, answer: PatternMatchingAnswer) -> bool:
        if not self.terms:
            return False
        assert not answer.assignments
        # NB: an empty accumulator is re-seeded by the next positive term —
        # observable behavior inherited from the reference accumulator test
        # (pattern_matcher.py:725-728), kept for answer-set parity.
        accumulated: Set[Assignment] = set()
        forbidden: Set[Assignment] = set()
        for term in self.terms:
            term_answer = PatternMatchingAnswer()
            if not term.matched(db, term_answer):
                return False
            if not term_answer.assignments:
                continue
            if term_answer.negation:
                forbidden |= term_answer.assignments
                continue
            if not accumulated:
                accumulated = term_answer.assignments
            else:
                accumulated = self._join_assignment_sets(
                    db, accumulated, term_answer.assignments
                )
        for assignment in accumulated:
            if all(assignment.check_negation(tabu) for tabu in forbidden):
                answer.assignments.add(assignment)
        return bool(answer.assignments)
