"""Variable-binding algebra.

Semantics-equivalent re-implementation of the reference assignment classes
(/root/reference/das/pattern_matcher/pattern_matcher.py:21-368):

* `OrderedAssignment` — a variable→value map.  Joining two assignments
  succeeds iff no shared variable binds different values; the join is the
  smaller-covering map or the union.
* `UnorderedAssignment` — the multiset binding produced by matching an
  unordered (Set/Similarity) link: a multiset of symbols and a multiset of
  values, *without* a committed pairing.  Freezing fails unless the count
  signatures agree.
* `CompositeAssignment` — one ordered map plus N unordered multiset
  constraints; maintained so that every unordered constraint stays
  satisfiable against the ordered map.

All are immutable after `freeze()` and hashable for set-level dedup.  The
`check_negation(tabu)` relation implements NOT-filtering: an answer survives
iff it is NOT covered by any forbidden assignment.

These objects live on the host: the TPU compiled path (das_tpu/ops/join.py)
materializes ordered joins as int64 binding-table kernels and only converts
to these objects at the API boundary; unordered/composite queries run here.
"""

from __future__ import annotations

import copy
from enum import Enum, auto
from typing import Dict, List, Optional, Set

# Enforce different values for different variables in ordered assignments
# (reference CONFIG['no_overload']).
CONFIG = {"no_overload": False}


class Compatibility(int, Enum):
    INCOMPATIBLE = auto()
    NO_COVERING = auto()
    FIRST_COVERS_SECOND = auto()
    SECOND_COVERS_FIRST = auto()
    EQUAL = auto()


class Assignment:
    __slots__ = ("variables", "hash", "frozen")

    def __init__(self):
        self.variables: Set[str] = set()
        self.hash: int = 0
        self.frozen: bool = False

    def __hash__(self):
        assert self.hash
        return self.hash

    def __eq__(self, other):
        assert self.hash and other.hash
        return self.hash == other.hash

    def __lt__(self, other):
        assert self.hash and other.hash
        return self.hash < other.hash

    def _base_freeze(self) -> bool:
        if self.frozen:
            return False
        self.frozen = True
        self.variables = frozenset(self.variables)
        return True


class OrderedAssignment(Assignment):
    __slots__ = ("mapping", "values")

    def __init__(self):
        super().__init__()
        self.mapping: Dict[str, str] = {}
        self.values: Set[str] = set()

    def __repr__(self):
        return repr(self.mapping)

    def freeze(self) -> bool:
        assert self._base_freeze()
        self.values = frozenset(self.values)
        self.hash = hash(frozenset(self.mapping.items()))
        return True

    def assign(self, variable: str, value: str) -> bool:
        if variable is None or value is None or self.frozen:
            raise ValueError(
                f"Invalid assignment: variable = {variable} value = {value} "
                f"frozen = {self.frozen}"
            )
        if variable in self.variables:
            return self.mapping[variable] == value
        if CONFIG["no_overload"] and value in self.values:
            return False
        self.variables.add(variable)
        self.values.add(value)
        self.mapping[variable] = value
        return True

    def compatibility(self, other: "OrderedAssignment") -> Compatibility:
        assert other is not None
        if self.hash == other.hash:
            return Compatibility.EQUAL
        for variable in self.variables & other.variables:
            if self.mapping[variable] != other.mapping[variable]:
                return Compatibility.INCOMPATIBLE
        if other.variables < self.variables:
            return Compatibility.FIRST_COVERS_SECOND
        if self.variables < other.variables:
            return Compatibility.SECOND_COVERS_FIRST
        return Compatibility.NO_COVERING

    #: reference API name (pattern_matcher.py:141 `evaluate_compatibility`)
    evaluate_compatibility = compatibility

    def compatible(self, other: "OrderedAssignment") -> bool:
        return self.compatibility(other) != Compatibility.INCOMPATIBLE

    def join(self, other: Assignment) -> Optional[Assignment]:
        assert self.frozen and other.frozen
        if not isinstance(other, OrderedAssignment):
            return other.join(self)
        status = self.compatibility(other)
        if status == Compatibility.INCOMPATIBLE:
            return None
        if status in (Compatibility.EQUAL, Compatibility.FIRST_COVERS_SECOND):
            return self
        if status == Compatibility.SECOND_COVERS_FIRST:
            return other
        merged = OrderedAssignment()
        for variable, value in self.mapping.items():
            if not merged.assign(variable, value):
                return None
        for variable, value in other.mapping.items():
            if not merged.assign(variable, value):
                return None
        merged.freeze()
        return merged

    def check_negation(self, negation: Assignment) -> bool:
        if isinstance(negation, OrderedAssignment):
            status = self.compatibility(negation)
            return status not in (Compatibility.EQUAL, Compatibility.FIRST_COVERS_SECOND)
        return not negation.is_covered_by_ordered(self)


class UnorderedAssignment(Assignment):
    __slots__ = ("symbols", "values")

    def __init__(self):
        super().__init__()
        self.symbols: Dict[str, int] = {}  # symbol -> multiplicity
        self.values: Dict[str, int] = {}   # value  -> multiplicity

    def __repr__(self):
        symbols = [s for s, c in self.symbols.items() for _ in range(c)]
        values = [v for v, c in self.values.items() for _ in range(c)]
        return "*" + repr(dict(zip(symbols, values)))

    def freeze(self) -> bool:
        assert self._base_freeze()
        if tuple(sorted(self.symbols.values())) != tuple(sorted(self.values.values())):
            return False
        self.hash = hash(
            (hash(frozenset(self.symbols.items())), hash(frozenset(self.values.items())))
        )
        return True

    def assign(self, variable: str, value: str) -> bool:
        if variable is None or value is None or self.frozen:
            raise ValueError(
                f"Invalid assignment: variable = {variable} value = {value} "
                f"frozen = {self.frozen}"
            )
        if variable in self.variables:
            return False
        self.symbols[variable] = self.symbols.get(variable, 0) + 1
        self.values[value] = self.values.get(value, 0) + 1
        self.variables.add(variable)
        return True

    def join(self, other: Assignment) -> Optional[Assignment]:
        assert self.frozen and other.frozen
        if isinstance(other, CompositeAssignment):
            return other.join(self)
        return CompositeAssignment(self).join(other)

    def check_negation(self, negation: Assignment) -> bool:
        if isinstance(negation, OrderedAssignment):
            return not self.contains_ordered(negation)
        if isinstance(negation, UnorderedAssignment):
            return not self.contains_unordered(negation)
        return all(
            not self.contains_unordered(u) for u in negation.unordered_mappings
        )

    def contains_ordered(self, ordered: OrderedAssignment) -> bool:
        """True iff the ordered map could be one concretization of this
        multiset constraint: all its variables are ours and its value counts
        fit inside our value multiset."""
        needed: Dict[str, int] = {}
        for variable, value in ordered.mapping.items():
            if variable not in self.variables:
                return False
            needed[value] = needed.get(value, 0) + 1
        return all(self.values.get(v, 0) >= c for v, c in needed.items())

    def is_covered_by_ordered(self, ordered: OrderedAssignment) -> bool:
        symbols = dict(self.symbols)
        values = dict(self.values)
        for variable, value in ordered.mapping.items():
            symbols[variable] = symbols.get(variable, 0) - 1
            values[value] = values.get(value, 0) - 1
        return all(c <= 0 for c in symbols.values()) and all(
            c <= 0 for c in values.values()
        )

    def contains_unordered(self, other: "UnorderedAssignment") -> bool:
        for symbol, count in other.symbols.items():
            if self.symbols.get(symbol, 0) < count:
                return False
        for value, count in other.values.items():
            if self.values.get(value, 0) < count:
                return False
        return True

    def compatible(self, other: "UnorderedAssignment") -> bool:
        """Weak mutual-satisfiability test on shared symbols/values."""
        shared_symbols = self.variables & other.variables
        need_self = sum(self.symbols[s] for s in shared_symbols)
        need_other = sum(other.symbols[s] for s in shared_symbols)
        shared_values = set(self.values) & set(other.values)
        have_self = sum(self.values[v] for v in shared_values)
        have_other = sum(other.values[v] for v in shared_values)
        return have_other >= need_self and have_self >= need_other


class CompositeAssignment(Assignment):
    __slots__ = ("unordered_mappings", "ordered_mapping")

    def __init__(self, assignment: UnorderedAssignment):
        super().__init__()
        self.unordered_mappings: List[UnorderedAssignment] = [assignment]
        self.ordered_mapping: Optional[OrderedAssignment] = None
        self.variables = set(assignment.variables)
        assert self._base_freeze()
        self._recompute_hash()

    def __repr__(self):
        return f"Ordered = {self.ordered_mapping} | Unordered = {self.unordered_mappings}"

    def _recompute_hash(self):
        h = self.ordered_mapping.hash if self.ordered_mapping else 1
        for unordered in self.unordered_mappings:
            h ^= unordered.hash
        self.hash = h

    def _ordered_viable(self) -> bool:
        if not self.ordered_mapping:
            return bool(self.unordered_mappings)
        return all(
            u.contains_ordered(self.ordered_mapping)
            or u.is_covered_by_ordered(self.ordered_mapping)
            for u in self.unordered_mappings
        )

    def _add_ordered(self, other: Optional[OrderedAssignment]) -> bool:
        if other is None:
            pass
        elif self.ordered_mapping is None:
            self.ordered_mapping = other
        else:
            self.ordered_mapping = self.ordered_mapping.join(other)
            if self.ordered_mapping is None:
                return False
        if not self._ordered_viable():
            return False
        self._recompute_hash()
        return True

    def _add_unordered(self, unordered: UnorderedAssignment) -> bool:
        if self.ordered_mapping and not unordered.contains_ordered(self.ordered_mapping):
            return False
        if any(not u.compatible(unordered) for u in self.unordered_mappings):
            return False
        self.unordered_mappings.append(unordered)
        self._recompute_hash()
        return True

    def join(self, other: Assignment) -> Optional["CompositeAssignment"]:
        assert self.frozen and other.frozen
        answer = copy.deepcopy(self)
        if isinstance(other, OrderedAssignment):
            return answer if answer._add_ordered(other) else None
        if isinstance(other, UnorderedAssignment):
            return answer if answer._add_unordered(other) else None
        if not answer._add_ordered(other.ordered_mapping):
            return None
        if all(answer._add_unordered(u) for u in other.unordered_mappings):
            return answer
        return None

    def check_negation(self, negation: Assignment) -> bool:
        if isinstance(negation, OrderedAssignment):
            return all(
                not u.contains_ordered(negation) for u in self.unordered_mappings
            )
        if isinstance(negation, UnorderedAssignment):
            return all(
                not u.contains_unordered(negation) for u in self.unordered_mappings
            )
        for u in self.unordered_mappings:
            if all(u.contains_unordered(n) for n in negation.unordered_mappings):
                return False
        return True

    def contains_ordered(self, ordered: OrderedAssignment) -> bool:
        return all(u.contains_ordered(ordered) for u in self.unordered_mappings)

    def contains_unordered(self, unordered: UnorderedAssignment) -> bool:
        return all(u.contains_unordered(unordered) for u in self.unordered_mappings)

    def is_covered_by_ordered(self, ordered: OrderedAssignment) -> bool:
        """Whether `ordered` fully accounts for this composite: every
        unordered constraint is covered and our ordered part (if any) is a
        sub-map of `ordered`.  (The reference crashes on this path —
        pattern_matcher.py:117 calls a method UnorderedAssignment-only; this
        is the intended closure of that relation.)"""
        if self.ordered_mapping is not None:
            status = self.ordered_mapping.compatibility(ordered)
            if status not in (Compatibility.EQUAL, Compatibility.SECOND_COVERS_FIRST):
                return False
        return all(u.is_covered_by_ordered(ordered) for u in self.unordered_mappings)
