"""Single-dispatch sharded execution of compiled conjunctive plans.

Round-1's sharded pipeline (parallel/sharded_db.py) launched one shard_map
program per stage, synced exact counts to the host between stages, and
joined by all_gathering the FULL right table to every shard — O(S x cap)
ICI traffic per join and a host round trip per stage.  Here the whole plan
— every shard-local probe, term table, join, anti-join and the count
reduction — lowers to ONE shard_map program per plan shape:

  * term probes stay slab-local (zero communication), mirroring Redis
    cluster client-side slot routing except all shards probe in parallel;
  * each join picks its collective statically by estimated size:
      - small right side  -> broadcast-right (one tiled `all_gather` of a
        table that fits in the broadcast budget);
      - large right side  -> HASH-PARTITIONED join: both sides scatter
        rows to `mix(join_cols) % S` via `all_to_all`, equal keys
        co-locate, and each shard joins only its key range — ICI moves
        each row once instead of S copies;
  * negation filters broadcast the (small) tabu tables once;
  * exact counts reduce in-program (`psum` for totals, `pmax` for
    per-shard capacity checks) into one replicated stats vector — the
    host fetches it in a single transfer and decides overflow/reseed,
    exactly like the single-device fused executor (query/fused.py).

Capacity discipline matches query/fused.py: all shapes static, learned per
plan signature, doubled on overflow (per-shard probe ranges, per-join
output rows, and per-destination exchange slots — the hash-partition
equivalent of the reference's hub-key skew problem)."""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from das_tpu import obs
from das_tpu.ops.join import (
    _SENTINEL_L,
    _SENTINEL_R,
    _anti_join_impl,
    _index_join_impl,
    _join_tables_impl,
    _mix_columns,
)
from das_tpu.parallel.mesh import SHARD_AXIS, shard_map
from das_tpu.query.fused import (
    ROUTE_CTYPE,
    ROUTE_TYPE,
    ROUTE_TYPE_POS,
    FusedTermSig,
    ResultCache,
    _pow2_at_least,
    _probe,
    _TreeExecJob,
    apply_index_joins,
    canonical_tree_names,
    clamp_index_terms,
    conj_stats_len,
    dispatch_pending,
    estimate_plan_rows,
    fold_join_meta,
    multiway_meta,
    order_plans,
    remember_caps,
    prepare_tree_job,
    program_model_bytes,
    run_tree_job,
    same_positive_order,
    settle_pending,
    settle_pending_iter,
    tree_model_bytes,
)
from das_tpu.ops.join import _dedup_table_impl

#: right tables whose capacity fits here are broadcast (one all_gather);
#: larger ones hash-partition with all_to_all
BROADCAST_LIMIT = 4096


@dataclass(frozen=True)
class ShardedPlanSig:
    terms: Tuple[FusedTermSig, ...]
    term_caps: Tuple[int, ...]   # per-shard probe capacities
    join_caps: Tuple[int, ...]   # per-shard join output capacities
    exch_caps: Tuple[int, ...]   # per-join per-destination slots; 0 = broadcast
    n_shards: int
    #: per join: -1 = move tables (broadcast or all_to_all); else an INDEX
    #: JOIN — broadcast the small LEFT once and let every shard probe its
    #: own slab's (type<<32|target) posting index at this position.  The
    #: whole-type right side never materializes; one collective per join.
    index_joins: Tuple[int, ...] = ()
    #: route the shard-LOCAL probe and join bodies through the Pallas
    #: fused kernels (das_tpu/kernels/) inside the shard_map program;
    #: collectives (all_gather / all_to_all / psum) stay lowered.  Part of
    #: the signature so kernel and lowered executables cache side by side.
    use_kernels: bool = False
    #: the bytes planner picked the GRID-CHUNKED layout for at least one
    #: shard-local stage (kernels/budget.py; see FusedPlanSig.tiled)
    tiled: bool = False
    #: budget.vmem_budget() snapshot at dispatch (0 when kernels are
    #: off) — cache-key honesty across budget changes (FusedPlanSig)
    vmem_budget: int = 0
    #: the cost-based planner ordered this plan and seeded its per-shard
    #: capacities — cache-key honesty for the planner A/B
    #: (FusedPlanSig.planned)
    planned: bool = False
    #: leading positives fused into ONE shard-local k-way multiway
    #: intersection step (kernels/multiway.py): the tail clauses'
    #: term tables broadcast-gather (S×cap each) and every shard
    #: intersects against its LOCAL clause-0 slab — union over shards
    #: is the full join.  Changes the traced program and the
    #: join_caps/exch_caps/index_joins layout (FusedPlanSig.multiway),
    #: so it is part of the cache key.
    multiway: int = 0


@dataclass
class ShardedFusedResult:
    var_names: Tuple[str, ...]
    vals: Optional[jax.Array]    # [S, capF, k] row-sharded
    valid: Optional[jax.Array]
    count: int
    reseed_needed: bool
    host_vals: Optional[np.ndarray] = None   # prefetched host copies (one
    host_valid: Optional[np.ndarray] = None  # transfer with the stats)
    multiway: bool = False   # answered by a k-way multiway mesh program


def _repartition(vals, valid, cols, sentinel, S: int, q: int):
    """Scatter rows to shard `mix(cols) % S` via one all_to_all.

    Returns ([S*q, k] rows now resident on the key-owning shard, their
    mask, and this shard's worst per-destination occupancy for overflow
    detection).  Equal join keys always co-locate because the destination
    is a function of the same mix the join verifies exactly."""
    k = vals.shape[1]
    key = _mix_columns(vals, cols, valid, sentinel)
    dest = ((key % S) + S) % S
    dest = jnp.where(valid, dest, S - 1).astype(jnp.int32)
    onehot = dest[:, None] == jnp.arange(S, dtype=jnp.int32)[None, :]
    onehot = onehot & valid[:, None]
    rank = jnp.cumsum(onehot.astype(jnp.int32), axis=0) - 1
    slot = jnp.take_along_axis(rank, dest[:, None], axis=1)[:, 0]
    dest_counts = onehot.sum(axis=0, dtype=jnp.int32)
    # invalid rows or overflow slots get slot >= q -> dropped by the scatter
    slot = jnp.where(valid, slot, q)
    # validity rides as an extra column: ONE all_to_all moves the table
    packed = jnp.concatenate([vals, valid.astype(vals.dtype)[:, None]], axis=1)
    buf = jnp.zeros((S, q, k + 1), dtype=vals.dtype).at[dest, slot].set(
        packed, mode="drop"
    )
    recv = lax.all_to_all(buf, SHARD_AXIS, split_axis=0, concat_axis=0)
    recv = recv.reshape(S * q, k + 1)
    return recv[:, :k], recv[:, k].astype(bool), dest_counts.max()


def _gather_packed(vals, valid):
    """Broadcast a table to every shard with ONE collective (validity
    packed as an extra column)."""
    k = vals.shape[1]
    packed = jnp.concatenate([vals, valid.astype(vals.dtype)[:, None]], axis=1)
    full = lax.all_gather(packed, SHARD_AXIS, tiled=True)
    return full[:, :k], full[:, k].astype(bool)


def _global_count(valid):
    """Global surviving-row count of a row-sharded validity mask (ONE
    psum) — a declared collective helper (parallel/mesh.py
    COLLECTIVE_SITES, daslint DL009)."""
    return lax.psum(valid.sum(dtype=jnp.int32), SHARD_AXIS)


def _trace_sharded_conj(sig: ShardedPlanSig, bucket_arrays, keys, fixed_vals):
    """Trace ONE conjunction inside a shard_map body — shard-local
    probes/joins, the per-step collective choice, and the in-program
    stat reductions.  Returns (acc_vals, acc_valid, stats_list) with
    stats_list = [count, reseed, any_pos_empty, *per-term worst shard
    ranges, *per-join worst shard totals, *per-partitioned-join worst
    destination occupancy] as traced scalars.  This is
    build_fused_sharded's whole body, extracted so the sharded
    whole-tree program (build_sharded_tree_fused, ISSUE 10) can trace
    several sites in one mesh executable.  Declared collective site
    (parallel/mesh.py COLLECTIVE_SITES, daslint DL009): the stats
    reductions (psum/pmax) and the gather/exchange helpers live here,
    never in shard-local kernel bodies."""
    S = sig.n_shards
    positives, _negatives, names, join_meta, anti_meta = fold_join_meta(sig.terms)
    mw = sig.multiway
    start = mw if mw else 1
    index_joins = sig.index_joins or tuple(
        [-1] * max(0, len(positives) - start)
    )
    index_right = {
        positives[start + t]: t for t, p in enumerate(index_joins) if p >= 0
    }
    if mw:
        mw_meta, mw_vcol0 = multiway_meta(join_meta, mw)
    use_k = sig.use_kernels
    if use_k or mw:
        from das_tpu import kernels as _kernels

        _interp = _kernels.interpret_mode()
        # no separate lowered chain for the multiway step: kernel route
        # off still traces its body by direct discharge (query/fused.py
        # build_fused's _mw_interp rationale)
        _mw_interp = _interp if use_k else True

    # blocks arrive with a leading [1, ...] slab dim; the probe kernel
    # itself is the single-device one (query/fused.py _probe) — probes
    # are slab-local, zero communication
    tables = {}
    term_ranges = []
    pos_count = {}
    for i, t in enumerate(sig.terms):
        arrays = tuple(a[0] for a in bucket_arrays[i])
        if i in index_right:
            # index-join right side: never materialized.  Candidate
            # count = the type's slab key ranges, summed over shards.
            keys_sorted = arrays[0]
            tid = jnp.asarray(keys[i], jnp.int64)
            lo = jnp.searchsorted(keys_sorted, tid << 32, side="left")
            hi = jnp.searchsorted(keys_sorted, (tid + 1) << 32, side="left")
            pos_count[i] = lax.psum((hi - lo).astype(jnp.int32), SHARD_AXIS)
            tables[i] = None
            term_ranges.append(jnp.int32(0))
            continue
        vals, mask, rng = _probe(
            t, arrays, keys[i], fixed_vals[i], sig.term_caps[i],
            use_kernels=use_k,
        )
        tables[i] = (vals, mask)
        pos_count[i] = lax.psum(mask.sum(dtype=jnp.int32), SHARD_AXIS)
        term_ranges.append(lax.pmax(rng, SHARD_AXIS))

    any_pos_empty = jnp.bool_(False)
    for i in positives:
        any_pos_empty = any_pos_empty | (pos_count[i] == 0)

    acc_vals, acc_valid = tables[positives[0]]
    if len(positives) > 1:
        reseed = pos_count[positives[0]] == 0
    else:
        reseed = jnp.bool_(False)
    join_totals = []
    exch_stats = []
    if mw:
        # shard-local k-way step: broadcast every tail's term table
        # once (S×cap rows, validity packed — one collective per
        # tail, the broadcast-right idiom) and intersect against
        # the LOCAL clause-0 slab; each output row has exactly one
        # clause-0 source row living on exactly one shard, so the
        # union over shards is the full join and the output stays
        # row-sharded by clause-0 locality.
        mw_tails = []
        for i in positives[1:mw]:
            tv, tm = tables[i]
            mw_tails.append(_gather_packed(tv, tm))
        acc_vals, acc_valid, mw_totals = _kernels.multiway_join_impl(
            acc_vals, acc_valid, mw_tails, mw_vcol0, mw_meta,
            sig.join_caps[0], interpret=_mw_interp,
        )
        # partial totals are per-shard: the reference's reseed rule
        # asks about GLOBAL intermediate emptiness, the capacity
        # retry about the worst shard's output
        g_totals = lax.psum(mw_totals, SHARD_AXIS)
        join_totals.append(lax.pmax(mw_totals[mw - 2], SHARD_AXIS))
        exch_stats.append(jnp.int32(0))
        for t in range(max(0, min(mw - 1, len(positives) - 2))):
            reseed = reseed | (g_totals[t] == 0)
    for t_step, i in enumerate(positives[start:]):
        n = start - 1 + t_step     # absolute join position
        pairs, extra = join_meta[n]
        jc = sig.join_caps[(1 if mw else 0) + t_step]
        q = sig.exch_caps[(1 if mw else 0) + t_step]
        if index_joins[t_step] >= 0:
            # broadcast the SMALL left once; every shard probes its own
            # slab's posting index — union over shards is the full join
            # (each link lives in exactly one slab)
            lv_full, lm_full = _gather_packed(acc_vals, acc_valid)
            ks, perm, targets, _tid = (
                a[0] for a in bucket_arrays[i]
            )
            if use_k:
                acc_vals, acc_valid, total = _kernels.index_join_impl(
                    lv_full, lm_full, ks, perm, targets, keys[i],
                    pairs, sig.terms[i].var_cols, extra,
                    jc, interpret=_interp,
                )
            else:
                acc_vals, acc_valid, total = _index_join_impl(
                    lv_full, lm_full, ks, perm, targets, keys[i],
                    pairs, sig.terms[i].var_cols, extra, jc,
                )
            exch_stats.append(jnp.int32(0))
            join_totals.append(
                lax.pmax(total, SHARD_AXIS)
            )
            if n < len(positives) - 2:
                global_n = lax.psum(
                    acc_valid.sum(dtype=jnp.int32), SHARD_AXIS
                )
                reseed = reseed | (global_n == 0)
            continue
        rv, rm = tables[i]
        join_impl = (
            partial(_kernels.join_tables_impl, interpret=_interp)
            if use_k
            else _join_tables_impl
        )
        if q == 0:
            # broadcast-right: ONE tiled all_gather of the small side
            # (validity packed as an extra column)
            rv_full, rm_full = _gather_packed(rv, rm)
            acc_vals, acc_valid, total = join_impl(
                acc_vals, acc_valid, rv_full, rm_full,
                pairs, extra, jc,
            )
            exch_stats.append(jnp.int32(0))
        else:
            # hash-partitioned: co-locate equal keys, join locally
            lcols = tuple(lc for lc, _ in pairs)
            rcols = tuple(rc for _, rc in pairs)
            lv2, lm2, l_occ = _repartition(
                acc_vals, acc_valid, lcols, _SENTINEL_L, S, q
            )
            rv2, rm2, r_occ = _repartition(rv, rm, rcols, _SENTINEL_R, S, q)
            acc_vals, acc_valid, total = join_impl(
                lv2, lm2, rv2, rm2, pairs, extra, jc
            )
            exch_stats.append(
                lax.pmax(jnp.maximum(l_occ, r_occ), SHARD_AXIS)
            )
        join_totals.append(lax.pmax(total, SHARD_AXIS))
        if n < len(positives) - 2:
            global_n = lax.psum(
                acc_valid.sum(dtype=jnp.int32), SHARD_AXIS
            )
            reseed = reseed | (global_n == 0)

    for i, pairs in anti_meta:
        rv, rm = tables[i]
        rv_full, rm_full = _gather_packed(rv, rm)
        if use_k:
            acc_valid = _kernels.anti_join_impl(
                acc_vals, acc_valid, rv_full, rm_full, pairs,
                interpret=_interp,
            )
        else:
            acc_valid = _anti_join_impl(
                acc_vals, acc_valid, rv_full, rm_full, pairs
            )

    count = _global_count(acc_valid)
    reseed = reseed & ~any_pos_empty
    stats_list = [
        count,
        reseed.astype(jnp.int32),
        any_pos_empty.astype(jnp.int32),
        *term_ranges,
        *join_totals,
        *exch_stats,
    ]
    return acc_vals, acc_valid, stats_list


def build_fused_sharded(sig: ShardedPlanSig, mesh, count_only: bool = False):
    """Lower one sharded plan signature to a single shard_map program.

    Call convention: fn(bucket_arrays, keys, fixed_vals) like
    query/fused.py build_fused, with bucket arrays shaped [S, m(, a)].
    Stats layout (replicated):
      [count, reseed, any_pos_empty,
       *per-term worst shard ranges, *per-join worst shard totals,
       *per-partitioned-join worst destination occupancy]
    The conjunction body itself lives in _trace_sharded_conj (shared
    with the whole-tree mesh program builder).
    """
    _pos, _neg, names, _jm, _am = fold_join_meta(sig.terms)

    def body(bucket_arrays, keys, fixed_vals):
        acc_vals, acc_valid, stats_list = _trace_sharded_conj(
            sig, bucket_arrays, keys, fixed_vals
        )
        stats = jnp.stack(stats_list)
        if count_only:
            return stats
        return acc_vals[None], acc_valid[None], stats

    spec = P(SHARD_AXIS)
    n_terms = len(sig.terms)
    in_specs = (
        tuple(tuple(spec for _ in range(4)) for _ in range(n_terms)),
        tuple(P() for _ in range(n_terms)),
        tuple(P() for _ in range(n_terms)),
    )
    out_specs = P() if count_only else (spec, spec, P())
    fn = shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    return fn, names


@dataclass(frozen=True)
class ShardedTreeSig:
    """Shape-static description of ONE whole-tree fused MESH program
    (ISSUE 10) — the sharded twin of query/fused.py FusedTreeSig.
    Nested ShardedPlanSigs carry per-site per-shard capacities,
    collective choices and kernel routing, so cache-key honesty is
    inherited (daslint DL002)."""

    sites: Tuple[ShardedPlanSig, ...]
    neg: Optional[ShardedPlanSig] = None


def build_sharded_tree_fused(sig: ShardedTreeSig, mesh, count_only: bool = False):
    """Lower a whole Or/negation plan tree to ONE shard_map program:
    every conjunction site traces via _trace_sharded_conj (shard-local
    bodies, declared collectives), the positive branches union with a
    per-shard concat + SHARD-LOCAL dedup, and the optional negative
    branch anti-joins the gathered union on all columns.

    Shard-local dedup is deliberate (the sharded_tree.py ShardedTreeOps
    rule): cross-shard duplicate assignments — possible when two Or
    branches ground the same answer through links living on different
    shards — survive on device and are removed by the host
    assignment-set identity at materialization, which establishes
    reference-exact dedup semantics anyway.  The difference branch DOES
    gather the union whole first (one packed all_gather): a negative
    row must be removed on whichever shard it lives, not only where its
    union twin happens to live.  The replicated final count therefore
    upper-bounds the distinct answer count (matched verdicts only need
    count > 0 per site, which psum reports exactly).

    Call convention: fn(*site_inputs), one (bucket_arrays, keys,
    fixed_vals) triple per positive site then one for the negative
    site.  Stats layout: [final_count, *site_0_block, ..., *neg_block]
    with each block exactly build_fused_sharded's stats vector."""
    out_names = canonical_tree_names(sig.sites[0].terms)
    K = len(out_names)
    perms = []
    for ssig in sig.sites + ((sig.neg,) if sig.neg is not None else ()):
        _p, _n, names, _jm, _am = fold_join_meta(ssig.terms)
        assert tuple(sorted(names)) == out_names, (
            "tree fusion requires one shared variable universe"
        )
        perms.append(tuple(names.index(v) for v in out_names))

    def body(*site_inputs):
        blocks = []
        parts = []
        for i, ssig in enumerate(sig.sites):
            ba, ks, fv = site_inputs[i]
            v, m, sl = _trace_sharded_conj(ssig, ba, ks, fv)
            blocks.append(sl)
            parts.append((v[:, jnp.asarray(perms[i], dtype=jnp.int32)], m))
        union_vals = jnp.concatenate([v for v, _ in parts], axis=0)
        union_valid = jnp.concatenate([m for _, m in parts], axis=0)
        if sig.neg is not None:
            ba, ks, fv = site_inputs[len(sig.sites)]
            nv, nm, nsl = _trace_sharded_conj(sig.neg, ba, ks, fv)
            blocks.append(nsl)
            nv = nv[:, jnp.asarray(perms[-1], dtype=jnp.int32)]
            # replicate the minus side (tree.py difference() contract);
            # the union is only a membership set here — duplicates are
            # harmless, so the raw concat gathers without a dedup sort
            uv_full, um_full = _gather_packed(union_vals, union_valid)
            all_pairs = tuple((c, c) for c in range(K))
            nm = _anti_join_impl(nv, nm, uv_full, um_full, all_pairs)
            out_vals, out_valid = nv, nm
        else:
            # shard-local dedup only (module docstring): cross-shard
            # duplicates die in the host assignment set
            out_vals, out_valid, _local = _dedup_table_impl(
                union_vals, union_valid
            )
        count = _global_count(out_valid)
        stats = jnp.stack(
            [count] + [s for block in blocks for s in block]
        )
        if count_only:
            return stats
        return out_vals[None], out_valid[None], stats

    spec = P(SHARD_AXIS)
    in_specs = tuple(
        (
            tuple(tuple(spec for _ in range(4)) for _ in ssig.terms),
            tuple(P() for _ in ssig.terms),
            tuple(P() for _ in ssig.terms),
        )
        for ssig in sig.sites + ((sig.neg,) if sig.neg is not None else ())
    )
    out_specs = P() if count_only else (spec, spec, P())
    fn = shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    return fn, out_names


class ShardedFusedExecutor:
    """Per-database cache of compiled sharded plan programs with capacity
    learning — the mesh counterpart of query/fused.py FusedExecutor."""

    def __init__(self, db):
        self.db = db
        self.mesh = db.mesh
        self.n_shards = int(db.mesh.devices.size)
        self.broadcast_limit = BROADCAST_LIMIT
        self._cache: Dict[Tuple, Tuple] = {}
        self._caps: Dict[Tuple, Tuple] = {}
        #: answered-result cache, delta-version guarded (query/fused.py
        #: ResultCache).  The mesh serving path (sharded_db
        #: _run_conjunctive) opts in with execute(use_cache=True); the
        #: incremental-commit counter (sharded_db.refresh ->
        #: storage/delta.py) invalidates on commit, and a FULL
        #: re-partition replaces db.tables and with it this executor.
        self.results = ResultCache(db)
        #: tree-composite cache (query/tree.py) — same version guard,
        #: dropped wholesale with this executor on a full re-partition
        self.tree_results = ResultCache(db)
        #: whole-tree fused mesh programs (ISSUE 10): ShardedTreeSig ->
        #: (jitted fn, names); bounded in _ShardedTreeExecJob.dispatch
        self._tree_progs: Dict[ShardedTreeSig, Tuple] = {}

    # -- plan mapping ------------------------------------------------------

    def _term_args(self, plan):
        sb = self.db.tables.buckets.get(plan.arity)
        if sb is None:
            return None
        if plan.ctype is not None:
            route, p0, extra = ROUTE_CTYPE, -1, ()
            arrays = (sb.key_ctype, sb.order_by_ctype, sb.targets, sb.type_id)
            key = np.int64(plan.ctype)
        elif plan.type_id is not None and plan.fixed:
            p0, v0 = plan.fixed[0]
            route, extra = ROUTE_TYPE_POS, tuple(p for p, _ in plan.fixed[1:])
            arrays = (
                sb.key_type_pos[p0], sb.order_by_type_pos[p0],
                sb.targets, sb.type_id,
            )
            key = np.int64((np.int64(plan.type_id) << 32) | np.int64(v0))
        else:
            assert plan.type_id is not None, "TermPlan without type or ctype"
            route, p0, extra = ROUTE_TYPE, -1, ()
            # the sharded type index stores int64 keys
            arrays = (sb.key_type, sb.order_by_type, sb.targets, sb.type_id)
            key = np.int64(plan.type_id)
        fixed_vals = np.asarray(
            [v for _, v in plan.fixed[1:]] if route == ROUTE_TYPE_POS else [],
            dtype=np.int32,
        )
        sig = FusedTermSig(
            arity=plan.arity,
            route=route,
            p0=p0,
            extra_fixed=extra,
            var_cols=plan.var_cols,
            eq_pairs=plan.eq_pairs,
            var_names=plan.var_names,
            negated=plan.negated,
        )
        return sig, arrays, key, fixed_vals

    def _estimate(self, plan) -> int:
        # shared with the single-device executor; sums the base bucket and
        # any incremental-commit overlay segments (sharded_db.refresh)
        return estimate_plan_rows(self.db, plan)

    def _shard_cap(self, global_est: int) -> int:
        """Per-shard probe capacity: even split plus 2x skew headroom
        (slabs are round-robin, so type/pattern ranges spread evenly; the
        headroom plus overflow retry covers hub-heavy skew)."""
        per = -(-max(global_est, 1) // self.n_shards)
        return _pow2_at_least(2 * per)

    # -- execution ---------------------------------------------------------

    def _exec_job(self, plans, count_only: bool) -> Optional["_ShardedExecJob"]:
        """Prepare one mesh execution's state (ordering, term args,
        capacity seeds incl. the per-join collective choice).  None when a
        bucket is missing or the merged caps exceed the configured ceiling
        — the caller falls back to the staged mesh path, as before.

        The cost-based planner hook mirrors the single-device executor
        (query/fused.py _exec_job): behind DasConfig.use_planner it fixes
        join order and PER-SHARD capacity seeds from the same host-side
        degree statistics (the mesh store exposes identical
        host_bucket_segments)."""
        from das_tpu import planner as _planner

        planned = (
            _planner.plan_conjunction(self.db, plans, n_shards=self.n_shards)
            if _planner.enabled(self.db.config) else None
        )
        # k-way multiway prefix (query/fused.py _exec_job mirror):
        # join_caps[0]/exch_caps[0] then belong to the multiway step
        mw = planned.multiway if planned is not None else 0
        if planned is not None:
            ordered = [plans[i] for i in planned.order]
        else:
            ordered = order_plans(plans, self._estimate)
        same_order = same_positive_order(ordered, plans)
        plans = ordered
        mapped = []
        for plan in plans:
            m = self._term_args(plan)
            if m is None:
                return None
            mapped.append(m)
        sigs = tuple(m[0] for m in mapped)
        arrays = tuple(m[1] for m in mapped)
        keys = tuple(m[2] for m in mapped)
        fvals = tuple(m[3] for m in mapped)

        cfg = self.db.config
        ests = [self._estimate(p) for p in plans]
        term_caps = tuple(self._shard_cap(e) for e in ests)
        index_joins, index_right, arrays, term_caps = apply_index_joins(
            self.db.tables.buckets, sigs, arrays, term_caps,
            start_join=max(0, mw - 1),
        )
        positives = [p for p in plans if not p.negated]
        n_joins = (
            (len(positives) - mw + 1) if mw else max(0, len(positives) - 1)
        )
        grounded = [
            e for p, e in zip(plans, ests)
            if p.fixed and p.ctype is None and not p.negated
        ]
        if grounded:
            # the estimator's row bound rides below the configured clamp
            # (query/fused.py _join_cap_seed): an operator-shrunk
            # initial_result_capacity must not seed under the exact
            # grounded row counts — that is a guaranteed retry round
            mg = max(grounded)
            jcap0 = _pow2_at_least(
                max(64, min(cfg.initial_result_capacity, 4 * mg), mg)
            )
        else:
            jcap0 = _pow2_at_least(
                max(cfg.initial_result_capacity // self.n_shards, *term_caps)
            )
        if planned is not None and len(planned.join_cap_seeds) == n_joins:
            join_caps = planned.join_cap_seeds  # per-shard costed seeds
        else:
            join_caps = tuple([jcap0] * n_joins)
        # static per-STEP collective choice: the multiway step (when
        # routed) broadcasts its tail tables (slot 0); index-joinable
        # right sides broadcast the LEFT instead (one collective,
        # nothing materialized); otherwise broadcast the right when its
        # whole table fits the budget, else hash-partition
        pos_sig_idx = [i for i, s in enumerate(sigs) if not s.negated]
        exch_caps = [0] if mw else []
        # the step's index-join slot aligns with index_joins[t] (tail
        # joins only); ij_of maps a step slot back to it for the
        # learned-caps merge below
        ij_of = ([-1] if mw else []) + list(index_joins)
        for t in range(len(index_joins)):
            if index_joins[t] >= 0:
                exch_caps.append(0)
                continue
            right_cap = term_caps[
                pos_sig_idx[(mw if mw else 1) + t]
            ]
            if right_cap * self.n_shards <= self.broadcast_limit:
                exch_caps.append(0)
            else:
                exch_caps.append(_pow2_at_least(2 * max(jcap0 // self.n_shards, 16)))
        exch_caps = tuple(exch_caps)
        learned = self._caps.get(sigs)
        # length guard (query/fused.py _learned_caps rationale): caps
        # learned on the binary-chain route must not zip-truncate into
        # the multiway route's per-step layout, or vice versa
        if learned is not None and (
            len(learned[0]) != len(term_caps)
            or len(learned[1]) != len(join_caps)
            or len(learned[2]) != len(exch_caps)
        ):
            learned = None
        if learned is not None:
            term_caps = clamp_index_terms(
                tuple(max(a, b) for a, b in zip(term_caps, learned[0])),
                index_right,
            )
            join_caps = tuple(max(a, b) for a, b in zip(join_caps, learned[1]))
            exch_caps = tuple(
                (0 if b == 0 or n_ij >= 0 else max(a, b))
                for (a, b), n_ij in zip(zip(exch_caps, learned[2]), ij_of)
            )
        if max(term_caps + join_caps, default=0) > cfg.max_result_capacity:
            return None
        from das_tpu import kernels

        # counted only once the job exists (query/fused.py _exec_job):
        # declines run the staged mesh fallback under legacy accounting
        if planned is not None:
            _planner.record_planned(planned)
        else:
            _planner.PLANNER_COUNTS["greedy"] += 1
        return _ShardedExecJob(
            self, count_only, same_order, sigs, arrays, keys, fvals,
            term_caps, join_caps, exch_caps, index_joins,
            use_kernels=kernels.enabled(cfg), planned=planned,
            multiway=mw,
        )

    def execute(
        self, plans, count_only: bool = False, use_cache: bool = False
    ) -> Optional[ShardedFusedResult]:
        """use_cache mirrors the single-device executor's contract: the
        serving path (sharded_db._run_conjunctive) opts in; the bare call
        stays uncached so repeated-execute measurements (the mesh scaling
        bench) keep timing the shard_map program, not a dict lookup."""
        if use_cache:
            cache_key = self.results.key(plans, count_only)
            hit = self.results.get(cache_key)
            if hit is not None:
                return hit
            cache_version = self.results.version()
        job = self._exec_job(plans, count_only)
        if job is None:
            return None
        from das_tpu.query.fused import FETCH_COUNTS

        while True:
            out = job.dispatch()
            FETCH_COUNTS["n"] += 1
            if job.settle(jax.device_get(out), out):
                if use_cache:
                    self.results.put(cache_key, job.result, cache_version)
                return job.result

    def dispatch_many(self, plans_lists, count_only: bool = False,
                      cache_only: bool = False):
        """Serving-pipeline phase 1 on the mesh (query/fused.py
        dispatch_many contract): resolve result-cache hits, dedup
        identical in-batch queries, and ENQUEUE each remaining job's first
        shard_map round — asynchronous, no host transfer.  The mesh
        executes this batch while the coalescer settles the previous one
        (the pipeline_depth window now covers mesh tenants too).  With
        cache_only (degraded-mode serving, ISSUE 13 breaker) no shard_map
        program is enqueued: hits answer, misses decline."""
        return dispatch_pending(
            self.results, self._exec_job, plans_lists, count_only,
            cache_only=cache_only,
        )

    def settle_many(self, pending) -> List[Optional[ShardedFusedResult]]:
        """Phase 2: one host transfer per retry round, per-job verdicts,
        version-guarded cache inserts — the shared settle loop
        (query/fused.py settle_pending)."""
        return settle_pending(self.results, pending)

    def settle_many_iter(self, pending):
        """Streaming phase 2 (ISSUE 6): yields (index, ShardedFusedResult)
        as each query's verdict lands — the shared streaming settle loop
        (query/fused.py settle_pending_iter), so mesh tenants' first rows
        reach their clients one RTT after their own dispatch too."""
        return settle_pending_iter(self.results, pending)

    def execute_many(
        self, plans_lists, count_only: bool = False
    ) -> List[Optional[ShardedFusedResult]]:
        return self.settle_many(self.dispatch_many(plans_lists, count_only))

    def tree_exec_job(self, pos_sites, neg_plans=None):
        """Prepare one whole-tree mesh execution (ISSUE 10) — the
        shared query/fused.py prepare_tree_job with the sharded job
        class (per-shard capacities and collective choices ride each
        site's _ShardedExecJob)."""
        return prepare_tree_job(
            self, pos_sites, neg_plans, _ShardedTreeExecJob
        )

    def execute_tree(self, pos_sites, neg_plans=None):
        """Run a whole Or/negation tree as ONE shard_map program (retry
        loop included) — the mesh twin of query/fused.py execute_tree,
        driven by the shared run_tree_job loop."""
        job = self.tree_exec_job(pos_sites, neg_plans)
        if job is None:
            return None
        return run_tree_job(job)


class _ShardedExecJob:
    """One mesh execute()'s mutable state, split into dispatch / settle
    halves (the query/fused.py _ExecJob idiom) so the coalescer can keep
    pipeline_depth sharded batches in flight.  Semantics are exactly the
    old synchronous execute(): same program cache, same capacity retry
    (term / join / exchange-slot), same reseed verdict, same cap
    learning."""

    __slots__ = (
        "ex", "count_only", "same_order", "sigs", "arrays", "keys", "fvals",
        "term_caps", "join_caps", "exch_caps", "index_joins", "use_kernels",
        "names", "result", "planned", "rounds", "last_ranges",
        "last_join_rows", "multiway", "count_route",
    )

    def __init__(
        self, ex, count_only, same_order, sigs, arrays, keys, fvals,
        term_caps, join_caps, exch_caps, index_joins, use_kernels=False,
        planned=None, multiway=0,
    ):
        self.ex = ex
        self.count_only = count_only
        self.same_order = same_order
        self.sigs = sigs
        self.arrays = arrays
        self.keys = keys
        self.fvals = fvals
        self.term_caps = term_caps
        self.join_caps = join_caps
        self.exch_caps = exch_caps
        self.index_joins = index_joins
        self.use_kernels = use_kernels
        self.names = None
        self.result: Optional[ShardedFusedResult] = None
        #: PlannedProgram that ordered/seeded this job (query/fused.py
        #: _ExecJob mirror); settle feeds estimates to planner telemetry
        self.planned = planned
        #: leading positives fused into one shard-local k-way step
        self.multiway = multiway
        self.rounds = 0
        self.last_ranges = None
        self.last_join_rows = None
        #: False for SITE jobs inside a whole-tree program — the tree
        #: job owns the per-answer route count (query/fused.py _ExecJob)
        self.count_route = True

    def plan_sig(self) -> ShardedPlanSig:
        """The sharded plan signature at the CURRENT capacities.  Kernel
        eligibility re-derives per round through the BYTES planner
        (query/fused.py kernel_program_plan): the per-shard slab shapes
        plus the COMBINED in-kernel footprint of every stage — the
        gathered right side of a broadcast join is S×cap rows next to the
        local accumulator, a hash-partitioned join holds S×q on both
        sides, an index join gathers the small left to S×cap — decide
        single-block / grid-chunked / lowered; a capacity retry that
        overflows the budget re-plans tiled before falling back.
        Shared by dispatch() and the whole-tree mesh job
        (_ShardedTreeExecJob)."""
        from das_tpu.kernels import budget
        from das_tpu.query.fused import kernel_program_plan

        ex = self.ex
        route = budget.ROUTE_LOWERED
        if self.use_kernels:
            # per-shard slab sizes: bucket arrays are [S, m(, a)]-shaped
            route = kernel_program_plan(
                self.sigs,
                tuple(
                    (a[0].shape[1], a[2].shape[1]) for a in self.arrays
                ),
                self.term_caps, self.join_caps, self.index_joins,
                n_shards=ex.n_shards, exch_caps=self.exch_caps,
                multiway=self.multiway,
            )
        use_k = route != budget.ROUTE_LOWERED
        tiled = route == budget.ROUTE_TILED
        return ShardedPlanSig(
            self.sigs, self.term_caps, self.join_caps, self.exch_caps,
            ex.n_shards, self.index_joins, use_k, tiled,
            budget.vmem_budget() if use_k else 0,
            self.planned is not None, self.multiway,
        )

    def dispatch(self):
        """Queue the shard_map program at the current capacities
        (async, no sync)."""
        from das_tpu.kernels import record_dispatch

        ex = self.ex
        plan_sig = self.plan_sig()
        use_k, tiled = plan_sig.use_kernels, plan_sig.tiled
        entry = ex._cache.get((plan_sig, self.count_only))
        if entry is None:
            fn, out_names = build_fused_sharded(
                plan_sig, ex.mesh, self.count_only
            )
            # program ledger (ISSUE 14): identity when DAS_TPU_PROFLOG
            # is off; the mesh program's compile/cost/memory record
            # keys on the sharded plan-sig digest like the single-device
            # twin (host-side bookkeeping only — dispatch stays
            # sync-free, DL001/DL010)
            entry = (
                obs.proflog.instrument(
                    "sharded",
                    obs.proflog.sig_digest(plan_sig, self.count_only),
                    jax.jit(fn),
                    model_bytes=partial(program_model_bytes, plan_sig),
                ),
                out_names,
            )
            ex._cache[(plan_sig, self.count_only)] = entry
        fn, self.names = entry
        self.rounds += 1
        if plan_sig.planned:
            from das_tpu.planner import PLANNER_COUNTS

            PLANNER_COUNTS["programs"] += 1
        record_dispatch("sharded")
        if use_k:
            record_dispatch("sharded_kernel")
            if tiled:
                record_dispatch("sharded_kernel_tiled")
        if self.multiway:
            record_dispatch("sharded_multiway")
        # mesh twin of _ExecJob.dispatch's trace span: same vocabulary,
        # same sync-free discipline (DL001/DL010), sharded route names
        sp = obs.NOOP_SPAN
        if obs.enabled():
            route = "sharded"
            if self.multiway:
                route = "sharded_multiway"
            elif use_k:
                route = "sharded_kernel"
            sp = obs.span(
                "exec.dispatch", route=route, round=self.rounds,
                count_only=self.count_only,
                est_join_rows=(
                    list(self.planned.est_join_rows)
                    if self.planned is not None else None
                ),
            )
        with sp, obs.annotation("exec.dispatch"):
            return fn(self.arrays, self.keys, self.fvals)

    def settle(self, host_out, dev_out) -> bool:
        """Consume one round's fetched stats.  True = finished (result
        set; None result = capacity ceiling — caller falls back to the
        staged mesh path as before); False = capacities grew, dispatch
        again."""
        if self.count_only:
            vals = valid = host_vals = host_valid = None
            stats = np.asarray(host_out)
        else:
            # ONE host transfer carried the row-sharded binding table and
            # the stats; device refs stay alongside for callers that keep
            # joining on device (the mesh tree executor's conj leaves)
            host_vals, host_valid, stats = host_out
            vals, valid, _ = dev_out
        n_terms = len(self.sigs)
        n_joins = len(self.join_caps)
        count, reseed = int(stats[0]), bool(stats[1])
        pos_empty = bool(stats[2])
        ranges = stats[3 : 3 + n_terms]
        jtotals = stats[3 + n_terms : 3 + n_terms + n_joins]
        eoccs = stats[3 + n_terms + n_joins :]
        new_tc = tuple(
            _pow2_at_least(int(r)) if int(r) > c else c
            for r, c in zip(ranges, self.term_caps)
        )
        new_jc = tuple(
            _pow2_at_least(int(t)) if int(t) > c else c
            for t, c in zip(jtotals, self.join_caps)
        )
        new_ec = tuple(
            (0 if c == 0 else (_pow2_at_least(int(o)) if int(o) > c else c))
            for o, c in zip(eoccs, self.exch_caps)
        )
        if (new_tc, new_jc, new_ec) != (
            self.term_caps, self.join_caps, self.exch_caps
        ):
            if (
                max(new_tc + new_jc + new_ec, default=0)
                > self.ex.db.config.max_result_capacity
            ):
                return True  # staged mesh path owns overflow policy
            self.term_caps, self.join_caps, self.exch_caps = (
                new_tc, new_jc, new_ec
            )
            return False
        remember_caps(
            self.ex._caps, (self.ex._cache,), self.sigs,
            (self.term_caps, self.join_caps, self.exch_caps),
            lambda ps: (ps.term_caps, ps.join_caps, ps.exch_caps),
        )
        self.last_ranges = [int(r) for r in ranges]
        self.last_join_rows = [int(t) for t in jtotals]
        if self.planned is not None:
            from das_tpu.planner import observe_settle

            observe_settle(
                self.planned, self.last_join_rows, self.rounds,
                shards=self.ex.n_shards,
            )
        n_positive = sum(1 for s in self.sigs if not s.negated)
        self.result = ShardedFusedResult(
            var_names=self.names,
            vals=vals,
            valid=valid,
            count=count,
            reseed_needed=reseed
            or (
                count == 0
                and n_positive > 1
                and not pos_empty
                and not self.same_order
            ),
            host_vals=host_vals,
            host_valid=host_valid,
            multiway=bool(self.multiway),
        )
        if self.multiway and self.count_route:
            # per-ANSWER route telemetry (query/fused.py settle mirror;
            # tree site jobs stay silent — count_route False)
            from das_tpu.query.compiler import ROUTE_COUNTS

            ROUTE_COUNTS["sharded_multiway"] += 1
        return True


class _ShardedTreeExecJob(_TreeExecJob):
    """One whole-tree MESH execution's mutable state (ISSUE 10): the
    query/fused.py _TreeExecJob base with the executor-specific hooks
    overridden — sharded tree signature/builder, the per-site block
    length (exchange occupancies appended), the row-sharded result
    class, and the sharded counter-key literals (DL004 pins counting
    sites as declared-key literals, so the thin dispatch/settle
    wrappers stay per-class)."""

    __slots__ = ()

    def tree_sig(self) -> ShardedTreeSig:
        return ShardedTreeSig(
            tuple(j.plan_sig() for j in self.site_jobs),
            self.neg_job.plan_sig() if self.neg_job is not None else None,
        )

    def _build(self, tree_sig):
        fn, out_names = build_sharded_tree_fused(tree_sig, self.ex.mesh)
        return obs.proflog.instrument(
            "sharded_tree", obs.proflog.sig_digest(tree_sig, False),
            jax.jit(fn), model_bytes=partial(tree_model_bytes, tree_sig),
        ), out_names

    def _blk_len(self, j) -> int:
        return conj_stats_len(
            len(j.sigs), len(j.join_caps)
        ) + len(j.exch_caps)

    def _make_result(self, vals, valid, count, host_vals, host_valid):
        return ShardedFusedResult(
            var_names=self.names,
            vals=vals,
            valid=valid,
            count=count,
            reseed_needed=False,
            host_vals=host_vals,
            host_valid=host_valid,
        )

    def dispatch(self):
        """Queue the whole-tree shard_map program (async, no sync)."""
        from das_tpu.kernels import record_dispatch

        record_dispatch("sharded_tree_fused")
        sp = obs.NOOP_SPAN
        if obs.enabled():
            sp = obs.span("exec.dispatch", route="sharded_tree_fused",
                          sites=len(self.site_jobs))
        with sp, obs.annotation("exec.dispatch"):
            return self._dispatch_common()

    def settle(self, host_out, dev_out) -> bool:
        done = self._settle_common(host_out, dev_out)
        if done and self.result is not None:
            from das_tpu.query.compiler import ROUTE_COUNTS

            ROUTE_COUNTS["sharded_tree_fused"] += 1
        return done


def get_sharded_executor(db) -> ShardedFusedExecutor:
    ex = getattr(db.tables, "_fused_executor", None)
    if ex is None or ex.db is not db:
        ex = ShardedFusedExecutor(db)
        db.tables._fused_executor = ex
    return ex
