"""Mesh execution of the generalized query tree (VERDICT r02 item 5).

`ShardedTreeOps` plugs into the tree evaluator's op layer
(das_tpu/query/tree.py `TreeOps`): the SAME evaluator — join condition
matrix, union/difference, negation filtering, the reseed quirk — runs with
every CTable's rows sharded across the mesh, so unordered (Set/Similarity)
links and negation trees execute on all chips instead of a replicated
single-chip tree copy (the round-2 design,
parallel/sharded_db.py:596-631).

Representation: a sharded CTable holds GLOBAL jax.Arrays of shape
[S*cap, k] with `NamedSharding(mesh, P("shards"))` on the row axis — each
shard owns a contiguous [cap, k] block.  Row-wise mask algebra
(ops/composite.py) runs eagerly on these arrays with sharding propagation
(no collectives: every mask is per-row).  Cross-row combinators go through
shard_map:

  * leaf probes  — slab-local searchsorted over the ShardedBucket probe
                   indexes (ZERO communication; each link lives on exactly
                   one shard, so leaf tables have no cross-shard
                   duplicates);
  * join         — broadcast-RIGHT: ONE tiled all_gather of the right
                   (newly-joined) table, then shard-local
                   `_join_tables_impl`.  join_ctables keeps the
                   accumulator on the left, so the gathered side is the
                   per-term table; side selection by size (the
                   fused_sharded strategy) is a future refinement;
  * dedup        — shard-local only.  Cross-shard duplicates (possible
                   after projections) survive on device and are removed by
                   the host assignment-set identity at materialization,
                   which tree.py establishes anyway for reference-exact
                   dedup semantics;
  * anti_join /
    difference   — the tabu side is REPLICATED first (`replicate`: one
                   all_gather), because a row must be removed on whichever
                   shard it lives — shard-local tabu would miss
                   cross-shard twins;
  * counts       — `valid.sum()` on the sharded validity vector (XLA
                   inserts the cross-shard reduction).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from das_tpu.core.exceptions import CapacityOverflowError
from das_tpu.parallel.mesh import SHARD_AXIS, shard_map
from das_tpu.ops import composite as comp_ops
from das_tpu.ops import posting
from das_tpu.ops.join import _anti_join_impl, _dedup_table_impl, _join_tables_impl
from das_tpu.query import compiler as qc
from das_tpu.query.plan import PUTermPlan
from das_tpu.query.tree import CTable, TreeOps, _finish_uterm


class ShardedTreeOps(TreeOps):
    """Mesh implementation of the tree evaluator's op layer."""

    def __init__(self, db):
        super().__init__(db)
        self.mesh = db.mesh
        self.S = db.mesh.devices.size
        #: id(t) -> (t, replicated) — the SOURCE table is kept alive so a
        #: freed id can never be recycled onto a different table (a bare
        #: id-keyed cache silently returned the previous query's rows)
        self._replicated: Dict[int, Tuple[CTable, CTable]] = {}
        #: static-params -> shard_map-wrapped callable; a fresh closure per
        #: call would defeat JAX's function-identity dispatch cache on every
        #: join/dedup/anti/replicate of every query node
        self._fn_cache: Dict[Tuple, object] = {}

    # -- shard_map plumbing ------------------------------------------------

    def _smap(self, fn, n_in, n_out, replicated_in=()):
        spec = P(SHARD_AXIS)
        in_specs = tuple(
            P() if i in replicated_in else spec for i in range(n_in)
        )
        out_specs = tuple(spec for _ in range(n_out))
        return shard_map(
            fn, mesh=self.mesh, in_specs=in_specs,
            out_specs=out_specs if n_out > 1 else out_specs[0],
        )

    def _cached(self, key, build):
        fn = self._fn_cache.get(key)
        if fn is None:
            fn = build()
            self._fn_cache[key] = fn
        return fn

    def _flatten(self, vals, valid):
        """[S, cap, k] / [S, cap] stacked slabs -> [S*cap, k] / [S*cap]
        global row-sharded arrays (pure local reshape, zero comm)."""
        def body(v, m):
            return v.reshape(-1, v.shape[-1]), m.reshape(-1)

        return self._cached(("flatten",), lambda: self._smap(body, 2, 2))(
            vals, valid
        )

    # -- leaves ------------------------------------------------------------

    def run_term(self, plan) -> Optional[CTable]:
        st = self.db._term_table(plan)
        if st is None or st.count == 0:
            return None
        vals, valid = self._flatten(st.vals, st.valid)
        return CTable(
            kind="O",
            onames=st.var_names,
            ocols=tuple(range(len(st.var_names))),
            ugroups=(),
            vals=vals,
            valid=valid,
            count=st.count,
        )

    def run_uterm(self, plan: PUTermPlan) -> Optional[CTable]:
        sb = self.db.tables.buckets.get(plan.arity)
        if sb is None or sb.size == 0:
            return None
        arity = plan.arity
        required = tuple(plan.required)
        probe_type = -1
        if plan.ctype is not None:
            probes = [(sb.key_ctype, sb.order_by_ctype, np.int64(plan.ctype))]
        elif required:
            v0 = required[0][0]
            if plan.type_id is not None:
                probe_type = plan.type_id
                probes = [
                    (sb.key_type_pos[p], sb.order_by_type_pos[p],
                     np.int64((plan.type_id << 32) | v0))
                    for p in range(arity)
                ]
            else:
                probes = [
                    (sb.key_pos[p], sb.order_by_pos[p], np.int64(v0))
                    for p in range(arity)
                ]
        elif plan.type_id is not None:
            probes = [(sb.key_type, sb.order_by_type, np.int64(plan.type_id))]
        else:
            probes = None  # full slab scan
        req_vals = np.asarray(
            [v for v, c in required for _ in range(c)], dtype=np.int32
        )
        k = len(plan.var_names)
        cap = min(
            self.config_cap(), max(sb.m_local * max(1, len(probes or [1])), 16)
        )
        keys = [p[0] for p in (probes or [])]
        perms = [p[1] for p in (probes or [])]
        n_keys = len(keys)
        # per-call DATA rides as traced replicated args; only shape-defining
        # statics key the function cache, so capacity retries and repeated
        # mesh uterm probes of the same shape reuse one compiled program
        pk_arr = np.asarray([p[2] for p in (probes or [])], dtype=np.int64)
        pair_vals = np.asarray([v for v, _ in required], dtype=np.int32)
        pair_cnts = np.asarray([c for _, c in required], dtype=np.int32)
        pt_arr = np.asarray([probe_type], dtype=np.int32)
        n_pairs = len(required)
        n_req = int(req_vals.size)

        def build(cap):
            def body(*args):
                targets, targets_sorted, type_col = args[:3]
                ks = args[3 : 3 + n_keys]
                ps = args[3 + n_keys : 3 + 2 * n_keys]
                pk_a, pv_a, pc_a, rv_a, pt_a = args[3 + 2 * n_keys :]
                t, ts, tc = targets[0], targets_sorted[0], type_col[0]
                if n_keys == 0:
                    m = t.shape[0]
                    local = jnp.arange(m, dtype=jnp.int32)
                    keep = tc != -1
                    worst = jnp.int32(0)
                else:
                    locs, valids, cnts = [], [], []
                    for i in range(n_keys):
                        local, valid, cnt = posting.range_probe(
                            ks[i][0], ps[i][0], pk_a[i], cap
                        )
                        locs.append(local)
                        valids.append(valid)
                        cnts.append(cnt)
                    local = jnp.concatenate(locs)
                    valid = jnp.concatenate(valids)
                    local, keep = posting.dedup_sorted(local, valid)
                    worst = jnp.max(jnp.stack(cnts))
                mask = posting.verify_multiset_traced(
                    t, tc, local, keep, pt_a[0], pv_a, pc_a, n_pairs
                )
                tvals, tmask = comp_ops.build_uterm_table(
                    ts, local, mask, rv_a, n_req, k
                )
                return tvals[None], tmask[None], worst[None]

            n_in = 3 + 2 * n_keys + 5
            return self._smap(
                body, n_in, 3, replicated_in=tuple(range(n_in - 5, n_in))
            )

        while True:
            fn = self._cached(
                ("uterm", arity, n_keys, cap, n_pairs, n_req, k),
                lambda: build(cap),
            )
            vals, mask, worsts = fn(
                sb.targets, sb.targets_sorted, sb.type_id, *keys, *perms,
                pk_arr, pair_vals, pair_cnts, req_vals, pt_arr,
            )
            worst = int(np.max(np.asarray(worsts)))
            if worst <= cap:
                break
            if cap >= self.db.config.max_result_capacity:
                raise CapacityOverflowError(
                    f"uterm probe needs {worst} rows > max_result_capacity"
                )
            cap = min(max(cap * 2, worst), self.db.config.max_result_capacity)

        vals, mask = self._flatten(vals, mask)
        return _finish_uterm(self, plan, vals, mask)

    def config_cap(self) -> int:
        return self.db.config.initial_result_capacity

    def conj(self, plans) -> Optional[CTable]:
        st = self.db._run_conjunctive(plans)
        if st is None or st.count == 0:
            return None
        vals, valid = self._flatten(st.vals, st.valid)
        return CTable(
            kind="O",
            onames=st.var_names,
            ocols=tuple(range(len(st.var_names))),
            ugroups=(),
            vals=vals,
            valid=valid,
            count=st.count,
        )

    # -- table combinators -------------------------------------------------

    @staticmethod
    def _gather_table(v, m):
        """Move one row-sharded table whole to every shard in ONE tiled
        all_gather (validity packed into the value block)."""
        packed = jnp.concatenate([v, m[:, None].astype(v.dtype)], axis=1)
        full = jax.lax.all_gather(packed, SHARD_AXIS, tiled=True)
        return full[:, :-1], full[:, -1] != 0

    def _join_fn(self, pairs, extra, cap, gather_left=False, perm=None):
        """Traceable mesh join.  Default broadcast-RIGHT: gather the right
        table, join shard-locally against the left shards.  With
        gather_left, roles swap (the caller supplies swapped pairs/extras
        and the output-column permutation restoring the canonical
        layout)."""

        def build():
            def body(lv, lm, rv, rm):
                if gather_left:
                    av_full, am_full = self._gather_table(lv, lm)
                    vals, valid, total = _join_tables_impl(
                        rv, rm, av_full, am_full, pairs, extra, cap
                    )
                else:
                    rv_full, rm_full = self._gather_table(rv, rm)
                    vals, valid, total = _join_tables_impl(
                        lv, lm, rv_full, rm_full, pairs, extra, cap
                    )
                if perm is not None:
                    vals = vals[:, perm]
                return vals, valid, total[None]

            return self._smap(body, 4, 3)

        return self._cached(
            ("join", pairs, extra, cap, gather_left,
             None if perm is None else tuple(perm)),
            build,
        )

    def _swapped_join_fn(self, pairs, extra, cap, n_a, n_b):
        """Broadcast-LEFT variant for when the accumulator is the smaller
        table: gather `a`, keep `b` row-sharded as the local side, then
        permute the joined columns back to the canonical
        [a-cols..., b-extras...] layout join_ctables expects.  Every a
        column is either a join key (equal to b's paired column) or
        carried as a right-extra, so the permutation is total."""
        pairs_sw = tuple((bc, ac) for ac, bc in pairs)
        shared_a = {ac: bc for ac, bc in pairs}
        a_extra = tuple(c for c in range(n_a) if c not in shared_a)
        perm = []
        for c in range(n_a):
            if c in shared_a:
                perm.append(shared_a[c])          # == b's paired column
            else:
                perm.append(n_b + a_extra.index(c))
        perm.extend(extra)                         # b extras keep b positions
        return self._join_fn(
            pairs_sw, a_extra, cap, gather_left=True,
            perm=np.asarray(perm, dtype=np.int32),
        )

    def join_tables(self, av, am, bv, bm, pairs, extra, cap, counts=None):
        if counts is not None and counts[0] < counts[1]:
            # accumulator is smaller: broadcast IT and join on b's shards
            fn = self._swapped_join_fn(
                pairs, extra, cap, av.shape[1], bv.shape[1]
            )
            vals, valid, totals = fn(av, am, bv, bm)
        else:
            vals, valid, totals = self._join_fn(pairs, extra, cap)(av, am, bv, bm)
        return vals, valid, int(np.max(np.asarray(totals)))

    def dedup(self, vals, valid):
        def body(v, m):
            s, keep, cnt = _dedup_table_impl(v, m)
            return s, keep, cnt[None]

        fn = self._cached(("dedup",), lambda: self._smap(body, 2, 3))
        vals, keep, counts = fn(vals, valid)
        return vals, keep, int(np.asarray(counts).sum())

    def _anti_fn(self, pairs):
        """Traceable mesh anti-join: the tabu side arrives REPLICATED
        (difference/apply_forbidden call replicate() first), so removal is
        purely shard-local — zero collectives."""

        def build():
            def body(v, m, tabu_v, tabu_m):
                return _anti_join_impl(v, m, tabu_v, tabu_m, pairs)

            return self._smap(body, 4, 1, replicated_in=(2, 3))

        return self._cached(("anti", pairs), build)

    def anti_join(self, lv, lm, rv, rm, pairs):
        return self._anti_fn(pairs)(lv, lm, rv, rm)

    def concat(self, parts):
        def body(*arrs):
            n = len(arrs) // 2
            return (
                jnp.concatenate(arrs[:n], axis=0),
                jnp.concatenate(arrs[n:], axis=0),
            )

        flat = [v for v, _ in parts] + [m for _, m in parts]
        fn = self._cached(
            ("concat", len(flat)), lambda: self._smap(body, len(flat), 2)
        )
        return fn(*flat)

    def _replicate_fn(self):
        def build():
            def body(v, m):
                packed = jnp.concatenate(
                    [v, m[:, None].astype(v.dtype)], axis=1
                )
                full = jax.lax.all_gather(packed, SHARD_AXIS, tiled=True)
                return full[:, :-1], full[:, -1] != 0

            spec = P(SHARD_AXIS)
            return shard_map(
                body, mesh=self.mesh, in_specs=(spec, spec),
                out_specs=(P(), P()),
                # tiled all_gather IS replication; the static VMA checker
                # just cannot prove it — outputs are identical per shard
                check_vma=False,
            )

        return self._cached(("replicate",), build)

    def replicate(self, t: CTable) -> CTable:
        cached = self._replicated.get(id(t))
        if cached is not None and cached[0] is t:
            return cached[1]
        vals, valid = self._replicate_fn()(t.vals, t.valid)
        out = CTable(t.kind, t.onames, t.ocols, t.ugroups, vals, valid, t.count)
        if len(self._replicated) > 256:
            self._replicated.clear()
        self._replicated[id(t)] = (t, out)
        return out
