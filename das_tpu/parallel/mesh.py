"""Device mesh helpers.

The reference's distribution substrate is a 3-node Redis cluster sharding
the index keyspace by hash slot (SURVEY.md §2.10 P1).  Here the substrate
is a `jax.sharding.Mesh`: atom-table rows are partitioned over the mesh
axis, probes run shard-local under `shard_map`, and fan-in happens with
XLA collectives over ICI (`all_gather` / `psum`) instead of RESP/TCP
round-trips.  Multi-host pods extend the same mesh over DCN via
`jax.distributed.initialize` — no separate communication backend."""

from __future__ import annotations

import inspect
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

try:  # modern API
    from jax import shard_map as _raw_shard_map  # jax.shard_map is the fn
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _raw_shard_map  # type: ignore

#: the replication-check kwarg was renamed check_rep -> check_vma across
#: jax versions; inspect ONCE which spelling this jax accepts so callers
#: can use the modern name everywhere (a TypeError here used to be a seed
#: failure in parallel/sharded_tree.py's replicate())
_SHARD_MAP_PARAMS = frozenset(inspect.signature(_raw_shard_map).parameters)
_REP_CHECK_KWARGS = ("check_vma", "check_rep")


def shard_map(f, **kwargs):
    """`jax.shard_map` with a version-compat shim for the replication
    checker kwarg: `check_vma`/`check_rep` are translated to whichever
    spelling this jax version supports, or dropped when neither exists
    (the check is an assertion aid, never a semantics change)."""
    for name in _REP_CHECK_KWARGS:
        if name in kwargs and name not in _SHARD_MAP_PARAMS:
            value = kwargs.pop(name)
            other = [k for k in _REP_CHECK_KWARGS if k != name][0]
            if other in _SHARD_MAP_PARAMS:
                kwargs[other] = value
    return _raw_shard_map(f, **kwargs)


SHARD_AXIS = "shards"

#: THE declared set of collective call sites (daslint rule DL009 —
#: shard_map collective discipline): every XLA collective call
#: (all_gather / all_to_all / psum / pmax / pmin / ppermute /
#: psum_scatter) in das_tpu/ must live inside one of these
#: "module.qualname" scopes — lowered mesh helpers whose collective use
#: is the point — and NEVER inside das_tpu/kernels/ (shard-local kernel
#: bodies run under shard_map per shard; a collective there would
#: deadlock or silently change semantics depending on lowering).  The
#: rule pins both directions: an undeclared collective call fails lint,
#: and so does a declared scope that no longer contains one.
COLLECTIVE_SITES = (
    "fused_sharded._repartition",
    "fused_sharded._gather_packed",
    "fused_sharded._global_count",
    "fused_sharded._trace_sharded_conj",
    "sharded_db.ShardedDB._join",
    "sharded_db.ShardedDB._anti_join",
    "sharded_tree.ShardedTreeOps._gather_table",
    "sharded_tree.ShardedTreeOps._replicate_fn",
)


def make_mesh(n_devices: Optional[int] = None, axis_name: str = SHARD_AXIS) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"Requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis_name,))


def row_sharding(mesh: Mesh, axis_name: str = SHARD_AXIS) -> NamedSharding:
    """Shard the leading (shard-stack) dimension over the mesh."""
    return NamedSharding(mesh, PartitionSpec(axis_name))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def multihost_initialize(**kwargs) -> None:
    """Join a multi-host pod (DCN).  Thin veneer over
    `jax.distributed.initialize` so callers stay backend-agnostic."""
    jax.distributed.initialize(**kwargs)
