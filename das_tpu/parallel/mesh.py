"""Device mesh helpers.

The reference's distribution substrate is a 3-node Redis cluster sharding
the index keyspace by hash slot (SURVEY.md §2.10 P1).  Here the substrate
is a `jax.sharding.Mesh`: atom-table rows are partitioned over the mesh
axis, probes run shard-local under `shard_map`, and fan-in happens with
XLA collectives over ICI (`all_gather` / `psum`) instead of RESP/TCP
round-trips.  Multi-host pods extend the same mesh over DCN via
`jax.distributed.initialize` — no separate communication backend."""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

try:  # modern API
    from jax import shard_map as _shard_map_mod

    shard_map = _shard_map_mod  # jax.shard_map is the function itself
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

SHARD_AXIS = "shards"


def make_mesh(n_devices: Optional[int] = None, axis_name: str = SHARD_AXIS) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"Requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis_name,))


def row_sharding(mesh: Mesh, axis_name: str = SHARD_AXIS) -> NamedSharding:
    """Shard the leading (shard-stack) dimension over the mesh."""
    return NamedSharding(mesh, PartitionSpec(axis_name))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def multihost_initialize(**kwargs) -> None:
    """Join a multi-host pod (DCN).  Thin veneer over
    `jax.distributed.initialize` so callers stay backend-agnostic."""
    jax.distributed.initialize(**kwargs)
