"""Mesh-sharded AtomSpace backend.

TPU counterpart of the reference's Redis-cluster hash-slot sharding
(SURVEY.md §2.10 P1): link-bucket rows are partitioned round-robin over the
mesh axis; every shard holds its own slab *plus slab-local sorted probe
indexes*, stacked into ``[n_shards, m_local, ...]`` arrays laid out with
`NamedSharding(P("shards"))` so slab s physically lives on device s.

Query execution (`sharded_execute`) runs the same probe→term-table→join
pipeline as the single-device compiler (query/compiler.py) but under
`shard_map`:

  * term probes are shard-local (no communication at all — the analogue of
    Redis cluster client-side slot routing, except *every* shard probes its
    slab in parallel instead of one client hitting one node);
  * joins are broadcast-right: the smaller right table is `all_gather`ed
    over ICI and joined against the resident left slab, so the accumulated
    table stays row-sharded end to end;
  * counts fan in with `psum`; only the final binding table is pulled to
    the host for (global) dedup + materialization.

The generic DBInterface surface is inherited from MemoryDB — answer-exact
and hardware-free — so this backend is always correct and uses the mesh
for the hot conjunctive path.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from das_tpu.core.config import DasConfig
from das_tpu.core.exceptions import CapacityOverflowError
from das_tpu.ops.join import _anti_join_impl, _join_tables_impl, _build_term_table_impl
from das_tpu.parallel.mesh import SHARD_AXIS, make_mesh, shard_map
from das_tpu.query import compiler as qc
from das_tpu.query.assignment import OrderedAssignment
from das_tpu.query.ast import LogicalExpression, PatternMatchingAnswer
from das_tpu.storage.atom_table import AtomSpaceData, Finalized
from das_tpu.storage.delta import (
    FULL,
    NOOP,
    IncrementalCommitMixin,
    capacity_class,
    delta_class,
    merge_sorted_index,
)
from das_tpu.storage.memory_db import MemoryDB

_I64_MAX = np.int64(2**63 - 1)
_I32_MAX = np.int32(2**31 - 1)


@dataclass
class ShardedBucket:
    """Slab-stacked device arrays, CAPACITY-padded along the local axis:
    m_local includes ~6% slack beyond the largest slab's real rows, so
    incremental commits scatter deltas into the slack with FIXED-shape
    shard_map programs — neither the merge nor cached query executables
    recompile per commit (mirrors storage/tensor_db.py DeviceBucket)."""

    arity: int
    n_shards: int
    m_local: int                   # padded local capacity
    size: int                      # global real (unpadded) row count
    #: per-shard real row counts [S] — host side, drives delta placement
    slab_sizes: np.ndarray
    type_id: jax.Array             # [S, m] int32, pad -1
    ctype: jax.Array               # [S, m] int64
    targets: jax.Array             # [S, m, a] int32, pad -2
    #: canonically sorted target multisets — the unordered (Set/Similarity)
    #: value blocks built by the mesh uterm probes (parallel/sharded_tree.py)
    targets_sorted: jax.Array      # [S, m, a] int32, pad -2
    key_type: jax.Array            # [S, m] int64 sorted, pad I64_MAX
    order_by_type: jax.Array
    key_ctype: jax.Array           # [S, m] int64 sorted, pad I64_MAX
    order_by_ctype: jax.Array
    key_type_pos: List[jax.Array]  # per pos: [S, m] int64 sorted
    order_by_type_pos: List[jax.Array]
    key_pos: List[jax.Array]       # [S, m] int64 sorted
    order_by_pos: List[jax.Array]


def _build_sharded_bucket(b, mesh: Mesh) -> ShardedBucket:
    """Partition one finalized LinkBucket round-robin over the mesh axis
    and build slab-local sorted probe indexes (one stacked [S, m_local]
    array family, physically laid out so slab s lives on device s).
    m_local is capacity-padded (see ShardedBucket)."""
    S = mesh.devices.size
    shard = NamedSharding(mesh, P(SHARD_AXIS))
    arity, m = b.arity, b.size
    m_local = capacity_class(max(1, -(-m // S)))
    slabs = [np.arange(s, m, S, dtype=np.int64) for s in range(S)]

    def padded(build, fill, dtype, extra_shape=()):
        out = np.full((S, m_local, *extra_shape), fill, dtype=dtype)
        for s, rows in enumerate(slabs):
            out[s, : len(rows)] = build(rows)
        return out

    type_id = padded(lambda r: b.type_id[r], -1, np.int32)
    ctype = padded(lambda r: b.ctype[r], _I64_MAX, np.int64)
    targets = padded(lambda r: b.targets[r], -2, np.int32, (arity,))
    targets_sorted = padded(lambda r: b.targets_sorted[r], -2, np.int32, (arity,))

    def sorted_index(keys_of):
        key_arr = np.full((S, m_local), _I64_MAX, dtype=np.int64)
        ord_arr = np.zeros((S, m_local), dtype=np.int32)
        for s, rows in enumerate(slabs):
            k = keys_of(rows).astype(np.int64)
            o = np.argsort(k, kind="stable")
            key_arr[s, : len(rows)] = k[o]
            ord_arr[s, : len(rows)] = o
        return key_arr, ord_arr

    key_type, order_by_type = sorted_index(lambda r: b.type_id[r])
    key_ctype, order_by_ctype = sorted_index(lambda r: b.ctype[r])
    key_type_pos, order_by_type_pos = [], []
    key_pos, order_by_pos = [], []
    for p in range(arity):
        k, o = sorted_index(
            lambda r, p=p: (b.type_id[r].astype(np.int64) << 32)
            | b.targets[r, p].astype(np.int64)
        )
        key_type_pos.append(jax.device_put(k, shard))
        order_by_type_pos.append(jax.device_put(o, shard))
        k2, o2 = sorted_index(lambda r, p=p: b.targets[r, p])
        key_pos.append(jax.device_put(k2, shard))
        order_by_pos.append(jax.device_put(o2, shard))

    return ShardedBucket(
        arity=arity,
        n_shards=S,
        m_local=m_local,
        size=m,
        slab_sizes=np.array([len(r) for r in slabs], dtype=np.int32),
        type_id=jax.device_put(type_id, shard),
        ctype=jax.device_put(ctype, shard),
        targets=jax.device_put(targets, shard),
        targets_sorted=jax.device_put(targets_sorted, shard),
        key_type=jax.device_put(key_type, shard),
        order_by_type=jax.device_put(order_by_type, shard),
        key_ctype=jax.device_put(key_ctype, shard),
        order_by_ctype=jax.device_put(order_by_ctype, shard),
        key_type_pos=key_type_pos,
        order_by_type_pos=order_by_type_pos,
        key_pos=key_pos,
        order_by_pos=order_by_pos,
    )


class SlabCapacityExhausted(Exception):
    """A commit no longer fits the slab slack: time for an early LSM
    compaction (full re-partition) of the sharded store."""


class ShardedTables:
    def __init__(self, fin: Finalized, mesh: Mesh):
        self.mesh = mesh
        self.n_shards = mesh.devices.size
        self.buckets: Dict[int, ShardedBucket] = {
            arity: _build_sharded_bucket(b, mesh)
            for arity, b in fin.buckets.items()
        }
        #: (arity, m_local, dcap) -> compiled fixed-shape merge program
        self._merge_cache: Dict[Tuple, object] = {}
        #: True when restored from a sharded checkpoint (observability/tests)
        self.restored = False

    @classmethod
    def from_buckets(
        cls, buckets: Dict[int, ShardedBucket], mesh: Mesh
    ) -> "ShardedTables":
        """Checkpoint-restore construction (storage/checkpoint.py
        try_restore_sharded): the slabs arrive ready-made — no
        re-partition, no per-slab index rebuild."""
        self = cls.__new__(cls)
        self.mesh = mesh
        self.n_shards = mesh.devices.size
        self.buckets = buckets
        self._merge_cache = {}
        self.restored = True
        return self

    def stage_delta(self, delta):
        """COMPUTE one arity's slab extension by a small commit bucket in
        O(n) device work and O(delta) host<->device traffic -- the mesh
        analogue of TensorDB._stage_delta_merge.  Returns (swap,
        became_base, slots): the merged ShardedBucket only becomes
        visible when the deferred `swap` assignment runs (the
        stage-then-swap commit contract, storage/delta.py _apply_delta
        -- a failure mid-compute, SlabCapacityExhausted included,
        leaves `self.buckets` untouched).

        Delta rows continue the round-robin rotation (delta row j goes to
        shard (size+j) % S) and land in each slab's capacity SLACK (local
        positions slab_sizes[s]..): the stacked array shapes never change,
        so the single shard_map merge program -- slab-local sorted-index
        merges (storage/delta.py merge_sorted_index) plus per-shard column
        inserts at traced offsets -- compiles ONCE per (arity, shape
        class) and every later commit is pure device work.  When the
        slack cannot absorb a commit, SlabCapacityExhausted asks the
        backend for an early LSM compaction (full re-partition).

        Returns (became_base, slots): slots = real delta rows — with
        fixed capacities, memory amplification is structurally bounded by
        the slack itself, so the LSM threshold charges real atoms."""
        arity, d = delta.arity, delta.size
        base = self.buckets.get(arity)
        if base is None or base.size == 0:
            built = _build_sharded_bucket(delta, self.mesh)

            def swap_base():
                self.buckets[arity] = built

            return swap_base, True, d
        S, m_local = self.n_shards, base.m_local
        shard = NamedSharding(self.mesh, P(SHARD_AXIS))
        js = [
            [j for j in range(d) if (base.size + j) % S == s] for s in range(S)
        ]
        worst = max(len(x) for x in js)
        dcap = delta_class(worst)
        if int(base.slab_sizes.max()) + dcap > m_local:
            raise SlabCapacityExhausted(
                f"arity-{arity} slab slack exhausted "
                f"({int(base.slab_sizes.max())}+{dcap} > {m_local})"
            )

        def d_padded(col, fill, dtype, extra_shape=()):
            out = np.full((S, dcap, *extra_shape), fill, dtype=dtype)
            for s, rows in enumerate(js):
                out[s, : len(rows)] = col[rows]
            return jax.device_put(out, shard)

        d_cols = [
            d_padded(delta.type_id, -1, np.int32),
            d_padded(delta.ctype, _I64_MAX, np.int64),
            d_padded(delta.targets, -2, np.int32, (arity,)),
            d_padded(delta.targets_sorted, -2, np.int32, (arity,)),
        ]

        def d_sorted(keys_of):
            key_arr = np.full((S, dcap), _I64_MAX, dtype=np.int64)
            perm_arr = np.zeros((S, dcap), dtype=np.int32)
            for s, rows in enumerate(js):
                k = keys_of(np.array(rows, dtype=np.int64)).astype(np.int64)
                o = np.argsort(k, kind="stable")
                key_arr[s, : len(rows)] = k[o]
                # the i-th delta row of shard s sits at slab_sizes[s] + i
                perm_arr[s, : len(rows)] = base.slab_sizes[s] + o.astype(
                    np.int32
                )
            return jax.device_put(key_arr, shard), jax.device_put(perm_arr, shard)

        idx_pairs = [
            ((base.key_type, base.order_by_type),
             d_sorted(lambda r: delta.type_id[r])),
            ((base.key_ctype, base.order_by_ctype),
             d_sorted(lambda r: delta.ctype[r])),
        ]
        for p in range(arity):
            idx_pairs.append((
                (base.key_type_pos[p], base.order_by_type_pos[p]),
                d_sorted(
                    lambda r, p=p: (delta.type_id[r].astype(np.int64) << 32)
                    | delta.targets[r, p].astype(np.int64)
                ),
            ))
            idx_pairs.append((
                (base.key_pos[p], base.order_by_pos[p]),
                d_sorted(lambda r, p=p: delta.targets[r, p]),
            ))

        fn = self._merge_cache.get((arity, m_local, dcap))
        if fn is None:
            def kernel(base_cols, delta_cols, base_idx, delta_idx, starts):
                s0 = starts[0]
                cols = [
                    jax.lax.dynamic_update_slice_in_dim(
                        b[0], e[0], s0, axis=0
                    )[None]
                    for b, e in zip(base_cols, delta_cols)
                ]
                idx = []
                for (bk, bo), (dk, do) in zip(base_idx, delta_idx):
                    cap = bk.shape[1]
                    k, o = merge_sorted_index(bk[0], bo[0], dk[0], do[0])
                    idx.append((k[:cap][None], o[:cap][None]))
                return cols, idx

            spec = P(SHARD_AXIS)
            fn = jax.jit(shard_map(
                kernel, mesh=self.mesh,
                in_specs=(spec, spec, spec, spec, spec),
                out_specs=(spec, spec),
            ))
            self._merge_cache[(arity, m_local, dcap)] = fn
        base_cols = [base.type_id, base.ctype, base.targets, base.targets_sorted]
        starts = jax.device_put(base.slab_sizes, shard)
        cols, idx = fn(
            base_cols, d_cols,
            [b for b, _ in idx_pairs], [e for _, e in idx_pairs],
            starts,
        )
        merged = ShardedBucket(
            arity=arity,
            n_shards=S,
            m_local=m_local,
            size=base.size + d,
            slab_sizes=base.slab_sizes
            + np.array([len(x) for x in js], dtype=np.int32),
            type_id=cols[0],
            ctype=cols[1],
            targets=cols[2],
            targets_sorted=cols[3],
            key_type=idx[0][0],
            order_by_type=idx[0][1],
            key_ctype=idx[1][0],
            order_by_ctype=idx[1][1],
            key_type_pos=[idx[2 + 2 * p][0] for p in range(arity)],
            order_by_type_pos=[idx[2 + 2 * p][1] for p in range(arity)],
            key_pos=[idx[3 + 2 * p][0] for p in range(arity)],
            order_by_pos=[idx[3 + 2 * p][1] for p in range(arity)],
        )

        def swap():
            self.buckets[arity] = merged

        return swap, False, d


@dataclass
class ShardedTable:
    var_names: Tuple[str, ...]
    vals: jax.Array    # [S, cap, k] row-sharded
    valid: jax.Array   # [S, cap]
    count: int         # global exact count
    host_vals: Optional[np.ndarray] = None   # prefetched host copies (the
    host_valid: Optional[np.ndarray] = None  # fused settle's one transfer)


def _probe_kernel(key_sorted, perm, targets, type_id, probe_key, fixed, cap, var_cols, eq_pairs):
    """Shard-local probe + term-table build.  Runs inside shard_map: blocks
    arrive as [1, m(, a)] slabs; outputs carry the same leading block dim."""
    key_sorted, perm, targets = key_sorted[0], perm[0], targets[0]
    lo = jnp.searchsorted(key_sorted, probe_key, side="left")
    hi = jnp.searchsorted(key_sorted, probe_key, side="right")
    range_count = (hi - lo).astype(jnp.int32)
    offs = jnp.arange(cap, dtype=jnp.int32)
    valid = offs < range_count
    idx = jnp.clip(lo.astype(jnp.int32) + offs, 0, key_sorted.shape[0] - 1)
    local = perm[idx]
    safe = jnp.clip(local, 0, targets.shape[0] - 1)
    mask = valid
    for pos, val in fixed:
        mask = mask & (targets[safe, pos] == val)
    vals, mask = _build_term_table_impl(targets, local, mask, var_cols, eq_pairs)
    return vals[None], mask[None], range_count[None]


class ShardedDB(IncrementalCommitMixin, MemoryDB):
    """MemoryDB surface + mesh-sharded conjunctive execution."""

    def __init__(
        self,
        data: Optional[AtomSpaceData] = None,
        config: Optional[DasConfig] = None,
        mesh: Optional[Mesh] = None,
    ):
        super().__init__(data)
        self.config = config or DasConfig()
        self.fin: Finalized = self.data.finalize()
        self.mesh = mesh if mesh is not None else make_mesh(
            None
            if self.config.mesh_shape is None
            else int(np.prod(self.config.mesh_shape))
        )
        tables = None
        if self.config.checkpoint_path:
            # shard-local restore: device_put the saved slabs directly
            # instead of re-partitioning the host-global Finalized
            from das_tpu.storage import checkpoint

            tables = checkpoint.try_restore_sharded(
                self.config.checkpoint_path, self.fin, self.mesh
            )
        self.tables = tables or ShardedTables(self.fin, self.mesh)
        self._reset_delta_state()

    def __repr__(self):
        return f"<ShardedDB over {self.tables.n_shards} shards>"

    def refresh(self) -> None:
        """Re-sync the sharded store after transaction commits.  Small
        deltas extend the slab-stacked device tables in place
        (`ShardedTables.stage_delta`) — O(delta) host↔device traffic,
        one shard_map merge program, no re-partition of the base.  The
        full-vs-delta decision, atom interning, and the incoming-set
        overlay are shared with TensorDB (storage/delta.py); past
        config.delta_merge_threshold accumulated atoms the store fully
        re-finalizes and re-partitions.

        Cache invalidation contract (mirrors TensorDB.refresh): the
        incremental path bumps the mixin's `delta_version`, which the
        sharded fused executor's result cache keys on
        (parallel/fused_sharded.py); the FULL path (threshold or slab
        exhaustion) replaces `self.tables`, dropping the executor and its
        cache wholesale."""
        self.prefetch()
        action = self._plan_refresh()
        if action == NOOP:
            return
        if action == FULL:
            # WAL (ISSUE 15): log the pending host tail fsynced before
            # the re-partition becomes visible (TensorDB.refresh has
            # the full rationale — shared contract)
            wal = self._wal
            if wal is not None:
                wal.append(self.data, self.delta_version + 1, kind="full")
            self.fin = self.data.finalize()
            self.tables = ShardedTables(self.fin, self.mesh)
            self._reset_delta_state()
            return
        self._commit_delta_with_retry(action)

    @classmethod
    def restore(cls, path: str, config: Optional[DasConfig] = None) -> "ShardedDB":
        """Warm-state restore on the mesh (ISSUE 15, storage/durable.py):
        newest VALID snapshot generation + WAL replay + warm bundle; the
        saved shard-local slabs device_put directly when the mesh size
        and content sig still match (checkpoint.try_restore_sharded)."""
        from das_tpu.storage import durable

        return durable.restore(path, config=config, backend="sharded")

    # _apply_delta / _reset_delta_state / host_bucket_segments come from
    # IncrementalCommitMixin; the backend-specific part is the device merge:

    def _stage_delta_merge(self, commit_bucket):
        return self.tables.stage_delta(commit_bucket)

    def _commit_delta_with_retry(self, action) -> None:
        try:
            super()._commit_delta_with_retry(action)
        except SlabCapacityExhausted:
            # early LSM compaction: a slab's capacity slack is gone before
            # the atom-count threshold tripped.  The aborted commit staged
            # but never swapped (stage-then-swap), so the full
            # re-partition starts from a clean pre-commit store.
            self.fin = self.data.finalize()
            self.tables = ShardedTables(self.fin, self.mesh)
            self._reset_delta_state()

    def _type_id(self, link_type: str) -> Optional[int]:
        h = self.data.table.get_named_type_hash(link_type)
        return self.fin.type_id_of_hash.get(h)

    # -- sharded pipeline --------------------------------------------------

    def _term_table(self, plan: qc.TermPlan) -> Optional[ShardedTable]:
        sb = self.tables.buckets.get(plan.arity)
        if sb is None:
            return None
        if plan.ctype is not None:
            key_sorted, perm = sb.key_ctype, sb.order_by_ctype
            probe_key = np.int64(plan.ctype)
            fixed = ()
        elif plan.type_id is not None and plan.fixed:
            p0, v0 = plan.fixed[0]
            key_sorted, perm = sb.key_type_pos[p0], sb.order_by_type_pos[p0]
            probe_key = np.int64((plan.type_id << 32) | v0)
            fixed = tuple(plan.fixed[1:])
        else:
            # plan_query guarantees type_id for every non-template plan
            key_sorted, perm = sb.key_type, sb.order_by_type
            probe_key = np.int64(plan.type_id)
            fixed = ()

        cap = min(self.config.initial_result_capacity, max(sb.m_local, 16))
        spec = P(SHARD_AXIS)
        while True:
            fn = shard_map(
                partial(
                    _probe_kernel,
                    probe_key=probe_key,
                    fixed=fixed,
                    cap=cap,
                    var_cols=plan.var_cols,
                    eq_pairs=plan.eq_pairs,
                ),
                mesh=self.mesh,
                in_specs=(spec, spec, spec, spec),
                out_specs=(spec, spec, spec),
            )
            vals, mask, range_counts = fn(key_sorted, perm, sb.targets, sb.type_id)
            worst = int(np.max(np.asarray(range_counts)))
            if worst <= cap:
                count = int(np.asarray(mask).sum())
                if count == 0:
                    return None
                return ShardedTable(plan.var_names, vals, mask, count)
            if cap >= self.config.max_result_capacity:
                raise CapacityOverflowError(
                    f"probe needs {worst} rows > max_result_capacity "
                    f"{self.config.max_result_capacity}"
                )
            cap = min(max(cap * 2, worst), self.config.max_result_capacity)

    def _join(self, left: ShardedTable, right: ShardedTable) -> ShardedTable:
        pairs = tuple(
            (left.var_names.index(v), right.var_names.index(v))
            for v in left.var_names
            if v in right.var_names
        )
        extra = tuple(
            i for i, v in enumerate(right.var_names) if v not in left.var_names
        )
        out_names = left.var_names + tuple(
            v for v in right.var_names if v not in left.var_names
        )
        spec = P(SHARD_AXIS)
        cap = max(64, min(left.count * right.count, self.config.initial_result_capacity))
        while True:
            def kernel(lv, lm, rv, rm):
                # broadcast-right: gather the full right table to this shard
                rv_full = jax.lax.all_gather(rv[0], SHARD_AXIS, tiled=True)
                rm_full = jax.lax.all_gather(rm[0], SHARD_AXIS, tiled=True)
                vals, valid, total = _join_tables_impl(
                    lv[0], lm[0], rv_full, rm_full, pairs, extra, cap
                )
                return vals[None], valid[None], total[None]

            fn = shard_map(
                kernel,
                mesh=self.mesh,
                in_specs=(spec, spec, spec, spec),
                out_specs=(spec, spec, spec),
            )
            vals, valid, totals = fn(left.vals, left.valid, right.vals, right.valid)
            worst = int(np.max(np.asarray(totals)))
            if worst <= cap:
                count = int(np.asarray(valid).sum())
                return ShardedTable(out_names, vals, valid, count)
            if cap >= self.config.max_result_capacity:
                raise CapacityOverflowError(
                    f"join needs {worst} rows > max_result_capacity "
                    f"{self.config.max_result_capacity}"
                )
            cap = min(max(cap * 2, worst), self.config.max_result_capacity)

    def _anti_join(self, left: ShardedTable, tabu: ShardedTable) -> ShardedTable:
        pairs = tuple(
            (left.var_names.index(v), tabu.var_names.index(v))
            for v in tabu.var_names
        )
        spec = P(SHARD_AXIS)

        def kernel(lv, lm, rv, rm):
            rv_full = jax.lax.all_gather(rv[0], SHARD_AXIS, tiled=True)
            rm_full = jax.lax.all_gather(rm[0], SHARD_AXIS, tiled=True)
            return _anti_join_impl(lv[0], lm[0], rv_full, rm_full, pairs)[None]

        fn = shard_map(
            kernel, mesh=self.mesh, in_specs=(spec, spec, spec, spec), out_specs=spec
        )
        valid = fn(left.vals, left.valid, tabu.vals, tabu.valid)
        return ShardedTable(
            left.var_names, left.vals, valid, int(np.asarray(valid).sum())
        )

    def sharded_execute(self, plans: List[qc.TermPlan]) -> Optional[ShardedTable]:
        tabu: List[ShardedTable] = []
        accumulated: Optional[ShardedTable] = None
        for plan in plans:
            table = self._term_table(plan)
            if plan.negated:
                if table is not None:
                    tabu.append(table)
                continue
            if table is None:
                return None
            if accumulated is None or accumulated.count == 0:
                accumulated = table
            else:
                accumulated = self._join(accumulated, table)
        if accumulated is None:
            return None
        for t in tabu:
            if set(t.var_names) <= set(accumulated.var_names):
                accumulated = self._anti_join(accumulated, t)
        return accumulated

    def materialize(self, table: Optional[ShardedTable], answer: PatternMatchingAnswer) -> bool:
        if table is None or table.count == 0:
            return False
        if table.host_vals is not None:
            vals, valid = table.host_vals, table.host_valid
        else:
            # one transfer for both arrays (each fetch is a tunnel RTT)
            from das_tpu.query.fused import FETCH_COUNTS

            FETCH_COUNTS["n"] += 1
            vals, valid = jax.device_get((table.vals, table.valid))
        vals = np.asarray(vals).reshape(-1, len(table.var_names))
        valid = np.asarray(valid).reshape(-1)
        hexes = self.fin.hex_of_row
        seen = set()
        for row in vals[valid]:
            key = tuple(int(v) for v in row)
            if key in seen:
                continue
            seen.add(key)
            a = OrderedAssignment()
            ok = True
            for name, val in zip(table.var_names, row):
                if not a.assign(name, hexes[int(val)]):
                    ok = False
                    break
            if ok and a.freeze():
                answer.assignments.add(a)
        return bool(answer.assignments)

    def _run_conjunctive(self, plans: List[qc.TermPlan]) -> Optional[ShardedTable]:
        """One conjunctive plan on the mesh: the fused single-dispatch
        program first (one shard_map launch, one stats transfer); plans it
        declines (reseed condition, capacity ceiling) replay on the staged
        reference-order pipeline, which is answer-identical."""
        from das_tpu.parallel.fused_sharded import get_sharded_executor

        # the serving path opts into the delta-versioned result cache;
        # bare executor.execute stays uncached (measurement honesty)
        res = get_sharded_executor(self).execute(plans, use_cache=True)
        if res is not None and not res.reseed_needed:
            return ShardedTable(
                res.var_names, res.vals, res.valid, res.count,
                host_vals=res.host_vals, host_valid=res.host_valid,
            )
        return self.sharded_execute(plans)

    def _or_branch_plans(self, query) -> Optional[List[List[qc.TermPlan]]]:
        """Plans for each branch of an all-positive Or of compilable
        conjunctions, or None.  Reference Or semantics for positive terms
        is a plain union of branch answer sets (query/ast.py Or.matched),
        so each branch can run on the mesh independently; any Not branch
        (de-Morgan joint-negative handling) disqualifies."""
        from das_tpu.query.ast import Not, Or

        if not isinstance(query, Or) or not query.terms:
            return None
        if any(isinstance(t, Not) for t in query.terms):
            return None
        branch_plans = []
        for term in query.terms:
            plans = qc.plan_query(self, term, unknown_atom_empty=True)
            if plans is qc.EMPTY_PLAN:
                continue  # grounded on a nonexistent atom: statically empty
            if plans is None:
                return None
            branch_plans.append(plans)
        return branch_plans

    @property
    def tree_ops(self):
        """Mesh op layer for the generalized tree evaluator — built lazily,
        invalidated whenever the sharded tables object is replaced (full
        re-finalize) so probes never read a stale store."""
        ops = getattr(self, "_tree_ops", None)
        if ops is None or ops.tables is not self.tables:
            from das_tpu.parallel.sharded_tree import ShardedTreeOps

            ops = ShardedTreeOps(self)
            ops.tables = self.tables
            self._tree_ops = ops
        return ops

    def query_sharded(self, query: LogicalExpression, answer: PatternMatchingAnswer) -> Optional[bool]:
        """Compiled sharded execution; None when not compilable.

        Conjunctive queries run on the mesh (`_run_conjunctive`); an Or of
        compilable conjunctions runs each branch on the mesh and unions
        the materialized assignment sets (set insertion dedups by the
        engines' hash identity, exactly like Or.matched's union).

        Everything else in the compilable language (unordered links,
        nested And/Or, negated Or branches) ALSO runs on the mesh: the
        generalized tree evaluator (query/tree.py) executes with this
        backend's ShardedTreeOps op layer (parallel/sharded_tree.py), so
        composite tables stay row-sharded across all chips.  Legacy
        config.sharded_tree_fallback values: 'tensor' re-enables the
        round-2 single-chip replicated tree copy; 'host' skips device
        trees entirely."""
        plans = qc.plan_query(self, query)
        if plans is not None:
            return self.materialize(self._run_conjunctive(plans), answer)
        branch_plans = self._or_branch_plans(query)
        if branch_plans is not None:
            # whole-tree fusion (ISSUE 10) BEFORE the per-branch Or
            # decomposition: an eligible N-branch Or settles as ONE
            # shard_map program and one transfer where the branch loop
            # below pays one mesh program + one materialization per
            # branch.  Attempted only HERE — every other non-conjunctive
            # shape reaches query_tree below, whose own fused attempt
            # runs the eligibility analysis exactly once.  Gated on the
            # "mesh" tree mode: "tensor"/"host" promise no mesh tree
            # programs, and the fused tree IS one.  A decline falls
            # through to the decomposition, answer-identical.
            from das_tpu.query import assignment as asn_mod
            from das_tpu.query import tree as tree_mod

            if (
                tree_mod.tree_fusion_enabled(self.config)
                and getattr(self.config, "sharded_tree_fallback", "mesh")
                == "mesh"
                and not asn_mod.CONFIG.get("no_overload")
            ):
                from das_tpu.query.plan import NotCompilable, build_plan

                try:
                    node = build_plan(self, query)
                except NotCompilable:
                    node = None
                if node is not None:
                    matched = tree_mod.query_tree_fused(
                        self, node, answer, tree_mod._tree_cache(self)
                    )
                    if matched is not None:
                        return matched
            matched = False
            for plans in branch_plans:
                table = self._run_conjunctive(plans)
                matched = self.materialize(table, answer) or matched
            return matched
        from das_tpu.query.tree import query_tree

        mode = getattr(self.config, "sharded_tree_fallback", "mesh")
        if mode == "host":
            return None  # host algebra
        try:
            if mode == "tensor":
                return query_tree(self._tree_db(), query, answer)
            return query_tree(self, query, answer)
        except CapacityOverflowError:
            raise
        except Exception as exc:  # degrade, never crash the query API
            from das_tpu.utils.logger import logger

            logger().warning(
                f"sharded tree execution failed ({exc!r}); host algebra"
            )
            answer.assignments.clear()
            answer.negation = False
            return None

    def _tree_db(self):
        """Single-device TensorDB view over the same AtomSpaceData, built
        on first use and refreshed when the sharded tables were."""
        from das_tpu.storage.tensor_db import TensorDB

        db = getattr(self, "_tree_tensor_db", None)
        if db is None or db.data is not self.data:
            # the replica may adopt the shared cached Finalized: delta
            # interning is idempotent across backends (fin.interned
            # counters) and bucket bases are per-backend (_base_buckets),
            # both in storage/delta.py — asserted by
            # tests/test_incremental.py::test_shared_finalized_no_double_intern
            db = TensorDB(self.data, self.config)
            self._tree_tensor_db = db
        else:
            db.refresh()  # no-op when the data hasn't changed
        return db
