"""dasfault — deterministic, seeded fault injection plus the recovery
machinery it exercises (ISSUE 13 tentpole).

The serving stack's failure paths were ad hoc: RPC threads blocked on
futures with no timeout, the settle-fetch transport retry was a
hard-coded retry-once, and nothing proved a mid-commit crash leaves
`delta_version` and the store consistent.  This module is the substrate
that makes those paths *testable* and *bounded*:

  * **Injection** — `maybe_fail(site)` at each declared `FAULT_SITES`
    seam, driven by a seeded schedule from the `DAS_TPU_FAULT` spec
    string.  Injection raises a typed `InjectedFault` (or sleeps, in
    latency mode) — never silent corruption.  Default off with a
    no-allocation fast path: one module-global read and a None check
    (the obs NOOP_SPAN idiom; tests pin `_PLAN is None` identity).
  * **RetryPolicy** — ONE shared retry/backoff implementation (max
    attempts, exponential backoff, deterministic jitter, per-class
    retryability) replacing the scattered retry-once sites; covers
    settle fetches (query/fused.py) and commit applies
    (storage/delta.py).
  * **CircuitBreaker** — the per-tenant degraded-mode state machine the
    coalescer (service/coalesce.py) drives: repeated retryable settle
    failures or sustained saturation trip it OPEN (speculation off,
    window at floor, cache-hit answers still served, fresh dispatches
    rejected retryable); after a cooldown a HALF_OPEN probe restores it.

The chaos-parity contract this buys (tests/test_zfault.py): under ANY
injected schedule, every query returns either bit-identical answers to
the fault-free run or a typed `DasError` subclass — never a wrong
answer, never a stranded future, never a dead worker — and the store
stays consistent (storage/delta.py stage-then-swap).

daslint rule DL015 pins `FAULT_SITES` both ways (an undeclared
`maybe_fail` site fires; a stale entry fails full runs) and bans
injection calls from `das_tpu/kernels/` and the dispatch halves — the
traced/async code paths must stay exactly as reviewed (DL001/DL010).

Spec string (`DAS_TPU_FAULT`, or `fault.configure(spec)`):
semicolon-separated `key=value` pairs —

    seed=7;sites=settle_fetch,commit_apply;rate=0.25;max=4
    seed=1;sites=*;every=3;max=2;mode=latency;latency_ms=5

  seed        deterministic schedule seed (default 0)
  sites       comma list of FAULT_SITES members, or `*` (required)
  rate        per-call failure probability, decided by a seeded hash
              of (seed, site, call index) — same spec, same schedule
  every       fire on every Nth call of a site (overrides rate)
  max         per-site cap on injected failures (default 4) — bounds
              every schedule so the system eventually heals; note a
              cap at or above RetryPolicy's attempts (3) can still
              fail one operation typed before the site goes quiet
  mode        error (raise InjectedFault, default) | latency (sleep)
  latency_ms  sleep duration for latency mode (default 1.0)
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from typing import Callable, Dict, Optional, Tuple

from das_tpu.core.exceptions import DasError, InjectedFault

#: the CLOSED set of host-side seams `maybe_fail` may guard (daslint
#: DL015, the COLLECTIVE_SITES/FETCH_SITES idiom applied to fault
#: injection).  Every entry names a recovery path the chaos suite
#: exercises; adding a seam means adding it here, under review, with
#: its degradation story.  Injection is banned from das_tpu/kernels/
#: and the dispatch halves — those stay bit-identical to the reviewed
#: fault-free code (DL001/DL010).
FAULT_SITES = (
    #: coalescer submit path (service/coalesce.py submit) — the caller
    #: sees the typed error on its future, like any per-query failure
    "submit_queue",
    #: top of the coalescer worker loop (service/coalesce.py _run) —
    #: proves the worker survives anything its iteration raises
    "worker_iteration",
    #: host-side group enqueue seam (service/coalesce.py
    #: _dispatch_group, OUTSIDE the DL001 dispatch halves) — the group
    #: degrades to per-query settle fallbacks
    "dispatch_enqueue",
    #: the settle round's host transfer (query/fused.py
    #: settle_pending_iter / _run_batch_group) — RetryPolicy's beat
    "settle_fetch",
    #: delta-versioned result-cache insert (query/fused.py
    #: ResultCache.put) — a cache failure degrades to "not cached",
    #: never to a failed query
    "cache_insert",
    #: incremental-commit apply, after staging and before the swap
    #: (storage/delta.py _apply_delta) — the mid-commit crash point the
    #: stage-then-swap ordering makes atomic
    "commit_apply",
    #: -- dasdur persistence seams (ISSUE 15, storage/durable.py): the
    #: chaos-parity contract extends to durability — inject a crash at
    #: any of these, recover via restore(), and query answers are
    #: bit-identical (tests/test_zdur.py crash-point matrix) --
    #: start of one atomic section write, before any byte lands
    #: (durable.atomic_write) — the prior file/generation survives
    "snapshot_write",
    #: between a section's fsync and its rename into place, and before
    #: the generation directory's final rename (durable.atomic_write /
    #: write_snapshot) — the torn-rename crash the dot-temp layout makes
    #: invisible to restore
    "snapshot_rename",
    #: start of one WAL record append, before framing (durable.DeltaLog
    #: .append) — the commit fails pre-swap, store stays consistent
    "wal_append",
    #: after the WAL record's write and before its fsync — the record
    #: may or may not be durable; a retried commit's twin record dedups
    #: by delta_version at replay
    "wal_fsync",
    #: restore-path section/WAL reads (durable._verified_bytes /
    #: read_wal) — a transient read flake retries on the shared
    #: RetryPolicy; real corruption stays a typed SnapshotCorruptError
    "restore_read",
)

#: per-site injected-failure tally (the FETCH_COUNTS idiom: plain +=
#: under the GIL, torn reads tolerated) — bench/tests read it to assert
#: a schedule actually fired
INJECT_COUNTS: Dict[str, int] = {site: 0 for site in FAULT_SITES}


class FaultSpecError(DasError):
    """Malformed `DAS_TPU_FAULT` spec string."""


def _hash_unit(seed: int, site: str, n: int) -> float:
    """Deterministic uniform in [0, 1) from (seed, site, call index) —
    the schedule is a pure function of the spec, never of RNG state."""
    h = zlib.crc32(f"{seed}:{site}:{n}".encode()) & 0xFFFFFFFF
    return h / 2.0**32


class _FaultPlan:
    """One parsed, armed injection schedule.  All counters live behind
    one lock — injection is a cold path by construction (the disabled
    fast path never reaches here)."""

    __slots__ = (
        "spec", "seed", "sites", "rate", "every", "max_failures",
        "mode", "latency_ms", "_calls", "_fails", "_lock",
    )

    def __init__(self, spec: str, seed: int, sites: Tuple[str, ...],
                 rate: float, every: int, max_failures: int,
                 mode: str, latency_ms: float):
        self.spec = spec
        self.seed = seed
        self.sites = frozenset(sites)
        self.rate = rate
        self.every = every
        self.max_failures = max_failures
        self.mode = mode
        self.latency_ms = latency_ms
        self._calls: Dict[str, int] = {}
        self._fails: Dict[str, int] = {}
        self._lock = threading.Lock()

    def _fires(self, site: str, n: int) -> bool:
        if self.every > 0:
            return (n + 1) % self.every == 0
        return _hash_unit(self.seed, site, n) < self.rate

    def check(self, site: str) -> None:
        with self._lock:
            n = self._calls.get(site, 0)
            self._calls[site] = n + 1
            if site not in self.sites:
                return
            if self._fails.get(site, 0) >= self.max_failures:
                return
            if not self._fires(site, n):
                return
            self._fails[site] = self._fails.get(site, 0) + 1
        INJECT_COUNTS[site] += 1
        from das_tpu import obs

        if obs.enabled():
            obs.event("fault.inject", site=site, call=n, mode=self.mode)
            obs.counter("fault.injected").inc()
        if self.mode == "latency":
            time.sleep(self.latency_ms / 1e3)
            return
        raise InjectedFault(site, n)

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "spec": self.spec,
                "calls": dict(self._calls),
                "failures": dict(self._fails),
            }


def parse_spec(spec: Optional[str]) -> Optional[_FaultPlan]:
    """Parse a `DAS_TPU_FAULT` spec string; None/empty means off.
    Unknown keys and undeclared site names are hard errors — a typo'd
    chaos schedule that silently injects nothing is worse than none."""
    if not spec:
        return None
    fields = {
        "seed": "0", "sites": "", "rate": "0.5", "every": "0",
        "max": "4", "mode": "error", "latency_ms": "1.0",
    }
    for pair in spec.split(";"):
        pair = pair.strip()
        if not pair:
            continue
        if "=" not in pair:
            raise FaultSpecError(f"malformed DAS_TPU_FAULT pair {pair!r}")
        key, value = pair.split("=", 1)
        key = key.strip()
        if key not in fields:
            raise FaultSpecError(f"unknown DAS_TPU_FAULT key {key!r}")
        fields[key] = value.strip()
    raw_sites = fields["sites"]
    if not raw_sites:
        raise FaultSpecError("DAS_TPU_FAULT needs sites=<name,...> or sites=*")
    if raw_sites == "*":
        sites = FAULT_SITES
    else:
        sites = tuple(s.strip() for s in raw_sites.split(",") if s.strip())
        unknown = [s for s in sites if s not in FAULT_SITES]
        if unknown:
            raise FaultSpecError(
                f"undeclared fault site(s) {unknown} — FAULT_SITES "
                f"declares {list(FAULT_SITES)}"
            )
    mode = fields["mode"]
    if mode not in ("error", "latency"):
        raise FaultSpecError(f"unknown DAS_TPU_FAULT mode {mode!r}")
    return _FaultPlan(
        spec=spec,
        seed=int(fields["seed"]),
        sites=sites,
        rate=float(fields["rate"]),
        every=int(fields["every"]),
        max_failures=int(fields["max"]),
        mode=mode,
        latency_ms=float(fields["latency_ms"]),
    )


#: THE armed schedule — None is the disabled fast path (identity-pinned
#: by tests/test_zfault.py, the obs NOOP_SPAN idiom): `maybe_fail` on
#: the serve path then costs one global read + a None check, allocating
#: nothing
_PLAN: Optional[_FaultPlan] = parse_spec(os.environ.get("DAS_TPU_FAULT"))


def configure(spec: Optional[str]) -> None:
    """Arm (or with None/"" disarm) an injection schedule — the test /
    bench entry point; the env var covers deployments."""
    global _PLAN
    _PLAN = parse_spec(spec)


def enabled() -> bool:
    return _PLAN is not None


def plan() -> Optional[_FaultPlan]:
    """The armed schedule (None when off) — tests read its snapshot."""
    return _PLAN


def maybe_fail(site: str) -> None:
    """The injection seam: no-op unless a schedule is armed AND decides
    this call fires.  `site` must be a FAULT_SITES member (daslint
    DL015 pins the literals both ways)."""
    armed = _PLAN
    if armed is None:
        return
    armed.check(site)


def reset_counts() -> None:
    """Zero INJECT_COUNTS (bench/test arms start from a clean window)."""
    for site in INJECT_COUNTS:
        INJECT_COUNTS[site] = 0


# -- retry / backoff ---------------------------------------------------------


def is_retryable(exc: BaseException) -> bool:
    """Per-class retryability shared by every recovery site: injected
    faults (unless marked terminal), jax runtime/transport failures
    (remote-compile tunnels drop large payloads occasionally), and
    plain OS-level connection errors.  Semantic errors — bad queries,
    capacity ceilings, deadline expiry — are NOT retryable here: each
    has its own, smarter recovery path."""
    if isinstance(exc, InjectedFault):
        return exc.retryable
    if isinstance(exc, ConnectionError):
        return True
    try:
        import jax

        if isinstance(exc, jax.errors.JaxRuntimeError):
            return True
    except Exception:  # noqa: BLE001 — no jax in a docs/lint venv
        pass
    return False


class RetryPolicy:
    """Bounded retry with exponential backoff and DETERMINISTIC jitter.

    One shared implementation for every transport-class recovery site
    (the settle fetch, the commit apply) — replacing the hard-coded
    retry-once idiom.  The jitter derives from (seed, attempt), never
    from RNG state, so a chaos run's timing is a pure function of its
    spec and the determinism test can pin the exact backoff sequence.
    """

    __slots__ = ("max_attempts", "base_ms", "multiplier", "max_backoff_ms",
                 "jitter_frac", "seed", "classify")

    def __init__(self, max_attempts: int = 3, base_ms: float = 1.0,
                 multiplier: float = 2.0, max_backoff_ms: float = 50.0,
                 jitter_frac: float = 0.25, seed: int = 0,
                 classify: Optional[Callable[[BaseException], bool]] = None):
        self.max_attempts = max(1, int(max_attempts))
        self.base_ms = float(base_ms)
        self.multiplier = float(multiplier)
        self.max_backoff_ms = float(max_backoff_ms)
        self.jitter_frac = float(jitter_frac)
        self.seed = int(seed)
        self.classify = classify or is_retryable

    def backoff_ms(self, attempt: int) -> float:
        """Delay before retry `attempt` (1-based): exponential from
        base_ms, capped, with deterministic jitter in
        [0, jitter_frac] of the raw delay."""
        raw = min(
            self.base_ms * self.multiplier ** (attempt - 1),
            self.max_backoff_ms,
        )
        return raw * (1.0 + self.jitter_frac
                      * _hash_unit(self.seed, "backoff", attempt))

    def run(self, fn: Callable, on_retry: Optional[Callable] = None):
        """Call `fn()` up to max_attempts times.  Retries only
        classify()-retryable failures, sleeping backoff_ms between
        attempts; the final failure re-raises typed and untouched.
        `on_retry(attempt, exc)` (optional) runs before each retry —
        call sites keep their own per-attempt accounting there (e.g.
        the FETCH_COUNTS tally stays at the fetch site, DL013)."""
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as exc:  # noqa: BLE001 — classified below
                attempt += 1
                if attempt >= self.max_attempts or not self.classify(exc):
                    raise
                from das_tpu import obs

                if obs.enabled():
                    obs.counter("fault.retries").inc()
                if on_retry is not None:
                    on_retry(attempt, exc)
                delay = self.backoff_ms(attempt)
                if delay > 0:
                    time.sleep(delay / 1e3)


def fetch_retry() -> RetryPolicy:
    """The settle-fetch policy (replaces query/fused.py's retry-once):
    3 attempts, millisecond-scale backoff — a transient tunnel drop
    costs one beat, a real outage surfaces typed after two retries."""
    return RetryPolicy(max_attempts=3, base_ms=1.0, max_backoff_ms=50.0)


def commit_retry() -> RetryPolicy:
    """The commit-apply policy: stage-then-swap (storage/delta.py) makes
    a failed apply side-effect-free, so a transient failure retries the
    whole staged commit safely."""
    return RetryPolicy(max_attempts=3, base_ms=1.0, max_backoff_ms=50.0)


# -- circuit breaker ---------------------------------------------------------

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-tenant degraded-mode state machine (driven by the coalescer
    worker thread — single-threaded by construction, service/coalesce.py
    LOCK_DISCIPLINE).

    CLOSED --(threshold consecutive retryable failures)--> OPEN
    OPEN   --(cooldown elapsed, one probe granted)-------> HALF_OPEN
    HALF_OPEN --(probe succeeds)--> CLOSED   (a recovery)
    HALF_OPEN --(probe fails)----> OPEN      (cooldown restarts)

    While OPEN the coalescer serves cache hits and rejects fresh
    dispatches retryable (`BreakerOpenError` + retry-after hint);
    `failure_threshold <= 0` disables the breaker entirely (allow()
    always True, nothing ever trips)."""

    __slots__ = ("failure_threshold", "cooldown_ms", "clock", "state",
                 "consecutive_failures", "opened_at", "trips", "probes",
                 "recoveries")

    def __init__(self, failure_threshold: int = 8,
                 cooldown_ms: float = 250.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = int(failure_threshold)
        self.cooldown_ms = float(cooldown_ms)
        self.clock = clock
        self.state = CLOSED
        self.consecutive_failures = 0
        self.opened_at = 0.0
        self.trips = 0
        self.probes = 0
        self.recoveries = 0

    def _transition(self, to: str) -> None:
        frm, self.state = self.state, to
        from das_tpu import obs

        if obs.enabled():
            obs.event("serve.breaker", frm=frm, to=to)

    def allow(self) -> bool:
        """True when a fresh dispatch may proceed.  OPEN past the
        cooldown grants exactly ONE half-open probe; further calls stay
        rejected until that probe's verdict lands."""
        if self.failure_threshold <= 0 or self.state == CLOSED:
            return True
        if self.state == OPEN:
            if (self.clock() - self.opened_at) * 1e3 >= self.cooldown_ms:
                self._transition(HALF_OPEN)
                self.probes += 1
                return True
            return False
        return False  # HALF_OPEN: the granted probe is still in flight

    def record_success(self) -> None:
        if self.state == HALF_OPEN:
            self._transition(CLOSED)
            self.recoveries += 1
            from das_tpu import obs

            if obs.enabled():
                obs.counter("serve.breaker_recoveries").inc()
        self.consecutive_failures = 0

    def record_failure(self) -> None:
        if self.failure_threshold <= 0:
            return
        if self.state == HALF_OPEN:
            # the probe failed: re-open, restart the cooldown
            self._transition(OPEN)
            self.opened_at = self.clock()
            return
        self.consecutive_failures += 1
        if self.state == CLOSED and (
            self.consecutive_failures >= self.failure_threshold
        ):
            self._transition(OPEN)
            self.opened_at = self.clock()
            self.trips += 1
            from das_tpu import obs

            if obs.enabled():
                obs.counter("serve.breaker_trips").inc()

    def retry_after_ms(self) -> float:
        """Hint for rejected callers: remaining cooldown (OPEN), or one
        full cooldown (HALF_OPEN/CLOSED edge races)."""
        if self.state == OPEN:
            elapsed = (self.clock() - self.opened_at) * 1e3
            return max(0.0, self.cooldown_ms - elapsed)
        return self.cooldown_ms

    def snapshot(self) -> Dict:
        return {
            "state": self.state,
            "consecutive_failures": self.consecutive_failures,
            "trips": self.trips,
            "probes": self.probes,
            "recoveries": self.recoveries,
        }
