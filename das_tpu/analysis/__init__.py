"""daslint — AST invariant analyzer for the das_tpu contracts.

Four PRs of perf work (fused kernels, dispatch/settle pipelining,
sharded parity, grid-chunked tiling) piled up invariants that existed
only by convention and reviewer memory: dispatch paths must be
transfer-free, every field routing a kernel must live in the plan
signature, every DAS_TPU_* env read must be declared, counter keys must
be registered and test-pinned, the VMEM byte models must track the
buffers the kernel bodies allocate, and the coalescer's worker-thread
state must honor its locks.  Query-on-tensor-runtime systems live or
die on exactly these silent-recompile / cache-poisoning hazards (a
plan/signature mismatch surfaces as a wrong answer, not a crash), so
this package checks them mechanically, on every run of `ops/lint.sh`
and in the tier-1 suite (tests/test_zlint.py).

Since ISSUE 11 the analyzer is project-wide, not per-file: a
call-graph + dataflow core (analysis/callgraph.py — module symbol
tables, intra-repo call resolution, transitive reachability over
function summaries) backs the rules that follow helper calls, and a
(path, mtime, size) parse cache keeps the growing rule count fast.

Usage:  python -m das_tpu.analysis [paths...]   (wrapper: ops/lint.sh;
        --select/--ignore for subsets, --format sarif for CI,
        ops/lint.sh --changed-only for the pre-commit fast path)

Rules (one module each under rules/; contracts in ARCHITECTURE.md §11):

  DL001 host-sync-in-dispatch   dispatch halves are transfer-free
  DL002 plan-sig completeness   routing fields live in the frozen sig
  DL003 env registry            DAS_TPU_* reads <-> ENV_REGISTRY
  DL004 counter discipline      DISPATCH/ROUTE keys <-> ops/counters.py
  DL005 budget-model drift      kernel-body refs <-> budget.KERNEL_BUFFERS
  DL006 lock discipline         coalescer mutations <-> LOCK_DISCIPLINE
  DL007 cache-insert guard      delta_version captured before dispatch
  DL008 planner vocabularies    routes/counter keys <-> ops/counters.py
  DL009 collective discipline   collectives <-> COLLECTIVE_SITES
  DL010 transitive host sync    DL001 through the whole call graph
  DL011 Mosaic readiness        ref/control-flow/dtype/lane contracts
  DL012 retrace hygiene         jit closures derive from *Sig/constants
  DL013 fetch-site registry     jax.device_get <-> FETCH_SITES + tally
  DL014 obs name discipline     span/metric names <-> obs/registry.py
  DL015 fault-site registry     maybe_fail <-> FAULT_SITES, ban in
                                kernels/ and dispatch halves
  DL016 program-site registry   jax.jit/pallas_call <-> PROGRAM_SITES
                                + the instrument/record_launch tally
  DL017 durability discipline   persist writes via atomic helpers,
                                fsync-before-rename, PERSIST_SITES

Per-file suppression: a comment line `# daslint: disable=DL001[,DL002]`
anywhere in a file disables those rules for that file.  Deliberate keeps
are grandfathered in daslint.baseline.json (repo root) with a one-line
justification; stale baseline entries fail the run so the file cannot
rot.  Everything here is stdlib-`ast` only — the analyzer never imports
the modules it checks.
"""

from das_tpu.analysis.core import (  # noqa: F401
    Finding,
    iter_rules,
    load_baseline,
    run_analysis,
)
