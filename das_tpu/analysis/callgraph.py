"""daslint v2 core: project-wide call graph + light dataflow over
stdlib-`ast` (never importing what it checks).

The v1 rules were per-file syntactic scans; the contracts they enforce
are not.  DL001's "no host sync in a dispatch half" is trivially
escaped by one helper-function hop — the exact silent-serialization
failure the async pipeline cannot afford — and the Mosaic-readiness
checks (DL011) need to follow a kernel body into the shared helpers
that actually touch its refs.  This module gives every rule the same
three layers:

  * **module symbol tables** (`ModuleTable`, cached on the SourceFile
    so the (path, mtime, size) file cache amortizes them): top-level
    defs/classes/constants plus an import map that resolves
    `from das_tpu.x import y` / `import das_tpu.x as z` to dotted
    targets, collected from EVERY scope (this codebase imports lazily
    inside functions to break cycles);
  * **intra-repo call resolution** (`CallGraph.resolve_call`): bare
    names through the import map and module scope, `self.method()`
    through the enclosing class and its repo-resolvable bases,
    `module.func()` through imported repo modules, constructor calls
    to `Class.__init__`.  Anything else (parameters holding callables,
    attribute chains on unknown objects) resolves to None — the graph
    under-approximates, deliberately: a lint rule built on it can
    miss, but what it reports is real;
  * **transitive reachability over function summaries**
    (`CallGraph.walk`): BFS from any def node, nested defs folded into
    their owner (a closure's effects belong to the function that runs
    it), cycle-safe, with the shortest call path kept so findings can
    render HOW a contract was reached, not just that it was.

Function identity is a qualified name "module::Class.func" /
"module::func" where `module` is the dotted das_tpu module when the
file sits under the package, else the file stem — so mutated-copy
tests on loose files resolve their intra-module calls exactly like the
installed tree.
"""

from __future__ import annotations

import ast
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from das_tpu.analysis.core import AnalysisContext, SourceFile, attr_chain


def module_dotted(sf: SourceFile) -> str:
    """Dotted module name: from the das_tpu package root when the file
    lives under it, else the bare stem ("__init__" files take their
    package directory's name — planner/__init__.py is `planner`)."""
    parts = list(sf.path.parts)
    stem = sf.path.stem
    if "das_tpu" in parts[:-1]:
        i = parts.index("das_tpu")
        mods = parts[i:-1] + ([stem] if stem != "__init__" else [])
        return ".".join(mods)
    if stem == "__init__" and len(parts) > 1:
        return parts[-2]
    return stem


def scope_module(sf: SourceFile) -> str:
    """Short module prefix for registry scopes ("fused", "planner"):
    the stem, or the package directory for __init__ modules."""
    stem = sf.path.stem
    if stem == "__init__" and len(sf.path.parts) > 1:
        return sf.path.parts[-2]
    return stem


class ModuleTable:
    """One module's top-level symbols + its (all-scopes) import map."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.dotted = module_dotted(sf)
        #: top-level name -> FunctionDef/AsyncFunctionDef/ClassDef
        self.defs: Dict[str, ast.AST] = {}
        #: class name -> {method name -> def node}
        self.methods: Dict[str, Dict[str, ast.AST]] = {}
        #: class name -> base expression names (unresolved)
        self.bases: Dict[str, List[ast.expr]] = {}
        #: local name -> dotted import target ("das_tpu.kernels.budget",
        #: "das_tpu.query.fused._TreeExecJob", ...)
        self.imports: Dict[str, str] = {}
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.defs[node.name] = node
                self.methods[node.name] = {
                    m.name: m for m in node.body
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                }
                self.bases[node.name] = list(node.bases)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else name
                    self.imports[name] = target
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.level:
                    continue  # no relative imports in this tree
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = (
                        f"{node.module}.{alias.name}"
                    )


def module_table(sf: SourceFile) -> ModuleTable:
    """The module's symbol table, cached on the SourceFile (which is
    itself cached by (path, mtime, size) — see core.collect_files)."""
    table = getattr(sf, "_modtable", None)
    if table is None:
        table = ModuleTable(sf)
        sf._modtable = table
    return table


class FunctionInfo:
    """One top-level function or method, nested defs folded in."""

    __slots__ = ("qname", "sf", "node", "class_name")

    def __init__(self, qname: str, sf: SourceFile, node: ast.AST,
                 class_name: Optional[str]):
        self.qname = qname
        self.sf = sf
        self.node = node
        self.class_name = class_name


class CallGraph:
    """Cross-module call graph over one AnalysisContext's file set.

    Built once per analysis run (AnalysisContext.callgraph() caches it)
    from the per-file ModuleTables; rules share it so the repo is
    resolved once however many rules follow calls."""

    def __init__(self, files: Sequence[SourceFile]):
        self.tables: List[ModuleTable] = [module_table(sf) for sf in files]
        #: dotted module name -> table (plus stem fallback for loose files)
        self.by_module: Dict[str, ModuleTable] = {}
        for t in self.tables:
            self.by_module.setdefault(t.dotted, t)
            self.by_module.setdefault(t.sf.name, t)
        self._edges_memo: Dict[int, List[Tuple[int, str]]] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        for t in self.tables:
            for name, node in t.defs.items():
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = f"{t.dotted}::{name}"
                    self.functions[q] = FunctionInfo(q, t.sf, node, None)
                elif isinstance(node, ast.ClassDef):
                    for mname, mnode in t.methods[name].items():
                        q = f"{t.dotted}::{name}.{mname}"
                        self.functions[q] = FunctionInfo(
                            q, t.sf, mnode, name
                        )

    # -- symbol resolution -------------------------------------------------

    def _resolve_dotted(self, target: str) -> Optional[str]:
        """A dotted import target -> qname of a repo function, walking
        "module.symbol" and "package.module" splits."""
        if target in self.by_module:
            return None  # a module itself, not callable
        if "." in target:
            mod, sym = target.rsplit(".", 1)
            table = self.by_module.get(mod)
            if table is not None:
                return self._resolve_in_table(table, sym)
        return None

    def _resolve_in_table(self, table: ModuleTable, name: str) -> Optional[str]:
        node = table.defs.get(name)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return f"{table.dotted}::{name}"
        if isinstance(node, ast.ClassDef):
            init = self._method_qname(table, name, "__init__")
            return init
        if name in table.imports:  # re-export hop
            return self._resolve_dotted(table.imports[name])
        return None

    def _class_table(self, table: ModuleTable, cls: str):
        """(table, class name) where `cls` (as visible from `table`) is
        actually defined — follows imports for cross-module bases."""
        if cls in table.methods:
            return table, cls
        target = table.imports.get(cls)
        if target and "." in target:
            mod, sym = target.rsplit(".", 1)
            t2 = self.by_module.get(mod)
            if t2 is not None and sym in t2.methods:
                return t2, sym
        return None

    def _method_qname(self, table: ModuleTable, cls: str, meth: str,
                      _seen=None) -> Optional[str]:
        """Method lookup through the class and its repo-resolvable
        bases (one definition order pass, cycle-guarded)."""
        _seen = _seen if _seen is not None else set()
        loc = self._class_table(table, cls)
        if loc is None or (id(loc[0]), loc[1]) in _seen:
            return None
        _seen.add((id(loc[0]), loc[1]))
        t, c = loc
        if meth in t.methods[c]:
            return f"{t.dotted}::{c}.{meth}"
        for base in t.bases.get(c, ()):  # single inheritance here
            bname = base.id if isinstance(base, ast.Name) else None
            if bname is None:
                continue
            q = self._method_qname(t, bname, meth, _seen)
            if q is not None:
                return q
        return None

    def resolve_call(self, sf: SourceFile, node: ast.Call,
                     class_name: Optional[str]) -> Optional[str]:
        """qname of the repo-local callee, or None (unresolvable —
        parameters holding callables, foreign modules, dynamic attrs)."""
        table = module_table(sf)
        fn = node.func
        if isinstance(fn, ast.Name):
            name = fn.id
            if name in table.defs:
                return self._resolve_in_table(table, name)
            if name in table.imports:
                return self._resolve_dotted(table.imports[name])
            return None
        chain = attr_chain(fn)
        if chain is None:
            return None
        parts = chain.split(".")
        if parts[0] in ("self", "cls") and class_name and len(parts) == 2:
            return self._method_qname(table, class_name, parts[1])
        if len(parts) == 2:
            base, sym = parts
            target = table.imports.get(base)
            if target is not None:
                t2 = self.by_module.get(target)
                if t2 is not None:
                    return self._resolve_in_table(t2, sym)
                return self._resolve_dotted(f"{target}.{sym}")
            # Class.method / Class() via a local class
            if base in table.methods and sym in table.methods[base]:
                return f"{table.dotted}::{base}.{sym}"
        return None

    # -- summaries + reachability -----------------------------------------

    def edges_from(self, sf: SourceFile, fn_node: ast.AST,
                   class_name: Optional[str]) -> List[Tuple[int, str]]:
        """Resolved (call line, callee qname) edges of one function,
        nested defs included (their calls charge to the owner).
        Memoized per def node — several rules (and several BFS roots)
        revisit the same hot helpers."""
        memo = self._edges_memo.get(id(fn_node))
        if memo is not None:
            return memo
        out: List[Tuple[int, str]] = []
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Call):
                q = self.resolve_call(sf, node, class_name)
                if q is not None and q in self.functions:
                    out.append((node.lineno, q))
        self._edges_memo[id(fn_node)] = out
        return out

    def walk(self, sf: SourceFile, root_node: ast.AST,
             class_name: Optional[str]) -> Iterable[
                 Tuple["FunctionInfo", Tuple[Tuple[int, str], ...]]]:
        """BFS over resolved edges from `root_node`, yielding each
        reachable FunctionInfo ONCE with the shortest call path that
        reached it — a tuple of (call line in caller, callee qname)
        hops, root first.  The root itself is not yielded."""
        seen = set()
        queue = deque()
        for line, q in self.edges_from(sf, root_node, class_name):
            if q not in seen:
                seen.add(q)
                queue.append((q, ((line, q),)))
        while queue:
            q, path = queue.popleft()
            info = self.functions[q]
            yield info, path
            for line, nq in self.edges_from(
                info.sf, info.node, info.class_name
            ):
                if nq not in seen:
                    seen.add(nq)
                    queue.append((nq, path + ((line, nq),)))


#: cross-run graph memo keyed by the identity of the (cached) file set:
#: core._FILE_CACHE keeps SourceFiles alive and stable until their file
#: changes, so two analyses of the same unchanged set share one graph —
#: the tier-1 suite re-analyzes das_tpu/ many times.  Small and bounded:
#: distinct file sets per process are a handful.
_GRAPH_MEMO: Dict[Tuple[int, ...], CallGraph] = {}


def callgraph(ctx: AnalysisContext) -> CallGraph:
    """The run's shared CallGraph, built lazily, cached on the context
    AND memoized per identical file set across runs."""
    graph = getattr(ctx, "_callgraph", None)
    if graph is None:
        key = tuple(id(sf) for sf in ctx.files)
        graph = _GRAPH_MEMO.get(key)
        if graph is None:
            if len(_GRAPH_MEMO) > 16:
                _GRAPH_MEMO.clear()
            graph = CallGraph(ctx.files)
            _GRAPH_MEMO[key] = graph
        ctx._callgraph = graph
    return graph
