"""DL004 — dispatch/route counter key discipline.

Contract (PR 1..4): the regression suites pin DISPATCH_COUNTS /
ROUTE_COUNTS totals so a refactor cannot silently re-fragment the
pipeline or re-route eligible shapes to the lowered chains.  That only
works if the key strings are a closed, declared set: a typo'd key
(`record_dispatch("fused_kernal")`) would count into a fresh dict slot,
the pinned key would stay zero... and the pins only catch it if someone
thought to pin that path.  `das_tpu/ops/counters.py` now declares both
key sets (DISPATCH_KEYS / ROUTE_KEYS) and the dicts are BUILT from
them; this rule pins the literals:

  * every string key used to subscript DISPATCH_COUNTS/ROUTE_COUNTS
    (assignment, +=, or read), passed to `record_dispatch(...)`, or
    assigned to a local that subscripts them, must be declared;
  * every declared key must be used by at least one counting site;
  * every declared key must appear (quoted) in at least one test file —
    an unpinned counter is telemetry nobody would notice breaking
    (tests/test_zlint.py's registry pin covers the long tail; hot keys
    are pinned by the kernel/pipeline/sharded suites);
  * a literal dict assigned to DISPATCH_COUNTS/ROUTE_COUNTS must have
    exactly the declared keys (the real dicts are comprehensions over
    the registry, so this leg guards fixtures and future forks).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from das_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    const_str,
    module_assign,
    register,
    str_collection,
)

_DICT_TO_REGISTRY = {
    "DISPATCH_COUNTS": "DISPATCH_KEYS",
    "ROUTE_COUNTS": "ROUTE_KEYS",
}


def _counts_name(node: ast.AST) -> Optional[str]:
    """DISPATCH_COUNTS / ROUTE_COUNTS for Name or dotted access."""
    if isinstance(node, ast.Name) and node.id in _DICT_TO_REGISTRY:
        return node.id
    if isinstance(node, ast.Attribute) and node.attr in _DICT_TO_REGISTRY:
        return node.attr
    return None


def _find_registries(ctx: AnalysisContext):
    out = {}
    for sf in ctx.modules():
        for reg_name in ("DISPATCH_KEYS", "ROUTE_KEYS"):
            keys = str_collection(module_assign(sf.tree, reg_name))
            if keys is not None and reg_name not in out:
                out[reg_name] = (sf, keys)
    return out


def _scope_nodes(func: ast.AST) -> Iterable[ast.AST]:
    """Walk a function's body, pruning nested function scopes — each
    nested def is its own scope and is visited by its own pass."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(node))


def _use_sites(sf) -> Iterable[Tuple[int, str, str]]:
    """(line, counts-dict name, key literal) for every counting site."""
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # resolve `route = "staged"; ...; ROUTE_COUNTS[route] += 1`
            # one function at a time: collect the names used as dynamic
            # subscripts, then every string constant assigned to them
            dyn: Dict[str, str] = {}
            for sub in _scope_nodes(node):
                if (
                    isinstance(sub, ast.Subscript)
                    and _counts_name(sub.value)
                    and isinstance(sub.slice, ast.Name)
                ):
                    dyn[sub.slice.id] = _counts_name(sub.value)
            if not dyn:
                continue
            for sub in _scope_nodes(node):
                if isinstance(sub, ast.Assign):
                    vals = [const_str(sub.value)]
                    if isinstance(sub.value, ast.IfExp):
                        vals = [
                            const_str(sub.value.body),
                            const_str(sub.value.orelse),
                        ]
                    vals = [v for v in vals if v is not None]
                    for t in sub.targets:
                        if isinstance(t, ast.Name) and t.id in dyn:
                            for v in vals:
                                yield sub.lineno, dyn[t.id], v
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Subscript):
            counts = _counts_name(node.value)
            key = const_str(node.slice)
            if counts and key is not None:
                yield node.lineno, counts, key
        elif isinstance(node, ast.Call):
            fname = getattr(
                node.func, "id", getattr(node.func, "attr", None)
            )
            if fname == "record_dispatch" and node.args:
                key = const_str(node.args[0])
                if key is not None:
                    yield node.lineno, "DISPATCH_COUNTS", key


def _dict_literal_keys(sf, dict_name: str) -> Optional[Set[str]]:
    node = module_assign(sf.tree, dict_name)
    if isinstance(node, ast.Dict):
        keys = {const_str(k) for k in node.keys if k is not None}
        keys.discard(None)
        return keys  # type: ignore[return-value]
    return None


@register("DL004", "counter keys vs ops/counters.py registry")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    registries = _find_registries(ctx)
    uses: List[Tuple[str, int, str, str]] = []
    for sf in ctx.modules():
        for line, counts, key in _use_sites(sf):
            uses.append((sf.posix, line, counts, key))
    if not uses and not registries:
        return
    for posix, line, counts, key in uses:
        reg_name = _DICT_TO_REGISTRY[counts]
        if reg_name not in registries:
            yield Finding(
                "DL004", posix, line,
                f"{counts}[{key!r}] but no {reg_name} registry in the "
                "analyzed set (das_tpu/ops/counters.py declares it)",
            )
            continue
        reg_sf, keys = registries[reg_name]
        if key not in keys:
            yield Finding(
                "DL004", posix, line,
                f"{counts}[{key!r}] is not declared in {reg_name} "
                f"({reg_sf.short}) — an undeclared key dodges every "
                "dispatch-count regression pin",
            )
    used_by_reg: Dict[str, Set[str]] = {"DISPATCH_KEYS": set(), "ROUTE_KEYS": set()}
    for _p, _l, counts, key in uses:
        used_by_reg[_DICT_TO_REGISTRY[counts]].add(key)
    if ctx.partial:
        # dead-key and test-reference legs are only provable on the
        # FULL set — a partial run may not include the counting module
        # (zeroed BEFORE the tests/ sweep: --changed-only exists to be
        # fast, reading the whole tests tree for an empty loop isn't)
        registries = {}
    tests_text = None
    if registries and ctx.tests_dir is not None and ctx.tests_dir.is_dir():
        tests_text = "\n".join(
            p.read_text() for p in sorted(ctx.tests_dir.rglob("*.py"))
        )
    for reg_name, (sf, keys) in registries.items():
        line = next(
            (
                n.lineno for n in sf.tree.body
                if isinstance(n, ast.Assign)
                and any(getattr(t, "id", None) == reg_name for t in n.targets)
            ),
            1,
        )
        for key in keys:
            if key not in used_by_reg[reg_name]:
                yield Finding(
                    "DL004", sf.posix, line,
                    f"{reg_name} declares {key!r} but no counting site "
                    "uses it — dead counter key",
                )
            if tests_text is not None and (
                f'"{key}"' not in tests_text and f"'{key}'" not in tests_text
            ):
                yield Finding(
                    "DL004", sf.posix, line,
                    f"{reg_name} key {key!r} is referenced by no test — "
                    "pin it (tests/test_zlint.py registry pin at minimum)",
                )
    # dict literals must mirror the registry exactly
    for sf in ctx.modules():
        for dict_name, reg_name in _DICT_TO_REGISTRY.items():
            lit = _dict_literal_keys(sf, dict_name)
            if lit is None or reg_name not in registries:
                continue
            _rsf, keys = registries[reg_name]
            missing = set(keys) - lit
            extra = lit - set(keys)
            if missing or extra:
                yield Finding(
                    "DL004", sf.posix, 1,
                    f"{dict_name} literal drifts from {reg_name}: "
                    f"missing={sorted(missing)} extra={sorted(extra)} — "
                    "build the dict from the registry instead",
                )
