"""DL008 — planner routes and counter keys from the ops/counters.py
registries.

Contract (ISSUE 8 / ROADMAP "keep daslint honest"): the cost-based
planner (das_tpu/planner) PREDICTS execution routes and counts its own
telemetry — and both vocabularies are closed, declared sets:

  * every route string the planner emits (a `route = "..."` assignment
    or a `route="..."` keyword, e.g. into `PlannedProgram`) must be a
    member of `ROUTE_KEYS` (ops/counters.py) — a planner inventing a
    route no counter tracks would make its explain/telemetry output
    unverifiable against the executors' actual route accounting, and
    the route-count regression pins could never catch the drift;
  * every `PLANNER_COUNTS[...]` key literal — anywhere in the tree,
    including the executors' planner hooks — must be declared in
    `PLANNER_KEYS`, every declared key must be counted somewhere, and a
    literal dict named PLANNER_COUNTS must mirror the registry exactly
    (the DL004 discipline, applied to the planner's own counter set).

Scope: the route-literal leg applies to planner modules — a file whose
path contains "planner", or that references the planner markers
(PLANNER_COUNTS / PLANNER_KEYS / PlannedProgram).  Executor-side route
locals stay DL004's jurisdiction (they subscript ROUTE_COUNTS), and the
kernels' budget-route locals ("single"/"tiled"/"lowered") never collide
because those are assigned from `budget.ROUTE_*` names, not literals.
Dynamic subscripts resolve like DL004: a local assigned only string
constants that later subscripts PLANNER_COUNTS pins those constants.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from das_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    const_str,
    module_assign,
    register,
    str_collection,
)

_MARKERS = ("PLANNER_COUNTS", "PLANNER_KEYS", "PlannedProgram")


def _find_registry(ctx: AnalysisContext, name: str):
    for sf in ctx.modules():
        keys = str_collection(module_assign(sf.tree, name))
        if keys is not None:
            return sf, keys
    return None


def _in_scope(sf) -> bool:
    if "planner" in sf.posix:
        return True
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Name) and node.id in _MARKERS:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _MARKERS:
            return True
    return False


def _literals(node: ast.AST) -> List[str]:
    """String constants an expression can evaluate to: plain constants
    and IfExp branches (nested), the shapes route assignments take."""
    s = const_str(node)
    if s is not None:
        return [s]
    if isinstance(node, ast.IfExp):
        return _literals(node.body) + _literals(node.orelse)
    return []


def _route_sites(sf) -> Iterable[Tuple[int, str]]:
    """(line, literal) for every route string the module emits."""
    for node in ast.walk(sf.tree):
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for t in targets:
            name = getattr(t, "id", getattr(t, "attr", None))
            if name == "route" and value is not None:
                for lit in _literals(value):
                    yield node.lineno, lit
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "route":
                    for lit in _literals(kw.value):
                        yield node.lineno, lit


def _counts_name(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Name) and node.id == "PLANNER_COUNTS"
    ) or (
        isinstance(node, ast.Attribute) and node.attr == "PLANNER_COUNTS"
    )


def _scope_nodes(func: ast.AST) -> Iterable[ast.AST]:
    stack: List[ast.AST] = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(node))


def _counter_sites(sf) -> Iterable[Tuple[int, str]]:
    """(line, key literal) for every PLANNER_COUNTS counting site,
    including DL004-style dynamic locals (`method = "dp"; ...;
    PLANNER_COUNTS[method] += 1`)."""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Subscript) and _counts_name(node.value):
            key = const_str(node.slice)
            if key is not None:
                yield node.lineno, key
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        dyn: Set[str] = set()
        for sub in _scope_nodes(node):
            if (
                isinstance(sub, ast.Subscript)
                and _counts_name(sub.value)
                and isinstance(sub.slice, ast.Name)
            ):
                dyn.add(sub.slice.id)
        if not dyn:
            continue
        for sub in _scope_nodes(node):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if isinstance(t, ast.Name) and t.id in dyn:
                        for lit in _literals(sub.value):
                            yield sub.lineno, lit


@register("DL008", "planner routes / counter keys vs ops/counters.py")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    route_reg = _find_registry(ctx, "ROUTE_KEYS")
    planner_reg = _find_registry(ctx, "PLANNER_KEYS")
    counter_uses: List[Tuple[str, int, str]] = []
    for sf in ctx.modules():
        if not _in_scope(sf):
            continue
        for line, lit in _route_sites(sf):
            if route_reg is None:
                yield Finding(
                    "DL008", sf.posix, line,
                    f"planner route {lit!r} but no ROUTE_KEYS registry in "
                    "the analyzed set (das_tpu/ops/counters.py declares it)",
                )
            elif lit not in route_reg[1]:
                yield Finding(
                    "DL008", sf.posix, line,
                    f"planner route {lit!r} is not declared in ROUTE_KEYS "
                    f"({route_reg[0].short}) — a route no counter tracks "
                    "makes planner telemetry unverifiable against the "
                    "executors' route accounting",
                )
        for line, lit in _counter_sites(sf):
            counter_uses.append((sf.posix, line, lit))
    used: Set[str] = set()
    for posix, line, key in counter_uses:
        used.add(key)
        if planner_reg is None:
            yield Finding(
                "DL008", posix, line,
                f"PLANNER_COUNTS[{key!r}] but no PLANNER_KEYS registry in "
                "the analyzed set (das_tpu/ops/counters.py declares it)",
            )
        elif key not in planner_reg[1]:
            yield Finding(
                "DL008", posix, line,
                f"PLANNER_COUNTS[{key!r}] is not declared in PLANNER_KEYS "
                f"({planner_reg[0].short}) — an undeclared key dodges the "
                "planner-telemetry pins",
            )
    # dead-key entries are only provable on the FULL set (--changed-only)
    if planner_reg is not None and counter_uses and not ctx.partial:
        sf, keys = planner_reg
        line = next(
            (
                n.lineno for n in sf.tree.body
                if isinstance(n, ast.Assign)
                and any(
                    getattr(t, "id", None) == "PLANNER_KEYS"
                    for t in n.targets
                )
            ),
            1,
        )
        for key in keys:
            if key not in used:
                yield Finding(
                    "DL008", sf.posix, line,
                    f"PLANNER_KEYS declares {key!r} but no counting site "
                    "uses it — dead planner counter key",
                )
    # literal dicts named PLANNER_COUNTS must mirror the registry
    if planner_reg is not None:
        _rsf, keys = planner_reg
        for sf in ctx.modules():
            node = module_assign(sf.tree, "PLANNER_COUNTS")
            if isinstance(node, ast.Dict):
                lit: Set[str] = set()
                for k in node.keys:
                    s = const_str(k) if k is not None else None
                    if s is not None:
                        lit.add(s)
                missing = set(keys) - lit
                extra = lit - set(keys)
                if missing or extra:
                    yield Finding(
                        "DL008", sf.posix, 1,
                        "PLANNER_COUNTS literal drifts from PLANNER_KEYS: "
                        f"missing={sorted(missing)} extra={sorted(extra)} "
                        "— build the dict from the registry instead",
                    )
