"""DL002 — plan-signature completeness.

Contract (PR 1..4): a compiled executable is cached under its plan
signature (FusedPlanSig / ShardedPlanSig / FusedExactSig), so EVERY
property that changes what the builder traces must be a field of that
frozen dataclass — and every field must participate in __eq__/__hash__.
The `tiled` / `vmem_budget` omissions caught by hand in PR 4 are the
canonical failure: routing consulted a value the signature didn't
carry, two different programs collided under one cache key, and the
wrong executable replayed silently (wrong layout, or at sharded scale
wrong answers — cache poisoning, not a crash).

Mechanical checks, per dataclass whose name ends in `Sig` (term sigs
ride along — they nest inside the plan sigs' hash):

  1. the decorator must say `@dataclass(frozen=True)` and not disable
     eq — an unfrozen or eq-less sig is unhashable-by-value;
  2. no field may opt out via `field(hash=False)`/`field(compare=False)`
     — that is precisely a routing input missing from the cache key;
  3. every attribute read through a parameter ANNOTATED with the sig
     class (`def build_fused(sig: FusedPlanSig, ...)` — the
     routing/executable-build consumers), including `getattr(sig, "x"
     [, default])`, must be a declared field, property, or method —
     the static catch for the next `tiled`-style omission;
  4. constructor calls must not exceed the field count positionally nor
     pass unknown keywords.

Checks 3/4 resolve sig classes across the whole analyzed set, so
`build_fused_sharded` reading a `FusedTermSig` imported from
query/fused.py is checked too.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from das_tpu.analysis.core import AnalysisContext, Finding, const_str, register


class _SigClass:
    def __init__(self, sf_posix: str, node: ast.ClassDef):
        self.posix = sf_posix
        self.node = node
        self.name = node.name
        self.fields: List[str] = []
        self.members: Set[str] = set()  # methods + properties
        self.frozen = False
        self.eq_disabled = False
        self.opted_out: List[Tuple[str, int]] = []  # field, line
        self._parse()

    def _parse(self) -> None:
        for dec in self.node.decorator_list:
            if isinstance(dec, ast.Call) and getattr(
                dec.func, "id", getattr(dec.func, "attr", "")
            ) == "dataclass":
                for kw in dec.keywords:
                    if kw.arg == "frozen" and getattr(kw.value, "value", None):
                        self.frozen = True
                    if kw.arg == "eq" and getattr(kw.value, "value", True) is False:
                        self.eq_disabled = True
            elif getattr(dec, "id", getattr(dec, "attr", "")) == "dataclass":
                pass  # bare @dataclass: not frozen
        for stmt in self.node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                name = stmt.target.id
                ann = ast.unparse(stmt.annotation)
                if ann.startswith("ClassVar"):
                    continue
                self.fields.append(name)
                if isinstance(stmt.value, ast.Call):
                    chain = ast.unparse(stmt.value.func)
                    if chain.endswith("field"):
                        for kw in stmt.value.keywords:
                            if kw.arg in ("hash", "compare") and getattr(
                                kw.value, "value", True
                            ) is False:
                                self.opted_out.append((name, stmt.lineno))
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.members.add(stmt.name)


def _collect_sig_classes(ctx: AnalysisContext) -> Dict[str, _SigClass]:
    out: Dict[str, _SigClass] = {}
    for sf in ctx.modules():
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef) and node.name.endswith("Sig"):
                is_dc = any(
                    "dataclass" in ast.unparse(d)
                    for d in node.decorator_list
                )
                if is_dc:
                    out[node.name] = _SigClass(sf.posix, node)
    return out


def _annotation_names(node: Optional[ast.AST]) -> List[str]:
    """Candidate class names an annotation may refer to — unwrapping
    Optional[...]/Union[...]/`X | None` so a consumer taking an optional
    sig keeps the rule's protection."""
    if node is None:
        return []
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, ast.Attribute):
        return [node.attr]
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value.rsplit(".", 1)[-1].strip("'\"")]
    if isinstance(node, ast.Subscript):
        base = getattr(node.value, "id", getattr(node.value, "attr", ""))
        if base in ("Optional", "Union"):
            inner = node.slice
            elts = inner.elts if isinstance(inner, ast.Tuple) else [inner]
            return [n for e in elts for n in _annotation_names(e)]
        return []
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        return _annotation_names(node.left) + _annotation_names(node.right)
    return []


def _check_reads(
    sf_posix: str, fn: ast.AST, param: str, sig: _SigClass
) -> Iterable[Finding]:
    known = set(sig.fields) | sig.members
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == param
            and node.attr not in known
            and not node.attr.startswith("__")
        ):
            yield Finding(
                "DL002", sf_posix, node.lineno,
                f"`{param}.{node.attr}` read by build/routing code but "
                f"`{node.attr}` is not a declared field of "
                f"{sig.name} — a routing input missing from the plan "
                "signature poisons the executable cache",
            )
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "getattr"
            and len(node.args) >= 2
            and isinstance(node.args[0], ast.Name)
            and node.args[0].id == param
        ):
            attr = const_str(node.args[1])
            if attr is not None and attr not in known:
                yield Finding(
                    "DL002", sf_posix, node.lineno,
                    f"getattr({param}, {attr!r}) but `{attr}` is not a "
                    f"declared field of {sig.name} — the default silently "
                    "papers over a missing plan-signature field",
                )


@register("DL002", "plan-signature completeness")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    sigs = _collect_sig_classes(ctx)
    # 1/2: hash integrity of the sig dataclasses themselves
    for sig in sigs.values():
        if not sig.frozen:
            yield Finding(
                "DL002", sig.posix, sig.node.lineno,
                f"{sig.name} must be @dataclass(frozen=True) — plan "
                "signatures are cache keys and must hash by value",
            )
        if sig.eq_disabled:
            yield Finding(
                "DL002", sig.posix, sig.node.lineno,
                f"{sig.name} disables eq — every field must feed the "
                "cache key",
            )
        for fname, lineno in sig.opted_out:
            yield Finding(
                "DL002", sig.posix, lineno,
                f"{sig.name}.{fname} opts out of hash/compare — a "
                "routing field excluded from the cache key is exactly "
                "the tiled/vmem_budget class of bug",
            )
    # 3: attribute reads through annotated consumer params
    for sf in ctx.modules():
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            args = list(node.args.posonlyargs) + list(node.args.args) + list(
                node.args.kwonlyargs
            )
            for a in args:
                for ann in _annotation_names(a.annotation):
                    if ann in sigs:
                        yield from _check_reads(
                            sf.posix, node, a.arg, sigs[ann]
                        )
                        break
    # 4: constructor discipline
    for sf in ctx.modules():
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = getattr(
                node.func, "id", getattr(node.func, "attr", None)
            )
            if name not in sigs:
                continue
            sig = sigs[name]
            if len(node.args) > len(sig.fields):
                yield Finding(
                    "DL002", sf.posix, node.lineno,
                    f"{name}(...) called with {len(node.args)} positional "
                    f"args but only {len(sig.fields)} fields are declared",
                )
            for kw in node.keywords:
                if kw.arg is not None and kw.arg not in sig.fields:
                    yield Finding(
                        "DL002", sf.posix, node.lineno,
                        f"{name}(...) passes unknown keyword `{kw.arg}` — "
                        "not a declared field",
                    )
