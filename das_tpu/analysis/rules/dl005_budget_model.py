"""DL005 — VMEM budget-model drift.

Contract (PR 4, ROADMAP's top hardware lever): `kernels/budget.py`'s
per-stage byte models decide single-block vs grid-chunked vs lowered by
summing the COMBINED buffers each kernel body holds concurrently.  The
models count DECLARED buffers — so a new Ref added to a kernel body (a
scratch table, an extra output block) that is not reflected in the byte
model is a latent VMEM OOM on real hardware: the planner keeps routing
shapes whose true footprint overflows, and nothing fails until the
first Mosaic compile on a TPU host.  Off-TPU (discharge/interpreter)
the bug is invisible by construction, which is why it must be caught
statically.

Mechanism: `budget.KERNEL_BUFFERS` declares, per kernel body, the exact
ordered tuple of `*_ref` parameters its byte model accounts for.  This
rule finds every kernel body in the analyzed set — a nested function
named `kernel` whose parameters end in `_ref` (the grid index `g` of
the tiled bodies is ignored) — keyed `<module stem>.<outer factory>`,
and pins signature <-> manifest both ways:

  * a body absent from the manifest, or whose ref tuple differs, means
    a buffer the byte model never priced: the fix is updating the model
    in kernels/budget.py AND its manifest entry in the same commit;
  * a manifest entry with no matching body is stale.

This is deliberately a tripwire, not a bytes proof: it cannot verify
the per-row arithmetic, but it guarantees every buffer-shape change
lands in the file where that arithmetic lives, under review.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from das_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    const_str,
    module_assign,
    register,
)


def _find_manifest(ctx: AnalysisContext):
    for sf in ctx.modules():
        node = module_assign(sf.tree, "KERNEL_BUFFERS")
        if isinstance(node, ast.Dict):
            manifest: Dict[str, Tuple[str, ...]] = {}
            for k, v in zip(node.keys, node.values):
                name = const_str(k) if k is not None else None
                if name is None:
                    continue
                refs = []
                if isinstance(v, (ast.Tuple, ast.List)):
                    refs = [const_str(e) for e in v.elts]
                manifest[name] = tuple(r for r in refs if r is not None)
            return sf, node.lineno, manifest
    return None


def _kernel_bodies(sf) -> List[Tuple[str, int, Tuple[str, ...]]]:
    """(qualified key, line, ref params) for each nested `kernel` def."""
    out = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for child in node.body:
            if (
                isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
                and child.name == "kernel"
            ):
                refs = tuple(
                    a.arg for a in child.args.args if a.arg.endswith("_ref")
                )
                if refs:
                    out.append(
                        (f"{sf.name}.{node.name}", child.lineno, refs)
                    )
    return out


@register("DL005", "kernel-body buffers vs budget.KERNEL_BUFFERS")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    bodies: List[Tuple[str, str, int, Tuple[str, ...]]] = []
    for sf in ctx.modules():
        for key, line, refs in _kernel_bodies(sf):
            bodies.append((sf.posix, key, line, refs))
    found = _find_manifest(ctx)
    if found is None:
        for posix, key, line, _refs in bodies:
            yield Finding(
                "DL005", posix, line,
                f"kernel body `{key}` but no KERNEL_BUFFERS manifest in "
                "the analyzed set (kernels/budget.py declares the "
                "buffers each byte model accounts for)",
            )
        return
    man_sf, man_line, manifest = found
    seen = set()
    for posix, key, line, refs in bodies:
        seen.add(key)
        if key not in manifest:
            yield Finding(
                "DL005", posix, line,
                f"kernel body `{key}` is not in budget.KERNEL_BUFFERS — "
                "its buffers are priced by no byte model (latent VMEM "
                "OOM on hardware); add the entry AND account for the "
                "refs in the stage model",
            )
            continue
        if manifest[key] != refs:
            extra = [r for r in refs if r not in manifest[key]]
            missing = [r for r in manifest[key] if r not in refs]
            yield Finding(
                "DL005", posix, line,
                f"kernel body `{key}` refs drifted from "
                f"budget.KERNEL_BUFFERS: unaccounted={extra} "
                f"stale={missing} — update the byte model and manifest "
                "together",
            )
    # stale entries are only provable against the FULL set — a partial
    # (--changed-only) run may not include a body's module
    for key in manifest if not ctx.partial else ():
        if key not in seen:
            yield Finding(
                "DL005", man_sf.posix, man_line,
                f"KERNEL_BUFFERS entry `{key}` matches no kernel body "
                "in the analyzed set — stale manifest entry",
            )
