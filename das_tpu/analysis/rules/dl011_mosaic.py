"""DL011 — Mosaic readiness of kernel bodies.

Contract (ISSUE 11; ARCHITECTURE §9 "what still needs a real TPU"): no
kernel in das_tpu/kernels/ has ever Mosaic-compiled — every body runs
off-TPU by direct ref-discharge, which accepts strictly MORE programs
than the Mosaic lowering will.  The hazards §9 enumerates are exactly
the ones that surface as burned tunneled-TPU hours at first compile,
so they are enforced at lint time instead:

  * **ref access discipline** — a `*_ref` parameter of a kernel body
    (the KERNEL_BUFFERS naming convention, which the shared helpers
    keep: `_emit_window(.., fvals_ref, perm_ref, ..)`) may only be
    subscripted (`ref[...]` load / `ref[...] = ...` store) or
    forwarded to a repo-local helper that binds it to another `*_ref`
    parameter.  Handing the raw ref to `jnp.*`, aliasing it, or
    passing it into an unresolvable callee works under the discharge
    (`_Ref` quacks enough) and fails or silently misbehaves under
    Mosaic, where a Ref is a memory space, not an array;
  * **no python control flow on traced values** — `if`/`while`/`for`
    whose condition derives from a ref load concretizes a tracer:
    an error under jit, but under the python-loop grid discharge it
    can EXECUTE (step index and hoisted host values mix in), taking
    one trace path and silently diverging from the Mosaic lowering.
    Dataflow: values loaded from refs taint through assignments and
    calls; `.shape`/`.ndim`/`.dtype` access and `len()` break taint
    (static under tracing), and `x is None` tests are exempt
    (identity on the python cell, never a concretization);
  * **no float64/unpriced dtypes** — the byte models price int32/
    int64/bool (and TPUs have no f64); a float64/complex/f16 constant
    or cast inside a kernel module is either a Mosaic lowering error
    or a silent x2 on the VMEM footprint the planner budgeted;
  * **lane-tiled chunk_rows** — every grid-chunked layout's chunk_rows
    must be PROVABLY a multiple of the (8,128) tiling's 128-lane
    minor axis at every budget.py emission site: `chunk_rows_for`'s
    returns and every `StagePlan(...)` chunk argument must reduce to
    lane-aligned arithmetic (literals divisible by 128, `_lane_floor`/
    `_lane_ceil`/`chunk_rows_for` results, min/max/products of
    those).  kernels/budget.py ships lane-aligned in this PR; this
    leg keeps it that way.

Scope: the ref/control-flow legs run on any function with a `*_ref`
parameter (the convention IS the marker, so fixtures and helpers
outside das_tpu/kernels/ are covered too); the dtype leg additionally
sweeps whole modules under a kernels/ directory; the lane legs run on
modules that define `chunk_rows_for` or declare `KERNEL_BUFFERS`.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from das_tpu.analysis.callgraph import callgraph, module_table
from das_tpu.analysis.core import AnalysisContext, Finding, register

LANE_ROWS = 128

_BANNED_DTYPES = frozenset((
    "float64", "complex64", "complex128", "float16",
))

#: callables whose results are lane-aligned by contract
_ALIGNED_CALLS = frozenset((
    "chunk_rows_for", "_lane_floor", "_lane_ceil", "lane_floor", "lane_ceil",
))

#: builtins whose results are static under tracing (taint breakers)
_TAINT_BREAKERS = frozenset(("len", "range", "isinstance", "enumerate"))

_STATIC_ATTRS = frozenset(("shape", "ndim", "dtype"))


def _ref_params(fn: ast.AST) -> Tuple[str, ...]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    return tuple(n for n in names if n.endswith("_ref"))


def _kernel_functions(sf) -> Iterable[Tuple[ast.AST, Tuple[str, ...]]]:
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            refs = _ref_params(node)
            if refs:
                yield node, refs


def _parents(root: ast.AST) -> Dict[int, ast.AST]:
    out: Dict[int, ast.AST] = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            out[id(child)] = node
    return out


def _callee_params(ctx, sf, call: ast.Call) -> Optional[List[str]]:
    """Parameter names of a repo-resolvable callee (for checking that a
    forwarded ref lands on a `*_ref` parameter)."""
    q = callgraph(ctx).resolve_call(sf, call, None)
    if q is None:
        return None
    fn = callgraph(ctx).functions[q].node
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    return names + [p.arg for p in a.kwonlyargs]


# -- ref access discipline ---------------------------------------------------


def _check_refs(ctx, sf, fn, refs) -> Iterable[Finding]:
    parents = _parents(fn)
    nested_params: Set[int] = set()  # param Name nodes of nested defs
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Name) and node.id in refs):
            continue
        if id(node) in nested_params:
            continue
        parent = parents.get(id(node))
        if isinstance(parent, ast.Subscript) and parent.value is node:
            continue
        if isinstance(parent, ast.Call) and node in parent.args:
            params = _callee_params(ctx, sf, parent)
            if params is not None:
                idx = parent.args.index(node)
                if idx < len(params) and params[idx].endswith("_ref"):
                    continue
                yield Finding(
                    "DL011", sf.posix, node.lineno,
                    f"ref `{node.id}` forwarded to a parameter not named "
                    "`*_ref` — the ref naming convention is what keeps "
                    "the access discipline (and KERNEL_BUFFERS) checkable "
                    "through helpers",
                )
                continue
            yield Finding(
                "DL011", sf.posix, node.lineno,
                f"ref `{node.id}` passed to an unresolvable callee — a "
                "raw Ref is a memory space under Mosaic, not an array; "
                "load `{0}[...]` first or forward to a repo-local "
                "`*_ref` parameter".format(node.id),
            )
            continue
        if isinstance(parent, ast.keyword):
            if parent.arg is not None and parent.arg.endswith("_ref"):
                continue
            yield Finding(
                "DL011", sf.posix, node.lineno,
                f"ref `{node.id}` passed as keyword "
                f"`{parent.arg}` (not `*_ref`) — refs may only be "
                "subscripted or forwarded to `*_ref` parameters",
            )
            continue
        yield Finding(
            "DL011", sf.posix, node.lineno,
            f"ref `{node.id}` used outside the subscript discipline — "
            "Mosaic refs must be loaded/stored via `[...]`; aliasing or "
            "wrapping the raw ref diverges between the discharge and "
            "Mosaic lowerings",
        )


# -- python control flow on traced values ------------------------------------


def _is_none_test(test: ast.AST) -> bool:
    return isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
    )


def _tainted_expr(e: ast.AST, tainted: Set[str], refs) -> bool:
    if isinstance(e, ast.Name):
        return e.id in tainted
    if isinstance(e, ast.Subscript):
        base = e.value
        if isinstance(base, ast.Name) and base.id in refs:
            return True
        return _tainted_expr(base, tainted, refs)
    if isinstance(e, ast.Attribute):
        if e.attr in _STATIC_ATTRS:
            return False
        return _tainted_expr(e.value, tainted, refs)
    if isinstance(e, ast.Call):
        fn = e.func
        if isinstance(fn, ast.Name) and fn.id in _TAINT_BREAKERS:
            return False
        if isinstance(fn, ast.Attribute) and _tainted_expr(
            fn.value, tainted, refs
        ):
            return True
        return any(_tainted_expr(a, tainted, refs) for a in e.args) or any(
            _tainted_expr(k.value, tainted, refs) for k in e.keywords
        )
    if isinstance(e, (ast.BinOp,)):
        return (
            _tainted_expr(e.left, tainted, refs)
            or _tainted_expr(e.right, tainted, refs)
        )
    if isinstance(e, ast.BoolOp):
        return any(_tainted_expr(v, tainted, refs) for v in e.values)
    if isinstance(e, ast.Compare):
        return _tainted_expr(e.left, tainted, refs) or any(
            _tainted_expr(c, tainted, refs) for c in e.comparators
        )
    if isinstance(e, ast.UnaryOp):
        return _tainted_expr(e.operand, tainted, refs)
    if isinstance(e, ast.IfExp):
        return (
            _tainted_expr(e.body, tainted, refs)
            or _tainted_expr(e.orelse, tainted, refs)
        )
    if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
        return any(_tainted_expr(v, tainted, refs) for v in e.elts)
    return False


def _target_names(t: ast.AST) -> List[str]:
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in t.elts:
            out.extend(_target_names(e))
        return out
    return []


def _taint_set(fn: ast.AST, refs) -> Set[str]:
    """Names holding ref-derived (traced) values — two passes to settle
    chains across nested defs (the hoisted-prologue closures)."""
    tainted: Set[str] = set()
    for _ in range(3):
        before = len(tainted)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                if _tainted_expr(node.value, tainted, refs):
                    for t in node.targets:
                        tainted.update(_target_names(t))
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if node.value is not None and _tainted_expr(
                    node.value, tainted, refs
                ):
                    tainted.update(_target_names(node.target))
            elif isinstance(node, ast.For):
                if _tainted_expr(node.iter, tainted, refs):
                    tainted.update(_target_names(node.target))
        if len(tainted) == before:
            break
    return tainted


def _check_control_flow(sf, fn, refs) -> Iterable[Finding]:
    tainted = _taint_set(fn, refs)

    def flag(test: ast.AST, what: str, line: int):
        if _is_none_test(test):
            return None
        if _tainted_expr(test, tainted, refs):
            return Finding(
                "DL011", sf.posix, line,
                f"python `{what}` on a traced (ref-derived) value inside "
                "a kernel body — data-dependent python control flow "
                "concretizes under jit and silently diverges between the "
                "discharge and Mosaic lowerings; use jnp.where/@pl.when",
            )
        return None

    for node in ast.walk(fn):
        f = None
        if isinstance(node, ast.If):
            f = flag(node.test, "if", node.lineno)
        elif isinstance(node, ast.While):
            f = flag(node.test, "while", node.lineno)
        elif isinstance(node, ast.IfExp):
            f = flag(node.test, "if-expression", node.lineno)
        elif isinstance(node, ast.Assert):
            f = flag(node.test, "assert", node.lineno)
        elif isinstance(node, ast.For):
            if _tainted_expr(node.iter, tainted, refs):
                f = Finding(
                    "DL011", sf.posix, node.lineno,
                    "python `for` over a traced (ref-derived) value "
                    "inside a kernel body — trip counts must be static",
                )
        if f is not None:
            yield f


# -- dtype sweep -------------------------------------------------------------


def _check_dtypes(sf, root: ast.AST, skip_docstrings: bool) -> Iterable[Finding]:
    doc_ids = set()
    if skip_docstrings:
        for node in ast.walk(root):
            if isinstance(node, (ast.Module, ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                body = getattr(node, "body", [])
                if body and isinstance(body[0], ast.Expr) and isinstance(
                    body[0].value, ast.Constant
                ):
                    doc_ids.add(id(body[0].value))
    for node in ast.walk(root):
        name = None
        if isinstance(node, ast.Attribute) and node.attr in _BANNED_DTYPES:
            name = node.attr
        elif isinstance(node, ast.Name) and node.id in _BANNED_DTYPES:
            name = node.id
        elif (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value in _BANNED_DTYPES
            and id(node) not in doc_ids
        ):
            name = node.value
        if name is not None:
            yield Finding(
                "DL011", sf.posix, node.lineno,
                f"dtype `{name}` in kernel code — unpriced by the "
                "kernels/budget.py byte models and unsupported/emulated "
                "under Mosaic (models price int32/int64/bool/float32)",
            )


# -- lane-tiled chunk_rows ---------------------------------------------------


def _module_int_consts(tree: ast.Module) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant):
            v = node.value.value
            if isinstance(v, int) and not isinstance(v, bool):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = v
    return out


def _aligned(e: ast.AST, env: Dict[str, bool], consts: Dict[str, int]) -> bool:
    if isinstance(e, ast.Constant):
        return isinstance(e.value, int) and not isinstance(e.value, bool) \
            and e.value % LANE_ROWS == 0
    if isinstance(e, ast.Name):
        if env.get(e.id):
            return True
        v = consts.get(e.id)
        return v is not None and v % LANE_ROWS == 0
    if isinstance(e, ast.Call):
        fn = e.func
        fname = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None
        )
        if fname in _ALIGNED_CALLS:
            return True
        if fname in ("min", "max"):
            return bool(e.args) and all(
                _aligned(a, env, consts) for a in e.args
            )
        return False
    if isinstance(e, ast.BinOp):
        if isinstance(e.op, ast.Mult):
            return _aligned(e.left, env, consts) or _aligned(
                e.right, env, consts
            )
        if isinstance(e.op, (ast.Add, ast.Sub)):
            return _aligned(e.left, env, consts) and _aligned(
                e.right, env, consts
            )
        return False
    if isinstance(e, ast.UnaryOp) and isinstance(e.op, ast.USub):
        return _aligned(e.operand, env, consts)
    if isinstance(e, ast.IfExp):
        return _aligned(e.body, env, consts) and _aligned(
            e.orelse, env, consts
        )
    return False


def _stmt_seq(fn: ast.AST) -> Iterable[ast.stmt]:
    """Statements of a function in source order, descending into
    compound bodies (good enough for the straight-line budget code)."""
    def rec(body):
        for s in body:
            yield s
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(s, attr, None)
                if sub:
                    yield from rec(sub)
    yield from rec(fn.body)


def _check_lane_alignment(sf) -> Iterable[Finding]:
    consts = _module_int_consts(sf.tree)
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        env: Dict[str, bool] = {}
        for stmt in _stmt_seq(node):
            if isinstance(stmt, ast.Assign):
                ok = _aligned(stmt.value, env, consts)
                for name in _target_names(
                    stmt.targets[0] if len(stmt.targets) == 1 else ast.Tuple(
                        elts=list(stmt.targets), ctx=ast.Load()
                    )
                ):
                    env[name] = ok
            elif isinstance(stmt, ast.Return) and node.name == "chunk_rows_for":
                if stmt.value is not None and not _aligned(
                    stmt.value, env, consts
                ):
                    yield Finding(
                        "DL011", sf.posix, stmt.lineno,
                        "chunk_rows_for returns a value not provably a "
                        "multiple of the 128-lane tiling — grid-chunked "
                        "blocks must round to the (8,128) TPU tile "
                        "(ARCHITECTURE §9)",
                    )
            for sub in ast.walk(stmt):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id == "StagePlan"
                ):
                    chunk = None
                    if len(sub.args) >= 2:
                        chunk = sub.args[1]
                    for kw in sub.keywords:
                        if kw.arg == "chunk_rows":
                            chunk = kw.value
                    if chunk is not None and not _aligned(chunk, env, consts):
                        yield Finding(
                            "DL011", sf.posix, sub.lineno,
                            "StagePlan chunk_rows emission not provably a "
                            "multiple of the 128-lane tiling — size "
                            "chunks via chunk_rows_for/_lane_floor "
                            "(ARCHITECTURE §9)",
                        )


# -- the rule ----------------------------------------------------------------


def _in_kernels(sf) -> bool:
    return "kernels" in sf.path.parts


@register("DL011", "Mosaic readiness of kernel bodies")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    for sf in ctx.modules():
        module_table(sf)  # prime (also validates the walk on odd files)
        kernel_fns = list(_kernel_functions(sf))
        scanned_ids = set()
        for fn, refs in kernel_fns:
            yield from _check_refs(ctx, sf, fn, refs)
            yield from _check_control_flow(sf, fn, refs)
            if not _in_kernels(sf):
                if id(fn) not in scanned_ids:
                    scanned_ids.add(id(fn))
                    yield from _check_dtypes(sf, fn, skip_docstrings=True)
        if _in_kernels(sf):
            yield from _check_dtypes(sf, sf.tree, skip_docstrings=True)
        if (
            "chunk_rows_for" in module_table(sf).defs
            or any(
                isinstance(n, ast.Assign) and any(
                    getattr(t, "id", None) == "KERNEL_BUFFERS"
                    for t in n.targets
                )
                for n in sf.tree.body
            )
        ):
            yield from _check_lane_alignment(sf)
