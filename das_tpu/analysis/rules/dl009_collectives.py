"""DL009 — shard_map collective discipline.

Contract (ISSUE 10 / ROADMAP "named candidate rules"): XLA collectives
(`all_gather` / `all_to_all` / `psum` / `pmax` / `pmin` / `ppermute` /
`psum_scatter`) are the mesh programs' ONLY cross-shard channel, and
where they may appear is a closed, declared set:

  * NEVER inside das_tpu/kernels/ — kernel bodies are SHARD-LOCAL by
    design (parallel/fused_sharded.py routes them inside shard_map, one
    shard's slab per invocation; ARCHITECTURE §9).  A collective inside
    a kernel body either fails to lower (Pallas), deadlocks (one shard
    takes a different trace path), or silently changes semantics
    between the interpret/discharge/Mosaic lowerings — the worst bug
    class on real hardware, invisible on the single-device CPU suite;
  * everywhere else, only inside the scopes declared in
    `COLLECTIVE_SITES` (parallel/mesh.py) — the lowered mesh helpers
    (gather/exchange/reduction) whose collective use IS their purpose.
    Concentrating the call sites keeps every cross-shard byte visible
    in one reviewable list (the ICI traffic model of ARCHITECTURE §8).

Attribution: a call is charged to its OUTERMOST enclosing scope —
leading class names plus the first function name, qualified by the
module stem ("fused_sharded._repartition",
"sharded_db.ShardedDB._join") — so nested closure bodies (`body`,
`kernel`, `build`) charge to the helper that owns them.  Both
directions are pinned: an undeclared collective call fails lint, and a
declared scope that no longer contains a collective is a stale entry.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from das_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    module_assign,
    register,
    str_collection,
)

#: the XLA cross-shard communication primitives this rule pins
COLLECTIVE_NAMES = frozenset((
    "all_gather",
    "all_to_all",
    "psum",
    "pmax",
    "pmin",
    "ppermute",
    "psum_scatter",
))


def _find_registry(ctx: AnalysisContext):
    for sf in ctx.modules():
        keys = str_collection(module_assign(sf.tree, "COLLECTIVE_SITES"))
        if keys is not None:
            return sf, keys
    return None


def _is_collective_call(node: ast.Call) -> Optional[str]:
    """The collective's name when `node` calls one (lax.psum /
    jax.lax.all_gather / a from-imported bare name), else None."""
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in COLLECTIVE_NAMES:
        return fn.attr
    if isinstance(fn, ast.Name) and fn.id in COLLECTIVE_NAMES:
        return fn.id
    return None


def _in_kernels(sf) -> bool:
    return "kernels" in sf.path.parts


def _collective_sites(sf) -> Iterable[Tuple[int, str, str]]:
    """(line, collective name, outermost qualified scope) per call."""

    def walk(node: ast.AST, classes: List[str], func: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                # a class nested under a function charges to the func
                yield from walk(
                    (child),
                    (classes + [child.name]) if func is None else classes,
                    func,
                )
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from walk(
                    child, classes,
                    func if func is not None else child.name,
                )
            else:
                if isinstance(child, ast.Call):
                    name = _is_collective_call(child)
                    if name is not None:
                        scope = (
                            ".".join([sf.name] + classes + [func])
                            if func is not None else "<module>"
                        )
                        yield child.lineno, name, scope
                yield from walk(child, classes, func)

    yield from walk(sf.tree, [], None)


@register("DL009", "shard_map collective discipline")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    registry = _find_registry(ctx)
    used_scopes: Set[str] = set()
    any_calls = False
    for sf in ctx.modules():
        kernels_file = _in_kernels(sf)
        for line, name, scope in _collective_sites(sf):
            any_calls = True
            if kernels_file:
                yield Finding(
                    "DL009", sf.posix, line,
                    f"collective `{name}` inside a shard-local kernel "
                    "body (das_tpu/kernels/) — kernel bodies run per "
                    "shard under shard_map; a collective here deadlocks "
                    "or silently diverges between the interpret/"
                    "discharge/Mosaic lowerings",
                )
                continue
            if registry is None:
                yield Finding(
                    "DL009", sf.posix, line,
                    f"collective `{name}` but no COLLECTIVE_SITES "
                    "registry in the analyzed set (das_tpu/parallel/"
                    "mesh.py declares it)",
                )
                continue
            used_scopes.add(scope)
            if scope not in registry[1]:
                yield Finding(
                    "DL009", sf.posix, line,
                    f"collective `{name}` in undeclared scope "
                    f"`{scope}` — collectives belong in the declared "
                    f"lowered helpers (COLLECTIVE_SITES, "
                    f"{registry[0].short}), where every cross-shard "
                    "byte stays reviewable in one list",
                )
    # stale entries are only provable against the FULL set — a partial
    # (--changed-only) run may simply not include a scope's module
    if registry is not None and any_calls and not ctx.partial:
        reg_sf, declared = registry
        line = next(
            (
                n.lineno for n in reg_sf.tree.body
                if isinstance(n, ast.Assign)
                and any(
                    getattr(t, "id", None) == "COLLECTIVE_SITES"
                    for t in n.targets
                )
            ),
            1,
        )
        for scope in declared:
            if scope not in used_scopes:
                yield Finding(
                    "DL009", reg_sf.posix, line,
                    f"COLLECTIVE_SITES declares `{scope}` but no "
                    "collective call lives there — stale entry (the "
                    "helper moved, got renamed, or lost its collective)",
                )
