"""DL016 — program-construction sites vs the PROGRAM_SITES registry
(ISSUE 14).

Contract: the program ledger's coverage claim — "every device program
the serving path compiles is compile/cost/memory-observable" — is only
as good as the registry.  A new `jax.jit(...)` / `pl.pallas_call(...)`
entry point added without a registry decision is a program whose
compile time, FLOPs and HBM footprint silently go dark (exactly the
blind spot ISSUE 14 closes); an instrumented scope whose
`instrument(...)` hook was refactored away keeps promising ledger
coverage that no longer exists.

The DL013 FETCH_SITES idiom, applied to program construction.
`PROGRAM_SITES` (das_tpu/obs/proflog.py) is a dict mapping every scope
that constructs a device program — attributed to its OUTERMOST
enclosing function, module-qualified like DL013 ("fused.build_fused",
"common.run_kernel") — to its ledger site label, or None for a
DECLARED-EXEMPT scope (per-op staged programs, kernel wrappers that
trace inside instrumented programs, ingest-time builders).  Four legs:

  * a jit/pallas reference in an UNdeclared scope fails lint — every
    program-construction site stays a reviewed decision in one list;
  * a declared scope with a non-None label must contain a ledger hook
    call (`instrument(...)` / `record_launch(...)`) passing EXACTLY
    that label literal — an instrumented site cannot silently drop its
    ledger coverage;
  * every `instrument("<label>")` / `record_launch("<label>")` literal
    anywhere must be a declared label — a typo'd site records into a
    lane nobody aggregates (the DL004/DL014 failure mode);
  * a declared scope with NO jit/pallas reference is a stale entry
    (full-set runs only — a --changed-only subset may not include the
    module).

Attribution counts ANY AST reference to `jax.jit` or `pl.pallas_call`
(call, decorator, `partial(jax.jit, ...)` argument) — the construction
primitive reaching a scope at all is what makes it a program site.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from das_tpu.analysis.callgraph import scope_module
from das_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    attr_chain,
    const_str,
    module_assign,
    register,
)

#: the program-construction primitives this registry closes over —
#: dotted references and the bare names a `from jax import jit` /
#: `from jax.experimental.pallas import pallas_call` import binds
_PROGRAM_CHAINS = frozenset(("jax.jit", "pl.pallas_call"))
_PROGRAM_NAMES = frozenset(("pallas_call", "jit"))

#: ledger hook call names whose first string argument is a site label
_HOOK_CALLS = frozenset(("instrument", "record_launch"))


def _find_registry(ctx: AnalysisContext):
    """(SourceFile, {scope: label-or-None}) of the PROGRAM_SITES dict —
    first declaring module wins (das_tpu/obs/proflog.py in the real
    tree; fixtures declare their own)."""
    for sf in ctx.modules():
        node = module_assign(sf.tree, "PROGRAM_SITES")
        if isinstance(node, ast.Dict):
            out: Dict[str, Optional[str]] = {}
            ok = True
            for k, v in zip(node.keys, node.values):
                key = const_str(k) if k is not None else None
                if key is None:
                    ok = False
                    break
                if isinstance(v, ast.Constant) and v.value is None:
                    out[key] = None
                else:
                    lab = const_str(v)
                    if lab is None:
                        ok = False
                        break
                    out[key] = lab
            if ok:
                return sf, out
    return None


def _program_refs(fn: ast.AST) -> Iterable[int]:
    """Lines where a program-construction primitive is referenced
    anywhere under `fn` — calls, decorators, and partial(...) args all
    contain the same Attribute/Name node."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute):
            if attr_chain(node) in _PROGRAM_CHAINS:
                yield node.lineno
        elif isinstance(node, ast.Name) and node.id in _PROGRAM_NAMES:
            yield node.lineno


def _toplevel_refs(sf) -> Iterable[int]:
    """Program-construction references OUTSIDE any function — module or
    class body, i.e. import-time program construction.  There is no
    scope to declare for these (PROGRAM_SITES entries are functions):
    an import-time jit is an unconditional compile with no ledger seam
    — the DL013 toplevel-fetch leg, applied to construction."""

    def walk(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(child, ast.Attribute):
                if attr_chain(child) in _PROGRAM_CHAINS:
                    yield child.lineno
            elif (
                isinstance(child, ast.Name)
                and child.id in _PROGRAM_NAMES
                and not isinstance(getattr(child, "ctx", None), ast.Store)
            ):
                yield child.lineno
            yield from walk(child)

    yield from walk(sf.tree)


def _outermost_scopes(sf) -> Iterable[Tuple[str, ast.AST]]:
    """(qualified scope, def node) for every OUTERMOST function — the
    DL013 attribution (class methods "mod.Class.meth")."""
    mod = scope_module(sf)

    def walk(node: ast.AST, classes):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from walk(child, classes + [child.name])
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield ".".join([mod] + classes + [child.name]), child
            else:
                yield from walk(child, classes)

    yield from walk(sf.tree, [])


def _hook_literals(fn: ast.AST) -> Iterable[Tuple[int, str]]:
    """(line, label literal) for every ledger hook call under `fn`."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None
        )
        if name in _HOOK_CALLS and node.args:
            lit = const_str(node.args[0])
            if lit is not None:
                yield node.lineno, lit


@register("DL016", "program-construction sites vs PROGRAM_SITES registry")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    registry = _find_registry(ctx)
    used_scopes: Set[str] = set()
    used_labels: Set[str] = set()
    any_ref = False
    for sf in ctx.modules():
        for line in _toplevel_refs(sf):
            any_ref = True
            yield Finding(
                "DL016", sf.posix, line,
                "program construction (jax.jit / pallas_call) outside "
                "any function — an import-time compile fires "
                "unconditionally and has no declarable PROGRAM_SITES "
                "scope; move it into a declared builder function",
            )
        for scope, fn in _outermost_scopes(sf):
            ref_lines = list(_program_refs(fn))
            hooks = list(_hook_literals(fn))
            for line, lit in hooks:
                used_labels.add(lit)
                if registry is not None and lit not in set(
                    v for v in registry[1].values() if v is not None
                ):
                    yield Finding(
                        "DL016", sf.posix, line,
                        f"ledger hook label {lit!r} is not a declared "
                        f"PROGRAM_SITES label ({registry[0].short}) — a "
                        "typo'd site records into an aggregate nobody "
                        "reads while the declared lane goes silent",
                    )
            if not ref_lines:
                continue
            any_ref = True
            if registry is None:
                yield Finding(
                    "DL016", sf.posix, ref_lines[0],
                    "program construction (jax.jit / pl.pallas_call) but "
                    "no PROGRAM_SITES registry in the analyzed set "
                    "(das_tpu/obs/proflog.py declares it)",
                )
                continue
            used_scopes.add(scope)
            if scope not in registry[1]:
                yield Finding(
                    "DL016", sf.posix, ref_lines[0],
                    f"program construction in undeclared scope `{scope}` "
                    "— every jit/pallas entry point must be declared in "
                    f"PROGRAM_SITES ({registry[0].short}) as instrumented "
                    "(ledger label) or reviewed-exempt (None), or its "
                    "compile/cost/memory telemetry silently goes dark",
                )
                continue
            label = registry[1][scope]
            if label is not None and label not in {
                lit for _line, lit in hooks
            }:
                yield Finding(
                    "DL016", sf.posix, ref_lines[0],
                    f"scope `{scope}` is declared as ledger-instrumented "
                    f"(label {label!r}) but contains no "
                    f"instrument/record_launch call passing that label — "
                    "the site's programs would compile unobserved while "
                    "the registry promises coverage",
                )
    if registry is not None and any_ref and not ctx.partial:
        reg_sf, declared = registry
        line = next(
            (
                n.lineno for n in reg_sf.tree.body
                if isinstance(n, (ast.Assign, ast.AnnAssign))
                and any(
                    getattr(t, "id", None) == "PROGRAM_SITES"
                    for t in (
                        n.targets if isinstance(n, ast.Assign)
                        else [n.target]
                    )
                )
            ),
            1,
        )
        for scope in declared:
            if scope not in used_scopes:
                yield Finding(
                    "DL016", reg_sf.posix, line,
                    f"PROGRAM_SITES declares `{scope}` but no jit/pallas "
                    "construction lives there — stale entry (the builder "
                    "moved, got renamed, or stopped constructing "
                    "programs)",
                )
