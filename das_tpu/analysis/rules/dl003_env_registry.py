"""DL003 — every DAS_TPU_* env read maps to the declared registry.

Contract (PR 0..4 accumulation): configuration flags drifted in both
directions — module-local `os.environ.get("DAS_TPU_...")` reads grew
outside DasConfig (DAS_TPU_STAR, DAS_TPU_HOST_COUNT,
DAS_TPU_FINALIZE_VERBOSE, ...) with no single place an operator could
enumerate, and nothing stopped a registered name from losing its last
reader and rotting in the docs.  `ENV_REGISTRY` in core/config.py is
now the one declared set (scripts/gen_env_table.py renders it into
ARCHITECTURE.md §11 so the docs cannot drift either); this rule pins
code <-> registry:

  * every `os.environ.get`/`os.environ[...]`/`os.getenv` read of a `DAS_TPU_*`
    name in the analyzed set must be a key of ENV_REGISTRY;
  * every ENV_REGISTRY key must be read somewhere in the analyzed set,
    unless listed in ENV_DECLARED_EXTERNAL (read outside das_tpu/ —
    e.g. tests/conftest.py's DAS_TPU_TEST_PLATFORM);
  * a registry entry naming a DasConfig field must match a declared
    field of the DasConfig dataclass (same module).

Registry shape (parsed statically, never imported):

    ENV_REGISTRY = {
        "DAS_TPU_PALLAS": ("use_pallas_kernels", "kernel routing ..."),
        "DAS_TPU_VMEM_BUDGET": (None, "bytes planner budget ..."),
    }
    ENV_DECLARED_EXTERNAL = ("DAS_TPU_TEST_PLATFORM",)
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from das_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    attr_chain,
    const_str,
    module_assign,
    register,
    str_collection,
)

_PREFIX = "DAS_TPU_"


def _find_registry(ctx: AnalysisContext):
    """(posix, line, {name: field-or-None}, external names) or None."""
    for sf in ctx.modules():
        node = module_assign(sf.tree, "ENV_REGISTRY")
        if not isinstance(node, ast.Dict):
            continue
        reg: Dict[str, Optional[str]] = {}
        for k, v in zip(node.keys, node.values):
            name = const_str(k) if k is not None else None
            if name is None:
                continue
            fld = None
            if isinstance(v, ast.Tuple) and v.elts:
                fld = const_str(v.elts[0])
            reg[name] = fld
        ext = str_collection(
            module_assign(sf.tree, "ENV_DECLARED_EXTERNAL")
        ) or ()
        return sf, node.lineno, reg, ext
    return None


def _env_reads(sf) -> Iterable[Tuple[int, str]]:
    for node in ast.walk(sf.tree):
        name = None
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain in (
                "os.environ.get", "os.getenv", "environ.get", "getenv",
                "_os.environ.get", "_os.getenv",
            ) and node.args:
                name = const_str(node.args[0])
        elif isinstance(node, ast.Subscript):
            chain = attr_chain(node.value)
            if chain in ("os.environ", "environ", "_os.environ"):
                name = const_str(node.slice)
        if name is not None and name.startswith(_PREFIX):
            yield node.lineno, name


def _dasconfig_fields(tree: ast.Module) -> Optional[List[str]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "DasConfig":
            return [
                s.target.id
                for s in node.body
                if isinstance(s, ast.AnnAssign)
                and isinstance(s.target, ast.Name)
            ]
    return None


@register("DL003", "DAS_TPU_* env reads vs ENV_REGISTRY")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    found = _find_registry(ctx)
    reads: List[Tuple[str, int, str]] = []  # posix, line, name
    for sf in ctx.modules():
        for line, name in _env_reads(sf):
            reads.append((sf.posix, line, name))
    if found is None:
        for posix, line, name in reads:
            yield Finding(
                "DL003", posix, line,
                f"env read of {name} but no ENV_REGISTRY in the analyzed "
                "set (core/config.py declares the flag registry)",
            )
        return
    reg_sf, reg_line, registry, external = found
    for posix, line, name in reads:
        if name not in registry:
            yield Finding(
                "DL003", posix, line,
                f"undeclared env var {name} — add it to ENV_REGISTRY "
                f"({reg_sf.short}) so operators can enumerate every flag",
            )
    read_names = {name for _p, _l, name in reads}
    # read-less entries are only provable on the FULL set — a partial
    # (--changed-only) run may simply not include a flag's reader
    for name in registry if not ctx.partial else ():
        if name not in read_names and name not in external:
            yield Finding(
                "DL003", reg_sf.posix, reg_line,
                f"ENV_REGISTRY declares {name} but nothing in the "
                "analyzed set reads it — dead flag (or move it to "
                "ENV_DECLARED_EXTERNAL with its out-of-tree reader)",
            )
    fields = _dasconfig_fields(reg_sf.tree)
    if fields is not None:
        for name, fld in registry.items():
            if fld is not None and fld not in fields:
                yield Finding(
                    "DL003", reg_sf.posix, reg_line,
                    f"ENV_REGISTRY maps {name} to DasConfig.{fld} but "
                    "DasConfig declares no such field",
                )
