"""DL013 — fetch-site registry: every host transfer is declared and
tallied.

Contract (ISSUE 11; ARCHITECTURE §10): on a tunneled TPU every
`jax.device_get` is a full RTT, and the serving pipeline's latency
story is literally the count of them — "one transfer per settle round"
(FETCH_COUNTS pins it in the bench/pipeline suites).  Until now that
was enforced only where someone thought to pin a delta; a new
device_get anywhere else (a debug fetch in a join helper, a
convenience `.tolist()` path) silently adds an RTT per query with no
test failing.

The DL009 COLLECTIVE_SITES idiom, applied to transfers:
`FETCH_SITES` (query/fused.py, next to FETCH_COUNTS) declares the
closed set of scopes allowed to call `jax.device_get`; calls attribute
to their OUTERMOST enclosing function qualified by module
("fused.settle_pending_iter", "sharded_db.ShardedDB.materialize" —
`__init__` modules take their package name, so planner/__init__.py is
"planner").  Three legs:

  * an undeclared device_get fails lint — every host transfer stays
    reviewable in one list;
  * a declared scope with no device_get is a stale entry (full-set
    runs only — a --changed-only run may not include the module);
  * a declared scope whose outermost function does NOT also increment
    a fetch tally (`FETCH_COUNTS[...] += ..` or starcount's
    `FETCHES[...]`) fails: the fetches-per-query telemetry the bench
    decomposes host latency with must not undercount, so the registry
    is pinned BOTH ways against the counter.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Optional, Set, Tuple

from das_tpu.analysis.callgraph import scope_module
from das_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    attr_chain,
    module_assign,
    register,
    str_collection,
)

#: the host-transfer primitives this registry closes over
_FETCH_CALLS = frozenset(("jax.device_get", "device_get"))

#: counter dicts that count as a fetch tally
_TALLY_NAMES = frozenset(("FETCH_COUNTS", "FETCHES"))


def _find_registry(ctx: AnalysisContext):
    for sf in ctx.modules():
        keys = str_collection(module_assign(sf.tree, "FETCH_SITES"))
        if keys is not None:
            return sf, keys
    return None


def _is_fetch_call(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id in _FETCH_CALLS
    chain = attr_chain(fn)
    return chain in _FETCH_CALLS


def _outermost_scopes(sf) -> Iterable[Tuple[str, ast.AST]]:
    """(qualified scope, def node) for every OUTERMOST function, class
    methods qualified ("mod.Class.meth") — the DL009 attribution."""
    mod = scope_module(sf)

    def walk(node: ast.AST, classes):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from walk(child, classes + [child.name])
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield ".".join([mod] + classes + [child.name]), child
            else:
                yield from walk(child, classes)

    yield from walk(sf.tree, [])


def _fetches_in(fn: ast.AST) -> Iterable[int]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and _is_fetch_call(node):
            yield node.lineno


def _toplevel_fetches(sf) -> Iterable[int]:
    """device_get calls OUTSIDE any function — module level or a class
    body, i.e. import-time transfers.  There is no scope to declare for
    these (FETCH_SITES entries are functions), and an import-time fetch
    is never legitimate: it fires unconditionally."""

    def walk(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(child, ast.Call) and _is_fetch_call(child):
                yield child.lineno
            yield from walk(child)

    yield from walk(sf.tree)


def _has_tally(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.AugAssign)
            and isinstance(node.target, ast.Subscript)
        ):
            base = node.target.value
            name = base.id if isinstance(base, ast.Name) else (
                base.attr if isinstance(base, ast.Attribute) else None
            )
            if name in _TALLY_NAMES:
                return True
    return False


@register("DL013", "host-transfer sites vs FETCH_SITES registry")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    registry = _find_registry(ctx)
    used: Set[str] = set()
    any_fetch = False
    for sf in ctx.modules():
        for line in _toplevel_fetches(sf):
            any_fetch = True
            yield Finding(
                "DL013", sf.posix, line,
                "jax.device_get outside any function (module/class "
                "body) — an import-time host transfer fires "
                "unconditionally and has no declarable FETCH_SITES "
                "scope; move it into a declared fetch function",
            )
        for scope, fn in _outermost_scopes(sf):
            lines = list(_fetches_in(fn))
            if not lines:
                continue
            any_fetch = True
            if registry is None:
                yield Finding(
                    "DL013", sf.posix, lines[0],
                    "jax.device_get but no FETCH_SITES registry in the "
                    "analyzed set (query/fused.py declares it, next to "
                    "FETCH_COUNTS)",
                )
                continue
            used.add(scope)
            if scope not in registry[1]:
                yield Finding(
                    "DL013", sf.posix, lines[0],
                    f"jax.device_get in undeclared scope `{scope}` — "
                    f"every host transfer is a tunnel RTT and must be "
                    f"declared in FETCH_SITES ({registry[0].short}) so "
                    "the one-transfer-per-settle-round contract stays "
                    "reviewable",
                )
                continue
            if not _has_tally(fn):
                yield Finding(
                    "DL013", sf.posix, lines[0],
                    f"declared fetch scope `{scope}` pays a device_get "
                    "without tallying FETCH_COUNTS — the fetches-per-"
                    "query telemetry (bench latency decomposition) "
                    "would undercount this site",
                )
    if registry is not None and any_fetch and not ctx.partial:
        reg_sf, declared = registry
        line = next(
            (
                n.lineno for n in reg_sf.tree.body
                if isinstance(n, ast.Assign)
                and any(
                    getattr(t, "id", None) == "FETCH_SITES"
                    for t in n.targets
                )
            ),
            1,
        )
        for scope in declared:
            if scope not in used:
                yield Finding(
                    "DL013", reg_sf.posix, line,
                    f"FETCH_SITES declares `{scope}` but no device_get "
                    "lives there — stale entry (the function moved, got "
                    "renamed, or stopped fetching)",
                )
