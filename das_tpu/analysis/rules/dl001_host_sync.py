"""DL001 — no host synchronization on a dispatch path.

Contract (PR 2/3, ARCHITECTURE §10): the serving pipeline's throughput
comes from dispatch being PURELY asynchronous — the coalescer keeps
pipeline_depth batches in flight precisely because dispatch_many
enqueues device programs without paying a host transfer.  One stray
`.item()` / `np.asarray` / `jax.device_get` (or a float()/int()/bool()
coercion, which jax resolves by blocking on the device value) inside a
dispatch half silently serializes the whole window: every query pays a
full tunnel RTT at dispatch time and the depth-N pipeline degrades to
serial without failing a single functional test.  Transfers belong in
settle — `settle_pending` pays exactly one `jax.device_get` per retry
round, which FETCH_COUNTS pins.

Scope (mechanical): function bodies, nested defs included, of
  * functions named `dispatch_many`, `dispatch_pending`, or matching
    `*_dispatch` (execute_fused_many_dispatch, query_many_dispatch, ...);
  * methods named `dispatch` on classes that also define `settle` — the
    _ExecJob / _ShardedExecJob dispatch/settle split; a bare function
    named `dispatch` (query/compiler.py's per-query router) legitimately
    does host work and is NOT scanned;
  * `__init__` of a class that defines `settle` but no `dispatch`
    (_QueryManyJob dispatches at construction).

Flagged constructs: `.item()` / `.tolist()` / `.block_until_ready()` /
`.copy_to_host_async()`, `jax.device_get(...)`, `np.asarray` /
`np.array`, and builtin float()/int()/bool() coercions.  A coercion of
a genuinely host-side value is a legitimate keep: suppress per file or
grandfather it in the baseline with its justification.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Tuple

from das_tpu.analysis.core import AnalysisContext, Finding, attr_chain, register

_BANNED_METHODS = {
    "item", "tolist", "block_until_ready", "copy_to_host_async",
}
_BANNED_CALLS = {
    "jax.device_get", "device_get",
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array",
}
_BANNED_BUILTINS = {"float", "int", "bool"}


def _dispatch_functions(tree: ast.Module) -> List[Tuple[str, ast.AST]]:
    """(qualified name, def node) for every dispatch-path function."""
    out: List[Tuple[str, ast.AST]] = []

    def visit(node: ast.AST, cls: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = child.name
                is_dispatch = (
                    name in ("dispatch_many", "dispatch_pending")
                    or name.endswith("_dispatch")
                )
                if (
                    name in ("dispatch", "__init__")
                    and cls
                    and isinstance(node, ast.ClassDef)
                ):
                    methods = {
                        m.name for m in node.body
                        if isinstance(m, ast.FunctionDef)
                    }
                    if name == "dispatch":
                        is_dispatch = "settle" in methods
                    else:  # __init__ dispatches when there is no dispatch()
                        is_dispatch = (
                            "settle" in methods and "dispatch" not in methods
                        )
                if is_dispatch:
                    out.append(
                        (f"{cls}.{name}" if cls else name, child)
                    )
                else:
                    visit(child, cls)  # nested defs may still qualify

    visit(tree, "")
    return out


def _banned_in(fn: ast.AST) -> Iterable[Tuple[int, str]]:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _BANNED_METHODS:
            yield node.lineno, f".{func.attr}()"
            continue
        chain = attr_chain(func)
        if chain in _BANNED_CALLS:
            yield node.lineno, f"{chain}()"
        elif (
            isinstance(func, ast.Name)
            and func.id in _BANNED_BUILTINS
            and node.args
        ):
            yield node.lineno, f"{func.id}() coercion"


@register("DL001", "host sync on a dispatch path")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    for sf in ctx.modules():
        for qname, fn in _dispatch_functions(sf.tree):
            for lineno, what in _banned_in(fn):
                yield Finding(
                    "DL001", sf.posix, lineno,
                    f"{what} inside dispatch-path function `{qname}` — "
                    "dispatch must stay transfer-free; host "
                    "synchronization belongs in the settle half",
                )
