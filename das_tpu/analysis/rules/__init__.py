"""Rule modules register themselves with core.register at import time."""

from das_tpu.analysis.rules import (  # noqa: F401
    dl001_host_sync,
    dl002_plan_sig,
    dl003_env_registry,
    dl004_counters,
    dl005_budget_model,
    dl006_locks,
    dl007_cache_guard,
    dl008_planner_routes,
    dl009_collectives,
    dl010_transitive_sync,
    dl011_mosaic,
    dl012_retrace,
    dl013_fetch_sites,
    dl014_obs_registry,
    dl015_fault_sites,
    dl016_proflog_sites,
    dl017_durability,
)
