"""DL007 — delta_version guard on result-cache inserts.

Contract (PR 2/6, ARCHITECTURE §10): every `ResultCache` insert must be
guarded by the delta version its result was DISPATCHED against —
`cache.put(key, result, version)` where `version` was captured via
`cache.version()` BEFORE the device dispatch.  `ResultCache.put`
re-checks that version under its lock, so a commit landing between
dispatch and settle can never smuggle a pre-commit answer in under the
post-commit version.

The async serving work (ISSUE 6) is exactly what makes this worth
enforcing mechanically: speculative dispatch and streaming early-settle
WIDEN the dispatch→insert window — a group may settle (and insert) many
window slots after it dispatched, with arbitrary commits in between —
and they added new insert sites (`settle_pending_iter`).  The two bug
shapes a new site can take:

  * no version argument at all — the insert lands unconditionally, so a
    racing commit's invalidation is silently undone;
  * the version computed AT INSERT TIME (`cache.put(k, r,
    cache.version())`) — reads the POST-commit version for a PRE-commit
    answer, which defeats the guard while looking guarded.

Mechanism: every call `X.put(...)` whose receiver's terminal name is
one of the result-cache spellings below must pass a version (third
positional or `version=`) that is a pre-captured Name or Attribute
(`version`, `pending.version`, `self.version`, `cache_version`) — any
Call expression there (or a missing argument) is a finding.  This is a
shape check, not a dataflow proof: it forces every insert through the
capture-then-pass idiom the existing sites use, where review can see
WHEN the version was read.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from das_tpu.analysis.core import AnalysisContext, Finding, register

#: receiver spellings that denote a delta-versioned ResultCache
#: (query/fused.py ResultCache and its executor/tree aliases).  A new
#: cache attribute name must be added here to stay covered — and the
#: fixture corpus (tests/lint_fixtures/dl007_*) pins the rule fires.
RESULT_CACHE_NAMES = (
    "results",
    "tree_results",
    "results_cache",
    "result_cache",
    "cache",
)


def _receiver_name(node: ast.AST) -> Optional[str]:
    """Terminal attribute/name of a receiver chain: `self.results` ->
    "results", `results_cache` -> "results_cache"."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


@register("DL007", "delta_version guard on result-cache inserts")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    for sf in ctx.modules():
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute) and fn.attr == "put"):
                continue
            if _receiver_name(fn.value) not in RESULT_CACHE_NAMES:
                continue
            version: Optional[ast.AST] = None
            if len(node.args) >= 3:
                version = node.args[2]
            else:
                for kw in node.keywords:
                    if kw.arg == "version":
                        version = kw.value
            if version is None:
                yield Finding(
                    "DL007", sf.posix, node.lineno,
                    "result-cache insert without a dispatch-time version "
                    "— `.put(key, result, version)` must re-check the "
                    "delta version captured BEFORE dispatch, or a commit "
                    "racing dispatch→settle poisons the cache",
                )
            elif not isinstance(version, (ast.Name, ast.Attribute)):
                yield Finding(
                    "DL007", sf.posix, node.lineno,
                    "result-cache insert computes its version AT INSERT "
                    "TIME — that reads the post-commit version for a "
                    "pre-commit answer, defeating the delta_version "
                    "guard; capture `cache.version()` before dispatch "
                    "and pass that name through",
                )
