"""DL006 — coalescer/pipeline lock discipline.

Contract (PR 2/3, service/coalesce.py): the coalescer is one worker
thread plus N RPC threads.  Its correctness story is explicit —
`_worker` spawn races are serialized by `_lock`, everything else
mutable is confined to the single worker thread — but nothing enforced
it: a future edit that bumps `stats` from `submit()` (an RPC thread) or
re-spawns the worker without the lock introduces a data race that no
CPython test reliably catches.

Mechanism: a module declares its discipline next to the class it
covers, and this rule pins every post-__init__ attribute MUTATION
(assign / augmented-assign / subscript-assign on `self.<attr>`, method
calls like `.append()` excluded) against it:

    LOCK_DISCIPLINE = {
        "QueryCoalescer._worker": "_lock",   # only under `with self._lock:`
        "QueryCoalescer.stats":   "worker",  # only in WORKER_METHODS
    }
    WORKER_METHODS = {
        "QueryCoalescer": ("_run", "_group_batch", ...),
    }

Semantics per map value:
  * a lock attribute name ("_lock"): the mutation must be lexically
    inside `with self.<lock>:`;
  * "worker": the enclosing method must be in WORKER_METHODS[cls] —
    thread confinement, the lock-free single-consumer idiom;
  * "init": never mutated after __init__.

`__init__` assignments are always exempt (the object is not shared
yet).  A post-init mutation of an attribute with NO map entry is itself
a finding: new mutable state must declare who may touch it.  Modules
without a LOCK_DISCIPLINE are skipped — the rule is opt-in per module,
and tests/test_zlint.py pins that service/coalesce.py declares one.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from das_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    const_str,
    module_assign,
    register,
    str_collection,
)


def _parse_discipline(sf) -> Optional[Tuple[Dict[str, str], Dict[str, Tuple[str, ...]]]]:
    node = module_assign(sf.tree, "LOCK_DISCIPLINE")
    if not isinstance(node, ast.Dict):
        return None
    discipline: Dict[str, str] = {}
    for k, v in zip(node.keys, node.values):
        key = const_str(k) if k is not None else None
        val = const_str(v)
        if key is not None and val is not None:
            discipline[key] = val
    workers: Dict[str, Tuple[str, ...]] = {}
    wnode = module_assign(sf.tree, "WORKER_METHODS")
    if isinstance(wnode, ast.Dict):
        for k, v in zip(wnode.keys, wnode.values):
            key = const_str(k) if k is not None else None
            methods = str_collection(v)
            if key is not None and methods is not None:
                workers[key] = methods
    return discipline, workers


def _self_attr_target(node: ast.AST) -> Optional[str]:
    """`self.x = ...` or `self.x[...] = ...` -> "x"."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    if isinstance(node, ast.Subscript):
        return _self_attr_target(node.value)
    return None


def _mutations(
    stmts: List[ast.stmt], held: Tuple[str, ...]
) -> Iterable[Tuple[str, int, Tuple[str, ...]]]:
    """(attr, line, locks lexically held) for each self-attr mutation in
    a statement list, tracked through nested With blocks and the other
    compound statements (if/for/while/try)."""
    for node in stmts:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # nested defs: separate (deferred) execution context
        if isinstance(node, (ast.With, ast.AsyncWith)):
            now = held
            for item in node.items:
                ctx_attr = _self_attr_target(item.context_expr)
                if ctx_attr is not None:
                    now = now + (ctx_attr,)
            yield from _mutations(node.body, now)
            continue
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            attr = _self_attr_target(t)
            if attr is not None:
                yield attr, node.lineno, held
        for fname in ("body", "orelse", "finalbody"):
            sub = getattr(node, fname, None)
            if sub:
                yield from _mutations(sub, held)
        for handler in getattr(node, "handlers", []):
            yield from _mutations(handler.body, held)
        for case in getattr(node, "cases", []):  # ast.Match
            yield from _mutations(case.body, held)


@register("DL006", "declared lock discipline for threaded state")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    for sf in ctx.modules():
        parsed = _parse_discipline(sf)
        if parsed is None:
            continue
        discipline, workers = parsed
        # every class in a declaring module is covered: "new mutable
        # state must declare its owner" has to include new classes, or
        # threaded state dodges the rule by moving next door
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            worker_methods = workers.get(node.name, ())
            for method in node.body:
                if not isinstance(
                    method, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if method.name == "__init__":
                    continue
                for attr, line, held in _mutations(method.body, ()):
                    spec = discipline.get(f"{node.name}.{attr}")
                    if spec is None:
                        yield Finding(
                            "DL006", sf.posix, line,
                            f"`self.{attr}` mutated in "
                            f"{node.name}.{method.name} but has no "
                            "LOCK_DISCIPLINE entry — declare which lock "
                            "(or thread) owns it",
                        )
                    elif spec == "init":
                        yield Finding(
                            "DL006", sf.posix, line,
                            f"`self.{attr}` is declared init-only but "
                            f"mutated in {node.name}.{method.name}",
                        )
                    elif spec == "worker":
                        if method.name not in worker_methods:
                            yield Finding(
                                "DL006", sf.posix, line,
                                f"`self.{attr}` is worker-thread-confined "
                                f"but {node.name}.{method.name} is not in "
                                "WORKER_METHODS — cross-thread mutation",
                            )
                    else:  # a lock attribute name
                        if spec not in held:
                            yield Finding(
                                "DL006", sf.posix, line,
                                f"`self.{attr}` mutated outside `with "
                                f"self.{spec}:` in "
                                f"{node.name}.{method.name}",
                            )
