"""DL010 — transitive host sync on a dispatch path (DL001, call-graph
edition).

Contract (ISSUE 11 tentpole): DL001 bans host synchronization inside
the dispatch halves SYNTACTICALLY — which a one-line refactor escapes:
move the `.item()` into a helper and the dispatch body is clean while
every query still pays a tunnel RTT at dispatch time, the depth-N
pipeline silently degrades to serial, and no functional test fails
(the silent-serialization failure mode tensor-runtime query engines
live or die on).  This rule runs the same dispatch-root discovery as
DL001 and then FOLLOWS repo-local calls (analysis/callgraph.py):
a dispatch root reaching `jax.device_get` / `.item()` / `.tolist()` /
`.block_until_ready()` / `.copy_to_host_async()` / `np.asarray` /
`np.array` through ANY chain of resolvable helpers fires, with the
offending call path rendered in the finding.

Scope notes:

  * depth >= 1 only — the root's own direct constructs are DL001's
    findings; reporting them twice would just double the baseline;
  * the builtin float()/int()/bool() coercions DL001 flags directly
    are NOT propagated: transitively, "some helper coerces an int"
    is almost always host arithmetic (capacity math, env parsing),
    and a rule that cries wolf gets suppressed.  The unambiguous
    transfer primitives propagate; the weak heuristic stays local;
  * resolution under-approximates (parameters holding callables and
    unknown attribute chains don't resolve — see callgraph.py), so a
    clean verdict is "no REACHABLE sync", not a proof.  What it does
    report is a real dispatch->transfer path.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Tuple

from das_tpu.analysis.callgraph import callgraph
from das_tpu.analysis.core import AnalysisContext, Finding, attr_chain, register
from das_tpu.analysis.rules.dl001_host_sync import _dispatch_functions

#: the unambiguous host-transfer constructs that propagate through
#: calls (DL001's set minus the weak builtin-coercion heuristic)
_SYNC_METHODS = {
    "item", "tolist", "block_until_ready", "copy_to_host_async",
}
_SYNC_CALLS = {
    "jax.device_get", "device_get",
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array",
}


def _direct_syncs(fn: ast.AST) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _SYNC_METHODS:
            out.append((node.lineno, f".{func.attr}()"))
            continue
        chain = attr_chain(func)
        if chain in _SYNC_CALLS:
            out.append((node.lineno, f"{chain}()"))
    return out


def _render_path(root: str, path) -> str:
    """`dispatch -> helper_a -> helper_b` with the short name of each
    hop (qnames carry full modules; the file is in the finding head)."""
    hops = [root] + [q.split("::", 1)[1] for _line, q in path]
    return " -> ".join(hops)


@register("DL010", "transitive host sync on a dispatch path")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    graph = callgraph(ctx)
    for sf in ctx.modules():
        for qname, fn in _dispatch_functions(sf.tree):
            cls = qname.split(".")[0] if "." in qname else None
            for info, path in graph.walk(sf, fn, cls):
                for line, what in _direct_syncs(info.node):
                    yield Finding(
                        "DL010", sf.posix, path[0][0],
                        f"dispatch path `{qname}` reaches {what} at "
                        f"{info.sf.short}:{line} via "
                        f"`{_render_path(qname, path)}` — dispatch must "
                        "stay transfer-free through every helper; host "
                        "synchronization belongs in the settle half",
                    )
