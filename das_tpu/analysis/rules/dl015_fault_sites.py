"""DL015 — fault-injection site registry discipline (ISSUE 13).

Contract: the chaos suite's coverage claim — "a seeded sweep injecting
every FAULT_SITES entry proves chaos-parity" — is only as good as the
registry.  An injection seam added without declaring it never gets
swept (the schedule can't name it); a declared seam whose `maybe_fail`
call was refactored away keeps promising coverage that no longer
exists.  And an injection call in the WRONG place is worse than none:
inside `das_tpu/kernels/` it would land in traced/Mosaic code (the
bodies DL011 certifies must stay exactly as reviewed), and inside a
dispatch half it would put host work — a potential raise, a latency
sleep — on the paths DL001/DL010 prove transfer-free and purely
asynchronous.

The DL013 FETCH_SITES idiom, applied to injection.  `FAULT_SITES`
(das_tpu/fault/__init__.py) declares the closed set of seam NAMES;
every `maybe_fail("<site>")` literal anywhere in the analyzed set is
pinned against it.  Three legs:

  * an undeclared site literal fails lint — every seam stays
    reviewable (and sweepable) in one list;
  * a declared site with no `maybe_fail` call is a stale entry
    (full-set runs only — a --changed-only subset may not include the
    caller);
  * ANY `maybe_fail` call — declared or not — inside a module under
    `das_tpu/kernels/` or inside a DL001 dispatch-half function fails:
    injection belongs at host-side recovery seams, never in traced
    code or the async dispatch path.

Attribution is syntactic (bare name or attribute, the DL004 idiom):
naming a function `maybe_fail` and passing it a string opts into this
discipline — injection entry points must not be ambiguous.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set, Tuple

from das_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    const_str,
    module_assign,
    register,
)
from das_tpu.analysis.rules.dl001_host_sync import _dispatch_functions

#: call names that count as the injection entry point
_INJECT_CALLS = frozenset(("maybe_fail",))


def _find_registry(ctx: AnalysisContext):
    """The (SourceFile, site names) of the FAULT_SITES declaration —
    first declaring module wins (das_tpu/fault/__init__.py in the real
    tree; fixtures declare their own)."""
    for sf in ctx.modules():
        node = module_assign(sf.tree, "FAULT_SITES")
        if isinstance(node, ast.Tuple):
            vals = [const_str(e) for e in node.elts]
            if all(v is not None for v in vals):
                return sf, tuple(vals)
    return None


def _is_inject_call(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id in _INJECT_CALLS
    if isinstance(fn, ast.Attribute):
        return fn.attr in _INJECT_CALLS
    return False


def _inject_calls(tree: ast.AST) -> Iterable[Tuple[int, str]]:
    """(line, site literal or None) for every maybe_fail call."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_inject_call(node):
            lit = const_str(node.args[0]) if node.args else None
            yield node.lineno, lit


def _in_kernels(sf) -> bool:
    return "kernels" in sf.path.parts[:-1]


@register("DL015", "fault-injection sites vs FAULT_SITES registry")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    registry = _find_registry(ctx)
    used: Set[str] = set()
    for sf in ctx.modules():
        calls: List[Tuple[int, str]] = list(_inject_calls(sf.tree))
        if not calls:
            continue
        if _in_kernels(sf):
            for line, _lit in calls:
                yield Finding(
                    "DL015", sf.posix, line,
                    "fault injection (maybe_fail) inside das_tpu/kernels/ "
                    "— kernel bodies are traced/Mosaic code (DL011) and "
                    "must stay exactly as reviewed; inject at the "
                    "host-side seam that CALLS the kernel instead",
                )
        # the dispatch-half ban: reuse DL001's root discovery so the two
        # rules cannot disagree about what "a dispatch half" is
        dispatch_spans = [
            (qname, fn) for qname, fn in _dispatch_functions(sf.tree)
        ]
        banned_lines: Set[int] = set()
        for qname, fn in dispatch_spans:
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and _is_inject_call(node):
                    banned_lines.add(node.lineno)
                    yield Finding(
                        "DL015", sf.posix, node.lineno,
                        f"fault injection (maybe_fail) inside dispatch "
                        f"half `{qname}` — dispatch stays purely "
                        "asynchronous and raise-free (DL001/DL010); "
                        "injected failures belong at the settle/recovery "
                        "seams",
                    )
        for line, lit in calls:
            if lit is None:
                continue
            if line in banned_lines:
                # the placement ban above already reported this call;
                # a second registry finding on the same line is noise
                used.add(lit)
                continue
            if registry is None:
                yield Finding(
                    "DL015", sf.posix, line,
                    "maybe_fail call but no FAULT_SITES registry in the "
                    "analyzed set (das_tpu/fault/__init__.py declares it)",
                )
                continue
            used.add(lit)
            if lit not in registry[1]:
                yield Finding(
                    "DL015", sf.posix, line,
                    f"maybe_fail site {lit!r} is not declared in "
                    f"FAULT_SITES ({registry[0].short}) — an undeclared "
                    "seam never gets swept by the chaos suite, so its "
                    "recovery path ships untested",
                )
    if registry is not None and used and not ctx.partial:
        reg_sf, declared = registry
        line = next(
            (
                n.lineno for n in reg_sf.tree.body
                if isinstance(n, ast.Assign)
                and any(
                    getattr(t, "id", None) == "FAULT_SITES"
                    for t in n.targets
                )
            ),
            1,
        )
        for site in declared:
            if site not in used:
                yield Finding(
                    "DL015", reg_sf.posix, line,
                    f"FAULT_SITES declares {site!r} but no maybe_fail "
                    "call injects there — stale entry (the seam moved or "
                    "was deleted; the chaos sweep would claim coverage "
                    "it no longer has)",
                )
