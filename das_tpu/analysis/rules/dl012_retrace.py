"""DL012 — retrace hygiene at program-construction sites.

Contract (ISSUE 11): every compiled-program construction site —
`jax.jit(...)`, `pl.pallas_call(...)`, `shard_map(...)` (calls and
decorators) — keys its executable cache on the STATIC inputs of the
traced callable: its closure and static arguments.  The codebase's
idiom is the frozen-`*Sig` builder (`build_fused(sig: FusedPlanSig)`)
— everything the traced function closes over derives from the frozen
signature that IS the cache key — plus explicit `static_argnames` on
module-level wrappers.  A per-request python value slipping into that
closure (the DL002 lesson, dynamic edition) silently keys a
recompile-per-query: no functional test fails, the serving pipeline
just compiles forever.

Two legs, both shape checks in the house style (they force the idiom
where review can see the keying, not prove a dataflow theorem):

  * **keying discipline** — an inner construction site must be one of:
    a module-level decorator/assignment (statics are explicit), inside
    a builder (a function with a `*Sig`-annotated parameter, or named
    `build_*`/`make_*` — the declared factory idiom), inside
    das_tpu/kernels/ (launch helpers whose statics thread from jitted
    wrappers), or its result must visibly flow to a cache (`X[key] =
    fn`), a `return`, or a call in the same function.  A constructed
    program that does none of those has no reviewable cache key;
  * **per-request taint** — a parameter of the enclosing function
    chain that is annotated as a mutable container (`dict`/`list`/
    `set`/`Dict[..]`/..), defaulted to a mutable literal, or taken as
    `**kwargs` must not reach the traced callable's free variables or
    the construction call's arguments.  Those are exactly the values
    whose identity/content change per request: closing over one keys
    the trace on it (or worse, on nothing).

Frozen `*Sig` parameters and module-level constants remain the blessed
origins; plain positional values (ints, tuples, arrays) pass — arrays
are traced operands, and hashable statics are the jit cache's job.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from das_tpu.analysis.core import AnalysisContext, Finding, attr_chain, register

_CONSTRUCTORS = frozenset(("jit", "pallas_call", "shard_map"))

_MUTABLE_ANNOTATIONS = frozenset((
    "dict", "list", "set", "Dict", "List", "Set", "DefaultDict",
    "MutableMapping", "MutableSequence", "Any", "object",
))


def _ctor_name(fn: ast.AST) -> Optional[str]:
    if isinstance(fn, ast.Name) and fn.id in _CONSTRUCTORS:
        return fn.id
    if isinstance(fn, ast.Attribute) and fn.attr in _CONSTRUCTORS:
        return attr_chain(fn) or fn.attr
    return None


def _is_ctor_call(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call):
        name = _ctor_name(node.func)
        if name is not None:
            return name
        # partial(jax.jit, static_argnames=...) decorator form
        if (
            isinstance(node.func, ast.Name) and node.func.id == "partial"
            and node.args
        ):
            return _ctor_name(node.args[0])
    return None


def _sig_param(fn: ast.AST) -> bool:
    for p in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
        ann = p.annotation
        name = None
        if isinstance(ann, ast.Name):
            name = ann.id
        elif isinstance(ann, ast.Attribute):
            name = ann.attr
        elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            name = ann.value.split(".")[-1].split("[")[0]
        if name is not None and name.endswith("Sig"):
            return True
    return False


def _is_builder(fn: ast.AST) -> bool:
    return (
        fn.name.startswith(("build_", "make_", "_build", "_make"))
        or _sig_param(fn)
    )


def _ann_name(ann: ast.AST) -> Optional[str]:
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Attribute):
        return ann.attr
    if isinstance(ann, ast.Subscript):
        return _ann_name(ann.value)
    return None


def _banned_params(fn: ast.AST) -> Dict[str, str]:
    """param name -> why it is a per-request mutable origin."""
    out: Dict[str, str] = {}
    a = fn.args
    params = a.posonlyargs + a.args + a.kwonlyargs
    defaults = list(a.defaults)
    # align defaults with the tail of positional params
    pos = a.posonlyargs + a.args
    for p, d in zip(pos[len(pos) - len(defaults):], defaults):
        if isinstance(d, (ast.Dict, ast.List, ast.Set)) or (
            isinstance(d, ast.Call) and isinstance(d.func, ast.Name)
            and d.func.id in ("dict", "list", "set")
        ):
            out[p.arg] = "mutable default"
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if isinstance(d, (ast.Dict, ast.List, ast.Set)):
            out[p.arg] = "mutable default"
    for p in params:
        name = _ann_name(p.annotation) if p.annotation is not None else None
        if name in _MUTABLE_ANNOTATIONS:
            out[p.arg] = f"param annotated `{name}`"
    if a.kwarg is not None:
        out[a.kwarg.arg] = "**kwargs"
    return out


def _propagate(fn: ast.AST, banned: Dict[str, str]) -> Dict[str, str]:
    """One forward pass: locals assigned from banned names inherit the
    reason (x = opts; ... closes over x)."""
    out = dict(banned)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Name):
            why = out.get(node.value.id)
            if why:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.setdefault(t.id, why)
    return out


def _local_defs(fn: ast.AST) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def _free_names(fn: ast.AST) -> Set[str]:
    """Names a nested def loads but does not bind itself (approximate:
    its own params + assigned names are bound; everything else is free
    and resolved against the enclosing chain by the caller)."""
    bound: Set[str] = set()
    a = fn.args
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        bound.add(p.arg)
    if a.vararg:
        bound.add(a.vararg.arg)
    if a.kwarg:
        bound.add(a.kwarg.arg)
    loads: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                loads.add(node.id)
            else:
                bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not fn:
                bound.add(node.name)
    return loads - bound


def _names_in(e: ast.AST) -> Set[str]:
    return {
        n.id for n in ast.walk(e)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


def _enclosing_chains(tree: ast.Module):
    """Yield (site node, ctor name, kind, chain) for every construction
    site, chain = enclosing defs outermost-first ([] = module level).
    kind is 'call' or 'decorated' (the decorated def is the callable)."""

    def walk(node: ast.AST, chain: List[ast.AST]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in child.decorator_list:
                    name = (
                        _ctor_name(dec) if not isinstance(dec, ast.Call)
                        else _is_ctor_call(dec)
                    )
                    if name:
                        yield child, name, "decorated", list(chain)
                walk_chain = chain + [child]
                yield from walk(child, walk_chain)
            else:
                if isinstance(child, ast.Call):
                    name = _ctor_name(child.func)
                    if name:
                        yield child, name, "call", list(chain)
                yield from walk(child, chain)

    yield from walk(tree, [])


def _keyed_ok(site: ast.Call, chain: List[ast.AST], sf) -> bool:
    if not chain:
        return True  # module-level: statics are explicit in the def
    if "kernels" in sf.path.parts:
        return True
    if any(_is_builder(fn) for fn in chain):
        return True
    inner = chain[-1]
    # the statement owning the site: Return is fine (factory idiom)
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(inner):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    stmt = site
    while id(stmt) in parents and not isinstance(stmt, ast.stmt):
        stmt = parents[id(stmt)]
    if isinstance(stmt, ast.Return):
        return True
    if isinstance(stmt, ast.Assign):
        targets: Set[str] = set()
        for t in stmt.targets:
            targets.update(
                n.id for n in ast.walk(t) if isinstance(n, ast.Name)
            )
        for node in ast.walk(inner):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in targets
            ):
                return True  # constructed-and-called in place
            if isinstance(node, ast.Assign) and isinstance(
                node.targets[0], ast.Subscript
            ) and targets & _names_in(node.value):
                return True  # stored into a cache under a key
            if isinstance(node, ast.Return) and node.value is not None and (
                targets & _names_in(node.value)
            ):
                return True
    return False


def _decorated_ok(fn_def: ast.AST, chain: List[ast.AST], sf) -> bool:
    if not chain:
        return True
    if "kernels" in sf.path.parts or any(_is_builder(f) for f in chain):
        return True
    # a nested jitted def that the enclosing function actually calls
    inner = chain[-1]
    for node in ast.walk(inner):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == fn_def.name
            and node is not fn_def
        ):
            return True
    return False


@register("DL012", "retrace hygiene at jit/pallas_call/shard_map sites")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    for sf in ctx.modules():
        for site, ctor, kind, chain in _enclosing_chains(sf.tree):
            # per-request taint leg
            tainted: Dict[str, str] = {}
            for fn in chain:
                tainted.update(_propagate(fn, _banned_params(fn)))
            if tainted:
                if kind == "decorated":
                    callable_defs = [site]
                    arg_names: Set[str] = set()
                else:
                    defs = {}
                    for fn in chain:
                        defs.update(_local_defs(fn))
                    callable_defs = [
                        defs[n.id] for n in ast.walk(site)
                        if isinstance(n, ast.Name) and n.id in defs
                    ]
                    arg_names = set()
                    for a in list(site.args) + [
                        k.value for k in site.keywords
                    ]:
                        if not isinstance(a, (ast.Lambda,)):
                            arg_names |= _names_in(a)
                hits: Dict[str, str] = {}
                for d in callable_defs:
                    for name in _free_names(d):
                        if name in tainted:
                            hits[name] = tainted[name]
                for name in arg_names:
                    if name in tainted and name not in {
                        d.name for d in callable_defs
                        if isinstance(d, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                    }:
                        hits[name] = tainted[name]
                for name, why in sorted(hits.items()):
                    yield Finding(
                        "DL012", sf.posix, site.lineno,
                        f"per-request mutable value `{name}` ({why}) "
                        f"reaches this {ctor} site's traced closure — "
                        "static/closure inputs must derive from frozen "
                        "*Sig fields or module constants, else every "
                        "request silently keys a fresh compile",
                    )
            # keying-discipline leg
            if kind == "call":
                ok = _keyed_ok(site, chain, sf)
            else:
                ok = _decorated_ok(site, chain, sf)
            if not ok:
                yield Finding(
                    "DL012", sf.posix, site.lineno,
                    f"{ctor} program constructed with no reviewable "
                    "cache keying — build it in a *Sig builder "
                    "(build_*/make_*), store it in a keyed cache, "
                    "return it, or call it in place (the executable "
                    "must not be re-created per request)",
                )
