"""DL014 — span/metric name registry discipline (ISSUE 12).

Contract: the obs layer's value is that dashboards, Perfetto queries
and the bench's percentile headlines key on STABLE names.  A typo'd
literal (`obs.span("serve.dipsatch")`) records into a lane nobody
watches while the declared name goes silent — the DL004 failure mode,
re-created one layer up.  `das_tpu/obs/registry.py` declares the three
closed sets (SPAN_NAMES / COUNTER_NAMES / HISTOGRAM_NAMES; the metric
dicts are BUILT from them), and this rule pins the literals both ways:

  * every string literal passed as the NAME argument of a recording
    call — `span(...)`, `event(...)`, `annotation(...)`, `record(...)`
    (first arg) and `counter(...)` / `histogram(...)` — anywhere in the
    analyzed set must be a declared member of the matching registry;
  * every declared name must be used by at least one recording call
    site (full-set runs only — a --changed-only subset may simply not
    include the caller): a stale entry is dead vocabulary the docs and
    dashboards would keep promising.

Attribution is syntactic (bare name or attribute, the DL004
`record_dispatch` idiom): naming a function `span`/`counter`/... in
das_tpu/ and passing it a string first argument OPTS INTO this
discipline — which is the point; observability entry points must not
be ambiguous.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from das_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    const_str,
    module_assign,
    register,
    str_collection,
)

#: recording-call function name -> the registry its first argument
#: must belong to.  `record` is the recorder's low-level entry (used
#: where the span's timing already exists, e.g. around the settle
#: fetch); `annotation` is the jax.profiler twin sharing the span
#: vocabulary.
_CALL_TO_REGISTRY = {
    "span": "SPAN_NAMES",
    "event": "SPAN_NAMES",
    "annotation": "SPAN_NAMES",
    "record": "SPAN_NAMES",
    "counter": "COUNTER_NAMES",
    "histogram": "HISTOGRAM_NAMES",
}

_REGISTRY_NAMES = ("SPAN_NAMES", "COUNTER_NAMES", "HISTOGRAM_NAMES")


def _find_registries(ctx: AnalysisContext):
    """{registry name: (SourceFile, names)} — first declaring module
    wins (das_tpu/obs/registry.py in the real tree; fixtures declare
    their own)."""
    out = {}
    for sf in ctx.modules():
        for reg_name in _REGISTRY_NAMES:
            keys = str_collection(module_assign(sf.tree, reg_name))
            if keys is not None and reg_name not in out:
                out[reg_name] = (sf, keys)
    return out


def _call_name(node: ast.Call):
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _use_sites(sf) -> Iterable[Tuple[int, str, str]]:
    """(line, registry name, literal) for every recording call with a
    constant string name argument."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fname = _call_name(node)
        reg = _CALL_TO_REGISTRY.get(fname)
        if reg is None:
            continue
        lit = const_str(node.args[0])
        if lit is not None:
            yield node.lineno, reg, lit


@register("DL014", "span/metric names vs obs/registry.py")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    registries = _find_registries(ctx)
    uses: List[Tuple[str, int, str, str]] = []
    for sf in ctx.modules():
        for line, reg, lit in _use_sites(sf):
            uses.append((sf.posix, line, reg, lit))
    if not uses and not registries:
        return
    used_by_reg: Dict[str, Set[str]] = {r: set() for r in _REGISTRY_NAMES}
    for posix, line, reg, lit in uses:
        if reg not in registries:
            yield Finding(
                "DL014", posix, line,
                f"obs name literal {lit!r} but no {reg} registry in the "
                "analyzed set (das_tpu/obs/registry.py declares it)",
            )
            continue
        used_by_reg[reg].add(lit)
        reg_sf, names = registries[reg]
        if lit not in names:
            yield Finding(
                "DL014", posix, line,
                f"obs name {lit!r} is not declared in {reg} "
                f"({reg_sf.short}) — an undeclared span/metric records "
                "into a lane no dashboard or percentile headline reads",
            )
    if ctx.partial:
        # the stale leg is only provable on the FULL set — a
        # --changed-only subset may not include a name's call site
        return
    for reg_name, (sf, names) in registries.items():
        line = next(
            (
                n.lineno for n in sf.tree.body
                if isinstance(n, ast.Assign)
                and any(
                    getattr(t, "id", None) == reg_name for t in n.targets
                )
            ),
            1,
        )
        for name in names:
            if name not in used_by_reg[reg_name]:
                yield Finding(
                    "DL014", sf.posix, line,
                    f"{reg_name} declares {name!r} but no recording site "
                    "uses it — stale entry (the instrumentation moved or "
                    "was deleted; prune the registry with it)",
                )
