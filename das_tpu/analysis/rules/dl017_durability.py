"""DL017 — durability discipline: persist writes via the atomic
helpers (ISSUE 15).

Contract: the dasdur recovery story — "a crash at any point leaves
either the complete new file/generation or the untouched prior one" —
holds only if EVERY byte written beneath the snapshot/WAL root flows
through the reviewed helpers (storage/durable.py `atomic_write`,
`DeltaLog.append`, `_truncate_wal`): write-temp → flush → fsync →
rename, directory fsync after.  One bare `open(path, "w")` or
`np.savez(path)` added to a persist module re-opens the exact
torn-file corruption the module exists to close — and it would pass
every test that doesn't kill the process mid-write.

The FAULT_SITES/FETCH_SITES idiom applied to persistence.
`PERSIST_SITES` (storage/durable.py) declares the CLOSED set of
functions allowed to open persist files for writing; `PERSIST_SCOPES`
declares which modules the discipline covers (matched by path suffix —
a module declaring its own PERSIST_SITES, e.g. a fixture, is a scope
too).  Four legs:

  * a write-mode `open()` (w/a/x/+) in a persist scope OUTSIDE a
    declared site fails lint;
  * `np.savez`/`savez_compressed` handed a PATH (anything but a bare
    name bound to an approved writer's file object) in a persist scope
    fails — file handles flowing out of `atomic_write` are fine, paths
    bypass it;
  * fsync-before-rename: any declared site (and any persist-scope
    function) that calls `os.replace`/`os.rename` must call
    `os.fsync` on an EARLIER line — rename-without-fsync is the
    classic "atomic" write that loses the file on power-cut;
  * both ways: an `os.replace`/write-open outside the declared set
    fires (above), and a declared site that performs no write at all
    is a STALE entry (full-set runs only — a --changed-only subset
    may simply not include durable.py).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from das_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    const_str,
    module_assign,
    register,
    str_collection,
)

#: write-intent open() modes: any of these chars in the mode string
_WRITE_MODE_CHARS = frozenset("wax+")

#: numpy zip-archive writers that accept a bare path
_SAVEZ_NAMES = frozenset(("savez", "savez_compressed"))


def _find_registry(ctx: AnalysisContext):
    """(SourceFile, sites tuple, scopes tuple) of the first module
    declaring PERSIST_SITES (storage/durable.py in the real tree;
    fixtures declare their own)."""
    for sf in ctx.modules():
        sites = str_collection(module_assign(sf.tree, "PERSIST_SITES"))
        if sites:
            scopes = str_collection(
                module_assign(sf.tree, "PERSIST_SCOPES")
            ) or ()
            return sf, sites, scopes
    return None


def _functions(tree: ast.Module):
    """(qualname, FunctionDef) for every function, methods as
    `Class.method` (the PERSIST_SITES naming)."""
    out: List[Tuple[str, ast.AST]] = []

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append((q, child))
                walk(child, f"{q}.")
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def _open_write_mode(call: ast.Call) -> bool:
    """True when this is an open() call with a write-intent mode."""
    fn = call.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None
    )
    if name != "open":
        return False
    mode = None
    if len(call.args) >= 2:
        mode = const_str(call.args[1])
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = const_str(kw.value)
    if mode is None:
        return False  # default "r" — reads are free
    return any(c in _WRITE_MODE_CHARS for c in mode)


def _os_call(call: ast.Call, names: Tuple[str, ...]) -> bool:
    fn = call.func
    return (
        isinstance(fn, ast.Attribute)
        and isinstance(fn.value, ast.Name)
        and fn.value.id == "os"
        and fn.attr in names
    )


def _savez_path_call(call: ast.Call) -> bool:
    """np.savez(...) whose first argument is NOT a bare name (i.e. a
    path literal / join / f-string): bypasses the atomic helper.  A
    bare name is a file object handed in by an approved writer."""
    fn = call.func
    if not (isinstance(fn, ast.Attribute) and fn.attr in _SAVEZ_NAMES):
        return False
    if not call.args:
        return False
    return not isinstance(call.args[0], ast.Name)


def _scan(fn_node: ast.AST):
    """(write_opens, replaces, fsyncs, savez_paths) line lists of one
    function body (nested defs fold in — a helper closure inside a
    declared site inherits its license)."""
    opens: List[int] = []
    replaces: List[int] = []
    fsyncs: List[int] = []
    savez: List[int] = []
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        if _open_write_mode(node):
            opens.append(node.lineno)
        if _os_call(node, ("replace", "rename")):
            replaces.append(node.lineno)
        if _os_call(node, ("fsync",)):
            fsyncs.append(node.lineno)
        if _savez_path_call(node):
            savez.append(node.lineno)
    return opens, replaces, fsyncs, savez


@register("DL017", "durability discipline: persist writes via atomic helpers")
def check(ctx: AnalysisContext) -> Iterable[Finding]:
    registry = _find_registry(ctx)
    if registry is None:
        return
    reg_sf, sites, scopes = registry
    declared: Set[str] = set(sites)
    used: Dict[str, bool] = {s: False for s in declared}
    for sf in ctx.modules():
        in_scope = sf is reg_sf or any(
            sf.posix.endswith(sc) for sc in scopes
        ) or str_collection(
            module_assign(sf.tree, "PERSIST_SITES")
        ) is not None
        if not in_scope:
            continue
        fn_nodes = _functions(sf.tree)
        covered: Set[int] = set()
        for qual, node in fn_nodes:
            opens, replaces, fsyncs, savez = _scan(node)
            for n in ast.walk(node):
                covered.add(getattr(n, "lineno", 0))
            if qual in declared:
                if opens or replaces or savez:
                    used[qual] = True
                # the fsync-before-rename pin: a site that renames a
                # file into place must have fsynced it first
                for line in replaces:
                    if not any(f < line for f in fsyncs):
                        yield Finding(
                            "DL017", sf.posix, line,
                            f"declared persist site `{qual}` calls "
                            "os.replace/os.rename with no earlier "
                            "os.fsync — rename-without-fsync loses the "
                            "file on power cut; fsync the temp file "
                            "(and the directory) first",
                        )
                continue
            for line in opens:
                yield Finding(
                    "DL017", sf.posix, line,
                    f"bare write-mode open() in persist scope "
                    f"(`{qual}`) outside PERSIST_SITES "
                    f"({reg_sf.short}) — persist bytes must flow "
                    "through the atomic-write/WAL helpers "
                    "(write-temp -> fsync -> rename), or a crash "
                    "mid-write corrupts the only copy",
                )
            for line in savez:
                yield Finding(
                    "DL017", sf.posix, line,
                    f"np.savez to a PATH in persist scope (`{qual}`) "
                    "outside PERSIST_SITES — hand it the file object "
                    "an atomic writer opened instead",
                )
            for line in replaces:
                yield Finding(
                    "DL017", sf.posix, line,
                    f"os.replace/os.rename in persist scope "
                    f"(`{qual}`) outside PERSIST_SITES — renames into "
                    "the persist root belong to the reviewed atomic "
                    "writers",
                )
        # module-level statements (outside every function)
        module_probe = ast.Module(body=sf.tree.body, type_ignores=[])
        opens, replaces, _fsyncs, savez = _scan(module_probe)
        for line in opens:
            if line not in covered:
                yield Finding(
                    "DL017", sf.posix, line,
                    "bare write-mode open() at module level of a "
                    "persist scope — persist bytes must flow through "
                    "PERSIST_SITES",
                )
        for line in savez:
            if line not in covered:
                yield Finding(
                    "DL017", sf.posix, line,
                    "np.savez to a PATH at module level of a persist "
                    "scope — persist bytes must flow through "
                    "PERSIST_SITES",
                )
        for line in replaces:
            if line not in covered:
                yield Finding(
                    "DL017", sf.posix, line,
                    "os.replace at module level of a persist scope — "
                    "renames belong to the reviewed atomic writers",
                )
    if not ctx.partial:
        line = _registry_line(reg_sf)
        for site in sorted(declared):
            if not used.get(site):
                yield Finding(
                    "DL017", reg_sf.posix, line,
                    f"PERSIST_SITES declares {site!r} but no such "
                    "function performs a persist write — stale entry "
                    "(the writer moved or was deleted; the discipline "
                    "would claim coverage it no longer has)",
                )


def _registry_line(reg_sf) -> int:
    for node in reg_sf.tree.body:
        if isinstance(node, ast.Assign) and any(
            getattr(t, "id", None) == "PERSIST_SITES" for t in node.targets
        ):
            return node.lineno
    return 1
