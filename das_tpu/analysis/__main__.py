"""daslint CLI — `python -m das_tpu.analysis [paths...]` (ops/lint.sh).

Exit codes: 0 clean (baseline-grandfathered findings allowed), 1 any
new finding OR stale baseline entry, 2 usage error (unknown rule ids
included — a typo'd --select must not silently run nothing).
`--format json|sarif` emit machine-readable records (SARIF 2.1.0 for
CI annotation; `--json` is kept as an alias of `--format json`);
default paths analyze the installed das_tpu package with the repo-root
baseline and tests/ directory.  `--select`/`--ignore` run rule subsets
incrementally; `--allow-partial` marks a deliberately incomplete file
set (ops/lint.sh --changed-only) so registry-staleness legs don't fire
on modules that simply aren't in the set.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from das_tpu.analysis.core import (
    apply_baseline,
    iter_rules,
    load_baseline,
    run_analysis,
)

_SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemas/sarif-schema-2.1.0.json"
)


def _sarif_record(findings, stale, baseline_path, rule_titles) -> dict:
    """Minimal SARIF 2.1.0 run: one result per NEW finding plus one per
    STALE baseline entry (both fail the run, so both must be visible to
    the CI annotation consumer), rule metadata from the registry."""
    results = [
        {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.line},
                },
            }],
        }
        for f in findings
    ]
    for b in stale:
        results.append({
            "ruleId": b.rule,
            "level": "error",
            "message": {"text": (
                f"stale baseline entry for {b.path}: {b.message!r} no "
                "longer matches any finding — delete it"
            )},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": str(baseline_path)},
                    "region": {"startLine": 1},
                },
            }],
        })
    used = sorted({r["ruleId"] for r in results})
    return {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "daslint",
                "informationUri": "ARCHITECTURE.md#11",
                "rules": [
                    {
                        "id": rid,
                        "shortDescription": {
                            "text": rule_titles.get(rid, rid)
                        },
                    }
                    for rid in used
                ],
            }},
            "results": results,
        }],
    }


def _repo_root() -> Path:
    import das_tpu

    return Path(das_tpu.__file__).resolve().parent.parent


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m das_tpu.analysis",
        description="daslint — AST invariant analyzer (ARCHITECTURE.md §11)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to analyze (default: the das_tpu package)",
    )
    parser.add_argument(
        "--select", "--rules", dest="select",
        help="comma-separated rule subset to run (e.g. DL001,DL010); "
             "unknown ids exit 2",
    )
    parser.add_argument(
        "--ignore",
        help="comma-separated rules to skip (applied after --select); "
             "unknown ids exit 2",
    )
    parser.add_argument(
        "--baseline", type=Path,
        help="baseline JSON (default: <repo>/daslint.baseline.json)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report grandfathered findings as errors too",
    )
    parser.add_argument(
        "--tests-dir", type=Path,
        help="tests directory for DL004's test-reference leg "
             "(default: <repo>/tests; pass a missing path to skip)",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="output format (sarif: one run, new findings as results)",
    )
    parser.add_argument(
        "--json", action="store_const", const="json", dest="format",
        help="alias of --format json",
    )
    parser.add_argument(
        "--allow-partial", action="store_true",
        help="the path set is deliberately incomplete (--changed-only): "
             "skip registry-staleness legs that need the full tree",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    args = parser.parse_args(argv)

    all_rules = dict(iter_rules())
    if args.list_rules:
        for rid, title in all_rules.items():
            print(f"{rid}  {title}")
        return 0

    root = _repo_root()
    paths = [Path(p) for p in args.paths] or [root / "das_tpu"]
    for p in paths:
        if not p.exists():
            print(f"daslint: no such path: {p}", file=sys.stderr)
            return 2

    def parse_ids(raw):
        ids = [r.strip() for r in raw.split(",") if r.strip()]
        unknown = [r for r in ids if r not in all_rules]
        if unknown:
            raise ValueError(f"unknown daslint rule(s): {sorted(unknown)}")
        return ids

    try:
        selected = parse_ids(args.select) if args.select else None
        ignored = set(parse_ids(args.ignore)) if args.ignore else set()
    except ValueError as exc:
        print(f"daslint: {exc}", file=sys.stderr)
        return 2
    rules = None
    if selected is not None or ignored:
        rules = [
            r for r in (selected if selected is not None else all_rules)
            if r not in ignored
        ]
    tests_dir = args.tests_dir if args.tests_dir is not None else root / "tests"

    try:
        findings = run_analysis(
            paths, rules=rules, tests_dir=tests_dir,
            partial=args.allow_partial,
        )
    except ValueError as exc:
        print(f"daslint: {exc}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or (root / "daslint.baseline.json")
    if args.baseline is not None and not baseline_path.is_file():
        # the default path is allowed to be absent (no baseline yet);
        # an explicit one that is missing would silently skip the
        # stale-entry check, so it is a usage error
        print(f"daslint: no such baseline: {baseline_path}", file=sys.stderr)
        return 2
    baseline = []
    if not args.no_baseline and baseline_path.is_file():
        try:
            baseline = load_baseline(baseline_path)
        except ValueError as exc:
            print(f"daslint: bad baseline: {exc}", file=sys.stderr)
            return 2
    if rules is not None:
        # a subset run must not report other rules' grandfathered
        # entries as stale — those findings were never searched for
        baseline = [b for b in baseline if b.rule in rules]
    new, kept, stale = apply_baseline(findings, baseline)
    if args.allow_partial:
        # the path subset is deliberately incomplete: an entry whose
        # file isn't in the set matches nothing, which proves exactly
        # as little as the rules-subset case above — staleness is the
        # full run's verdict
        stale = []

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "grandfathered": [f.to_json() for f in kept],
            "stale_baseline": [
                {"rule": b.rule, "path": b.path, "message": b.message}
                for b in stale
            ],
        }, indent=2))
    elif args.format == "sarif":
        print(json.dumps(
            _sarif_record(new, stale, baseline_path, all_rules), indent=2
        ))
    else:
        for f in new:
            print(f.render())
        for b in stale:
            print(
                f"stale baseline entry: {b.rule} {b.path}: {b.message!r} "
                "no longer matches any finding — delete it"
            )
        summary = (
            f"daslint: {len(new)} finding(s), {len(kept)} grandfathered, "
            f"{len(stale)} stale baseline entr(y/ies)"
        )
        print(summary)
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
