"""daslint CLI — `python -m das_tpu.analysis [paths...]` (ops/lint.sh).

Exit codes: 0 clean (baseline-grandfathered findings allowed), 1 any
new finding OR stale baseline entry, 2 usage error.  `--json` emits a
machine-readable record; default paths analyze the installed das_tpu
package with the repo-root baseline and tests/ directory.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from das_tpu.analysis.core import (
    apply_baseline,
    iter_rules,
    load_baseline,
    run_analysis,
)


def _repo_root() -> Path:
    import das_tpu

    return Path(das_tpu.__file__).resolve().parent.parent


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m das_tpu.analysis",
        description="daslint — AST invariant analyzer (ARCHITECTURE.md §11)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to analyze (default: the das_tpu package)",
    )
    parser.add_argument(
        "--rules", help="comma-separated rule subset (e.g. DL001,DL003)"
    )
    parser.add_argument(
        "--baseline", type=Path,
        help="baseline JSON (default: <repo>/daslint.baseline.json)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report grandfathered findings as errors too",
    )
    parser.add_argument(
        "--tests-dir", type=Path,
        help="tests directory for DL004's test-reference leg "
             "(default: <repo>/tests; pass a missing path to skip)",
    )
    parser.add_argument("--json", action="store_true", dest="as_json")
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid, title in iter_rules():
            print(f"{rid}  {title}")
        return 0

    root = _repo_root()
    paths = [Path(p) for p in args.paths] or [root / "das_tpu"]
    for p in paths:
        if not p.exists():
            print(f"daslint: no such path: {p}", file=sys.stderr)
            return 2
    rules = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules else None
    )
    tests_dir = args.tests_dir if args.tests_dir is not None else root / "tests"

    try:
        findings = run_analysis(paths, rules=rules, tests_dir=tests_dir)
    except ValueError as exc:
        print(f"daslint: {exc}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or (root / "daslint.baseline.json")
    if args.baseline is not None and not baseline_path.is_file():
        # the default path is allowed to be absent (no baseline yet);
        # an explicit one that is missing would silently skip the
        # stale-entry check, so it is a usage error
        print(f"daslint: no such baseline: {baseline_path}", file=sys.stderr)
        return 2
    baseline = []
    if not args.no_baseline and baseline_path.is_file():
        try:
            baseline = load_baseline(baseline_path)
        except ValueError as exc:
            print(f"daslint: bad baseline: {exc}", file=sys.stderr)
            return 2
    if rules is not None:
        # a subset run must not report other rules' grandfathered
        # entries as stale — those findings were never searched for
        baseline = [b for b in baseline if b.rule in rules]
    new, kept, stale = apply_baseline(findings, baseline)

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "grandfathered": [f.to_json() for f in kept],
            "stale_baseline": [
                {"rule": b.rule, "path": b.path, "message": b.message}
                for b in stale
            ],
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        for b in stale:
            print(
                f"stale baseline entry: {b.rule} {b.path}: {b.message!r} "
                "no longer matches any finding — delete it"
            )
        summary = (
            f"daslint: {len(new)} finding(s), {len(kept)} grandfathered, "
            f"{len(stale)} stale baseline entr(y/ies)"
        )
        print(summary)
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
