"""daslint infrastructure: findings, checker registry, suppressions,
baseline, and the driver that runs every rule over a parsed file set.

Rules are whole-set checkers, not per-file visitors: several contracts
are cross-file (an env read in storage/columnar.py against the registry
in core/config.py; a counter literal in api/atomspace.py against
ops/counters.py), so each rule receives the complete AnalysisContext
and yields findings wherever it likes.  Registration is import-time
(`@register` in each rules/ module); das_tpu.analysis.rules imports
them all.
"""

from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

#: per-FILE suppression — a comment reading
#: "daslint: disable=DL001,DL002" after its leading hash(es); the whole
#: file opts out of those rules (deliberately no line-level variant: a
#: file either honors a contract or documents why not).  Anchored to
#: real COMMENT tokens (tokenize), so quoting the syntax in a docstring
#: or a string literal does not silently disable anything.
_SUPPRESS_RE = re.compile(r"daslint:\s*disable=([A-Za-z0-9_,\s-]+)")


def _parse_suppressions(text: str) -> frozenset:
    disabled = set()
    for tok in tokenize.generate_tokens(io.StringIO(text).readline):
        if tok.type != tokenize.COMMENT:
            continue
        body = tok.string.lstrip("#").strip()
        m = _SUPPRESS_RE.match(body)
        if m:
            disabled.update(
                r.strip() for r in m.group(1).split(",") if r.strip()
            )
    return frozenset(disabled)


@dataclass(frozen=True)
class Finding:
    rule: str      # "DL001"
    path: str      # path as analyzed (posix)
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_json(self) -> Dict:
        return {
            "rule": self.rule, "path": self.path,
            "line": self.line, "message": self.message,
        }


class SourceFile:
    """One parsed module: text, AST, and its per-file rule suppressions."""

    def __init__(self, path: Path, text: str):
        self.path = path
        self.posix = path.as_posix()
        #: invocation-stable display form (last two components) for use
        #: INSIDE finding messages: baseline entries match messages
        #: exactly, so a message must not change between a relative
        #: `das_tpu` run (ops/lint.sh) and an absolute-path run
        self.short = "/".join(path.parts[-2:])
        self.name = path.stem
        self.text = text
        self.tree = ast.parse(text, filename=str(path))
        self.disabled = _parse_suppressions(text)


class AnalysisContext:
    """The whole analyzed file set plus the tests directory (DL004's
    "every counter key is referenced by at least one test" leg).

    `partial` marks a deliberately incomplete file set (ops/lint.sh
    --changed-only): registry-completeness legs — stale COLLECTIVE_SITES/
    FETCH_SITES/KERNEL_BUFFERS entries, declared-but-uncounted keys,
    read-less env registrations — are skipped, because an entry whose
    owner simply isn't in the set would fire falsely.  Presence legs
    (an undeclared call/read/key in an analyzed file) still run; the
    full-set run remains the authority on staleness."""

    def __init__(self, files: List[SourceFile], tests_dir: Optional[Path],
                 partial: bool = False):
        self.files = files
        self.tests_dir = tests_dir
        self.partial = partial

    def modules(self) -> Iterable[SourceFile]:
        return self.files


RuleFunc = Callable[[AnalysisContext], Iterable[Finding]]

_REGISTRY: Dict[str, Tuple[RuleFunc, str]] = {}


def register(rule_id: str, title: str):
    """Register a rule checker.  rule_id is the stable DLxxx name used in
    suppressions, the baseline file, and ARCHITECTURE.md §11."""

    def deco(fn: RuleFunc) -> RuleFunc:
        if rule_id in _REGISTRY:
            raise ValueError(f"duplicate daslint rule {rule_id}")
        _REGISTRY[rule_id] = (fn, title)
        return fn

    return deco


def iter_rules() -> List[Tuple[str, str]]:
    _load_rules()
    return sorted((rid, title) for rid, (_fn, title) in _REGISTRY.items())


def _load_rules() -> None:
    # import-time registration; idempotent
    import das_tpu.analysis.rules  # noqa: F401


#: per-process parse/summary cache keyed by (path, mtime_ns, size): the
#: tier-1 suite calls run_analysis dozens of times (fixture corpus,
#: mutated-copy regressions, the whole-tree pin) and re-parsing ~160
#: modules each time would dominate as the rule count grows.  The AST
#: and everything lazily hung off the SourceFile (per-module symbol
#: tables, callgraph.ModuleTable) ride along; an edited file re-parses
#: because its mtime_ns/size stamp moves.
_FILE_CACHE: Dict[str, Tuple[Tuple[int, int], SourceFile]] = {}


def _load_source(path: Path) -> SourceFile:
    key = path.as_posix()
    st = path.stat()
    stamp = (st.st_mtime_ns, st.st_size)
    hit = _FILE_CACHE.get(key)
    if hit is not None and hit[0] == stamp:
        return hit[1]
    sf = SourceFile(path, path.read_text())
    _FILE_CACHE[key] = (stamp, sf)
    return sf


def collect_files(paths: Sequence[Path]) -> List[SourceFile]:
    """Expand files/directories into parsed SourceFiles (sorted, no
    __pycache__), through the (path, mtime, size) parse cache.  A syntax
    error is surfaced as the caller's problem — the analyzer refuses to
    half-check a tree it cannot parse."""
    out: List[SourceFile] = []
    seen = set()
    for p in paths:
        p = Path(p)
        if p.is_dir():
            candidates = sorted(p.rglob("*.py"))
        else:
            candidates = [p]
        for c in candidates:
            if "__pycache__" in c.parts or c in seen:
                continue
            seen.add(c)
            out.append(_load_source(c))
    return out


def run_analysis(
    paths: Sequence[Path],
    *,
    rules: Optional[Sequence[str]] = None,
    tests_dir: Optional[Path] = None,
    partial: bool = False,
) -> List[Finding]:
    """Run (a subset of) the registered rules over `paths` and return the
    findings that survive per-file suppressions, sorted for stable
    output.  Baseline filtering is the caller's second step
    (apply_baseline) so tests can inspect raw findings.  `partial`
    relaxes the registry-completeness legs for deliberately incomplete
    file sets (see AnalysisContext)."""
    _load_rules()
    ctx = AnalysisContext(collect_files(paths), tests_dir, partial)
    # an EMPTY subset (e.g. --select X --ignore X) runs nothing — only
    # None means "all rules"
    wanted = set(rules) if rules is not None else set(_REGISTRY)
    unknown = wanted - set(_REGISTRY)
    if unknown:
        raise ValueError(f"unknown daslint rule(s): {sorted(unknown)}")
    suppressed = {f.posix: f.disabled for f in ctx.files}
    findings: List[Finding] = []
    for rid in sorted(wanted):
        fn, _title = _REGISTRY[rid]
        for finding in fn(ctx):
            if finding.rule in suppressed.get(finding.path, ()):
                continue
            findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings


# -- baseline ---------------------------------------------------------------
#
# daslint.baseline.json grandfathers findings we deliberately keep.  An
# entry matches by (rule, path SUFFIX, exact message) — no line numbers,
# so unrelated edits above a kept finding don't churn the file.  Every
# entry must carry a one-line justification, and entries that no longer
# match anything are STALE and fail the run: the baseline records debt,
# it must not outlive it.


@dataclass
class BaselineEntry:
    rule: str
    path: str
    message: str
    justification: str
    matched: bool = field(default=False, compare=False)

    def matches(self, f: Finding) -> bool:
        return (
            f.rule == self.rule
            and f.message == self.message
            and (f.path == self.path or f.path.endswith("/" + self.path))
        )


def load_baseline(path: Path) -> List[BaselineEntry]:
    data = json.loads(Path(path).read_text())
    entries = []
    for raw in data.get("findings", []):
        if not raw.get("justification"):
            raise ValueError(
                f"baseline entry without justification: {raw!r}"
            )
        entries.append(BaselineEntry(
            rule=raw["rule"], path=raw["path"], message=raw["message"],
            justification=raw["justification"],
        ))
    return entries


def apply_baseline(
    findings: List[Finding], baseline: List[BaselineEntry]
) -> Tuple[List[Finding], List[Finding], List[BaselineEntry]]:
    """Partition into (new, grandfathered) and return stale entries."""
    new: List[Finding] = []
    kept: List[Finding] = []
    for f in findings:
        entry = next((b for b in baseline if b.matches(f)), None)
        if entry is None:
            new.append(f)
        else:
            entry.matched = True
            kept.append(f)
    stale = [b for b in baseline if not b.matched]
    return new, kept, stale


# -- shared AST helpers (used by several rules) -----------------------------


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def module_assign(tree: ast.Module, name: str) -> Optional[ast.AST]:
    """The value of a module-level `name = ...` assignment, or None."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return node.value
        elif isinstance(node, ast.AnnAssign):
            if (
                isinstance(node.target, ast.Name)
                and node.target.id == name
            ):
                return node.value
    return None


def str_collection(node: Optional[ast.AST]) -> Optional[Tuple[str, ...]]:
    """A tuple/list/set literal of string constants, else None."""
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        vals = [const_str(e) for e in node.elts]
        if all(v is not None for v in vals):
            return tuple(vals)  # type: ignore[arg-type]
    return None


def attr_chain(node: ast.AST) -> Optional[str]:
    """Dotted-name string for Name/Attribute chains ("os.environ.get")."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
