"""`DistributedAtomSpace` — the public API facade.

Method-for-method parity with the reference facade
(/root/reference/das/distributed_atom_space.py:26-414): get_node/get_nodes/
get_link/get_links/get_atom, query, count_atoms, clear_database,
open/commit_transaction, load_knowledge_base, load_canonical_knowledge_base,
plus `QueryOutputFormat`.  Differences are all backend-side: instead of
Mongo+Redis connections resolved from env vars, construction picks an
in-process backend ("memory" | "tensor" | "sharded") and `query()`
transparently routes compilable conjunctive queries through the device
pipeline (das_tpu/query/compiler.py), falling back to the host algebra.

One reference bug not reproduced: query(output_format=ATOM_INFO/JSON)
iterated `assignments.items()` on a set and crashed
(distributed_atom_space.py:311-318); here those formats render each
assignment's variable→atom mapping.
"""

from __future__ import annotations

import json
from enum import Enum, auto
from typing import Dict, List, Optional, Tuple, Union

from das_tpu.core.config import DasConfig
from das_tpu.core.exceptions import BreakerOpenError
from das_tpu.core.schema import UNORDERED_LINK_TYPES, WILDCARD
from das_tpu.query import compiler as query_compiler
from das_tpu.query.ast import LogicalExpression, PatternMatchingAnswer
from das_tpu.storage.atom_table import AtomSpaceData
from das_tpu.storage.memory_db import MemoryDB
from das_tpu.storage.tensor_db import TensorDB
from das_tpu.utils.logger import logger


class QueryOutputFormat(int, Enum):
    HANDLE = auto()
    ATOM_INFO = auto()
    JSON = auto()


class Transaction:
    """Buffer of toplevel MeTTa expression strings for incremental commit
    (role of /root/reference/das/transaction.py:1-10)."""

    def __init__(self):
        self.expressions: List[str] = []

    def add(self, expression: str) -> None:
        self.expressions.append(expression)

    # reference spelling (transaction.py:6-7) — same operation
    add_toplevel_expression = add

    def metta_string(self) -> str:
        return "\n".join(self.expressions)


class _QueryManyJob:
    """One coalesced batch mid-pipeline: planning and the asynchronous
    device dispatch happen at construction (query_many_dispatch); settle()
    pays the host transfer and materializes.  Queries the fused path
    cannot take (not compilable, missing bucket, capacity ceiling) resolve
    through the per-query dispatcher during settle — the pipeline degrades
    to the serial path for exactly those entries, never for the batch."""

    __slots__ = ("das", "queries", "output_format", "plans_lists", "idxs",
                 "pending", "db_ref", "version", "sharded", "settle_rtt_ms",
                 "cache_only")

    def __init__(self, das, queries, output_format, cache_only=False):
        self.das = das
        self.queries = queries
        self.output_format = output_format
        # degraded-mode serving (ISSUE 13, the coalescer's open circuit
        # breaker): answer from the delta-versioned result cache ONLY —
        # no device dispatch, no staged fallback, no per-query re-run;
        # entries the cache cannot answer yield a typed, retryable
        # BreakerOpenError instead
        self.cache_only = cache_only
        self.plans_lists: List = []
        self.idxs: List[int] = []
        self.pending = None
        # the streamed round's first host-transfer duration (fused.py
        # _PendingMany.fetch_ms[0]) — the settle round-trip, set once
        # settle_iter's fused/sharded branch finishes streaming; None
        # when no fetch happened (all hits, all declined, commit race),
        # so the coalescer's window estimator is fed ONLY real wire time
        self.settle_rtt_ms = None
        # mesh tenants take the sharded executor's dispatch/settle halves
        # (parallel/fused_sharded.py) — same pipeline shape, shard_map
        # programs instead of single-device fused ones
        self.sharded = hasattr(das.db, "query_sharded")
        # the store (by identity — clear_database swaps the backend and a
        # fresh one restarts the counter) and commit version this batch
        # planned/dispatched against: a commit landing before settle()
        # may re-intern global row ids (a FULL re-finalize moves every
        # link row), so settle must not materialize this snapshot's
        # tables through the new registries
        self.db_ref = das.db
        self.version = getattr(das.db, "delta_version", None)
        if (hasattr(das.db, "dev") or self.sharded) and queries:
            from das_tpu import obs

            with obs.span("serve.plan", queries=len(queries)) as sp:
                for i, q in enumerate(queries):
                    plans = query_compiler.plan_query(das.db, q)
                    if plans is not None:
                        self.plans_lists.append(plans)
                        self.idxs.append(i)
                sp.set(compilable=len(self.plans_lists))
            if self.plans_lists:
                dispatch = (
                    query_compiler.execute_sharded_many_dispatch
                    if self.sharded
                    else query_compiler.execute_fused_many_dispatch
                )
                self.pending = dispatch(
                    das.db, self.plans_lists, cache_only=cache_only
                )

    def _stale(self) -> bool:
        """True when the dispatched round's row ids and plans no longer
        describe the live store: the backend was swapped, or a commit
        bumped delta_version past the one captured at dispatch."""
        db = self.das.db
        return (db is not self.db_ref
                or getattr(db, "delta_version", None) != self.version)

    def _stream_settled(self, pending, settle_iter_fn, answer_fn):
        """The correctness-critical streaming scaffold, shared by the
        sharded and fused settle branches so its ORDERING exists once:
        (1) record the settle round-trip EAGERLY at the first post-fetch
        yield (fused.py `_PendingMany.fetch_ms`) — a later mid-stream
        failure must not drop the genuine wire sample, or the
        coalescer's estimator would hold a failing tenant at the floor
        forever; (2) re-check the dispatch-time delta_version guard PER
        YIELD — streaming paces settle to the CONSUMER, so a commit
        landing between yields invalidates every not-yet-materialized
        entry (already-yielded answers were consistent when delivered):
        abandon the round, the per-query loop in settle_iter re-runs
        the rest on the post-commit store; (3) materialize/format via
        `answer_fn(j, result)`, a failure degrading that entry (and
        only it) to the per-query dispatcher.  Yields
        `(query index, formatted answer)`."""
        for j, res in settle_iter_fn(
            self.das.db, self.plans_lists, pending
        ):
            if self.settle_rtt_ms is None and pending.fetch_ms:
                self.settle_rtt_ms = pending.fetch_ms[0]
            if self._stale():
                break
            try:
                out_s = answer_fn(j, res)
            except Exception:  # noqa: BLE001 — e.g. CapacityOverflow:
                continue       # per-query dispatcher takes this entry
            yield self.idxs[j], out_s

    def settle_iter(self):
        """Streaming settle (ISSUE 6 early-settle): yields
        `(query index, answer-or-Exception)` as each answer becomes
        FINAL, instead of blocking until the whole group settles and
        materializes.  Fused-settled entries stream first, in
        verdict-arrival order — a query whose first retry round fit is
        materialized and yielded while its batch-mates are still
        settling, so its first rows reach the client one RTT after its
        own dispatch.  A settle-time decline replays on the staged path
        IN verdict order (its slot in the stream pays the replay
        inline); dispatch-time declines and non-compilable queries
        (per-query dispatcher) follow after the stream; a failed entry
        yields its OWN exception, never a batch-mate's.  Every
        index is yielded exactly once; settle() is the drain-to-list
        form.  The dispatch-time delta_version guard is re-checked per
        yield, not just once up front: streaming paces settle to the
        CONSUMER, so a commit can land between yields — when it does,
        the not-yet-materialized remainder re-runs per query on the
        post-commit store."""
        das = self.das
        done = [False] * len(self.queries)
        if self.pending is not None and self._stale():
            # a commit raced in between dispatch and settle: drop the
            # dispatched round wholesale (its row ids and plans belong to
            # the pre-commit store) and re-run everything per query on
            # the post-commit store — correctness over the saved
            # transfer.  This is the guard that keeps SPECULATIVE
            # dispatch (a group dispatched before earlier settles
            # landed, service/coalesce.py) sound: however deep the
            # window ran, each group re-checks its dispatch-time version
            # here before materializing anything.
            self.pending = None
        if self.pending is not None and self.sharded:
            from das_tpu import kernels as _kernels
            from das_tpu.parallel.sharded_db import ShardedTable

            pending, self.pending = self.pending, None
            kernel_route = _kernels.enabled(getattr(das.db, "config", None))

            def sharded_answer(j, res):
                if res is None:
                    if self.cache_only:
                        # degraded mode: a cache miss must not run the
                        # staged mesh pipeline — degrade this entry to
                        # the typed rejection (the final loop below)
                        raise BreakerOpenError()
                    # fused mesh declined (ceiling/reseed): the staged
                    # mesh pipeline answers — answer-identical, same
                    # fallback _run_conjunctive takes
                    table = das.db.sharded_execute(self.plans_lists[j])
                else:
                    table = ShardedTable(
                        res.var_names, res.vals, res.valid, res.count,
                        host_vals=res.host_vals,
                        host_valid=res.host_valid,
                    )
                answer = PatternMatchingAnswer()
                matched = das.db.materialize(table, answer)
                out_s = das._format_answer(
                    matched, answer, self.output_format
                )
                query_compiler.ROUTE_COUNTS["sharded"] += 1
                # staged-fallback answers (res None) ran the lowered
                # mesh pipeline — only fused-answered entries count
                # as kernel-routed (exact program counts live in
                # kernels.DISPATCH_COUNTS)
                if kernel_route and res is not None:
                    query_compiler.ROUTE_COUNTS["sharded_kernel"] += 1
                return out_s

            settled = self._stream_settled(
                pending,
                query_compiler.execute_sharded_many_settle_iter,
                sharded_answer,
            )
            for i, out_s in settled:
                done[i] = True
                yield i, out_s
        elif self.pending is not None:
            pending, self.pending = self.pending, None

            def fused_answer(j, table):
                route = "fused"
                if table is None:
                    if self.cache_only:
                        # degraded mode: no staged replay for a cache
                        # miss — the final loop rejects it typed
                        raise BreakerOpenError()
                    # fused declined (ceiling/reseed): go straight to
                    # the answer-identical staged path — re-trying the
                    # fused program via query() would just rediscover
                    # the decline at the cost of another dispatch
                    table = query_compiler.execute_plan(
                        das.db, self.plans_lists[j]
                    )
                    route = "staged"
                answer = PatternMatchingAnswer()
                matched = query_compiler.materialize(das.db, table, answer)
                out_s = das._format_answer(
                    matched, answer, self.output_format
                )
                # counted only once the answer exists: a failure re-runs
                # via query(), which counts its own route — incrementing
                # earlier would double-count
                query_compiler.ROUTE_COUNTS[route] += 1
                return out_s

            settled = self._stream_settled(
                pending,
                query_compiler.execute_fused_many_settle_iter,
                fused_answer,
            )
            for i, out_s in settled:
                done[i] = True
                yield i, out_s
        for i, q in enumerate(self.queries):
            if done[i]:
                continue
            if self.cache_only:
                # degraded-mode contract: cache hits streamed above,
                # everything else is rejected RETRYABLE — fresh device
                # dispatches are what the open breaker exists to stop
                # (the coalescer stamps the retry-after hint)
                yield i, BreakerOpenError()
                continue
            try:
                yield i, das.query(q, self.output_format)
            except Exception as exc:  # noqa: BLE001 — per-query isolation
                yield i, exc

    def settle(self) -> List[Union[str, Exception]]:
        """One entry per query: the answer string, or that query's OWN
        exception — a failure never leaks onto a batch-mate (the coalescer
        maps Exception entries to their individual futures).  Drains
        settle_iter; use the iterator directly for streaming delivery."""
        out: List[Union[str, Exception]] = [None] * len(self.queries)
        for i, answer in self.settle_iter():
            out[i] = answer
        return out


class DistributedAtomSpace:
    def __init__(self, **kwargs):
        self.database_name = kwargs.get("database_name", "das")
        self.config: DasConfig = kwargs.get("config") or DasConfig.from_env()
        backend = kwargs.get("backend", self.config.backend)
        self.config.backend = backend
        db = kwargs.get("db")
        if db is not None:
            # wrap an existing backend (service tenants attached to an
            # already-built store — bench/tests; skips checkpoint load
            # and re-upload entirely)
            self.data = db.data
            self.db = db
            self.pattern_black_list = list(self.config.pattern_black_list)
            logger().info(
                f"New Distributed Atom Space '{self.database_name}' "
                f"(attached backend {type(db).__name__})"
            )
            return
        data = kwargs.get("data")
        if (
            data is None
            and self.config.snapshot_dir
            and backend in ("tensor", "sharded")
        ):
            # dasdur warm restore (ISSUE 15): a bare DistributedAtomSpace()
            # with a populated snapshot root comes up from the newest
            # VALID generation + WAL replay + warm bundle — the
            # replica-fleet cold start in seconds instead of minutes —
            # and keeps appending commits to the generation's WAL
            from das_tpu.storage import durable

            if durable.list_generations(self._snapshot_root()):
                self.db = durable.restore(
                    self._snapshot_root(), config=self.config,
                    backend=backend,
                )
                self.data = self.db.data
                self.pattern_black_list = list(
                    self.config.pattern_black_list
                )
                logger().info(
                    f"New Distributed Atom Space '{self.database_name}' "
                    f"(backend={backend}, restored from "
                    f"{self.config.snapshot_dir})"
                )
                return
        if data is None and self.config.checkpoint_path:
            import os

            from das_tpu.storage import checkpoint

            if os.path.isdir(self.config.checkpoint_path):
                data = checkpoint.load(self.config.checkpoint_path)
            else:
                # reference-analogous behavior: env-var endpoints with no
                # data behind them attach to an empty store (and a server's
                # create RPC must not die on a tenant construction error)
                logger().warning(
                    "DAS_TPU_CHECKPOINT path "
                    f"'{self.config.checkpoint_path}' does not exist; "
                    "starting with an empty AtomSpace"
                )
        self.data = data or AtomSpaceData()
        self.db = self._make_backend(backend)
        self.pattern_black_list = list(self.config.pattern_black_list)
        if self.config.snapshot_dir and backend in ("tensor", "sharded"):
            # fresh store under a durability root: write generation 1
            # (the WAL needs a base to replay onto) and arm the delta log
            from das_tpu.storage import durable

            durable.attach(self.db, self._snapshot_root(), self.config)
        logger().info(
            f"New Distributed Atom Space '{self.database_name}' "
            f"(backend={backend})"
        )

    def _snapshot_root(self) -> Optional[str]:
        """This AtomSpace's durability root: `snapshot_dir` NAMESPACED by
        database_name.  One generation lineage holds exactly ONE store's
        history — a shared DAS_TPU_SNAPSHOT_DIR across service tenants
        must not let tenant B restore tenant A's atoms or interleave two
        delta_version sequences into one WAL (replay would fail its
        continuity check and brick the root).  Backend-level callers
        (`TensorDB.restore(path)`) address a lineage dir directly."""
        import os

        if not self.config.snapshot_dir:
            return None
        return os.path.join(self.config.snapshot_dir, self.database_name)

    def _make_backend(self, backend: str):
        if backend == "memory":
            return MemoryDB(self.data)
        if backend == "tensor":
            return TensorDB(self.data, self.config)
        if backend == "sharded":
            from das_tpu.parallel.sharded_db import ShardedDB

            return ShardedDB(self.data, self.config)
        raise ValueError(f"Unknown backend: {backend}")

    def _get_file_list(self, source: str) -> List[str]:
        """Knowledge-base path expansion (reference
        distributed_atom_space.py:81-99; its own test suite probes this
        name directly, so it is part of the compat surface)."""
        from das_tpu.ingest.pipeline import knowledge_base_file_list

        return knowledge_base_file_list(source)

    def _refresh(self) -> None:
        if hasattr(self.db, "refresh"):
            self.db.refresh()
        else:
            self.db.prefetch()

    @property
    def pattern_black_list(self) -> List[str]:
        """Lives on the AtomSpaceData so every backend and planner reads the
        same list; assignment writes through (no aliasing to de-sync)."""
        return self.data.pattern_black_list

    @pattern_black_list.setter
    def pattern_black_list(self, value: List[str]) -> None:
        self.data.pattern_black_list = list(value)

    # -- public API --------------------------------------------------------

    def clear_database(self) -> None:
        black_list = self.pattern_black_list
        self.data = AtomSpaceData()
        self.data.pattern_black_list = black_list
        self.db = self._make_backend(self.config.backend)
        if self.config.snapshot_dir and self.config.backend in (
            "tensor", "sharded",
        ):
            # a durable tenant's clear IS a state change: persist the
            # empty store as a NEW generation (re-attaching the old
            # generation's WAL to a fresh backend would break replay's
            # delta_version continuity)
            from das_tpu.storage import durable

            durable.write_snapshot(self.db, self._snapshot_root())

    def count_atoms(self) -> Tuple[int, int]:
        return self.db.count_atoms()

    def get_atom(
        self, handle: str, output_format: QueryOutputFormat = QueryOutputFormat.HANDLE
    ) -> Union[str, Dict]:
        if output_format == QueryOutputFormat.HANDLE or not handle:
            atom = self.db.get_atom_as_dict(handle)
            return atom["handle"] if atom else ""
        if output_format == QueryOutputFormat.ATOM_INFO:
            return self.db.get_atom_as_dict(handle)
        if output_format == QueryOutputFormat.JSON:
            answer = self.db.get_atom_as_deep_representation(handle)
            return json.dumps(answer, sort_keys=False, indent=4)
        raise ValueError(f"Invalid output format: '{output_format}'")

    def get_node(
        self,
        node_type: str,
        node_name: str,
        output_format: QueryOutputFormat = QueryOutputFormat.HANDLE,
    ) -> Union[str, Dict, None]:
        node_handle = self.db.get_node_handle(node_type, node_name)
        if not self.db.node_exists(node_type, node_name):
            logger().warning(
                f"Attempt to access an invalid Node '{node_type}:{node_name}'"
            )
            return None
        if output_format == QueryOutputFormat.HANDLE:
            return node_handle
        if output_format == QueryOutputFormat.ATOM_INFO:
            return self.db.get_atom_as_dict(node_handle)
        if output_format == QueryOutputFormat.JSON:
            answer = self.db.get_atom_as_deep_representation(node_handle)
            return json.dumps(answer, sort_keys=False, indent=4)
        raise ValueError(f"Invalid output format: '{output_format}'")

    def get_nodes(
        self,
        node_type: str,
        node_name: Optional[str] = None,
        output_format: QueryOutputFormat = QueryOutputFormat.HANDLE,
    ) -> Union[List[str], List[Dict], str]:
        if node_name is not None:
            handle = self.db.get_node_handle(node_type, node_name)
            answer = [handle] if self.db.node_exists(node_type, node_name) else []
        else:
            answer = self.db.get_all_nodes(node_type)
        if output_format == QueryOutputFormat.HANDLE or not answer:
            return answer
        if output_format == QueryOutputFormat.ATOM_INFO:
            return [self.db.get_atom_as_dict(h) for h in answer]
        if output_format == QueryOutputFormat.JSON:
            deep = [self.db.get_atom_as_deep_representation(h) for h in answer]
            return json.dumps(deep, sort_keys=False, indent=4)
        raise ValueError(f"Invalid output format: '{output_format}'")

    def get_link(
        self,
        link_type: str,
        targets: Optional[List[str]] = None,
        output_format: QueryOutputFormat = QueryOutputFormat.HANDLE,
    ) -> Union[str, Dict, None]:
        link_handle = self.db.get_link_handle(link_type, targets or [])
        if not self.db.link_exists(link_type, targets or []):
            return None
        if output_format == QueryOutputFormat.HANDLE:
            return link_handle
        if output_format == QueryOutputFormat.ATOM_INFO:
            return self.db.get_atom_as_dict(link_handle, len(targets or []))
        if output_format == QueryOutputFormat.JSON:
            answer = self.db.get_atom_as_deep_representation(
                link_handle, len(targets or [])
            )
            return json.dumps(answer, sort_keys=False, indent=4)
        raise ValueError(f"Invalid output format: '{output_format}'")

    def _to_handle_list(self, db_answer) -> List[str]:
        if not db_answer:
            return []
        return [
            atom if isinstance(atom, str) else atom[0] for atom in db_answer
        ]

    def _to_link_dict_list(self, db_answer) -> List[Dict]:
        answer = []
        for atom in db_answer or []:
            if isinstance(atom, str):
                handle, arity = atom, -1
            else:
                handle, targets = atom
                arity = len(targets)
            answer.append(self.db.get_atom_as_dict(handle, arity))
        return answer

    def _to_json(self, db_answer) -> str:
        answer = []
        for atom in db_answer or []:
            if isinstance(atom, str):
                handle, arity = atom, -1
            else:
                handle, targets = atom
                arity = len(targets)
            answer.append(self.db.get_atom_as_deep_representation(handle, arity))
        return json.dumps(answer, sort_keys=False, indent=4)

    def get_links(
        self,
        link_type: str,
        target_types: Optional[List[str]] = None,
        targets: Optional[List[str]] = None,
        output_format: QueryOutputFormat = QueryOutputFormat.HANDLE,
    ) -> Union[List[str], List[Dict], str]:
        if link_type is None:
            link_type = WILDCARD
        if target_types is not None and link_type != WILDCARD:
            db_answer = self.db.get_matched_type_template([link_type, *target_types])
        elif targets is not None:
            if link_type in UNORDERED_LINK_TYPES and WILDCARD in targets:
                # Production-DB semantics for an unordered wildcard probe
                # (reference redis_mongo_db.py:249-252 over the ingest keys
                # of parser_threads.py:188-218): the probe key hashes the
                # SORTED handles while ingest emits keys in STORED order,
                # so the probe matches POSITIONALLY against the sorted
                # probe tuple.  The engine keeps the reference StubDB's
                # multiset semantics (stub_db.py:129-146, differentially
                # verified); that probe is a superset, filtered down here.
                probe = sorted(targets)
                db_answer = [
                    m
                    for m in self.db.get_matched_links(link_type, probe)
                    if all(
                        p == WILDCARD or p == t for p, t in zip(probe, m[1])
                    )
                ]
            else:
                db_answer = self.db.get_matched_links(link_type, targets)
        elif link_type != WILDCARD:
            db_answer = self.db.get_matched_type(link_type)
        else:
            raise ValueError("Invalid parameters")
        if output_format == QueryOutputFormat.HANDLE:
            return self._to_handle_list(db_answer)
        if output_format == QueryOutputFormat.ATOM_INFO:
            return self._to_link_dict_list(db_answer)
        if output_format == QueryOutputFormat.JSON:
            return self._to_json(db_answer)
        raise ValueError(f"Invalid output format: '{output_format}'")

    def get_link_type(self, link_handle: str) -> str:
        return self.db.get_link_type(link_handle)

    def get_link_targets(self, link_handle: str) -> List[str]:
        return self.db.get_link_targets(link_handle)

    def get_node_type(self, node_handle: str) -> str:
        return self.db.get_node_type(node_handle)

    def get_node_name(self, node_handle: str) -> str:
        return self.db.get_node_name(node_handle)

    # -- query -------------------------------------------------------------

    def _render_assignment(self, assignment, deep: bool):
        get = (
            self.db.get_atom_as_deep_representation
            if deep
            else self.db.get_atom_as_dict
        )
        if hasattr(assignment, "mapping"):
            return {var: get(h) for var, h in assignment.mapping.items()}
        return repr(assignment)

    def _dispatch_query(self, query: LogicalExpression, answer: PatternMatchingAnswer):
        """Route compilable queries to the device/mesh pipeline, fall back
        to the host algebra otherwise — including when a join legitimately
        exceeds max_result_capacity (a valid query must degrade to the
        host algebra, never crash the API).  Routing lives in
        query_compiler.dispatch so the reference-compat shim shares it."""
        return query_compiler.dispatch(self.db, query, answer)

    def query(
        self,
        query: LogicalExpression,
        output_format: QueryOutputFormat = QueryOutputFormat.HANDLE,
    ) -> str:
        answer = PatternMatchingAnswer()
        matched = self._dispatch_query(query, answer)
        return self._format_answer(matched, answer, output_format)

    def query_many(
        self,
        queries: List[LogicalExpression],
        output_format: QueryOutputFormat = QueryOutputFormat.HANDLE,
    ) -> List[str]:
        """Batched `query`: fused-compilable queries on a device backend
        dispatch together and pay ONE host transfer per retry round (the
        serving coalescer's path — each separate fetch is a full tunnel
        RTT); everything else falls back to the per-query dispatcher.
        Output strings are identical to query()'s."""
        if len(queries) <= 1:
            return [self.query(q, output_format) for q in queries]
        answers = self.query_many_dispatch(queries, output_format).settle()
        for a in answers:
            if isinstance(a, Exception):
                raise a
        return answers

    def query_many_dispatch(
        self,
        queries: List[LogicalExpression],
        output_format: QueryOutputFormat = QueryOutputFormat.HANDLE,
        cache_only: bool = False,
    ) -> "_QueryManyJob":
        """Pipeline half of query_many, for the serving coalescer
        (service/coalesce.py): plan the batch and ENQUEUE its fused device
        programs (async, result-cache aware), returning a job whose
        `.settle()` pays the host transfer, materializes, and resolves
        fallbacks.  Between dispatch and settle the device executes this
        batch while the caller settles the previous one — the bounded
        in-flight pipeline that keeps the device queue full under load.
        settle() returns one entry per query: the formatted answer string,
        or the query's OWN Exception (never a batch-mate's).  cache_only
        is degraded-mode serving (ISSUE 13, open circuit breaker): cache
        hits answer with zero device work, everything else resolves to a
        typed retryable BreakerOpenError."""
        return _QueryManyJob(self, queries, output_format,
                             cache_only=cache_only)

    def _format_answer(
        self, matched, answer: PatternMatchingAnswer, output_format
    ) -> str:
        tag_not = ""
        mapping = ""
        if matched:
            if answer.negation:
                tag_not = "NOT "
            if output_format == QueryOutputFormat.HANDLE:
                mapping = str(answer.assignments)
            elif output_format == QueryOutputFormat.ATOM_INFO:
                mapping = str(
                    [self._render_assignment(a, deep=False) for a in answer.assignments]
                )
            elif output_format == QueryOutputFormat.JSON:
                mapping = json.dumps(
                    [self._render_assignment(a, deep=True) for a in answer.assignments],
                    sort_keys=False,
                    indent=4,
                )
            else:
                raise ValueError(f"Invalid output format: '{output_format}'")
        return f"{tag_not}{mapping}"

    def query_answer(self, query: LogicalExpression) -> Tuple[bool, PatternMatchingAnswer]:
        """Structured query result (assignment objects, not strings)."""
        answer = PatternMatchingAnswer()
        matched = self._dispatch_query(query, answer)
        return bool(matched), answer

    def explain(self, query: LogicalExpression, execute: bool = False,
                compile: bool = False) -> Dict:
        """Costed-plan explain (das_tpu/planner, ISSUE 8): the planner's
        decision for `query` — chosen join order, expected route (an
        ops/counters.py ROUTE_KEYS member), estimated per-term and
        per-join rows, and the capacity seeds — without dispatching
        anything.  With execute=True the query also RUNS through the
        executor's real dispatch/settle halves and the actual per-stage
        rows and retry rounds are reported next to the estimates, so
        estimator error is observable per query (the aggregate lives in
        coalescer_stats()["planner"]).  With compile=True (implies
        execute) each entry gains the program ledger's compile/cost/
        memory record for the dispatched signature (ISSUE 14,
        das_tpu/obs/proflog.py).  Tree composites (Or / negation trees)
        report one entry per ordered-conjunction site; queries outside
        the compiled language report route "host"."""
        return query_compiler.explain(
            self.db, query, execute=execute, compile=compile
        )

    # -- transactions ------------------------------------------------------

    def open_transaction(self) -> Transaction:
        return Transaction()

    def commit_transaction(self, transaction: Transaction) -> None:
        from das_tpu.storage.atom_table import load_metta_text

        load_metta_text(transaction.metta_string(), self.data)
        self._refresh()

    # -- bulk loads --------------------------------------------------------

    def load_knowledge_base(self, source: str) -> None:
        from das_tpu.ingest.pipeline import load_knowledge_base

        load_knowledge_base(self.data, source)
        self._refresh()
        nodes, links = self.count_atoms()
        logger().info(f"Loaded KB: {nodes} nodes, {links} links")

    def load_canonical_knowledge_base(self, source: str) -> None:
        from das_tpu.ingest.pipeline import load_canonical_knowledge_base

        load_canonical_knowledge_base(self.data, source)
        self._refresh()
        nodes, links = self.count_atoms()
        logger().info(f"Loaded canonical KB: {nodes} nodes, {links} links")

    def load_metta_text(self, text: str) -> None:
        """Convenience: load a MeTTa string directly."""
        from das_tpu.storage.atom_table import load_metta_text

        load_metta_text(text, self.data)
        self._refresh()

    # -- checkpoint / resume ----------------------------------------------

    def save_checkpoint(self, path: str, with_indexes: bool = True) -> None:
        """Persist the AtomSpace (records + probe indexes) to a directory.
        On the sharded backend the shard-local slabs are saved too, so a
        restart restores each device's slab directly (no re-partition)."""
        from das_tpu.storage import checkpoint

        if with_indexes and hasattr(self.db, "tables"):
            checkpoint.save_sharded(self.db, path)
        else:
            checkpoint.save(self.data, path, with_indexes=with_indexes)

    def load_checkpoint(self, path: str) -> None:
        """Restore an AtomSpace checkpoint (replaces current contents)."""
        from das_tpu.storage import checkpoint

        self.data = checkpoint.load(path)
        self.db = self._make_backend(self.config.backend)

    # -- durability (ISSUE 15, storage/durable.py) ------------------------

    def save_snapshot(self, path: Optional[str] = None) -> str:
        """One atomic generational snapshot of the live backend: records,
        probe indexes, (sharded) slabs and the warm-state bundle land in
        a new `gen-NNNNNN` directory under the root, verified by a
        CRC-digest manifest; the write-ahead log rotates to the new
        generation.  Returns the generation directory."""
        from das_tpu.storage import durable

        root = path or self._snapshot_root()
        if not root:
            raise ValueError(
                "no snapshot root: pass a path or set "
                "DasConfig.snapshot_dir / DAS_TPU_SNAPSHOT_DIR"
            )
        return durable.write_snapshot(self.db, root)

    def restore_snapshot(self, path: Optional[str] = None) -> None:
        """Replace the current contents with a verified warm restore:
        newest valid generation + WAL replay to head + warm bundle
        (TensorDB.restore / ShardedDB.restore are the backend-level
        spellings)."""
        from das_tpu.storage import durable

        root = path or self._snapshot_root()
        if not root:
            raise ValueError(
                "no snapshot root: pass a path or set "
                "DasConfig.snapshot_dir / DAS_TPU_SNAPSHOT_DIR"
            )
        self.db = durable.restore(
            root, config=self.config, backend=self.config.backend
        )
        self.data = self.db.data
