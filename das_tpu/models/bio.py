"""Synthetic bio-atomspace generator.

Stands in for the reference's gene-level bio atomspace
(scripts/benchmark.py:36-128 query shapes; SimplePatternMiner.ipynb scale)
so benchmarks are reproducible without the private FlyBase dump.  The graph
shape mirrors the benchmark's schema: Gene/BiologicalProcess/Reactome
nodes, ``Member`` links gene→process, ``Interacts`` gene↔gene (stored both
orientations like the animals KB's Similarity closure), and two-level
``Evaluation``/``List`` noise links exercising nested arities.

Atoms are built straight into `AtomSpaceData` records (no text round-trip)
so multi-million-atom KBs materialize in seconds.
"""

from __future__ import annotations

import random
from typing import Optional

from das_tpu.core.expression import Expression
from das_tpu.core.hashing import ExpressionHasher
from das_tpu.core.schema import BASIC_TYPE, TYPEDEF_MARK
from das_tpu.storage.atom_table import AtomSpaceData


def _add_type(data: AtomSpaceData, name: str) -> None:
    t = data.table
    mark_hash = t.get_named_type_hash(TYPEDEF_MARK)
    base_hash = t.get_named_type_hash(BASIC_TYPE)
    name_hash = t.get_named_type_hash(name)
    t.named_types[name] = BASIC_TYPE
    t.parent_type[name_hash] = base_hash
    elements = [name_hash, base_hash]
    expr = Expression(
        toplevel=True,
        typedef_name=name,
        typedef_name_hash=name_hash,
        named_type=TYPEDEF_MARK,
        named_type_hash=mark_hash,
        composite_type=[mark_hash, base_hash, base_hash],
        composite_type_hash=ExpressionHasher.composite_hash(
            [mark_hash, base_hash, base_hash]
        ),
        elements=elements,
        hash_code=ExpressionHasher.expression_hash(mark_hash, elements),
    )
    t.symbol_hash[name] = expr.hash_code
    data.add_typedef(expr)


def _add_node(data: AtomSpaceData, node_type: str, name: str) -> str:
    t = data.table
    type_hash = t.get_named_type_hash(node_type)
    h = t.get_terminal_hash(node_type, name)
    data.add_terminal(
        Expression(
            terminal_name=name,
            named_type=node_type,
            named_type_hash=type_hash,
            composite_type=[type_hash],
            composite_type_hash=type_hash,
            hash_code=h,
        )
    )
    return h


#: (link_type, element_ctypes) -> (type_hash, composite_type,
#: composite_type_hash).  Every value is a pure md5 function of the names,
#: so the memo needs no table identity; the composite-type hash is one md5
#: per link SCHEMA, not per link — at the 27.9M-link flybase scale
#: recomputing it per link doubled the builder's hashing work.
_LINK_SCHEMA_MEMO: dict = {}


def _link_schema(t, link_type: str, element_ctypes):
    # always exercised (not only on memo miss): registers the link type in
    # THIS table's name registry — the memo is shared across tables
    type_hash = t.get_named_type_hash(link_type)
    key = (link_type, tuple(
        c if isinstance(c, str) else tuple(c) for c in element_ctypes
    ))
    hit = _LINK_SCHEMA_MEMO.get(key)
    if hit is None:
        # memoize only immutable copies (the key's frozen tuples), never the
        # caller's list objects — a caller mutating its element_ctypes after
        # the first _add_link must not change what later lookups return
        composite_type = (type_hash, *key[1])
        cth = ExpressionHasher.composite_hash(
            [
                c if isinstance(c, str) else ExpressionHasher.composite_hash(list(c))
                for c in composite_type
            ]
        )
        hit = (type_hash, composite_type, cth)
        if len(_LINK_SCHEMA_MEMO) >= 1 << 16:  # bound the module-global memo
            _LINK_SCHEMA_MEMO.clear()
        _LINK_SCHEMA_MEMO[key] = hit
    # fresh (nested) list per link: records own their composite_type mutably
    composite = [list(c) if isinstance(c, tuple) else c for c in hit[1]]
    return hit[0], composite, hit[2]


def _add_link(data: AtomSpaceData, link_type: str, elements, element_ctypes) -> str:
    type_hash, composite_type, cth = _link_schema(
        data.table, link_type, element_ctypes
    )
    h = ExpressionHasher.expression_hash(type_hash, list(elements))
    data.add_link(
        Expression(
            toplevel=True,
            named_type=link_type,
            named_type_hash=type_hash,
            composite_type=composite_type,
            composite_type_hash=cth,
            elements=list(elements),
            hash_code=h,
        )
    )
    return h


def _skew_idx(rng: random.Random, n: int, skew: float) -> int:
    """One index draw.  skew == 0 is uniform (exactly one rng.randrange
    call, preserving historical draw sequences); skew > 0 maps a uniform
    u through u^(1+skew), concentrating mass on LOW indices — a power-law
    participation profile like real annotation datasets (FlyBase-style
    hub genes/processes), unlike the uniform synthetic KB (VERDICT r03
    weak #7)."""
    if skew <= 0:
        return rng.randrange(n)
    return min(n - 1, int(n * (rng.random() ** (1.0 + skew))))


def _member_sample(rng, n: int, k: int, skew: float):
    """The per-gene process memberships: UP TO k distinct indices.
    skew <= 0 is exactly rng.sample (always k, historical draw
    sequence); skew > 0 redraws from the power-law profile, BOUNDED
    (20k tries) so the rng sequence stays deterministic and identical
    between the in-process builder and the canonical writer — at
    extreme skew over a tiny pool a gene can therefore end up with
    fewer than k memberships (both builders shortfall identically, so
    handle parity holds, but workload accounting must not assume
    exactly n_genes*k Member links under skew)."""
    k = min(k, n)
    if skew <= 0:
        return rng.sample(range(n), k)
    out = []
    tries = 0
    while len(out) < k and tries < 20 * k:
        tries += 1
        i = _skew_idx(rng, n, skew)
        if i not in out:
            out.append(i)
    return out


def build_bio_atomspace(
    n_genes: int = 1000,
    n_processes: int = 200,
    members_per_gene: int = 5,
    n_interactions: int = 2000,
    n_evaluations: int = 0,
    seed: int = 42,
    data: Optional[AtomSpaceData] = None,
    skew: float = 0.0,
):
    """Returns (data, genes, processes) with handles for query building.
    `skew` > 0 draws gene/process participation from a power-law profile
    (hub atoms with degrees orders of magnitude above the median) instead
    of uniform — the degree shape of real annotation data."""
    rng = random.Random(seed)
    if data is None:
        data = AtomSpaceData()
    for type_name in ("Gene", "BiologicalProcess", "Member", "Interacts",
                      "Predicate", "Evaluation", "List"):
        _add_type(data, type_name)
    t = data.table
    gene_ct = t.get_named_type_hash("Gene")
    proc_ct = t.get_named_type_hash("BiologicalProcess")

    genes = [_add_node(data, "Gene", f"GENE:{i:07d}") for i in range(n_genes)]
    processes = [
        _add_node(data, "BiologicalProcess", f"GO:{i:07d}")
        for i in range(n_processes)
    ]

    for gi, g in enumerate(genes):
        for p in _member_sample(rng, n_processes, members_per_gene, skew):
            _add_link(data, "Member", [g, processes[p]], [gene_ct, proc_ct])

    for _ in range(n_interactions):
        a = _skew_idx(rng, n_genes, skew)
        b = _skew_idx(rng, n_genes, skew)
        if a == b:
            continue
        # symmetric closure, as the sample KBs store unordered relations
        _add_link(data, "Interacts", [genes[a], genes[b]], [gene_ct, gene_ct])
        _add_link(data, "Interacts", [genes[b], genes[a]], [gene_ct, gene_ct])

    if n_evaluations:
        pred_ct = t.get_named_type_hash("Predicate")
        pred = _add_node(data, "Predicate", "Predicate:has_name")
        for i in range(n_evaluations):
            a = genes[_skew_idx(rng, n_genes, skew)]
            b = processes[_skew_idx(rng, n_processes, skew)]
            inner = _add_link(data, "List", [a, b], [gene_ct, proc_ct])
            _add_link(
                data,
                "Evaluation",
                [pred, inner],
                [pred_ct, [t.get_named_type_hash("List"), gene_ct, proc_ct]],
            )

    return data, genes, processes


def write_bio_canonical(
    path: str,
    n_genes: int = 1000,
    n_processes: int = 200,
    members_per_gene: int = 5,
    n_interactions: int = 2000,
    n_evaluations: int = 0,
    seed: int = 42,
    skew: float = 0.0,
) -> int:
    """Stream the SAME KB `build_bio_atomspace` constructs as a canonical
    .metta file — types, then terminals, then one toplevel expression per
    line (the converter output format, ingest/canonical.py) — WITHOUT
    building an intermediate AtomSpaceData.  The rng draw order mirrors the
    builder exactly, so loading the file reproduces the identical handle
    set (differentially asserted in tests/test_native.py).  This is the
    input generator for the end-to-end ingest benchmark at reference scale
    (bench.py flybase section, VERDICT r02 item 4).  Returns the number of
    expression lines written."""
    rng = random.Random(seed)
    lines = 0
    with open(path, "w", buffering=1 << 20) as w:
        for type_name in ("Gene", "BiologicalProcess", "Member", "Interacts",
                          "Predicate", "Evaluation", "List"):
            w.write(f"(: {type_name} Type)\n")
        for i in range(n_genes):
            w.write(f'(: "GENE:{i:07d}" Gene)\n')
        for i in range(n_processes):
            w.write(f'(: "GO:{i:07d}" BiologicalProcess)\n')
        if n_evaluations:
            # the builder interns this terminal lazily; the canonical
            # format needs every terminal before the first expression
            w.write('(: "Predicate:has_name" Predicate)\n')

        def gene(i):
            return f'"Gene GENE:{i:07d}"'

        def proc(i):
            return f'"BiologicalProcess GO:{i:07d}"'

        for gi in range(n_genes):
            for p in _member_sample(rng, n_processes, members_per_gene, skew):
                w.write(f"(Member {gene(gi)} {proc(p)})\n")
                lines += 1
        for _ in range(n_interactions):
            a = _skew_idx(rng, n_genes, skew)
            b = _skew_idx(rng, n_genes, skew)
            if a == b:
                continue
            w.write(f"(Interacts {gene(a)} {gene(b)})\n")
            w.write(f"(Interacts {gene(b)} {gene(a)})\n")
            lines += 2
        for _ in range(n_evaluations):
            a = _skew_idx(rng, n_genes, skew)
            b = _skew_idx(rng, n_processes, skew)
            w.write(
                f'(Evaluation "Predicate Predicate:has_name" '
                f"(List {gene(a)} {proc(b)}))\n"
            )
            lines += 1
    return lines


def build_bio_ontology_atomspace(
    n_genes: int = 1000,
    n_processes: int = 200,
    members_per_gene: int = 5,
    n_interactions: int = 2000,
    n_reactomes: int = 100,
    n_uniprots: int = 300,
    seed: int = 42,
):
    """Bio atomspace + the ontology/annotation layers exercised by the
    reference benchmark layouts (scripts/benchmark.py:89-128, 252-289):

    * ``Inheritance`` tree over BiologicalProcess nodes (QUERY_2's
      inherited-process disjunct);
    * ``Reactome``/``Uniprot`` nodes, ``Member`` uniprot→reactome and
      uniprot→process;
    * named-Concept pathway names (every 10th contains the 'CoA'
      substring QUERY_3 greps for) wired ``List(reactome, concept)``.

    Returns (data, genes, processes).
    """
    rng = random.Random(seed + 1)
    data, genes, processes = build_bio_atomspace(
        n_genes=n_genes,
        n_processes=n_processes,
        members_per_gene=members_per_gene,
        n_interactions=n_interactions,
        seed=seed,
    )
    for type_name in ("Reactome", "Uniprot", "Concept"):
        _add_type(data, type_name)
    t = data.table
    proc_ct = t.get_named_type_hash("BiologicalProcess")
    reac_ct = t.get_named_type_hash("Reactome")
    uni_ct = t.get_named_type_hash("Uniprot")
    con_ct = t.get_named_type_hash("Concept")

    # process ontology tree: each process inherits from one of the first
    # n/10 "root" processes
    n_roots = max(1, n_processes // 10)
    for i in range(n_roots, n_processes):
        parent = rng.randrange(n_roots)
        _add_link(
            data, "Inheritance", [processes[i], processes[parent]],
            [proc_ct, proc_ct],
        )

    reactomes = [
        _add_node(data, "Reactome", f"R-HSA-{i:06d}") for i in range(n_reactomes)
    ]
    concepts = [
        _add_node(
            data,
            "Concept",
            f"pathway {i:05d}" + (" CoA metabolism" if i % 10 == 0 else ""),
        )
        for i in range(n_reactomes)
    ]
    for r, c in zip(reactomes, concepts):
        _add_link(data, "List", [r, c], [reac_ct, con_ct])

    uniprots = [
        _add_node(data, "Uniprot", f"P{i:05d}") for i in range(n_uniprots)
    ]
    for u in uniprots:
        _add_link(
            data, "Member", [u, reactomes[rng.randrange(n_reactomes)]],
            [uni_ct, reac_ct],
        )
        _add_link(
            data, "Member", [u, processes[rng.randrange(n_processes)]],
            [uni_ct, proc_ct],
        )

    return data, genes, processes
