"""The classic OpenCog "animals" sample KB, generated programmatically.

Same facts as the reference sample knowledge base
(/root/reference/data/samples/animals.metta): 14 Concept nodes, 7 Similarity
pairs stored in both orientations (26 links total with the 12 Inheritance
edges), used across the test suite for md5 handle parity (e.g.
Concept:human = af12f10f9ae2002a1607ba0b47ba8407).
"""

from __future__ import annotations

CONCEPTS = [
    "human", "monkey", "chimp", "snake", "earthworm", "rhino", "triceratops",
    "vine", "ent", "mammal", "animal", "reptile", "dinosaur", "plant",
]

SIMILARITY_PAIRS = [
    ("human", "monkey"),
    ("human", "chimp"),
    ("chimp", "monkey"),
    ("snake", "earthworm"),
    ("rhino", "triceratops"),
    ("snake", "vine"),
    ("human", "ent"),
]

INHERITANCE_EDGES = [
    ("human", "mammal"),
    ("monkey", "mammal"),
    ("chimp", "mammal"),
    ("mammal", "animal"),
    ("reptile", "animal"),
    ("snake", "reptile"),
    ("dinosaur", "reptile"),
    ("triceratops", "dinosaur"),
    ("earthworm", "animal"),
    ("rhino", "mammal"),
    ("vine", "plant"),
    ("ent", "plant"),
]


def animals_metta() -> str:
    """Render the KB as canonical MeTTa text (typedefs first, then terminal
    typedefs, then expressions; Similarity link set closed under reversal)."""
    lines = ["(: Similarity Type)", "(: Concept Type)", "(: Inheritance Type)"]
    # terminal typedefs in a stable order matching CONCEPTS grouping
    order = [
        "human", "monkey", "chimp", "snake", "earthworm", "rhino",
        "triceratops", "vine", "ent", "mammal", "animal", "reptile",
        "dinosaur", "plant",
    ]
    for name in order:
        lines.append(f'(: "{name}" Concept)')
    for a, b in SIMILARITY_PAIRS:
        lines.append(f'(Similarity "{a}" "{b}")')
    for a, b in INHERITANCE_EDGES:
        lines.append(f'(Inheritance "{a}" "{b}")')
    for a, b in SIMILARITY_PAIRS:
        lines.append(f'(Similarity "{b}" "{a}")')
    return "\n".join(lines) + "\n"


def write_animals_metta(path: str) -> str:
    text = animals_metta()
    with open(path, "w") as fh:
        fh.write(text)
    return path
