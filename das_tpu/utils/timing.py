"""Wall-clock instrumentation.

Covers the roles of the reference's ad-hoc timing helpers (das/util.py
Clock/AccumulatorClock/Statistics, scripts/benchmark.py BenchmarkResults)
with one coherent set, plus a context manager that blocks on JAX async
dispatch so device work is actually measured.
"""

from __future__ import annotations

import statistics as _stats
import time
from contextlib import contextmanager
from typing import Dict, List, Optional


class Clock:
    def __init__(self):
        self.start()

    def start(self):
        self._t0 = time.perf_counter()

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0


class AccumulatorClock:
    def __init__(self):
        self._acc = 0.0
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        if self._t0 is not None:
            self._acc += time.perf_counter() - self._t0
            self._t0 = None

    def total(self) -> float:
        return self._acc


class Statistics:
    def __init__(self):
        self.samples: List[float] = []

    def add(self, v: float):
        self.samples.append(v)

    def mean(self) -> float:
        return _stats.fmean(self.samples) if self.samples else 0.0

    def median(self) -> float:
        return _stats.median(self.samples) if self.samples else 0.0

    def stdev(self) -> float:
        return _stats.stdev(self.samples) if len(self.samples) > 1 else 0.0

    def percentile(self, p: float) -> float:
        if not self.samples:
            return 0.0
        xs = sorted(self.samples)
        k = min(len(xs) - 1, max(0, int(round(p / 100.0 * (len(xs) - 1)))))
        return xs[k]


class BenchmarkResults:
    """Per-round wall-time aggregation (reference scripts/benchmark.py:140-191)."""

    def __init__(self, tag: str):
        self.tag = tag
        self.stats = Statistics()

    def add_round(self, seconds: float):
        self.stats.add(seconds)

    def summary(self) -> Dict[str, float]:
        return {
            "tag": self.tag,
            "rounds": len(self.stats.samples),
            "mean_s": self.stats.mean(),
            "median_s": self.stats.median(),
            "p50_ms": self.stats.percentile(50) * 1e3,
            "p99_ms": self.stats.percentile(99) * 1e3,
            "stdev_s": self.stats.stdev(),
            "total_s": sum(self.stats.samples),
        }


@contextmanager
def device_timer(stats: Optional[Statistics] = None):
    """Times a block, calling jax.block_until_ready on nothing — callers that
    produce arrays should block themselves; this is the host-side fallback."""
    t0 = time.perf_counter()
    yield
    dt = time.perf_counter() - t0
    if stats is not None:
        stats.add(dt)
