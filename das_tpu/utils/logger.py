"""Singleton file logger (role of /root/reference/das/logger.py:3-43)."""

from __future__ import annotations

import logging
import sys
from typing import Optional

_LOGGER: Optional[logging.Logger] = None


def logger(log_file: str = "/tmp/das_tpu.log", level: str = "INFO") -> logging.Logger:
    global _LOGGER
    if _LOGGER is None:
        log = logging.getLogger("das_tpu")
        log.setLevel(getattr(logging, level.upper(), logging.INFO))
        fmt = logging.Formatter("%(asctime)s %(levelname)s %(message)s")
        try:
            fh = logging.FileHandler(log_file)
            fh.setFormatter(fmt)
            log.addHandler(fh)
        except OSError:
            sh = logging.StreamHandler(sys.stderr)
            sh.setFormatter(fmt)
            log.addHandler(sh)
        log.propagate = False
        _LOGGER = log
    return _LOGGER
