"""Multi-tenant gRPC service hosting named AtomSpaces.

Role of /root/reference/service/server.py:109-257, rebuilt for the TPU
backend with three deliberate departures:

* **No global lock.**  The reference serializes every RPC behind one
  Condition (server.py:114-115); here each atom space carries its own
  lock so tenants never block each other, and read RPCs on the device
  backend are just jitted probes.
* **Error-path status.**  The reference's async KB loader has no failure
  path (server.py:92-106); loading here transitions READY→LOADING→READY
  or →FAILED(msg), observable via check_das_status.
* **No protoc codegen.**  gRPC generic handlers + the JSON codec in
  protocol.py carry the identical 10-RPC contract.

KB sources accepted by load_knowledge_base: a local path (file or
directory of .metta/.scm files), a ``file://`` URL, or a ``.tgz``/``.tar``
archive of those (unpacked with tarfile, not os.system).
"""

from __future__ import annotations

import random
import shutil
import string
import tarfile
import tempfile
import threading
import traceback
from concurrent import futures
from concurrent.futures import TimeoutError as FuturesTimeoutError
from enum import Enum
from typing import Dict, Optional

import grpc

from das_tpu.api.atomspace import DistributedAtomSpace, QueryOutputFormat
from das_tpu.core.exceptions import (
    BreakerOpenError,
    CoalescerSaturatedError,
    DasDeadlineError,
)
from das_tpu.service import protocol
from das_tpu.service.query_dsl import parse_query
from das_tpu.utils.logger import logger

#: the final backstop on any coalesced future wait when deadlines are
#: OFF: the worker normally resolves every future (expiry included),
#: so this only fires if the serving loop itself wedged — but "an RPC
#: thread never blocks forever" must hold unconditionally (ISSUE 13)
_RPC_WAIT_BACKSTOP_S = 600.0


class AtomSpaceStatus(str, Enum):
    READY = "Ready"
    LOADING = "Loading knowledge base"
    FAILED = "Load failed"


_OUTPUT_FORMATS = {
    "HANDLE": QueryOutputFormat.HANDLE,
    "DICT": QueryOutputFormat.ATOM_INFO,
    "JSON": QueryOutputFormat.JSON,
}


def _random_token(length: int = 20) -> str:
    return "".join(random.choice(string.ascii_lowercase) for _ in range(length))


class _Tenant:
    def __init__(self, name: str, das: DistributedAtomSpace):
        self.name = name
        self.das = das
        self.status = AtomSpaceStatus.READY
        self.status_detail = ""
        self.lock = threading.RLock()
        #: per-TENANT query coalescer (service/coalesce.py), created on
        #: first use: tenants never serialize behind each other's batches
        #: (the service's no-global-lock design holds under coalescing)
        self.coalescer = None
        self._coalescer_lock = threading.Lock()

    def get_coalescer(self):
        if self.coalescer is None:
            with self._coalescer_lock:
                if self.coalescer is None:
                    from das_tpu.service.coalesce import QueryCoalescer

                    # ceiling and pipeline depth come from the tenant's
                    # DasConfig (DAS_TPU_COALESCE_MAX_BATCH /
                    # DAS_TPU_PIPELINE_DEPTH via from_env), not hardcoded
                    # constants: the served path's throughput knobs must
                    # be deployment-tunable
                    cfg = getattr(self.das, "config", None)
                    self.coalescer = QueryCoalescer(
                        max_batch=getattr(cfg, "coalesce_max_batch", None),
                        pipeline_depth=getattr(cfg, "pipeline_depth", None),
                        pipeline_depth_max=getattr(
                            cfg, "pipeline_depth_max", None
                        ),
                        queue_max=getattr(cfg, "coalesce_queue_max", None),
                        deadline_ms=getattr(cfg, "query_deadline_ms", None),
                        breaker_threshold=getattr(
                            cfg, "breaker_failure_threshold", None
                        ),
                        breaker_cooldown_ms=getattr(
                            cfg, "breaker_cooldown_ms", None
                        ),
                    )
        return self.coalescer


class _KnowledgeBaseLoader(threading.Thread):
    """Async KB fetch+load with an explicit failure transition."""

    def __init__(self, tenant: _Tenant, url: str):
        super().__init__(daemon=True)
        self.tenant = tenant
        self.url = url

    def run(self):
        temp_dir = tempfile.mkdtemp()
        try:
            path = self.url
            if path.startswith("file://"):
                path = path[len("file://"):]
            if path.endswith((".tgz", ".tar.gz", ".tar")):
                with tarfile.open(path) as tar:
                    tar.extractall(temp_dir, filter="data")
                source = temp_dir
            else:
                source = path
            with self.tenant.lock:
                self.tenant.das.load_knowledge_base(source)
                self.tenant.status = AtomSpaceStatus.READY
                self.tenant.status_detail = ""
        except Exception as exc:  # noqa: BLE001 — surfaced via status RPC
            logger().info(f"KB load failed for '{self.tenant.name}': {exc}")
            self.tenant.status = AtomSpaceStatus.FAILED
            self.tenant.status_detail = str(exc)
        finally:
            shutil.rmtree(temp_dir, ignore_errors=True)


class DasService:
    """RPC method implementations (request dict -> Status dict)."""

    def __init__(self, backend: Optional[str] = None):
        import os

        self.backend = backend
        self.tenants: Dict[str, _Tenant] = {}
        self.registry_lock = threading.Lock()
        # serving-edge query coalescing: concurrent singles batch into one
        # device program + one fetch, PER TENANT (service/coalesce.py);
        # DAS_TPU_COALESCE=0 restores the direct per-RPC path
        self.coalesce_enabled = os.environ.get("DAS_TPU_COALESCE", "1") != "0"

    def coalescer_stats(self) -> Dict[str, int]:
        """Aggregate serving-path observability (bench/tests): per-tenant
        coalescer counters, the execution pipeline's in-flight high-water
        mark, the result caches' hit/miss/invalidation counters (the
        conjunctive, tree-composite and count-batch caches all fold in),
        and the process-wide route counters — incl. the sharded mesh
        routes (`sharded`/`sharded_kernel`) now that mesh tenants ride the
        same pipeline.  `tenants` breaks the aggregates down per tenant
        name so a noisy mesh tenant is distinguishable from a quiet
        single-device one."""
        out = {
            "batches": 0, "items": 0, "max_batch": 0, "max_batch_limit": 0,
            "pipeline_depth": 0, "pipeline_depth_max": 0,
            "effective_depth": 0, "rtt_ewma_ms": 0.0,
            "dispatch_ewma_ms": 0.0, "inflight_peak": 0,
            "speculative_dispatches": 0, "early_settles": 0,
            "queue_rejections": 0,
            "deadline_expired": 0, "breaker_rejections": 0,
            "breaker_trips": 0, "breaker_recoveries": 0,
            "breaker_open_tenants": 0,
            "cache_hits": 0, "cache_misses": 0, "cache_invalidations": 0,
            "tenants": {},
        }
        for tenant in list(self.tenants.values()):
            per = {
                "backend": getattr(
                    getattr(tenant.das, "config", None), "backend", None
                ),
                "inflight_peak": 0,
            }
            c = tenant.coalescer
            if c is not None:
                snap = c.snapshot()
                out["batches"] += snap["batches"]
                out["items"] += snap["items"]
                out["max_batch"] = max(out["max_batch"], snap["max_batch"])
                out["max_batch_limit"] = max(
                    out["max_batch_limit"], snap["max_batch_limit"]
                )
                out["pipeline_depth"] = max(
                    out["pipeline_depth"], snap["pipeline_depth"]
                )
                out["pipeline_depth_max"] = max(
                    out["pipeline_depth_max"], snap["pipeline_depth_max"]
                )
                # the deepest adaptive window any tenant reached, with
                # BOTH inputs of THAT tenant's ceil(rtt/dispatch) sizing
                # — taking independent maxima across tenants would pair
                # one tenant's wire with another's dispatch cost, a
                # ratio no window actually uses; per-tenant dicts below
                # are the authoritative breakdown.  Without the dispatch
                # EWMA an operator cannot tell "wire is fast" from
                # "dispatch cost inflated" when the window sticks at
                # the floor (§10)
                if snap["effective_depth"] >= out["effective_depth"]:
                    out["effective_depth"] = snap["effective_depth"]
                    out["rtt_ewma_ms"] = snap["rtt_ewma_ms"]
                    out["dispatch_ewma_ms"] = snap["dispatch_ewma_ms"]
                out["inflight_peak"] = max(
                    out["inflight_peak"], snap["inflight_peak"]
                )
                out["speculative_dispatches"] += snap["speculative_dispatches"]
                out["early_settles"] += snap["early_settles"]
                out["queue_rejections"] += snap["queue_rejections"]
                # robustness aggregates (ISSUE 13): deadline misses,
                # degraded-mode rejections and the breaker lifecycle —
                # per-tenant state below tells WHICH tenant is degraded
                out["deadline_expired"] += snap["deadline_expired"]
                out["breaker_rejections"] += snap["breaker_rejections"]
                out["breaker_trips"] += snap["breaker_trips"]
                out["breaker_recoveries"] += snap["breaker_recoveries"]
                if snap["breaker_state"] != "closed":
                    out["breaker_open_tenants"] += 1
                per.update(
                    batches=snap["batches"],
                    items=snap["items"],
                    max_batch=snap["max_batch"],
                    inflight_peak=snap["inflight_peak"],
                    effective_depth=snap["effective_depth"],
                    rtt_ewma_ms=snap["rtt_ewma_ms"],
                    dispatch_ewma_ms=snap["dispatch_ewma_ms"],
                    speculative_dispatches=snap["speculative_dispatches"],
                    early_settles=snap["early_settles"],
                    queue_rejections=snap["queue_rejections"],
                    deadline_expired=snap["deadline_expired"],
                    breaker_state=snap["breaker_state"],
                    breaker_rejections=snap["breaker_rejections"],
                    breaker_trips=snap["breaker_trips"],
                    breaker_recoveries=snap["breaker_recoveries"],
                    # last-K (rtt_ewma, dispatch_ewma, effective_depth)
                    # samples (ISSUE 12 satellite) — the §10
                    # window-formula history, per tenant
                    window_history=snap["window_history"],
                )
            db = getattr(tenant.das, "db", None)
            if db is not None:
                from das_tpu.query.fused import result_cache_stats

                cache = result_cache_stats(db)
                out["cache_hits"] += cache["hits"]
                out["cache_misses"] += cache["misses"]
                out["cache_invalidations"] += cache["invalidations"]
                per["cache_hits"] = cache["hits"]
                per["cache_misses"] = cache["misses"]
            out["tenants"][tenant.name] = per
        from das_tpu.query.compiler import ROUTE_COUNTS

        out["routes"] = dict(ROUTE_COUNTS)
        # cost-based planner telemetry (das_tpu/planner, ISSUE 8):
        # planned-vs-greedy traffic, retry rounds planned programs still
        # paid, and the summed estimated-vs-actual join rows whose ratio
        # is the production estimator-error signal
        from das_tpu import planner

        out["planner"] = planner.snapshot()
        # program ledger (das_tpu/obs/proflog.py, ISSUE 14): XLA
        # compiles observed, total/cold-start compile seconds, the
        # ledger hit rate, and the per-site byte-model calibration
        # aggregate — the device-side compile story next to the host
        # serving counters above
        from das_tpu.obs import proflog

        out["programs"] = proflog.snapshot()
        # dasdur durability (ISSUE 15, storage/durable.py): active
        # snapshot generation, WAL records appended/replayed, torn-tail
        # truncations and the last restore's wall seconds — the
        # replica-fleet cold-start story next to the serving counters
        from das_tpu.storage import durable

        out["durability"] = durable.snapshot_stats()
        return out

    def metrics_text(self) -> str:
        """Prometheus text exposition of the obs metric layer (ISSUE 12)
        plus the serving-path aggregate gauges out of coalescer_stats() —
        ONE scrape surface for counters, latency histograms
        (p50/p95/p99 via histogram_quantile) and the adaptive-window
        state.  Served over HTTP when env DAS_TPU_METRICS_PORT is set
        (serve() starts the exposition thread); also callable in-process
        by tests/benches."""
        from das_tpu import obs

        stats = self.coalescer_stats()
        gauges = {
            f"serving.{k}": float(stats[k])
            for k in (
                "batches", "items", "inflight_peak", "effective_depth",
                "rtt_ewma_ms", "dispatch_ewma_ms",
                "speculative_dispatches", "early_settles",
                "queue_rejections", "deadline_expired",
                "breaker_rejections", "breaker_trips",
                "breaker_recoveries", "breaker_open_tenants",
                "cache_hits", "cache_misses",
                "cache_invalidations",
            )
        }
        # program-ledger gauges (ISSUE 14) — the prof.compile_ms
        # histogram rides the declared HISTOGRAMS surface automatically;
        # these are the scalar compile/cold-start/hit-rate aggregates
        progs = stats.get("programs") or {}
        for k in ("compiles", "compile_s", "cold_start_s",
                  "persistent_cache_hits", "ledger_hits"):
            gauges[f"programs.{k}"] = float(progs.get(k) or 0)
        if progs.get("hit_rate") is not None:
            gauges["programs.hit_rate"] = float(progs["hit_rate"])
        # durability gauges (ISSUE 15): generation / wal_records /
        # recovery_replayed / last restore seconds
        dur = stats.get("durability") or {}
        for k in ("generation", "snapshots", "wal_records",
                  "recovery_replayed", "torn_tail_truncations",
                  "corrupt_generations"):
            gauges[f"durability.{k}"] = float(dur.get(k) or 0)
        if dur.get("last_restore_s") is not None:
            gauges["durability.last_restore_s"] = float(
                dur["last_restore_s"]
            )
        return obs.prometheus_text(extra_gauges=gauges)

    # -- helpers -----------------------------------------------------------

    def _new_tenant(self, name: str):
        with self.registry_lock:
            if any(t.name == name for t in self.tenants.values()):
                return None, protocol.status(False, f"DAS named '{name}' already exists")
            token = self._fresh_token()
            kwargs = {"database_name": name}
            if self.backend:
                kwargs["backend"] = self.backend
            self.tenants[token] = _Tenant(name, DistributedAtomSpace(**kwargs))
            return token, None

    def _tenant_ready(self, key: str):
        tenant = self.tenants.get(key)
        if tenant is None:
            return None, protocol.status(False, "Invalid DAS key")
        if tenant.status == AtomSpaceStatus.LOADING:
            return None, protocol.status(False, f"DAS {key} is busy")
        return tenant, None

    @staticmethod
    def _map_failure(exc: Exception):
        """Typed retryable statuses (ISSUE 13): saturation, deadline
        expiry, and breaker rejections each map to a DISTINCT
        machine-parsable status with a retry-after hint
        (protocol.retryable_status) — clients back off and retry
        instead of treating a transient rejection as a hard failure.
        Everything else keeps the generic traceback status."""
        if isinstance(exc, CoalescerSaturatedError):
            return protocol.retryable_status("saturated", 50, str(exc))
        if isinstance(exc, DasDeadlineError):
            # the hint says when capacity may RETURN, which the expired
            # deadline's duration says nothing about — a momentary
            # backlog clears in milliseconds; use the same short beat
            # as saturation rather than parking clients for a full
            # deadline
            return protocol.retryable_status("deadline", 50, str(exc))
        if isinstance(exc, BreakerOpenError):
            hint = getattr(exc, "retry_after_ms", None)
            return protocol.retryable_status(
                "breaker_open", 250 if hint is None else hint, str(exc)
            )
        lines = traceback.format_exc().splitlines()
        return protocol.status(False, f"{exc} {lines}")

    def _call(self, key: str, method: str, args: list):
        tenant, err = self._tenant_ready(key)
        if err:
            return err
        try:
            with tenant.lock:
                answer = getattr(tenant.das, method)(*args)
        except Exception as exc:  # noqa: BLE001 — RPC surface, never raise
            return self._map_failure(exc)
        return protocol.status(True, answer)

    @staticmethod
    def _format(request) -> QueryOutputFormat:
        return _OUTPUT_FORMATS.get(
            request.get("output_format", "HANDLE"), QueryOutputFormat.HANDLE
        )

    # -- the 10 RPCs -------------------------------------------------------

    def create(self, request):
        token, err = self._new_tenant(request.get("name", ""))
        return err if err else protocol.status(True, token)

    def reconnect(self, request):
        # same semantics as create for a stateless-storage deployment: a
        # fresh token bound to the named space (reference server.py:152-164)
        token, err = self._new_tenant(request.get("name", ""))
        return err if err else protocol.status(True, token)

    def load_knowledge_base(self, request):
        key = request.get("key", "")
        # atomic check-then-set: two concurrent loads on one key must not
        # both pass the LOADING guard
        with self.registry_lock:
            tenant, err = self._tenant_ready(key)
            if err:
                return err
            tenant.status = AtomSpaceStatus.LOADING
        _KnowledgeBaseLoader(tenant, request.get("url", "")).start()
        return protocol.status(True, AtomSpaceStatus.LOADING.value)

    def check_das_status(self, request):
        tenant = self.tenants.get(request.get("key", ""))
        if tenant is None:
            return protocol.status(False, "Invalid DAS key")
        msg = tenant.status.value
        if tenant.status_detail:
            msg = f"{msg}: {tenant.status_detail}"
        return protocol.status(True, msg)

    def clear(self, request):
        return self._call(request.get("key", ""), "clear_database", [])

    def count(self, request):
        return self._call(request.get("key", ""), "count_atoms", [])

    def get_atom(self, request):
        return self._call(
            request.get("key", ""),
            "get_atom",
            [request.get("handle", ""), self._format(request)],
        )

    def search_nodes(self, request):
        return self._call(
            request.get("key", ""),
            "get_nodes",
            [
                request.get("node_type") or None,
                request.get("node_name") or None,
                self._format(request),
            ],
        )

    def search_links(self, request):
        return self._call(
            request.get("key", ""),
            "get_links",
            [
                request.get("link_type") or None,
                request.get("target_types") or None,
                request.get("targets") or None,
                self._format(request),
            ],
        )

    def query(self, request):
        query = parse_query(request.get("query", ""))
        if query is None:
            return protocol.status(False, "Invalid query")
        if self.coalesce_enabled:
            tenant, err = self._tenant_ready(request.get("key", ""))
            if err:
                return err
            coalescer = tenant.get_coalescer()
            future = coalescer.submit(tenant, query, self._format(request))
            # BOUNDED wait (ISSUE 13): the worker resolves every future
            # (deadline expiry included), so the timeout is a backstop —
            # with a deadline configured it tracks it with slack, and
            # even with deadlines off no RPC thread blocks forever
            deadline_ms = coalescer.deadline_ms
            timeout = (
                deadline_ms / 1e3 * 2 + 30.0
                if deadline_ms > 0 else _RPC_WAIT_BACKSTOP_S
            )
            try:
                return protocol.status(True, future.result(timeout=timeout))
            except FuturesTimeoutError:
                future.cancel()
                return self._map_failure(
                    DasDeadlineError(
                        "coalesced query timed out at the RPC wait "
                        "backstop", deadline_ms=deadline_ms,
                    )
                )
            except Exception as exc:  # noqa: BLE001 — RPC surface
                return self._map_failure(exc)
        return self._call(
            request.get("key", ""), "query", [query, self._format(request)]
        )

    # -- test/bench plumbing ----------------------------------------------

    def attach_tenant(self, name: str, das) -> str:
        """Register an already-constructed DistributedAtomSpace as a tenant
        (tests and benches attach a pre-built store instead of re-loading
        through the create+load RPCs).  Same registry rules as create."""
        with self.registry_lock:
            if any(t.name == name for t in self.tenants.values()):
                raise ValueError(f"DAS named '{name}' already exists")
            token = self._fresh_token()
            self.tenants[token] = _Tenant(name, das)
            return token

    def _fresh_token(self) -> str:
        """Caller holds registry_lock."""
        while True:
            token = _random_token()
            if token not in self.tenants:
                return token


def _message_to_dict(msg) -> dict:
    """Protobuf request message -> the plain request dict the RPC
    implementations consume (repeated fields become lists)."""
    out = {}
    for f in msg.DESCRIPTOR.fields:
        value = getattr(msg, f.name)
        # feature-detect: modern protobuf deprecates .label in favor of
        # .is_repeated; older runtimes have only .label
        repeated = (
            f.is_repeated
            if hasattr(f, "is_repeated")
            else f.label == f.LABEL_REPEATED
        )
        out[f.name] = list(value) if repeated else value
    return out


def _make_servicer(service: DasService):
    """Protobuf wire contract — byte-compatible with the reference's
    generated service (service_spec/das.proto:49-60), so an unmodified
    reference service/client.py can drive this server.  One
    ServiceDefinitionServicer subclass whose methods adapt protobuf
    messages to the dict-based RPC implementations."""
    from das_tpu.service.service_spec import das_pb2, das_pb2_grpc

    def adapt(method):
        def call(request, context):
            d = method(_message_to_dict(request))
            return das_pb2.Status(success=d["success"], msg=d["msg"])

        return staticmethod(call)

    methods = {
        rpc: adapt(getattr(service, rpc))
        for rpc in das_pb2_grpc.RPC_REQUEST_TYPES
    }
    servicer_cls = type(
        "DasServicer", (das_pb2_grpc.ServiceDefinitionServicer,), methods
    )
    return servicer_cls()


def start_metrics_http(service: DasService, port: int):
    """Prometheus text-exposition endpoint (`GET /metrics`) on a daemon
    thread — stdlib http.server, no new dependency.  Returns the bound
    HTTPServer (`.server_port` for port-0 tests)."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            if self.path.rstrip("/") not in ("", "/metrics"):
                self.send_error(404)
                return
            body = service.metrics_text().encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrapes must not spam stderr
            pass

    httpd = HTTPServer(("0.0.0.0", port), _Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    logger().info(f"metrics exposition on port {httpd.server_port}")
    return httpd


def serve(
    port: int = protocol.DEFAULT_PORT,
    backend: Optional[str] = None,
    max_workers: int = 10,
    block: bool = True,
):
    """Start the service; returns (grpc_server, DasService)."""
    import os

    service = DasService(backend=backend)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    from das_tpu.service.service_spec import das_pb2_grpc

    das_pb2_grpc.add_ServiceDefinitionServicer_to_server(
        _make_servicer(service), server
    )
    bound = server.add_insecure_port(f"[::]:{port}")
    server.bound_port = bound  # ephemeral-port tests read this back
    # Prometheus exposition (ISSUE 12): env DAS_TPU_METRICS_PORT opens
    # GET /metrics with the obs metric layer + serving gauges; unset/0
    # keeps the old surface exactly
    from das_tpu import obs

    metrics_port = os.environ.get("DAS_TPU_METRICS_PORT")
    if metrics_port and int(metrics_port) > 0:
        # asking for exposition IS asking for the metric layer: every
        # .inc()/.observe() site is behind obs.enabled(), so a scrape
        # endpoint over a disabled recorder would serve permanently-zero
        # counters — the silent-dashboard failure DL014 exists to
        # prevent.  DAS_TPU_TRACE=0 alongside the port still wins
        # (explicit off beats implied on).
        if not obs.enabled() and os.environ.get("DAS_TPU_TRACE") is None:
            obs.configure(enabled=True)
        server.metrics_http = start_metrics_http(service, int(metrics_port))
    # jax.profiler device trace (obs/jaxprof.py): starts only when a
    # DasConfig.profiler_trace_dir (env DAS_TPU_TRACE_DIR) is configured
    from das_tpu.core.config import DasConfig

    obs.maybe_start_trace(DasConfig.from_env())
    server.start()
    logger().info(f"DAS service listening on port {bound}")
    if block:
        server.wait_for_termination()
    return server, service


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description="DAS TPU gRPC service")
    ap.add_argument("--port", type=int, default=protocol.DEFAULT_PORT)
    ap.add_argument("--backend", default=None, help="memory | tensor | sharded")
    args = ap.parse_args()
    serve(port=args.port, backend=args.backend)
