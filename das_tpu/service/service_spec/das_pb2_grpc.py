"""gRPC stubs for das.proto, hand-written against the stable grpc API.

The reference generates this file with `grpc_tools.protoc`
(/root/reference/service/build-proto.sh:3); grpc_tools is not available in
this image, so the stub/servicer classes are written out by hand — the
wire behavior is identical (method paths `/das.ServiceDefinition/<rpc>`,
protobuf request messages, `Status` responses), which is what lets an
unmodified reference service/client.py (:29-163) talk to the das_tpu
server.  Regenerate das_pb2.py itself with ops/build-proto.sh.
"""

import grpc

try:
    from . import das_pb2
except ImportError:  # imported as a top-level module (reference client.py
    import das_pb2   # appends service_spec/ to sys.path and imports bare)

_SERVICE = "das.ServiceDefinition"

# rpc name -> request message class (das.proto:49-60)
RPC_REQUEST_TYPES = {
    "create": das_pb2.BindingRequest,
    "reconnect": das_pb2.BindingRequest,
    "load_knowledge_base": das_pb2.LoadRequest,
    "check_das_status": das_pb2.DASKey,
    "clear": das_pb2.DASKey,
    "count": das_pb2.DASKey,
    "get_atom": das_pb2.AtomRequest,
    "search_nodes": das_pb2.NodeRequest,
    "search_links": das_pb2.LinkRequest,
    "query": das_pb2.Query,
}


class ServiceDefinitionStub:
    def __init__(self, channel):
        for rpc, request_type in RPC_REQUEST_TYPES.items():
            setattr(
                self,
                rpc,
                channel.unary_unary(
                    f"/{_SERVICE}/{rpc}",
                    request_serializer=request_type.SerializeToString,
                    response_deserializer=das_pb2.Status.FromString,
                ),
            )


class ServiceDefinitionServicer:
    """Default method bodies answer UNIMPLEMENTED (codegen parity)."""


def _unimplemented(request, context):
    context.set_code(grpc.StatusCode.UNIMPLEMENTED)
    context.set_details("Method not implemented!")
    raise NotImplementedError("Method not implemented!")


for _rpc in RPC_REQUEST_TYPES:
    setattr(ServiceDefinitionServicer, _rpc, staticmethod(_unimplemented))


def add_ServiceDefinitionServicer_to_server(servicer, server):
    handlers = {
        rpc: grpc.unary_unary_rpc_method_handler(
            getattr(servicer, rpc),
            request_deserializer=request_type.FromString,
            response_serializer=das_pb2.Status.SerializeToString,
        )
        for rpc, request_type in RPC_REQUEST_TYPES.items()
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_SERVICE, handlers),)
    )
