"""Postfix query DSL used at the service edge.

Same comma-separated language the reference server accepts
(/root/reference/service/server.py:34-81): a prefix of ``Node`` bindings,
then ``Link`` terms pushing onto a stack, then postfix ``AND`` / ``OR``
(fold the whole stack) and ``NOT`` (pop one):

    Node n1 Concept human, Link Inheritance n1 $1, Link Similarity $1 $2, AND

Variables start with ``$``.  Unordered link types (Similarity, Set) get
``ordered=False`` automatically.  Returns None for malformed input — the
server maps that to an error Status, never an exception.
"""

from __future__ import annotations

from typing import Optional

from das_tpu.core.schema import UNORDERED_LINK_TYPES
from das_tpu.query.ast import And, Link, LogicalExpression, Node, Not, Or, Variable


def parse_query(query_str: str) -> Optional[LogicalExpression]:
    nodes = {}
    stack = []
    reading_nodes = True
    for chunk in query_str.split(","):
        words = chunk.strip().split()
        if not words:
            return None
        head = words[0]
        if reading_nodes:
            if head == "Node":
                if len(words) != 4:
                    return None
                nodes[words[1]] = Node(words[2], words[3])
                continue
            reading_nodes = False
        if head == "Link":
            if len(words) < 3:
                return None
            link_type = words[1]
            targets = []
            for word in words[2:]:
                if word.startswith("$"):
                    targets.append(Variable(word))
                elif word in nodes:
                    targets.append(nodes[word])
                else:
                    return None
            stack.append(Link(link_type, targets, link_type not in UNORDERED_LINK_TYPES))
        elif head == "AND":
            if not stack:
                return None
            stack = [And(stack)]
        elif head == "OR":
            if not stack:
                return None
            stack = [Or(stack)]
        elif head == "NOT":
            if not stack:
                return None
            stack.append(Not(stack.pop()))
        else:
            return None
    if len(stack) != 1:
        return None
    return stack[0]
