"""Wire protocol for the DAS service edge.

Same 10-RPC contract AND wire format as the reference's proto
(/root/reference/service/service_spec/das.proto:1-60) — create,
reconnect, load_knowledge_base, check_das_status, clear, count, get_atom,
search_nodes, search_links, query — every RPC returning
``Status{success, msg}``.  The protobuf messages live in
service_spec/das_pb2.py (protoc-generated from the carried das.proto;
regenerate with ops/build-proto.sh) with hand-written stubs in
service_spec/das_pb2_grpc.py, so an *unmodified* reference
service/client.py interoperates with the das_tpu server byte-for-byte.
Inside the server, requests are plain dicts (converted at the handler
boundary) and responses are `status()` dicts.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional

SERVICE_NAME = "das.ServiceDefinition"
DEFAULT_PORT = 7025

# The authoritative request/response schema is service_spec/das.proto;
# the rpc -> message-type map is service_spec/das_pb2_grpc.RPC_REQUEST_TYPES.


def status(success: bool, msg: Any) -> Dict[str, Any]:
    """The universal response message (proto `Status`, das.proto:44-47)."""
    return {"success": bool(success), "msg": str(msg)}


#: typed RETRYABLE failure statuses (ISSUE 13): the server maps
#: saturation / deadline / breaker rejections onto these kinds instead
#: of a generic failure string, carried INSIDE Status.msg so the
#: 10-RPC wire contract stays byte-compatible.  Clients
#: (service/client.py) parse the prefix and honor the retry-after hint
#: with ONE bounded backoff.
RETRYABLE_PREFIX = "DAS-RETRY"
RETRY_KINDS = ("saturated", "deadline", "breaker_open")

_RETRY_RE = re.compile(
    rf"^{RETRYABLE_PREFIX} kind=(?P<kind>[a-z_]+) "
    r"retry_after_ms=(?P<retry_after_ms>\d+)(?: (?P<detail>.*))?$",
    re.DOTALL,
)


def retryable_status(kind: str, retry_after_ms: float,
                     detail: str = "") -> Dict[str, Any]:
    """A failed Status whose msg is a machine-parsable retryable marker:
    `DAS-RETRY kind=<kind> retry_after_ms=<int> <detail>`."""
    if kind not in RETRY_KINDS:
        raise ValueError(f"unknown retryable status kind {kind!r}")
    msg = (
        f"{RETRYABLE_PREFIX} kind={kind} "
        f"retry_after_ms={max(0, int(retry_after_ms))}"
    )
    if detail:
        msg = f"{msg} {detail}"
    return {"success": False, "msg": msg}


def parse_retryable(msg: str) -> Optional[Dict[str, Any]]:
    """{kind, retry_after_ms, detail} when `msg` is a retryable status
    marker, else None — the client-side half of the contract."""
    m = _RETRY_RE.match(msg or "")
    if m is None:
        return None
    return {
        "kind": m.group("kind"),
        "retry_after_ms": int(m.group("retry_after_ms")),
        "detail": m.group("detail") or "",
    }


def method_path(rpc: str) -> str:
    return f"/{SERVICE_NAME}/{rpc}"
