"""Wire protocol for the DAS service edge.

Same 10-RPC contract AND wire format as the reference's proto
(/root/reference/service/service_spec/das.proto:1-60) — create,
reconnect, load_knowledge_base, check_das_status, clear, count, get_atom,
search_nodes, search_links, query — every RPC returning
``Status{success, msg}``.  The protobuf messages live in
service_spec/das_pb2.py (protoc-generated from the carried das.proto;
regenerate with ops/build-proto.sh) with hand-written stubs in
service_spec/das_pb2_grpc.py, so an *unmodified* reference
service/client.py interoperates with the das_tpu server byte-for-byte.
Inside the server, requests are plain dicts (converted at the handler
boundary) and responses are `status()` dicts.
"""

from __future__ import annotations

from typing import Any, Dict

SERVICE_NAME = "das.ServiceDefinition"
DEFAULT_PORT = 7025

# The authoritative request/response schema is service_spec/das.proto;
# the rpc -> message-type map is service_spec/das_pb2_grpc.RPC_REQUEST_TYPES.


def status(success: bool, msg: Any) -> Dict[str, Any]:
    """The universal response message (proto `Status`, das.proto:44-47)."""
    return {"success": bool(success), "msg": str(msg)}


def method_path(rpc: str) -> str:
    return f"/{SERVICE_NAME}/{rpc}"
