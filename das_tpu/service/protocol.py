"""Wire protocol for the DAS service edge.

Same 10-RPC contract as the reference's proto
(/root/reference/service/service_spec/das.proto:49-60) — create,
reconnect, load_knowledge_base, check_das_status, clear, count, get_atom,
search_nodes, search_links, query — every RPC returning
``Status{success, msg}``.  The reference ships protobuf messages whose
payloads are stringly typed anyway; here messages are plain dicts with a
JSON codec plugged into gRPC generic handlers, so the service needs no
protoc codegen while keeping the identical method surface and semantics.
"""

from __future__ import annotations

import json
from typing import Any, Dict

SERVICE_NAME = "das.ServiceDefinition"
DEFAULT_PORT = 7025

# RPC name -> request field names (documentation of the contract;
# requests are dicts, unknown fields are ignored, missing default to "").
RPC_REQUEST_FIELDS: Dict[str, tuple] = {
    "create": ("name",),
    "reconnect": ("name",),
    "load_knowledge_base": ("key", "url"),
    "check_das_status": ("key",),
    "clear": ("key",),
    "count": ("key",),
    "get_atom": ("key", "handle", "output_format"),
    "search_nodes": ("key", "node_type", "node_name", "output_format"),
    "search_links": ("key", "link_type", "target_types", "targets", "output_format"),
    "query": ("key", "query", "output_format"),
}


def serialize(message: Dict[str, Any]) -> bytes:
    return json.dumps(message, sort_keys=True).encode("utf-8")


def deserialize(payload: bytes) -> Dict[str, Any]:
    if not payload:
        return {}
    return json.loads(payload.decode("utf-8"))


def status(success: bool, msg: Any) -> Dict[str, Any]:
    """The universal response message (proto `Status`, das.proto:44-47)."""
    return {"success": bool(success), "msg": str(msg)}


def method_path(rpc: str) -> str:
    return f"/{SERVICE_NAME}/{rpc}"
