"""Seed a checkpoint volume with the animals KB (idempotent).

The compose stack's one-shot `seed` service (ops/compose.yml) and the
process-mode `ops/stack-up.sh` both run this before starting the service:
the service's DAS_TPU_CHECKPOINT env then auto-attaches the store to
every created AtomSpace, so a fresh deployment answers count == (14, 26)
with zero load RPCs — the analogue of the reference stack's pre-loaded
database volumes.

Since ISSUE 15 the seed rides the dasdur GENERATIONAL layout
(storage/durable.py): the volume holds `gen-000001/` with a CRC-digest
manifest, so the service's auto-load goes through the verified restore
path — and a service pointed at the same dir via DAS_TPU_SNAPSHOT_DIR
gets the write-ahead delta log on top.  A pre-existing seed (either
layout — `checkpoint.load` reads both) is left untouched."""

from __future__ import annotations

import os
import sys


def seed(path: str) -> None:
    from das_tpu.core.config import DasConfig
    from das_tpu.models.animals import animals_metta
    from das_tpu.storage import checkpoint, durable
    from das_tpu.storage.atom_table import load_metta_text
    from das_tpu.storage.tensor_db import TensorDB

    if os.path.exists(os.path.join(path, checkpoint.RECORDS_FILE)):
        print(f"checkpoint already present at {path} (flat layout)")
        return
    if durable.list_generations(path):
        print(f"checkpoint already present at {path} (generational)")
        return
    data = load_metta_text(animals_metta())
    db = TensorDB(data, DasConfig())
    gen_dir = durable.write_snapshot(db, path)
    nodes, links = data.count_atoms()
    print(f"seeded {gen_dir}: {nodes} nodes / {links} links")


if __name__ == "__main__":
    seed(sys.argv[1] if len(sys.argv) > 1 else "/checkpoint/kb")
