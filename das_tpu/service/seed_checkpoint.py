"""Seed a checkpoint volume with the animals KB (idempotent).

The compose stack's one-shot `seed` service (ops/compose.yml) and the
process-mode `ops/stack-up.sh` both run this before starting the service:
the service's DAS_TPU_CHECKPOINT env then auto-attaches the store to
every created AtomSpace, so a fresh deployment answers count == (14, 26)
with zero load RPCs — the analogue of the reference stack's pre-loaded
database volumes."""

from __future__ import annotations

import os
import sys


def seed(path: str) -> None:
    from das_tpu.models.animals import animals_metta
    from das_tpu.storage import checkpoint
    from das_tpu.storage.atom_table import load_metta_text

    if os.path.exists(os.path.join(path, checkpoint.RECORDS_FILE)):
        print(f"checkpoint already present at {path}")
        return
    data = load_metta_text(animals_metta())
    checkpoint.save(data, path)
    nodes, links = data.count_atoms()
    print(f"seeded {path}: {nodes} nodes / {links} links")


if __name__ == "__main__":
    seed(sys.argv[1] if len(sys.argv) > 1 else "/checkpoint/kb")
