"""Client for the DAS service (library class + CLI).

Mirrors /root/reference/service/client.py:13-163: one subcommand per RPC,
``--output-format {HANDLE,DICT,JSON}`` where applicable, printing the
Status message.  The library class is the programmatic surface the
reference lacks (its client is CLI-only).
"""

from __future__ import annotations

import argparse
import time
from typing import Dict, List, Optional

import grpc

from das_tpu.service import protocol


class DasClient:
    #: longest single client-side backoff honored from a server
    #: retry-after hint (ms) — a misbehaving hint must not park the
    #: client
    MAX_RETRY_WAIT_MS = 2000

    def __init__(self, host: str = "localhost", port: int = protocol.DEFAULT_PORT):
        from das_tpu.service.service_spec import das_pb2_grpc

        self.channel = grpc.insecure_channel(f"{host}:{port}")
        self._request_types = das_pb2_grpc.RPC_REQUEST_TYPES
        self._stub = das_pb2_grpc.ServiceDefinitionStub(self.channel)

    def call(self, rpc: str, **request) -> Dict:
        # protobuf scalar fields reject None; drop unset optionals
        clean = {k: v for k, v in request.items() if v is not None}
        status = getattr(self._stub, rpc)(self._request_types[rpc](**clean))
        return {"success": status.success, "msg": status.msg}

    def call_with_retry(self, rpc: str, **request) -> Dict:
        """`call`, honoring the server's typed RETRYABLE statuses
        (ISSUE 13): on a `DAS-RETRY kind=... retry_after_ms=N` failure —
        coalescer saturation, deadline expiry, an open circuit breaker —
        sleep min(N, MAX_RETRY_WAIT_MS) ONCE and retry once.  Exactly
        one bounded backoff: the hint says when capacity should return;
        anything beyond one beat is the caller's policy."""
        result = self.call(rpc, **request)
        if result["success"]:
            return result
        hint = protocol.parse_retryable(result["msg"])
        if hint is None:
            return result
        time.sleep(min(hint["retry_after_ms"], self.MAX_RETRY_WAIT_MS) / 1e3)
        return self.call(rpc, **request)

    def close(self):
        self.channel.close()

    # -- typed conveniences ------------------------------------------------

    def create(self, name: str) -> Dict:
        return self.call("create", name=name)

    def reconnect(self, name: str) -> Dict:
        return self.call("reconnect", name=name)

    def load_knowledge_base(self, key: str, url: str) -> Dict:
        return self.call("load_knowledge_base", key=key, url=url)

    def check_das_status(self, key: str) -> Dict:
        return self.call("check_das_status", key=key)

    def clear(self, key: str) -> Dict:
        return self.call("clear", key=key)

    def count(self, key: str) -> Dict:
        return self.call("count", key=key)

    def get_atom(self, key: str, handle: str, output_format: str = "HANDLE") -> Dict:
        return self.call(
            "get_atom", key=key, handle=handle, output_format=output_format
        )

    def search_nodes(
        self,
        key: str,
        node_type: Optional[str] = None,
        node_name: Optional[str] = None,
        output_format: str = "HANDLE",
    ) -> Dict:
        return self.call(
            "search_nodes",
            key=key,
            node_type=node_type or "",
            node_name=node_name or "",
            output_format=output_format,
        )

    def search_links(
        self,
        key: str,
        link_type: Optional[str] = None,
        target_types: Optional[List[str]] = None,
        targets: Optional[List[str]] = None,
        output_format: str = "HANDLE",
    ) -> Dict:
        return self.call(
            "search_links",
            key=key,
            link_type=link_type or "",
            target_types=target_types,
            targets=targets,
            output_format=output_format,
        )

    def query(self, key: str, query: str, output_format: str = "HANDLE") -> Dict:
        return self.call_with_retry(
            "query", key=key, query=query, output_format=output_format
        )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="DAS TPU service client")
    ap.add_argument("--host", default="localhost")
    ap.add_argument("--port", type=int, default=protocol.DEFAULT_PORT)
    sub = ap.add_subparsers(dest="command", required=True)

    def fmt(p):
        p.add_argument(
            "--output-format", default="HANDLE", choices=("HANDLE", "DICT", "JSON")
        )

    sub.add_parser("create").add_argument("name")
    sub.add_parser("reconnect").add_argument("name")
    p = sub.add_parser("load")
    p.add_argument("key")
    p.add_argument("url")
    sub.add_parser("status").add_argument("key")
    sub.add_parser("clear").add_argument("key")
    sub.add_parser("count").add_argument("key")
    p = sub.add_parser("atom")
    p.add_argument("key")
    p.add_argument("handle")
    fmt(p)
    p = sub.add_parser("search-nodes")
    p.add_argument("key")
    p.add_argument("--node-type")
    p.add_argument("--node-name")
    fmt(p)
    p = sub.add_parser("search-links")
    p.add_argument("key")
    p.add_argument("--link-type")
    p.add_argument("--target-types", nargs="*")
    p.add_argument("--targets", nargs="*")
    fmt(p)
    p = sub.add_parser("query")
    p.add_argument("key")
    p.add_argument("query")
    fmt(p)

    args = ap.parse_args(argv)
    client = DasClient(args.host, args.port)
    try:
        if args.command == "create":
            result = client.create(args.name)
        elif args.command == "reconnect":
            result = client.reconnect(args.name)
        elif args.command == "load":
            result = client.load_knowledge_base(args.key, args.url)
        elif args.command == "status":
            result = client.check_das_status(args.key)
        elif args.command == "clear":
            result = client.clear(args.key)
        elif args.command == "count":
            result = client.count(args.key)
        elif args.command == "atom":
            result = client.get_atom(args.key, args.handle, args.output_format)
        elif args.command == "search-nodes":
            result = client.search_nodes(
                args.key, args.node_type, args.node_name, args.output_format
            )
        elif args.command == "search-links":
            result = client.search_links(
                args.key,
                args.link_type,
                args.target_types,
                args.targets,
                args.output_format,
            )
        else:
            result = client.query(args.key, args.query, args.output_format)
    finally:
        client.close()
    print(result.get("msg", ""))
    return 0 if result.get("success") else 1


if __name__ == "__main__":
    raise SystemExit(main())
