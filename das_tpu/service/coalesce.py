"""Serving-edge query coalescing (VERDICT r03 weak #5) with cross-batch
execution pipelining (ISSUE 2 tentpole).

Each device fetch through a tunneled TPU is a full RTT (~100 ms), so N
concurrent single-query RPCs paying one fetch each serialize into N RTTs
behind the tenant lock.  This worker NATURALLY batches them: every cycle
it drains whatever is queued, groups by tenant, and runs each group
through `DistributedAtomSpace.query_many_dispatch` — all queries in the
group dispatch before one host transfer (query/fused.py dispatch_many /
settle_many on single-device tenants; parallel/fused_sharded.py's
identical halves on mesh tenants, so ShardedDB rides the same window).
While a batch executes, new arrivals queue up and form the next batch,
so under load the batch size tracks the concurrency level with ZERO
added idle latency (no timers: a lone query is picked up immediately).

Pipelining: execution used to be strictly serial — `_run_group` blocked
on batch N's host settle before batch N+1 could even dispatch, leaving
the device idle exactly when traffic is heaviest.  Now the worker keeps
up to `pipeline_depth` dispatched-but-unsettled groups in flight
(DasConfig.pipeline_depth, env DAS_TPU_PIPELINE_DEPTH, default 2): it
drains and DISPATCHES batch N+1 (async, no host sync) while batch N's
settle/materialization is still pending, then settles the oldest group.
Depth 1 restores the serial behavior exactly.  Capacity-retry rounds
inside a settle re-dispatch serially (query/fused.py settle_many) — the
graceful fallback; total device programs are identical to serial
execution, only their overlap with host work changes.

Failure isolation is per QUERY, not per group: `_QueryManyJob.settle`
returns each query's answer or its OWN exception, so one bad query in a
coalesced batch no longer fails (or re-runs) its neighbors.  A
dispatch/settle-level failure of the whole group degrades to individual
`query()` calls, each surfacing only its own error.

The reference serializes every RPC behind one global Condition
(/root/reference/service/server.py:114-115); this is the opposite design
— concurrency is the input that makes the device program wider and the
device queue deeper.
"""

from __future__ import annotations

import queue
import threading
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

#: Declared lock discipline (daslint rule DL006, das_tpu/analysis): who
#: may mutate each piece of post-__init__ coalescer state.  `_worker` is
#: the spawn check-then-set — racing submit() threads serialize on
#: `_lock`; `stats` is confined to the single worker thread (the
#: lock-free single-consumer idiom — RPC threads only ever read it via
#: coalescer_stats(), tolerating torn counters).  Any NEW mutable
#: attribute fails lint until it declares its owner here, and a mutation
#: from the wrong side (e.g. bumping stats from submit()) fails lint
#: outright.
LOCK_DISCIPLINE = {
    "QueryCoalescer._worker": "_lock",
    "QueryCoalescer.stats": "worker",
}

#: the methods that run ON the worker thread (_run and its helpers) —
#: the confinement domain for "worker"-disciplined attributes
WORKER_METHODS = {
    "QueryCoalescer": ("_run", "_group_batch", "_dispatch_group",
                       "_settle_group"),
}


class QueryCoalescer:
    def __init__(self, max_batch: int = None, pipeline_depth: int = None):
        # defaults come from DasConfig (env DAS_TPU_COALESCE_MAX_BATCH /
        # DAS_TPU_PIPELINE_DEPTH) — ONE source of truth for the served
        # path's throughput knobs (BENCH_r05: per-query cost halves as
        # concurrency doubles, so the ceiling decides the batched regime;
        # the depth decides how full the device queue stays); a bare
        # QueryCoalescer() therefore tracks the deployment defaults
        # instead of local constants
        if max_batch is None or pipeline_depth is None:
            from das_tpu.core.config import DasConfig

            if max_batch is None:
                max_batch = DasConfig.coalesce_max_batch
            if pipeline_depth is None:
                pipeline_depth = DasConfig.pipeline_depth
        self.max_batch = max_batch
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._queue: "queue.Queue[Tuple]" = queue.Queue()
        self._worker: threading.Thread = None
        self._lock = threading.Lock()
        #: observability: batches formed, items served, widest batch seen,
        #: the configured ceiling (so operators can tell "never batched
        #: wider than N" from "capped at N"), the configured pipeline
        #: depth, and the in-flight high-water mark (how deep the
        #: dispatch/settle pipeline actually ran)
        self.stats = {
            "batches": 0, "items": 0, "max_batch": 0,
            "max_batch_limit": self.max_batch,
            "pipeline_depth": self.pipeline_depth,
            "inflight_peak": 0,
        }

    def submit(self, tenant, query, output_format) -> Future:
        fut: Future = Future()
        self._queue.put((tenant, query, output_format, fut))
        self._ensure_worker()
        return fut

    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        with self._lock:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(target=self._run, daemon=True)
                self._worker.start()

    def _drain(self, block: bool) -> List[Tuple]:
        """One batch: blocking waits for the first item (idle coalescer);
        non-blocking returns [] when nothing is queued (pipeline top-up)."""
        try:
            batch = [self._queue.get(block=block)]
        except queue.Empty:
            return []
        while len(batch) < self.max_batch:
            try:
                batch.append(self._queue.get_nowait())
            except queue.Empty:
                break
        return batch

    def _run(self) -> None:
        # the in-flight window and the grouped-but-undispatched queue
        # live here; everything batch-scoped stays inside the helpers so
        # an idle coalescer (empty window, blocked in queue.get) never
        # pins a multi-GB store alive
        inflight: deque = deque()   # dispatched, awaiting settle
        ready: deque = deque()      # (tenant, fmt, group) not yet dispatched
        while True:
            # the worker must never die: every helper resolves its own
            # futures (dispatch/settle/grouping each catch internally and
            # the resolution loop tolerates cancel races), so anything
            # escaping here is unexpected — survive it, keep serving the
            # remaining in-flight entries, and never strand the queue
            # (RPC threads block on these futures with no timeout)
            try:
                # fill the window up to pipeline_depth — ONE dispatch per
                # entry, so a drained batch that splits into several
                # (tenant, format) groups never overshoots the configured
                # in-flight bound (the extra groups wait in `ready`)
                while len(inflight) < self.pipeline_depth:
                    if not ready:
                        # block for work only when nothing is in flight
                        # or grouped — otherwise an empty queue must fall
                        # through to settle, not wait
                        batch = self._drain(block=not (inflight or ready))
                        if not batch:
                            break
                        self._group_batch(batch, ready)
                        batch = None  # don't pin store refs while idle
                        continue
                    inflight.append(self._dispatch_group(*ready.popleft()))
                    self.stats["inflight_peak"] = max(
                        self.stats["inflight_peak"], len(inflight)
                    )
                if inflight:
                    self._settle_group(inflight.popleft())
            except Exception:  # noqa: BLE001 — see comment above
                continue

    def _group_batch(self, batch: List[Tuple], ready: deque) -> None:
        """Split one drained batch into (tenant, format) groups onto the
        ready queue.  A failure here must not strand futures: the RPC
        threads block on them with no timeout."""
        try:
            self.stats["batches"] += 1
            self.stats["items"] += len(batch)
            self.stats["max_batch"] = max(self.stats["max_batch"], len(batch))
            by_tenant: Dict[int, List[Tuple]] = {}
            for item in batch:
                by_tenant.setdefault(id(item[0]), []).append(item)
            for items in by_tenant.values():
                tenant = items[0][0]
                # one format group at a time keeps the job's signature
                # simple; mixed-format batches are split (rare in practice)
                by_fmt: Dict[object, List[Tuple]] = {}
                for item in items:
                    by_fmt.setdefault(item[2], []).append(item)
                for fmt, group in by_fmt.items():
                    ready.append((tenant, fmt, group))
        except Exception as exc:  # noqa: BLE001 — futures must resolve
            for item in batch:
                if not item[3].done() and not item[3].cancelled():
                    item[3].set_exception(exc)

    @staticmethod
    def _dispatch_group(tenant, fmt, group: List[Tuple]) -> Tuple:
        """Phase 1 for one (tenant, format) group: plan + async device
        dispatch under the tenant lock.  Returns the in-flight entry;
        job=None means settle must run the serial per-query fallback."""
        job = None
        try:
            with tenant.lock:
                job = tenant.das.query_many_dispatch(
                    [item[1] for item in group], fmt
                )
        except Exception:  # noqa: BLE001 — settle's fallback isolates
            job = None
        return (tenant, fmt, group, job)

    @staticmethod
    def _settle_group(entry: Tuple) -> None:
        """Phase 2: pay the host transfer, then resolve each query's
        future with its OWN result or exception."""
        tenant, fmt, group, job = entry
        answers: Optional[List] = None
        if job is not None:
            try:
                with tenant.lock:
                    answers = job.settle()
            except Exception:  # noqa: BLE001 — per-query fallback below
                answers = None
        if answers is None:
            # whole-group dispatch/settle failure: per-RPC isolation,
            # exactly like the uncoalesced path — run each individually
            # and surface only its OWN error
            answers = []
            for item in group:
                try:
                    with tenant.lock:
                        answers.append(tenant.das.query(item[1], fmt))
                except Exception as exc:  # noqa: BLE001 — per-future
                    answers.append(exc)
        for item, answer in zip(group, answers):
            fut = item[3]
            if fut.done() or fut.cancelled():
                continue
            try:
                if isinstance(answer, Exception):
                    fut.set_exception(answer)
                else:
                    fut.set_result(answer)
            except Exception:  # noqa: BLE001 — cancelled/resolved between
                pass          # the check and the set: nothing is owed
