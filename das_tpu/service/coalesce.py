"""Serving-edge query coalescing (VERDICT r03 weak #5) with fully
asynchronous, adaptively-deep execution pipelining (ISSUE 2 tentpole,
ISSUE 6 async end-to-end).

Each device fetch through a tunneled TPU is a full RTT (~100 ms), so N
concurrent single-query RPCs paying one fetch each serialize into N RTTs
behind the tenant lock.  This worker NATURALLY batches them: every cycle
it drains whatever is queued, groups by tenant, and runs each group
through `DistributedAtomSpace.query_many_dispatch` — all queries in the
group dispatch before one host transfer (query/fused.py dispatch_many /
settle_many on single-device tenants; parallel/fused_sharded.py's
identical halves on mesh tenants, so ShardedDB rides the same window).
While a batch executes, new arrivals queue up and form the next batch,
so under load the batch size tracks the concurrency level with ZERO
added idle latency (no timers: a lone query is picked up immediately).

Pipelining (adaptive, ISSUE 6): the worker keeps dispatched-but-
unsettled groups in flight and SIZES the window from what it measures —
per-settle round-trip and per-dispatch cost EWMAs — as
`ceil(rtt / dispatch_cost)`, clamped between the configured
`DasConfig.pipeline_depth` floor (default 2, so local-dispatch behavior
is unchanged) and `DasConfig.pipeline_depth_max` (env
`DAS_TPU_PIPELINE_DEPTH_MAX`).  On a tunneled TPU the settle RTT dwarfs
the host-side dispatch cost, so the window deepens until dispatch work
fully hides the wire; on local dispatch the ratio stays near 1 and the
floor holds.  Depth 1 restores the serial behavior exactly (an explicit
`pipeline_depth=1` never adapts upward).  Every dispatch issued while an
earlier group is still unsettled is SPECULATIVE — its result may be
invalidated by a racing commit, which the dispatch-time `delta_version`
guard (api/atomspace.py `_QueryManyJob`) catches at settle by
re-answering on the post-commit store — counted in
`stats["speculative_dispatches"]`.  Settles stay FIFO (`inflight` is a
deque), so per-tenant answer order follows dispatch order.

Adaptive drain: batch width trades against window depth.  When the
window is starved the backlog is spread across the free slots
(`_adaptive_width`) so narrow batches dispatch IMMEDIATELY and fill the
pipeline; when the window is nearly full the whole backlog coalesces
into one wide batch (maximum in-batch dedup, one settle).  This replaces
the old fixed block/non-block split: blocking still happens only when
nothing is in flight or grouped.  Splitting narrower is a deliberate
trade: duplicates landing in different groups each dispatch their own
program (in-batch dedup is per group), bounded at effective_depth
concurrent groups — and once the first settle lands, the delta-versioned
result cache answers the repeats with zero programs.  For a GIVEN
grouping, program counts stay identical to serial (the test pins).

Streaming early-settle: `_settle_group` consumes
`_QueryManyJob.settle_iter()` and resolves each query's future AS ITS
ANSWER LANDS, so a client's first rows arrive one RTT after its own
dispatch instead of after the whole group settles and materializes —
results delivered before their group finished are counted in
`stats["early_settles"]`.  Capacity-retry rounds inside a settle
re-dispatch serially (query/fused.py settle_pending_iter) — the graceful
fallback; total device programs are identical to serial execution, only
their overlap with host work changes.

Backpressure: the submit queue is bounded (`DasConfig.coalesce_queue_max`,
env `DAS_TPU_COALESCE_QUEUE_MAX`; 0 = unbounded).  Past the bound,
submit() rejects with `CoalescerSaturatedError` instead of letting an
open-loop client population grow host memory without limit; rejections
are counted (`queue_rejections` in `snapshot()`/`coalescer_stats()`).

Failure isolation is per QUERY, not per group: `settle_iter` yields each
query's answer or its OWN exception, so one bad query in a coalesced
batch no longer fails (or re-runs) its neighbors, and a
dispatch/settle-level failure of the whole group degrades to individual
`query()` calls for exactly the still-unresolved members.

Bounded failure (ISSUE 13, das_tpu/fault — ARCHITECTURE §14): every
submit tuple carries an optional deadline (`DasConfig.query_deadline_ms`)
the worker enforces in the queued/grouped states and at the settle
fallback (typed `DasDeadlineError`; an already-computed late answer is
still delivered — only further work is cut), a per-tenant circuit
breaker turns repeated retryable settle failures or sustained
saturation into DEGRADED serving — speculation off, window at its
floor, groups dispatched cache-only (hits answer bit-identically with
zero device work, everything else rejects with a retryable
`BreakerOpenError` + retry-after hint), a half-open probe restoring
full service after the cooldown — and the declared fault-injection
seams (`fault.maybe_fail` at submit/worker/dispatch) let the chaos
suite prove all of it under seeded schedules.

The reference serializes every RPC behind one global Condition
(/root/reference/service/server.py:114-115); this is the opposite design
— concurrency is the input that makes the device program wider and the
device queue deeper.
"""

from __future__ import annotations

import math
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Tuple

from das_tpu import fault, obs
from das_tpu.core.exceptions import (
    BreakerOpenError,
    CoalescerSaturatedError,
    DasDeadlineError,
    InjectedFault,
)

#: Declared lock discipline (daslint rule DL006, das_tpu/analysis): who
#: may mutate each piece of post-__init__ coalescer state.  `_worker` is
#: the spawn check-then-set — racing submit() threads serialize on
#: `_lock`; `stats` is confined to the single worker thread (the
#: lock-free single-consumer idiom — RPC threads only ever read it via
#: coalescer_stats()/snapshot(), tolerating torn counters); `rejected`
#: is bumped by RPC threads on the backpressure path, under `_lock`
#: (rejections are rare — the bound is the failure mode, not the hot
#: path).  Any NEW mutable attribute fails lint until it declares its
#: owner here, and a mutation from the wrong side (e.g. bumping stats
#: from submit()) fails lint outright.
LOCK_DISCIPLINE = {
    "QueryCoalescer._worker": "_lock",
    "QueryCoalescer.stats": "worker",
    "QueryCoalescer.rejected": "_lock",
}

#: the methods that run ON the worker thread (_run and its helpers) —
#: the confinement domain for "worker"-disciplined attributes.  The
#: breaker object (das_tpu/fault CircuitBreaker) is likewise driven
#: only from these methods — single-threaded by construction, like
#: `stats`.
WORKER_METHODS = {
    "QueryCoalescer": ("_run", "_group_batch", "_dispatch_group",
                       "_settle_group", "_observe", "_effective_depth",
                       "_expire", "_breaker_sync"),
}

#: EWMA smoothing for the rtt/dispatch-cost estimators: recent samples
#: dominate (load shifts fast) but one outlier drain cannot whipsaw the
#: window size
_EWMA_ALPHA = 0.25

#: bound of the per-tenant (rtt_ewma_ms, dispatch_ewma_ms,
#: effective_depth) sample ring (ISSUE 12 satellite): the HISTORY the
#: ARCHITECTURE §10 window-formula decision needs — the closeout run
#: compares how the window tracked the wire over time, which the
#: current-point EWMAs in coalescer_stats() cannot show.  One sample
#: per settled group that actually paid a wire fetch; 64 samples ≈ the
#: recent serving window at any realistic depth.
_HISTORY_K = 64


class QueryCoalescer:
    def __init__(self, max_batch: int = None, pipeline_depth: int = None,
                 pipeline_depth_max: int = None, queue_max: int = None,
                 deadline_ms: int = None, breaker_threshold: int = None,
                 breaker_cooldown_ms: int = None):
        # defaults come from DasConfig (env DAS_TPU_COALESCE_MAX_BATCH /
        # DAS_TPU_PIPELINE_DEPTH / DAS_TPU_PIPELINE_DEPTH_MAX /
        # DAS_TPU_COALESCE_QUEUE_MAX / DAS_TPU_DEADLINE_MS /
        # DAS_TPU_BREAKER_*) — ONE source of truth for the
        # served path's throughput knobs (BENCH_r05: per-query cost
        # halves as concurrency doubles, so the ceiling decides the
        # batched regime; the depth window decides how full the device
        # queue stays); a bare QueryCoalescer() therefore tracks the
        # deployment defaults instead of local constants
        if (max_batch is None or pipeline_depth is None
                or pipeline_depth_max is None or queue_max is None
                or deadline_ms is None or breaker_threshold is None
                or breaker_cooldown_ms is None):
            from das_tpu.core.config import DasConfig

            if max_batch is None:
                max_batch = DasConfig.coalesce_max_batch
            if pipeline_depth is None:
                pipeline_depth = DasConfig.pipeline_depth
            if pipeline_depth_max is None:
                pipeline_depth_max = DasConfig.pipeline_depth_max
            if queue_max is None:
                queue_max = DasConfig.coalesce_queue_max
            if deadline_ms is None:
                deadline_ms = DasConfig.query_deadline_ms
            if breaker_threshold is None:
                breaker_threshold = DasConfig.breaker_failure_threshold
            if breaker_cooldown_ms is None:
                breaker_cooldown_ms = DasConfig.breaker_cooldown_ms
        self.max_batch = max_batch
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.pipeline_depth_max = max(self.pipeline_depth,
                                      int(pipeline_depth_max))
        self.queue_max = max(0, int(queue_max))
        #: per-query serving deadline (ms, 0=off): stamped onto the
        #: submit tuple as an absolute monotonic expiry; the worker
        #: expires queued/grouped entries past it (typed
        #: DasDeadlineError) so no future waits forever on a backlog
        self.deadline_ms = max(0, int(deadline_ms))
        #: per-tenant degraded-mode state machine (das_tpu/fault):
        #: repeated retryable settle failures or sustained saturation
        #: trip it OPEN — speculation off, window at its floor, cache
        #: hits still served, fresh dispatches rejected retryable —
        #: and a half-open probe restores it.  Driven ONLY from worker
        #: methods (WORKER_METHODS), like `stats`.
        self.breaker = fault.CircuitBreaker(
            failure_threshold=int(breaker_threshold),
            cooldown_ms=float(breaker_cooldown_ms),
        )
        # Queue(maxsize=0) is unbounded — the queue itself enforces the
        # backpressure bound race-free across RPC threads
        self._queue: "queue.Queue[Tuple]" = queue.Queue(maxsize=self.queue_max)
        self._worker: threading.Thread = None
        self._lock = threading.Lock()
        #: observability: batches formed, items served, widest batch seen,
        #: the configured ceiling (so operators can tell "never batched
        #: wider than N" from "capped at N"), the configured depth floor
        #: and ceiling, the CURRENT adaptive window size and the EWMAs it
        #: derives from, the in-flight high-water mark, and the
        #: speculation/early-settle counters
        self.stats = {
            "batches": 0, "items": 0, "max_batch": 0,
            "max_batch_limit": self.max_batch,
            "pipeline_depth": self.pipeline_depth,
            "pipeline_depth_max": self.pipeline_depth_max,
            "effective_depth": self.pipeline_depth,
            "rtt_ewma_ms": 0.0,
            "dispatch_ewma_ms": 0.0,
            "inflight_peak": 0,
            "speculative_dispatches": 0,
            "early_settles": 0,
            #: robustness counters (ISSUE 13): queries expired past
            #: their deadline, fresh dispatches rejected by an open
            #: breaker, and the breaker lifecycle itself
            "deadline_expired": 0,
            "breaker_rejections": 0,
            "breaker_state": fault.CLOSED,
            "breaker_trips": 0,
            "breaker_probes": 0,
            "breaker_recoveries": 0,
        }
        #: backpressure rejections (RPC-thread side, under _lock)
        self.rejected = {"n": 0}
        #: last-K (rtt_ewma_ms, dispatch_ewma_ms, effective_depth)
        #: samples, appended by the worker after each wire-fed settle —
        #: the window-formula history (§10); maxlen bounds it, append
        #: is atomic, readers snapshot via snapshot()
        self.history: deque = deque(maxlen=_HISTORY_K)

    def submit(self, tenant, query, output_format) -> Future:
        fut: Future = Future()
        # trace birth (ISSUE 12): the mark (trace id + submit time)
        # rides the queue tuple to the worker, which closes it at
        # answer delivery; None (zero cost) when tracing is off
        mark = obs.mark()
        # deadline stamp (ISSUE 13): an absolute monotonic expiry rides
        # the tuple; None when deadlines are off so the disabled path
        # costs one comparison
        deadline = (
            time.monotonic() + self.deadline_ms / 1e3
            if self.deadline_ms > 0 else None
        )
        try:
            # declared injection seam (das_tpu/fault): a submit-path
            # failure surfaces on THIS caller's future, typed — never
            # on a neighbor's.  Delivered via _resolve so the trace
            # opened by mark() above closes (serve.answer + latency
            # sample) like every other resolution path.
            fault.maybe_fail("submit_queue")
        except InjectedFault as exc:
            self._resolve(fut, exc, mark)
            return fut
        try:
            self._queue.put_nowait(
                (tenant, query, output_format, fut, mark, deadline)
            )
        except queue.Full:
            # reject-with-error beyond the bound: unbounded acceptance
            # would grow host memory with the open-loop client count;
            # the caller sees the error on its future, same surface as
            # any per-query failure
            with self._lock:
                self.rejected["n"] += 1
            if mark is not None:
                obs.event("serve.reject", trace=mark[0],
                          bound=self.queue_max)
                obs.counter("serve.rejections").inc()
            fut.set_exception(CoalescerSaturatedError(
                f"coalescer submit queue at its bound "
                f"({self.queue_max}); retry later"
            ))
            return fut
        if mark is not None:
            obs.event("serve.submit", trace=mark[0],
                      tenant=getattr(tenant, "name", None))
            obs.counter("serve.submitted").inc()
        self._ensure_worker()
        return fut

    def snapshot(self) -> Dict:
        """One merged observability dict (worker stats + the RPC-side
        rejection counter + the last-K window-formula sample ring) —
        torn reads tolerated, same as stats."""
        out = dict(self.stats)
        out["queue_rejections"] = self.rejected["n"]
        out["window_history"] = list(self.history)
        return out

    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        with self._lock:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(target=self._run, daemon=True)
                self._worker.start()

    def _drain(self, block: bool, limit: int = None) -> List[Tuple]:
        """One batch up to `limit` (None = the configured ceiling):
        blocking waits for the first item (idle coalescer); non-blocking
        returns [] when nothing is queued (pipeline top-up)."""
        limit = self.max_batch if limit is None else limit
        try:
            batch = [self._queue.get(block=block)]
        except queue.Empty:
            return []
        while len(batch) < limit:
            try:
                batch.append(self._queue.get_nowait())
            except queue.Empty:
                break
        return batch

    @staticmethod
    def _depth_from(rtt_ms: float, dispatch_ms: float,
                    floor: int, cap: int) -> int:
        """Window size that hides the wire: enough dispatches in flight
        to cover one settle round-trip, `ceil(rtt / dispatch_cost)`,
        clamped to [floor, cap].  No samples yet (either EWMA zero) →
        the floor, i.e. exactly the pre-adaptive behavior."""
        if rtt_ms <= 0.0 or dispatch_ms <= 0.0:
            return floor
        return max(floor, min(cap, math.ceil(rtt_ms / dispatch_ms)))

    def _effective_depth(self) -> int:
        """Current adaptive window size.  An explicit serial coalescer
        (pipeline_depth=1) never adapts upward — depth 1 must stay
        exactly the old serial behavior.  A non-CLOSED breaker forces
        depth 1: degraded mode turns speculation OFF (every speculative
        dispatch is a program a failing tenant would waste) and holds
        the window at its floor until a probe restores service."""
        if self.breaker.state != fault.CLOSED:
            self.stats["effective_depth"] = 1
            return 1
        if self.pipeline_depth <= 1:
            return 1
        depth = self._depth_from(
            self.stats["rtt_ewma_ms"], self.stats["dispatch_ewma_ms"],
            self.pipeline_depth, self.pipeline_depth_max,
        )
        self.stats["effective_depth"] = depth
        return depth

    def _adaptive_width(self, free_slots: int) -> int:
        """Drain ceiling for the next batch: spread the current backlog
        evenly across the free window slots.  A starved window (many
        free slots) gets narrow batches that dispatch immediately; a
        nearly-full window coalesces wide (one settle, maximum in-batch
        dedup).  Empty queue → the full ceiling (the blocking first-item
        wait then takes whatever arrives)."""
        queued = self._queue.qsize()
        if queued <= 0 or free_slots <= 1:
            return self.max_batch
        return max(1, min(self.max_batch, -(-queued // free_slots)))

    def _observe(self, key: str, ms: float) -> None:
        """EWMA update for the rtt / dispatch-cost estimators."""
        prev = self.stats[key]
        self.stats[key] = round(
            ms if prev == 0.0 else (1 - _EWMA_ALPHA) * prev + _EWMA_ALPHA * ms,
            4,
        )

    def _run(self) -> None:
        # the in-flight window and the grouped-but-undispatched queue
        # live here; everything batch-scoped stays inside the helpers so
        # an idle coalescer (empty window, blocked in queue.get) never
        # pins a multi-GB store alive
        inflight: deque = deque()   # dispatched, awaiting settle (FIFO)
        ready: deque = deque()      # (tenant, fmt, group) not yet dispatched
        rej_seen = 0                # rejections already fed to the breaker
        while True:
            # the worker must never die: every helper resolves its own
            # futures (dispatch/settle/grouping each catch internally and
            # the resolution loop tolerates cancel races), so anything
            # escaping here is unexpected — survive it, keep serving the
            # remaining in-flight entries, and never strand the queue
            # (RPC threads block on these futures with no timeout)
            try:
                # declared injection seam (das_tpu/fault): anything this
                # iteration raises — injected included — lands in the
                # catch below and the worker keeps serving
                fault.maybe_fail("worker_iteration")
                # sustained saturation feeds the breaker: every submit
                # rejection since the last pass counts as a failure
                # signal (the worker reads the RPC-side counter, never
                # writes it — the single-consumer idiom).  Only while
                # CLOSED: once tripped, the queue drains slowly by
                # design, and a rejection landing mid-probe must not
                # re-open the breaker over the probe's own verdict —
                # the half-open probe is the sole recovery authority.
                rejected_now = self.rejected["n"]
                if self.breaker.state == fault.CLOSED:
                    for _ in range(rejected_now - rej_seen):
                        self.breaker.record_failure()
                if rejected_now != rej_seen:
                    rej_seen = rejected_now
                    self._breaker_sync()
                # fill the window up to the ADAPTIVE depth — ONE dispatch
                # per entry, so a drained batch that splits into several
                # (tenant, format) groups never overshoots the in-flight
                # bound (the extra groups wait in `ready`)
                depth = self._effective_depth()
                while len(inflight) < depth:
                    if not ready:
                        # block for work only when nothing is in flight
                        # or grouped — otherwise an empty queue must fall
                        # through to settle, not wait
                        width = self._adaptive_width(depth - len(inflight))
                        with obs.span("serve.drain", width=width) as sp:
                            batch = self._drain(
                                block=not (inflight or ready),
                                limit=width,
                            )
                            sp.set(queries=len(batch))
                        if not batch:
                            break
                        self._group_batch(batch, ready)
                        batch = None  # don't pin store refs while idle
                        continue
                    speculative = bool(inflight)
                    if speculative:
                        # an earlier group is still unsettled: this
                        # dispatch is speculative — a racing commit
                        # invalidates it via the delta_version guard
                        self.stats["speculative_dispatches"] += 1
                        if obs.enabled():
                            obs.counter("serve.speculative").inc()
                    inflight.append(
                        self._dispatch_group(*ready.popleft(),
                                             speculative=speculative)
                    )
                    self.stats["inflight_peak"] = max(
                        self.stats["inflight_peak"], len(inflight)
                    )
                if inflight:
                    self._settle_group(inflight.popleft())
            except Exception:  # noqa: BLE001 — see comment above
                continue

    def _group_batch(self, batch: List[Tuple], ready: deque) -> None:
        """Split one drained batch into (tenant, format) groups onto the
        ready queue.  A failure here must not strand futures: the RPC
        threads block on them with no timeout."""
        try:
            with obs.span("serve.group", queries=len(batch)) as sp:
                self.stats["batches"] += 1
                self.stats["items"] += len(batch)
                self.stats["max_batch"] = max(
                    self.stats["max_batch"], len(batch)
                )
                # deadline expiry in the QUEUED state (ISSUE 13): an
                # entry that waited out its deadline in the submit queue
                # resolves typed here and never forms a group
                now = time.monotonic()
                batch = [
                    item for item in batch if not self._expire(item, now)
                ]
                by_tenant: Dict[int, List[Tuple]] = {}
                for item in batch:
                    by_tenant.setdefault(id(item[0]), []).append(item)
                n_groups = 0
                for items in by_tenant.values():
                    tenant = items[0][0]
                    # one format group at a time keeps the job's signature
                    # simple; mixed-format batches are split (rare in
                    # practice)
                    by_fmt: Dict[object, List[Tuple]] = {}
                    for item in items:
                        by_fmt.setdefault(item[2], []).append(item)
                    for fmt, group in by_fmt.items():
                        ready.append((tenant, fmt, group))
                        n_groups += 1
                sp.set(groups=n_groups)
        except Exception as exc:  # noqa: BLE001 — futures must resolve
            for item in batch:
                if not item[3].done() and not item[3].cancelled():
                    item[3].set_exception(exc)

    def _dispatch_group(self, tenant, fmt, group: List[Tuple],
                        speculative: bool = False) -> Tuple:
        """Phase 1 for one (tenant, format) group: plan + async device
        dispatch under the tenant lock.  Returns the in-flight entry;
        job=None means settle must run the serial per-query fallback.
        The host-side cost feeds the dispatch EWMA the window sizes from
        ONLY when the group actually ENQUEUED device programs — the
        symmetric twin of the rtt guard: a sub-ms all-cache-hit or
        failed dispatch read as "the per-slot cost" would drag the
        estimator toward zero and peg ceil(rtt/dispatch) at
        pipeline_depth_max exactly when deeper speculation buys nothing
        (and maximizes the programs a racing commit can invalidate).

        Tracing (ISSUE 12): the group gets a GROUP id published through
        the recorder's thread-local, so the executor spans recorded
        under this dispatch (exec.dispatch inside query_many_dispatch,
        cache events) link back to the member traces without signature
        changes; the serve.dispatch span carries the window state AT
        dispatch time — effective depth, both EWMAs, the tenant's
        delta_version — the attributes the §10 window-formula decision
        reads off a trace."""
        # deadline expiry in the GROUPED state: entries that waited out
        # their deadline in `ready` resolve typed instead of paying a
        # device dispatch nobody is waiting for
        now = time.monotonic()
        group = [item for item in group if not self._expire(item, now)]
        if not group:
            return (tenant, fmt, group, None, 0, False)
        # degraded-mode gate (ISSUE 13): a non-closed breaker refuses
        # fresh device dispatches — the group runs CACHE-ONLY (hits
        # still answer with zero device work; misses become typed
        # retryable rejections at settle).  allow() grants exactly one
        # half-open probe per cooldown, which dispatches normally and
        # whose settle verdict decides recovery.
        degraded = not self.breaker.allow()
        self._breaker_sync()
        gid = 0
        sp = obs.NOOP_SPAN
        if obs.enabled():
            gid = obs.new_trace()
            now = time.perf_counter()
            marks = [self._mark_of(item) for item in group]
            for m in marks:
                if m is not None:
                    obs.histogram("serve.queue_ms").observe(
                        (now - m[1]) * 1e3
                    )
            obs.set_context(
                lane=getattr(tenant, "name", None), group=gid
            )
            sp = obs.span(
                "serve.dispatch", trace=gid,
                queries=len(group), speculative=speculative,
                degraded=degraded,
                effective_depth=self.stats["effective_depth"],
                rtt_ewma_ms=self.stats["rtt_ewma_ms"],
                dispatch_ewma_ms=self.stats["dispatch_ewma_ms"],
                delta_version=getattr(
                    getattr(tenant.das, "db", None), "delta_version", None
                ),
                traces=[m[0] for m in marks if m is not None],
            )
        t0 = time.perf_counter()
        job = None
        try:
            # declared injection seam (das_tpu/fault): a failed enqueue
            # degrades the whole group to settle's per-query fallbacks —
            # the host seam, NOT inside the DL001 dispatch halves
            fault.maybe_fail("dispatch_enqueue")
            with tenant.lock, sp:
                job = tenant.das.query_many_dispatch(
                    [item[1] for item in group], fmt,
                    cache_only=degraded,
                )
        except Exception:  # noqa: BLE001 — settle's fallback isolates
            job = None
        pending = getattr(job, "pending", None)
        if pending is not None and getattr(pending, "jobs", None):
            dispatch_ms = (time.perf_counter() - t0) * 1e3
            self._observe("dispatch_ewma_ms", dispatch_ms)
            if obs.enabled():
                obs.histogram("serve.dispatch_ms").observe(dispatch_ms)
        return (tenant, fmt, group, job, gid, degraded)

    @staticmethod
    def _mark_of(item: Tuple):
        """The obs mark riding a queue tuple — None when tracing was off
        at submit, and tolerant of 4-tuples built by direct callers of
        the group helpers (the test harness idiom)."""
        return item[4] if len(item) > 4 else None

    @staticmethod
    def _deadline_of(item: Tuple):
        """The absolute monotonic expiry riding a queue tuple — None
        when deadlines are off or for short tuples built by direct
        callers of the group helpers."""
        return item[5] if len(item) > 5 else None

    def _expire(self, item: Tuple, now: float = None) -> bool:
        """Expire one entry past its deadline (worker-side, ISSUE 13):
        resolve its future with a typed DasDeadlineError and count the
        miss.  Returns True when the entry is DEAD (expired now or
        already resolved by an earlier expiry pass) — callers skip dead
        entries instead of dispatching/falling back for them, which is
        what keeps a backlogged worker from burning device time on
        answers nobody is waiting for."""
        deadline = self._deadline_of(item)
        if deadline is None:
            return False
        if (time.monotonic() if now is None else now) < deadline:
            return False
        delivered = self._resolve(
            item[3],
            DasDeadlineError(deadline_ms=self.deadline_ms),
            self._mark_of(item),
        )
        if delivered:
            self.stats["deadline_expired"] += 1
            if obs.enabled():
                mark = self._mark_of(item)
                obs.event("serve.deadline",
                          trace=mark[0] if mark else 0,
                          deadline_ms=self.deadline_ms)
                obs.counter("serve.deadline_misses").inc()
        return True

    def _breaker_sync(self) -> None:
        """Mirror the breaker's lifecycle into `stats` (worker-side) so
        snapshot()/coalescer_stats() surface state + transition counts
        without reaching into the fault layer."""
        snap = self.breaker.snapshot()
        self.stats["breaker_state"] = snap["state"]
        self.stats["breaker_trips"] = snap["trips"]
        self.stats["breaker_probes"] = snap["probes"]
        self.stats["breaker_recoveries"] = snap["recoveries"]

    @staticmethod
    def _resolve(fut: Future, answer, mark=None) -> bool:
        """Deliver one answer; True only when the future was actually
        set — the early-settle counters must not credit deliveries that
        never happened (a client cancelling mid-settle).  A delivered
        answer closes its trace (serve.answer + the submit→answer
        latency histogram the bench's p50/p95/p99 derive from)."""
        if fut.done() or fut.cancelled():
            return False
        try:
            if isinstance(answer, Exception):
                fut.set_exception(answer)
            else:
                fut.set_result(answer)
        except Exception:  # noqa: BLE001 — cancelled/resolved between
            return False  # the check and the set: nothing is owed
        if mark is not None and obs.enabled():
            obs.event("serve.answer", trace=mark[0],
                      error=isinstance(answer, Exception))
            obs.counter("serve.answers").inc()
            obs.histogram("serve.answer_ms").observe(
                (time.perf_counter() - mark[1]) * 1e3
            )
        return True

    def _settle_group(self, entry: Tuple) -> None:
        """Phase 2: STREAM the settle — resolve each query's future as
        its answer lands (settle_iter), so early answers reach their
        clients before the group's later fallbacks run.  Any query the
        iterator never reached (a group-level settle failure) degrades
        to an individual `query()` call surfacing only its OWN error.
        The rtt EWMA the window sizes from is fed ONLY the group's first
        host transfer, timed at the PRODUCER where the fetch happens
        (query/fused.py settle_pending_iter → `job.settle_rtt_ms`) —
        never inferred from yield timing here.  A group with no fetch at
        all (every entry a dispatch-time cache hit, everything declined,
        or a commit race dropping the round to the per-query re-run
        path) reports None and feeds nothing: cache hits, staged
        replays, materialization, and per-query fallbacks are host CPU
        work the single worker thread cannot overlap, and counting any
        of it would mis-size the window — a sub-ms hit read as "the
        wire" collapses it to the floor on the hot cached workload, a
        fallback re-run read as "the wire" pegs it at
        pipeline_depth_max exactly when deeper speculation buys
        nothing.

        The tenant lock is held only AROUND each settle_iter step, never
        across a future resolution: done-callbacks run client code, and
        a blocking callback must not extend the tenant lock (the old
        blocking settle resolved outside the lock too).  A commit CAN
        therefore land between steps — settle_iter's per-yield
        delta_version re-check (api/atomspace.py) is what keeps the
        remainder sound."""
        tenant, fmt, group, job = entry[:4]
        # the group id links this settle to its dispatch span; 0 for
        # 4-entries built by direct callers (the test harness idiom)
        gid = entry[4] if len(entry) > 4 else 0
        # degraded flag (ISSUE 13): this group was dispatched cache-only
        # under an open breaker — unresolved members reject retryable
        # instead of falling back to per-query device work
        degraded = entry[5] if len(entry) > 5 else False
        sp = obs.NOOP_SPAN
        if obs.enabled():
            obs.set_context(lane=getattr(tenant, "name", None), group=gid)
            sp = obs.span("serve.settle", trace=gid, queries=len(group),
                          degraded=degraded)
        t_settle0 = time.perf_counter()
        streamed = 0
        delivered_last = False
        settle_broke = False    # the streamed settle died mid-iteration
        retryable_errors = 0    # transport-class per-query failures
        with sp:
            if job is not None:
                it = job.settle_iter()
                while True:
                    try:
                        with tenant.lock:
                            i, answer = next(it)
                    except StopIteration:
                        break
                    except Exception:  # noqa: BLE001 — per-query fallback
                        settle_broke = True
                        break
                    if isinstance(answer, BreakerOpenError):
                        # degraded-mode rejection from the cache-only
                        # job: stamp the retry-after hint only the
                        # breaker knows
                        if answer.retry_after_ms is None:
                            answer.retry_after_ms = (
                                self.breaker.retry_after_ms()
                            )
                        self.stats["breaker_rejections"] += 1
                    elif isinstance(answer, Exception) and (
                        fault.is_retryable(answer)
                    ):
                        retryable_errors += 1
                    delivered_last = self._resolve(
                        group[i][3], answer, self._mark_of(group[i])
                    )
                    if delivered_last:
                        streamed += 1
                rtt = getattr(job, "settle_rtt_ms", None)
                if rtt is not None:
                    self._observe("rtt_ewma_ms", rtt)
                    # the window-formula history (§10): one sample per
                    # wire-fed settle — exactly the settles whose rtt the
                    # adaptive window actually sized from
                    self.history.append((
                        self.stats["rtt_ewma_ms"],
                        self.stats["dispatch_ewma_ms"],
                        self.stats["effective_depth"],
                    ))
                sp.set(streamed=streamed, settle_rtt_ms=rtt)
            fellback = 0
            for item in group:
                # whole-or-partial settle failure: per-RPC isolation,
                # exactly like the uncoalesced path — run the unresolved
                # individually
                fut = item[3]
                if fut.done() or fut.cancelled():
                    continue
                # deadline expiry IN FLIGHT: an entry whose deadline
                # passed while its group was dispatched/settling is
                # abandoned host-side — typed, no fallback query
                if self._expire(item):
                    continue
                if degraded:
                    # degraded mode never runs fresh per-query device
                    # work; unresolved members reject retryable with
                    # the breaker's retry-after hint
                    self.stats["breaker_rejections"] += 1
                    self._resolve(
                        fut,
                        BreakerOpenError(
                            retry_after_ms=self.breaker.retry_after_ms()
                        ),
                        self._mark_of(item),
                    )
                    continue
                try:
                    with tenant.lock:
                        answer = tenant.das.query(item[1], fmt)
                except Exception as exc:  # noqa: BLE001 — per-future
                    answer = exc
                if isinstance(answer, Exception) and (
                    fault.is_retryable(answer)
                ):
                    retryable_errors += 1
                if self._resolve(fut, answer, self._mark_of(item)):
                    fellback += 1
            sp.set(fallbacks=fellback)
            # breaker verdict for this group (worker-side, ISSUE 13):
            # transport-class failures — a broken streamed settle or
            # retryable per-query errors — count against the tenant;
            # a clean non-degraded group is the success signal that
            # closes a half-open probe and clears the failure streak.
            # Degraded (cache-only) groups are neither: they never
            # touched the device, so they carry no health signal.
            if group and not degraded:
                if settle_broke or retryable_errors:
                    self.breaker.record_failure()
                else:
                    self.breaker.record_success()
                self._breaker_sync()
        if obs.enabled():
            obs.histogram("serve.settle_ms").observe(
                (time.perf_counter() - t_settle0) * 1e3
            )
        if streamed:
            # every delivered answer except the group's last reached its
            # client BEFORE the group finished settling — and when
            # anything happened AFTER the last delivery (a fallback
            # resolution, or a trailing yield whose future was already
            # cancelled), even that last delivery preceded group
            # completion
            self.stats["early_settles"] += (
                streamed if (fellback or not delivered_last)
                else streamed - 1
            )
