"""Serving-edge query coalescing (VERDICT r03 weak #5).

Each device fetch through a tunneled TPU is a full RTT (~100 ms), so N
concurrent single-query RPCs paying one fetch each serialize into N RTTs
behind the tenant lock.  This worker NATURALLY batches them: every cycle
it drains whatever is queued, groups by tenant, and runs each group
through `DistributedAtomSpace.query_many` — all queries in the group
dispatch before one host transfer (query/fused.py execute_many).  While a
batch executes, new arrivals queue up and form the next batch, so under
load the batch size tracks the concurrency level with ZERO added idle
latency (no timers: a lone query is picked up immediately).

The reference serializes every RPC behind one global Condition
(/root/reference/service/server.py:114-115); this is the opposite design
— concurrency is the input that makes the device program wider.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from typing import Dict, List, Tuple


class QueryCoalescer:
    def __init__(self, max_batch: int = None):
        # default drain ceiling comes from DasConfig.coalesce_max_batch
        # (env DAS_TPU_COALESCE_MAX_BATCH) — ONE source of truth for the
        # served path's throughput knob (BENCH_r05: per-query cost halves
        # as concurrency doubles, so the ceiling decides the batched
        # regime); a bare QueryCoalescer() therefore tracks the
        # deployment default instead of a local constant
        if max_batch is None:
            from das_tpu.core.config import DasConfig

            max_batch = DasConfig.coalesce_max_batch
        self.max_batch = max_batch
        self._queue: "queue.Queue[Tuple]" = queue.Queue()
        self._worker: threading.Thread = None
        self._lock = threading.Lock()
        #: observability: batches formed, items served, widest batch seen,
        #: and the configured ceiling (so operators can tell "never batched
        #: wider than N" from "capped at N")
        self.stats = {
            "batches": 0, "items": 0, "max_batch": 0,
            "max_batch_limit": self.max_batch,
        }

    def submit(self, tenant, query, output_format) -> Future:
        fut: Future = Future()
        self._queue.put((tenant, query, output_format, fut))
        self._ensure_worker()
        return fut

    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        with self._lock:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(target=self._run, daemon=True)
                self._worker.start()

    def _drain(self) -> List[Tuple]:
        batch = [self._queue.get()]
        while len(batch) < self.max_batch:
            try:
                batch.append(self._queue.get_nowait())
            except queue.Empty:
                break
        return batch

    def _run(self) -> None:
        while True:
            # one batch per helper call: when _cycle returns, its frame —
            # and with it the batch's tenant/store references — dies
            # before the worker blocks in queue.get again, so an idle
            # coalescer never pins a multi-GB store alive
            self._cycle()

    def _cycle(self) -> None:
        batch = self._drain()
        try:
            self.stats["batches"] += 1
            self.stats["items"] += len(batch)
            self.stats["max_batch"] = max(self.stats["max_batch"], len(batch))
            by_tenant: Dict[int, List[Tuple]] = {}
            for item in batch:
                by_tenant.setdefault(id(item[0]), []).append(item)
            for items in by_tenant.values():
                tenant = items[0][0]
                # one format group at a time keeps query_many's signature
                # simple; mixed-format batches are split (rare in practice)
                by_fmt: Dict[object, List[Tuple]] = {}
                for item in items:
                    by_fmt.setdefault(item[2], []).append(item)
                for fmt, group in by_fmt.items():
                    self._run_group(tenant, fmt, group)
        except Exception as exc:  # noqa: BLE001 — futures must resolve
            # an unexpected failure between drain and resolution must not
            # strand the batch: the RPC threads block on these futures
            # with no timeout
            for item in batch:
                if not item[3].done() and not item[3].cancelled():
                    item[3].set_exception(exc)

    @staticmethod
    def _run_group(tenant, fmt, group: List[Tuple]) -> None:
        try:
            with tenant.lock:
                answers = tenant.das.query_many(
                    [item[1] for item in group], fmt
                )
        except Exception:
            # per-RPC isolation, exactly like the uncoalesced path: one
            # query's failure must not fail its batch-mates — re-run each
            # individually and surface only its OWN error
            answers = []
            for item in group:
                try:
                    with tenant.lock:
                        answers.append(tenant.das.query(item[1], fmt))
                except Exception as exc:  # noqa: BLE001 — per-future
                    answers.append(exc)
        for item, answer in zip(group, answers):
            if item[3].cancelled():
                continue
            if isinstance(answer, Exception):
                item[3].set_exception(answer)
            else:
                item[3].set_result(answer)
