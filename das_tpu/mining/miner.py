"""Frequent-subgraph pattern miner.

Role of /root/reference/notebooks/SimplePatternMiner.ipynb (the reference
ships it as a notebook; here it is a first-class module):

1. **Halo expansion** — collect all links within `halo_length` hops of the
   seed nodes.  The reference probes 5 wildcard templates per node per
   level (cell 6; ~0.1 ms/query against Redis, its stored baseline).
   das_tpu already materializes the incoming-set CSR on device, so the
   halo is a vectorized offsets gather per frontier — no per-node queries.
2. **Pattern building** — for each halo link, every wildcard variant
   (each subset of targets → variables) becomes a candidate pattern with
   its match count (cell 9 `build_patterns`).
3. **Mining loop** — sample `ngram`-term composite patterns (roulette
   over halo levels by `depth_weight`), count conjunctive matches through
   the compiled device path, score by **I-Surprisingness**: the gap
   between observed probability and the best independence estimate over
   the term partition (cell 5 `compute_isurprisingness`).

All counting funnels through `query/compiler.count_matches` (device
probe+join, no host materialization) with the host algebra as fallback.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from itertools import combinations
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from das_tpu.core.schema import UNORDERED_LINK_TYPES, WILDCARD
from das_tpu.query import compiler
from das_tpu.query.ast import And, Link, LogicalExpression, Node, PatternMatchingAnswer, Variable


@dataclass
class MinedPattern:
    pattern: LogicalExpression
    count: int
    isurprisingness: float
    term_handles: Tuple[str, ...]


@dataclass
class _Candidate:
    pattern: Link
    count: int
    level: int


class PatternMiner:
    def __init__(
        self,
        db,
        halo_length: int = 2,
        depth_weight: Optional[Sequence[float]] = None,
        link_rate: float = 0.01,
        support: int = 1,
        seed: int = 0,
    ):
        self.db = db
        self.halo_length = halo_length
        self.depth_weight = list(depth_weight or [1.0] * halo_length)
        assert len(self.depth_weight) == halo_length
        self.link_rate = link_rate
        self.support = support
        self.rng = random.Random(seed)
        self.levels: List[Set[str]] = []
        self.candidates: List[List[_Candidate]] = []
        self.universe_size = 0
        self._joint_count_cache: Dict[frozenset, int] = {}

    # -- stage 1: halo ----------------------------------------------------

    def expand_halo(self, seed_handles: Sequence[str]) -> int:
        """BFS over the incoming-set index; returns the universe size
        (total halo links).  Levels hold *newly discovered* links only
        (notebook cell 6 difference pass)."""
        frontier = set(seed_handles)
        seen_links: Set[str] = set()
        self.levels = []
        for _level in range(self.halo_length):
            new_links: Set[str] = set()
            next_frontier: Set[str] = set()
            for node_handle in frontier:
                for link_handle in self.db.get_incoming(node_handle):
                    if link_handle in seen_links:
                        continue
                    new_links.add(link_handle)
                    for target in self.db.get_link_targets(link_handle):
                        next_frontier.add(target)
            seen_links.update(new_links)
            self.levels.append(new_links)
            frontier = next_frontier
        self.universe_size = len(seen_links)
        return self.universe_size

    # -- stage 2: patterns -------------------------------------------------

    def _wildcard_variants(self, link_handle: str) -> List[Link]:
        """Each nonempty subset of target positions → variables (the
        notebook's build_patterns variants)."""
        as_dict = self.db.get_atom_as_dict(link_handle)
        link_type = as_dict["type"]
        targets = as_dict["targets"]
        variants = []
        arity = len(targets)
        for mask in range(1, 2 ** arity):
            out = []
            var_index = 1
            skip = False
            for position, handle in enumerate(targets):
                if mask & (1 << position):
                    out.append(Variable(f"V{var_index}"))
                    var_index += 1
                else:
                    try:
                        out.append(
                            Node(
                                self.db.get_node_type(handle),
                                self.db.get_node_name(handle),
                            )
                        )
                    except Exception:
                        skip = True  # grounded target is itself a link
                        break
            if skip:
                continue
            variants.append(
                Link(link_type, out, link_type not in UNORDERED_LINK_TYPES)
            )
        return variants

    def _fast_countable(self) -> bool:
        """Host closed-form routes (trivial single-term counts, the star
        fold) need only the finalized host store — they work on BOTH
        device backends (TensorDB and the mesh-sharded ShardedDB), not
        just the one with single-chip buffers."""
        return getattr(self.db, "fin", None) is not None

    def count(self, query: LogicalExpression) -> int:
        """Exact match count, device path first."""
        if hasattr(self.db, "dev"):
            n = compiler.count_matches(self.db, query)
            if n is not None:
                return n
        elif self._fast_countable():
            from das_tpu.query import starcount
            from das_tpu.query.fused import trivial_plan_count

            plans = compiler.plan_query(self.db, query)
            n = trivial_plan_count(self.db, plans)
            if n is not None:
                return n
            n = starcount.try_star_count(self.db, plans)
            if n is not None:
                compiler.ROUTE_COUNTS["star"] += 1  # same telemetry as
                return n                            # count_matches
        return self._dispatch_count(query)

    def _dispatch_count(self, query: LogicalExpression) -> int:
        """General-path count once the closed forms have declined: the
        shared router (mesh program → compiled single-chip → host algebra)
        with its overflow-to-host fallback — a sharded join overflowing
        past retry must degrade exactly as it does for API queries, not
        abort the mining run."""
        answer = PatternMatchingAnswer()
        matched = compiler.dispatch(self.db, query, answer)
        return len(answer.assignments) if matched else 0

    def count_many(self, queries: List[LogicalExpression]) -> List[int]:
        """Batched exact counts.  Host closed forms first on ANY finalized
        backend: grounded single-term candidates (fused.trivial_plan_count)
        and star-shaped joints (starcount host fold) are answered with zero
        device work.  What remains runs as one vmapped device program per
        pattern *shape* on TensorDB (query/fused.py count_batch) or through
        the mesh path per query on ShardedDB; host algebra is the last
        resort."""
        out: List[Optional[int]] = [None] * len(queries)
        if self._fast_countable() and queries:
            from das_tpu.query import starcount
            from das_tpu.query.fused import trivial_plan_count

            plans_list, idxs = [], []
            star_lanes, star_idxs = [], []
            for i, q in enumerate(queries):
                plans = compiler.plan_query(self.db, q)
                if plans is None:
                    continue
                n = trivial_plan_count(self.db, plans)
                if n is not None:
                    out[i] = n
                    continue
                lane = starcount.plan_star(self.db, plans)
                if lane is not None:
                    # the miner's joint shape: closed-form degree-product
                    # fold — no join-output buffers
                    star_lanes.append(lane)
                    star_idxs.append(i)
                else:
                    plans_list.append(plans)
                    idxs.append(i)
            if star_lanes:
                # every star count is exact (the fold computes the reseed
                # semantics in-program) — no general-path recounts
                for i, n in zip(
                    star_idxs, starcount.star_count_many(self.db, star_lanes)
                ):
                    out[i] = n
                compiler.ROUTE_COUNTS["star"] += len(star_lanes)
            if plans_list and hasattr(self.db, "dev"):
                from das_tpu.query.fused import get_executor

                ex = get_executor(self.db)
                for i, plans, n in zip(idxs, plans_list, ex.count_batch(plans_list)):
                    if n is None:
                        # batch already proved fused can't honor reference
                        # semantics here — go straight to the staged path
                        n = compiler.count_matches_staged(self.db, plans)
                    out[i] = n
            elif plans_list:
                # dev-less backend (the mesh store): the closed forms
                # above already declined these — route them without
                # re-trying trivial/star per query
                for i in idxs:
                    out[i] = self._dispatch_count(queries[i])
        return [
            self.count(q) if n is None else n for q, n in zip(queries, out)
        ]

    def build_patterns(self) -> int:
        """Generate + count candidate patterns per halo level; level-0
        links are all kept, deeper levels sampled at `link_rate`
        (notebook cell 9)."""
        self.candidates = []
        seen: Set[str] = set()
        per_level: List[List[Link]] = []
        for level, links in enumerate(self.levels):
            variants: List[Link] = []
            # sorted: deterministic sampling under a fixed rng seed
            for link_handle in sorted(links):
                if level > 0 and self.rng.random() > self.link_rate:
                    continue
                for variant in self._wildcard_variants(link_handle):
                    key = repr(variant)
                    if key in seen:
                        continue
                    seen.add(key)
                    variants.append(variant)
            per_level.append(variants)
        flat = [v for vs in per_level for v in vs]
        counts = iter(self.count_many(flat))
        for level, variants in enumerate(per_level):
            self.candidates.append(
                [
                    _Candidate(v, n, level)
                    for v in variants
                    if (n := next(counts)) >= self.support
                ]
            )
        return sum(len(c) for c in self.candidates)

    # -- stage 3: scoring --------------------------------------------------

    def _prob(self, count: int) -> float:
        return count / max(1, self.universe_size)

    def _composite(self, terms: List[Link]) -> LogicalExpression:
        """Conjunction with variables renamed apart except the first
        variable, which is shared — the joint the miner scores."""
        renamed = []
        for i, term in enumerate(terms):
            targets = []
            for target in term.targets:
                if isinstance(target, Variable):
                    name = "V0" if target.name == "V1" else f"T{i}_{target.name}"
                    targets.append(Variable(name))
                else:
                    targets.append(target)
            renamed.append(Link(term.atom_type, targets, term.ordered))
        return And(renamed)

    def _subset_prob(self, terms: List[_Candidate], idxs: Tuple[int, ...]) -> float:
        """Probability of the conjunction of a term subset; joint counts
        for |subset| >= 2 are memoized across the whole mining run (the
        stochastic loop redraws the same combinations constantly)."""
        if len(idxs) == 1:
            return self._prob(terms[idxs[0]].count)
        key = frozenset(repr(terms[i].pattern) for i in idxs)
        n = self._joint_count_cache.get(key)
        if n is None:
            n = self.count(self._composite([terms[i].pattern for i in idxs]))
            self._joint_count_cache[key] = n
        return self._prob(n)

    def isurprisingness(
        self, count: int, terms: List[_Candidate], normalized: bool = False
    ) -> float:
        """I-surprisingness of the joint vs its independence estimates
        (notebook cell 5 `compute_isurprisingness`): over the full
        independence product and every binary partition {S, complement},
        the signed distance of observed p outside the [min, max] estimate
        band — max(p - max(est), min(est) - p) — so patterns co-occurring
        far *less* than predicted score positive too."""
        p = self._prob(count)
        n = len(terms)
        estimates = [float(np.prod([self._prob(t.count) for t in terms]))]
        if n >= 3:
            # all binary partitions: subsets containing index 0 (canonical
            # side of each unordered {S, complement} pair)
            rest_all = range(1, n)
            for size in range(1, n):
                for tail in combinations(rest_all, size - 1):
                    subset = (0, *tail)
                    comp = tuple(i for i in rest_all if i not in tail)
                    if not comp:
                        continue
                    estimates.append(
                        self._subset_prob(terms, subset)
                        * self._subset_prob(terms, comp)
                    )
        surprise = max(p - max(estimates), min(estimates) - p)
        if normalized and p > 0:
            surprise /= p
        return surprise

    # -- mining loops ------------------------------------------------------

    def _roulette_level(self) -> int:
        weights = [
            w if self.candidates[i] else 0.0
            for i, w in enumerate(self.depth_weight)
        ]
        total = sum(weights)
        if total == 0:
            return 0
        x = self.rng.random() * total
        acc = 0.0
        for i, w in enumerate(weights):
            acc += w
            if x <= acc:
                return i
        return len(weights) - 1

    def mine(
        self, ngram: int = 3, epochs: int = 1000, normalized: bool = False
    ) -> Optional[MinedPattern]:
        """Stochastic mining (notebook cell 11): sample ngram-term
        composites, keep the most surprising."""
        if not self.candidates or not self.candidates[0]:
            return None
        # draw every epoch's sample first, then count all composites in one
        # batched device pass — scoring (which needs memoized subset joints,
        # themselves batched inside _prefetch_joints) runs after
        samples: List[List[_Candidate]] = []
        for _ in range(epochs):
            chosen = [self.rng.choice(self.candidates[0])]
            tries = 0
            while len(chosen) < ngram and tries < 50:
                tries += 1
                level = self._roulette_level()
                candidate = self.rng.choice(self.candidates[level])
                if any(c.pattern is candidate.pattern for c in chosen):
                    continue
                chosen.append(candidate)
            if len(chosen) == ngram:
                samples.append(chosen)
        composites = [self._composite([c.pattern for c in s]) for s in samples]
        counts = self.count_many(composites)
        kept = [
            (s, comp, n)
            for s, comp, n in zip(samples, composites, counts)
            if n >= self.support
        ]
        self._prefetch_joints([s for s, _, _ in kept])
        best: Optional[MinedPattern] = None
        for chosen, composite, n in kept:
            score = self.isurprisingness(n, chosen, normalized)
            if best is None or score > best.isurprisingness:
                best = MinedPattern(
                    composite, n, score, tuple(repr(c.pattern) for c in chosen)
                )
        return best

    def _prefetch_joints(self, samples: List[List[_Candidate]]) -> None:
        """Batch-count every joint subset isurprisingness will ask for."""
        need: Dict[frozenset, List[Link]] = {}
        for chosen in samples:
            n = len(chosen)
            if n < 3:
                continue
            for size in range(2, n):
                for combo in combinations(range(n), size):
                    terms = [chosen[i].pattern for i in combo]
                    key = frozenset(repr(t) for t in terms)
                    if key not in self._joint_count_cache and key not in need:
                        need[key] = terms
        if not need:
            return
        keys = list(need)
        counts = self.count_many([self._composite(need[k]) for k in keys])
        self._joint_count_cache.update(zip(keys, counts))

    def mine_exhaustive(
        self, ngram: int = 2, normalized: bool = False
    ) -> Optional[MinedPattern]:
        """Deterministic full sweep (notebook cell 12): every level-0
        pattern against every (ngram-1)-combination of all patterns."""
        flat = [c for level in self.candidates for c in level]
        best: Optional[MinedPattern] = None
        for base in self.candidates[0]:
            for combo in combinations(flat, ngram - 1):
                if any(c.pattern is base.pattern for c in combo):
                    continue
                chosen = [base, *combo]
                composite = self._composite([c.pattern for c in chosen])
                n = self.count(composite)
                if n < self.support:
                    continue
                score = self.isurprisingness(n, chosen, normalized)
                if best is None or score > best.isurprisingness:
                    best = MinedPattern(
                        composite, n, score, tuple(repr(c.pattern) for c in chosen)
                    )
        return best
