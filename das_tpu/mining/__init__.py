from das_tpu.mining.miner import MinedPattern, PatternMiner  # noqa: F401
