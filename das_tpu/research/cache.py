"""Size-bounded write-back KV cache.

Role of /root/reference/das/research/cache.py:20-109: the research
layer's workaround for slow Couchbase upserts — hold the largest values
in a budgeted in-memory cache (min-heap eviction by size: the SMALLEST
cached value is flushed first, so the entries that are most expensive to
re-upsert stay resident) and write through only when a value is bigger
than the whole budget or smaller than everything already cached.

das_tpu carries the same algebra over an abstract KV client (the
concrete backend is any store with add/get — the reference bound it to a
Couchbase collection).  The tensor store made the original use case
obsolete (incoming sets are a device CSR with no 20 MB value limit), but
the cache remains a usable host-side batching utility and the
differential oracle for tests/test_research.py.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from copy import deepcopy
from typing import Any, Dict

from das_tpu.research.heap import Heap, PrioritizedItem


class CacheException(Exception):
    pass


class DocumentNotFoundException(CacheException):
    pass


class AbstractKVClient(ABC):
    """The two-method store surface the cache fronts (reference
    AbstractCouchbaseClient)."""

    @abstractmethod
    def add(self, key: str, value: Any) -> None: ...

    @abstractmethod
    def get(self, key: str) -> Any: ...


class FakeKVClient(AbstractKVClient):
    """In-memory fake (reference FakeCouchbaseClient) — returns deep
    copies so callers can't mutate the store through reads, and counts
    writes so tests can assert write-back batching."""

    def __init__(self):
        self.d: Dict[str, Any] = {}
        self.total_add_calls = 0

    def add(self, key: str, value: Any) -> None:
        self.total_add_calls += 1
        self.d[key] = value

    def get(self, key: str) -> Any:
        if key in self.d:
            return deepcopy(self.d[key])
        raise DocumentNotFoundException(key)


class CachedKVClient:
    """Write-back cache with a size budget (reference
    CachedCouchbaseClient, same observable behavior):

    * a value larger than the whole budget — or smaller than the current
      minimum — writes straight through;
    * otherwise it enters the heap, evicting smallest-first until the
      budget holds (evictions are the deferred writes);
    * `get` prefers the cached copy; `flush` writes everything back.
    """

    def __init__(self, kv_client: AbstractKVClient, limit: int):
        self.kv_client = kv_client
        self.heap = Heap()
        self.limit = limit
        self.current_size = 0

    def remove_until_below_limit(self, delta: int) -> None:
        while self.current_size + delta > self.limit:
            item = self.heap.heap_pop()
            self.current_size -= item.size
            self.kv_client.add(item.key, item.value)

    def add(self, key: str, value: Any, size: int) -> None:
        # Departure from the reference (its add, cache.py:73-97, carries
        # two latent bugs this class must not inherit because the
        # incoming-set builder is promoted as a differential oracle):
        # an existing entry under `key` is DETACHED first, so
        #   (a) the eviction pass can never pop the key being updated
        #       (ref: KeyError from get_idx_by_key after self-eviction);
        #   (b) a write-through can never leave a stale cached copy whose
        #       later flush would clobber the newer backend value.
        if self.heap.contains(key):
            old_item = self.heap.remove_by_key(key)
            self.current_size -= old_item.size

        if (self.heap and size < self.heap[0].size) or size > self.limit:
            self.kv_client.add(key, value)
            return

        if self.current_size + size > self.limit:
            self.remove_until_below_limit(size)
        self.heap.heap_push(PrioritizedItem(key=key, value=value, size=size))
        self.current_size += size

    def flush(self) -> None:
        for item in self.heap:
            self.kv_client.add(item.key, item.value)
        self.heap = Heap()
        self.current_size = 0

    def get(self, key: str) -> Any:
        if self.heap.contains(key):
            return self.heap.get_item_by_key(key).value
        return self.kv_client.get(key)
