"""Research/legacy layer — role of /root/reference/das/research/.

The reference's research code is the historical Couchbase path: a
size-bounded write-back cache (cache.py:60-109) over a keyed min-heap
(heap.py:12-117), driven by an incoming/outgoing-set index builder
(das_couch_cached.py:59-140) that worked around Couchbase's 20 MB value
limit.  das_tpu's tensor store supersedes all of it (incoming sets are a
device CSR), but the layer is carried for inventory completeness: the
cache/heap algebra is generic KV machinery, and the builder is kept as a
host-side differential oracle for the CSR.
"""
