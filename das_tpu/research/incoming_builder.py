"""Incoming/outgoing-set builder over a KV store, through the cache.

Role of /root/reference/das/research/das_couch_cached.py:39-140: stream
every link, upsert its outgoing set, and APPEND it to each target's
incoming set via the cached client (read-modify-write with set-dedup) —
the workload the 20 MB-value workaround existed for.  Instrumented with
the same Clock/Statistics accumulators (das_tpu/utils/timing.py).

In das_tpu the real incoming index is the finalized device CSR
(storage/atom_table.py); this builder exists as the legacy-path analogue
and as a host-side differential oracle: tests assert its KV output
matches the CSR exactly.
"""

from __future__ import annotations

from typing import Dict, Tuple

from das_tpu.research.cache import (
    AbstractKVClient,
    CachedKVClient,
    DocumentNotFoundException,
)
from das_tpu.utils.timing import Clock, Statistics

INCOMING_PREFIX = "incoming:"
OUTGOING_PREFIX = "outgoing:"


def _append(cached: CachedKVClient, key: str, new_values) -> int:
    """Reference `append` (das_couch_cached.py:39-56): read-extend-dedup-
    write through the cache; returns the new set size."""
    value = []
    try:
        value = cached.get(key)
    except DocumentNotFoundException:
        pass
    value.extend(new_values)
    v = sorted(set(value))
    cached.add(key=key, value=v, size=len(v))
    return len(v)


def populate_sets(
    data, kv_client: AbstractKVClient, cache_limit: int = 10_000_000
) -> Dict[str, Statistics]:
    """Build `outgoing:<link>` and `incoming:<atom>` sets for every link
    record in the store, incoming through the write-back cache (reference
    populate_sets, das_couch_cached.py:59-140).  Returns the timing/size
    statistics the reference logged."""
    incoming_cached = CachedKVClient(kv_client, limit=cache_limit)
    stats = {
        "incoming_time_ms": Statistics(),
        "outgoing_time_ms": Statistics(),
        "incoming_size": Statistics(),
        "outgoing_size": Statistics(),
    }
    clock = Clock()
    for handle, rec in data.links.items():
        clock.start()
        outgoing = sorted(set(rec.elements))
        kv_client.add(OUTGOING_PREFIX + handle, outgoing)
        stats["outgoing_time_ms"].add(clock.elapsed() * 1e3)
        stats["outgoing_size"].add(len(outgoing))

        incoming_batch: Dict[str, list] = {}
        for element in rec.elements:
            incoming_batch.setdefault(element, []).append(handle)
        clock.start()
        for key, values in incoming_batch.items():
            size = _append(incoming_cached, INCOMING_PREFIX + key, values)
            stats["incoming_size"].add(size)
        stats["incoming_time_ms"].add(clock.elapsed() * 1e3)
    incoming_cached.flush()
    return stats


def read_sets(kv_client: AbstractKVClient, handle: str) -> Tuple[list, list]:
    """(outgoing, incoming) of one atom, empty lists when absent."""
    try:
        outgoing = kv_client.get(OUTGOING_PREFIX + handle)
    except DocumentNotFoundException:
        outgoing = []
    try:
        incoming = kv_client.get(INCOMING_PREFIX + handle)
    except DocumentNotFoundException:
        incoming = []
    return outgoing, incoming
