"""Keyed min-heap ordered by item size.

Role of /root/reference/das/research/heap.py:12-117: the eviction
structure under the research layer's write-back cache — a binary
min-heap over (size, key, value) items with an auxiliary key→position
map so membership tests, keyed lookup, and in-place priority updates
(`fix_down` after a size change) are O(1)/O(log n).

Own implementation (array heap with position tracking); only the
surface the cache consumes is carried: push/pop, contains,
get_item_by_key, get_idx_by_key, indexed assignment + fix_down,
iteration, len.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List


@dataclass(order=True)
class PrioritizedItem:
    size: int
    key: str = field(compare=False)
    value: Any = field(compare=False)


class Heap:
    def __init__(self):
        self._v: List[PrioritizedItem] = []
        self._pos: Dict[str, int] = {}

    # -- sequence surface --------------------------------------------------

    def __len__(self) -> int:
        return len(self._v)

    def __bool__(self) -> bool:
        return bool(self._v)

    def __iter__(self) -> Iterator[PrioritizedItem]:
        return iter(self._v)

    def __getitem__(self, i: int) -> PrioritizedItem:
        return self._v[i]

    def __setitem__(self, i: int, item: PrioritizedItem) -> None:
        self._v[i] = item
        self._pos[item.key] = i

    # -- keyed access ------------------------------------------------------

    def contains(self, key: str) -> bool:
        return key in self._pos

    def get_item_by_key(self, key: str) -> PrioritizedItem:
        return self._v[self._pos[key]]

    def get_idx_by_key(self, key: str) -> int:
        return self._pos[key]

    # -- heap ops ----------------------------------------------------------

    def _swap(self, i: int, j: int) -> None:
        self[i], self[j] = self._v[j], self._v[i]

    def _up(self, i: int) -> None:
        while i > 0:
            parent = (i - 1) >> 1
            if self._v[i] < self._v[parent]:
                self._swap(i, parent)
                i = parent
            else:
                break

    def _down(self, i: int) -> None:
        n = len(self._v)
        while True:
            left, right = 2 * i + 1, 2 * i + 2
            smallest = i
            if left < n and self._v[left] < self._v[smallest]:
                smallest = left
            if right < n and self._v[right] < self._v[smallest]:
                smallest = right
            if smallest == i:
                return
            self._swap(i, smallest)
            i = smallest

    def heap_push(self, item: PrioritizedItem) -> None:
        self._v.append(item)
        self._pos[item.key] = len(self._v) - 1
        self._up(len(self._v) - 1)

    def heap_pop(self) -> PrioritizedItem:
        """Pop the smallest item, maintaining the invariant."""
        assert self._v
        top = self._v[0]
        last = self._v.pop()
        del self._pos[top.key]
        if self._v:
            self[0] = last
            self._down(0)
        return top

    def fix_down(self, item: PrioritizedItem) -> None:
        """Restore the invariant after `item` (already in the heap) had
        its size changed upward or was replaced in place."""
        i = self._pos.get(item.key)
        if i is None:
            return
        self._down(i)
        self._up(i)

    def remove_by_key(self, key: str) -> PrioritizedItem:
        """Remove and return the item stored under `key` (swap-with-last
        then repair) — the cache's update path detaches an old entry
        before re-inserting at its new size."""
        i = self._pos.pop(key)
        item = self._v[i]
        last = self._v.pop()
        if i < len(self._v):
            self[i] = last
            self._down(i)
            self._up(i)
        return item
