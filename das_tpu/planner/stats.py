"""Cardinality estimation from the wildcard-index degree statistics.

The storage layer already holds everything a textbook System-R style
estimator needs, in host memory, sorted:

  * exact per-term candidate counts — the same binary searches the
    device probes run (`query/fused.py estimate_plan_rows` over
    `host_segments`, base bucket + incremental-delta overlays);
  * exact distinct-value counts per (arity, type, position) — the
    number of run-length boundaries in the contiguous
    ``(type_id << 32 | target)`` slice of the sorted `key_type_pos`
    index (the same extraction `query/starcount.py _table_sparse`
    uses for its closed-form degree products, reduced to a count).

From those two, joins estimate with the standard independence model:

    |L ⋈ R|  ≈  |L| · |R| · Π_{v ∈ shared}  1 / max(dv_L(v), dv_R(v))

with per-variable distinct counts folded through the chain
(``dv_out(v) = min(dv_L, dv_R)`` on shared variables, clamped by the
estimated row count).  On uniform data this is exact for the star/FK
shapes the serving workload is made of; on skew it errs low — which the
planner's capacity margin (cost.py CAP_MARGIN) plus the existing
overflow-retry ladder absorb, and which the est-vs-actual planner
counters (`ops/counters.py PLANNER_KEYS`) make observable.

Invalidation rides the SAME commit counter as the result caches
(`storage/delta.py delta_version`): `estimator_for` rebuilds the
estimator whenever the backend's version moved, so estimates can never
describe pre-commit tables — exactly the ResultCache contract, for
exactly the same reason.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from das_tpu.query.fused import estimate_plan_rows


def _probe_degrees(ia, ib, cb):
    """Align two sorted degree supports: for every atom row in `ia`,
    its multiplicity in (ib, cb) — 0 where absent.  The asymmetric probe
    idiom both the pairwise dot and the k-way intersection fold use:
    the (smaller) probe side binary-searches the (larger) key side, so
    grounded-vs-FlyBase-scale supports stay O(small · log big)."""
    if ia.size == 0 or ib.size == 0:
        return np.zeros(ia.shape, np.int64)
    pos = np.searchsorted(ib, ia)
    pos_safe = np.minimum(pos, ib.size - 1)
    match = ib[pos_safe] == ia
    return np.where(match, cb[pos_safe], 0).astype(np.int64)


class RelEstimate:
    """Estimated shape of one relation mid-plan: row count plus the
    per-variable distinct-value counts the join model folds.  `plan` is
    set while the relation is still a BASE TERM — leaf-leaf joins then
    take the exact degree-product path instead of the independence
    model."""

    __slots__ = ("rows", "dv", "plan")

    def __init__(self, rows: float, dv: Dict[str, float], plan=None):
        self.rows = rows
        self.dv = dv
        self.plan = plan


class CardinalityEstimator:
    """Per-backend cardinality estimates, valid for ONE delta version.

    All statistics are memoized: the per-term counts and distinct-value
    extractions are host searchsorted/diff passes over index arrays the
    store already keeps resident, so a planner call on a warm estimator
    is dictionary lookups plus float arithmetic."""

    def __init__(self, db):
        self.db = db
        self.version = getattr(db, "delta_version", None)
        self._rows: Dict[Tuple, int] = {}
        self._distinct: Dict[Tuple[int, int, int], int] = {}

    # -- raw statistics ----------------------------------------------------

    @staticmethod
    def _plan_key(plan) -> Tuple:
        return (
            plan.arity, plan.type_id, plan.ctype, plan.fixed, plan.negated,
        )

    def rows(self, plan) -> int:
        """EXACT candidate count of one term (host searchsorted, zero
        device work) — shared with the executors' capacity sizing."""
        key = self._plan_key(plan)
        hit = self._rows.get(key)
        if hit is None:
            hit = self._rows[key] = int(estimate_plan_rows(self.db, plan))
        return hit

    def distinct_at(self, arity: int, type_id: int, pos: int) -> int:
        """Distinct REAL targets at `pos` among links of `type_id`: the
        run-length boundary count of the contiguous slice of the sorted
        (type<<32|target) key — dangling (-1) targets OR to negative
        keys and fall outside the slice, mirroring starcount's
        `_table_sparse` extraction.  Summed over overlay segments (a
        value present in two segments counts twice — an overcount of at
        most the small delta overlay, fine for an estimate)."""
        from das_tpu.storage.atom_table import host_segments

        key = (arity, type_id, pos)
        hit = self._distinct.get(key)
        if hit is not None:
            return hit
        base = np.int64(type_id) << 32
        total = 0
        for b in host_segments(self.db, arity):
            keys = b.key_type_pos[pos]
            lo = int(np.searchsorted(keys, base, side="left"))
            hi = int(np.searchsorted(
                keys, base + (np.int64(1) << 31), side="left"
            ))
            if hi > lo:
                total += 1 + int(np.count_nonzero(np.diff(keys[lo:hi])))
        self._distinct[key] = total
        return total

    # -- relation-level estimates ------------------------------------------

    def term_estimate(self, plan) -> RelEstimate:
        """Estimate for one materialized term table."""
        rows = self.rows(plan)
        dv: Dict[str, float] = {}
        for name, col in zip(plan.var_names, plan.var_cols):
            if plan.ctype is not None or plan.type_id is None:
                # template probes carry no per-position degree index
                # entry worth scanning — all-distinct is the safe bound
                d = rows
            else:
                d = self.distinct_at(plan.arity, plan.type_id, col)
                if plan.fixed:
                    # a grounded term's column can't exceed its own rows
                    d = min(d, rows)
            dv[name] = float(max(min(d, rows), 1 if rows else 0))
        return RelEstimate(float(rows), dv, plan=plan)

    def _support(self, plan, var: str):
        """Sparse degree support ((sorted atom rows, multiplicities),
        total) of a base term over `var` — straight from the star-count
        degree fast path (query/starcount.py), whose host caches are
        segment-identity-validated so commits invalidate naturally.
        None when the shape has no support extraction (templates,
        repeated variables)."""
        if plan.ctype is not None or plan.type_id is None or plan.eq_pairs:
            return None
        from das_tpu.query import starcount

        pos = plan.var_cols[plan.var_names.index(var)]
        spec = (plan.arity, plan.type_id, pos, tuple(plan.fixed))
        if plan.fixed:
            return starcount._host_sparse_deg(self.db, spec)
        return starcount._table_sparse(self.db, spec)

    def exact_join_rows(self, pa, pb, var: str) -> Optional[int]:
        """EXACT output rows of a leaf ⋈ leaf join on ONE shared
        variable: the sparse degree dot product Σ_v deg_a(v)·deg_b(v) —
        the miner's closed-form degree-product count (mining/miner.py,
        query/starcount.py), which is exact because every non-shared
        position is a distinct free variable and links are
        content-addressed (no two rows of a term bind identical
        tuples).  This is what catches the skew-heavy self-join blow-up
        (Σ deg² ≫ |L|·|R|/dv) that the independence model misses.

        The dot is asymmetric on purpose: the smaller support binary-
        searches the larger (both are sorted by construction), so a
        serving-shaped grounded term (a handful of rows) against a
        FlyBase-scale whole-type support costs O(small · log big), not
        a sort of the big side per query."""
        # the memo key must carry each side's PROBED POSITION, not just
        # the term shape: two same-shaped leaves sharing `var` at
        # different positions have different supports (Member(B, P) vs
        # Member(G, B)) and must not serve each other's dot product
        pos_a = pa.var_cols[pa.var_names.index(var)]
        pos_b = pb.var_cols[pb.var_names.index(var)]
        key = ("dot", self._plan_key(pa), pos_a, self._plan_key(pb), pos_b)
        hit = self._rows.get(key)
        if hit is not None:
            return hit if hit >= 0 else None
        ea = self._support(pa, var)
        eb = self._support(pb, var)
        if ea is None or eb is None:
            self._rows[key] = -1
            return None
        (ia, ca), _ta = ea
        (ib, cb), _tb = eb
        if ia.size > ib.size:
            (ia, ca), (ib, cb) = (ib, cb), (ia, ca)
        out = int((ca * _probe_degrees(ia, ib, cb)).sum())
        self._rows[key] = out
        return out

    def multiway_rows(self, plans, var: str) -> Tuple[float, bool]:
        """(rows, exact) of the k-way STAR join of base terms on ONE
        shared variable — the multiway kernel's output capacity model
        (kernels/multiway.py): Σ_v Π_j deg_j(v) over the INTERSECTION
        of the per-clause supports.  Exact whenever every clause has a
        support extraction — the k-way generalization of
        `exact_join_rows`, realizing the min-degree intersection bound
        (the surviving v set can never exceed the SMALLEST clause's
        distinct count, which is why the intersection deletes exactly
        the intermediates the chain's independence model over-admits);
        margin-free seeds follow.  Estimated by folding the pairwise
        model otherwise.

        Same asymmetric-searchsorted discipline as the pairwise dot:
        the smallest support probes the others, so a serving-shaped
        grounded clause against FlyBase-scale whole-type supports costs
        O(small · k · log big)."""
        key = ("mdot",) + tuple(
            (self._plan_key(p), p.var_cols[p.var_names.index(var)])
            for p in plans
        )
        hit = self._rows.get(key)
        if hit is not None and hit >= 0:
            return float(hit), True
        if hit is None:
            sups = [self._support(p, var) for p in plans]
            if all(s is not None for s in sups):
                arrs = sorted(
                    ((ia, ca) for (ia, ca), _t in sups),
                    key=lambda t: t[0].size,
                )
                base_i, prod = arrs[0][0], arrs[0][1].astype(np.int64)
                for ia, ca in arrs[1:]:
                    prod = prod * _probe_degrees(base_i, ia, ca)
                out = int(prod.sum()) if prod.size else 0
                self._rows[key] = out
                return float(out), True
            self._rows[key] = -1
        # no support for some clause (template/repeated-var shapes):
        # fold the pairwise model — the chain's estimate, same error bar
        rels = [self.term_estimate(p) for p in plans]
        acc = rels[0]
        for r in rels[1:]:
            acc = self.join_estimate(acc, r)
        return acc.rows, False

    def pair_join_rows(
        self, left: RelEstimate, right: RelEstimate, var: str
    ) -> Tuple[float, bool]:
        """(rows, exact) of the join restricted to ONE shared variable
        — the CAPACITY model of an INDEX JOIN (query/fused.py
        plan_index_joins): the kernel probes the posting index at the
        first shared variable's position and materializes every
        candidate BEFORE the remaining shared columns verify, so the
        buffer (and the overflow stats the retry ladder reads) scale
        with the single-variable candidate count, not the final match
        count.  Exact (degree dot product) while both sides are base
        terms; independence otherwise."""
        if left.plan is not None and right.plan is not None:
            exact = self.exact_join_rows(left.plan, right.plan, var)
            if exact is not None:
                return float(exact), True
        return left.rows * right.rows / max(
            left.dv.get(var, 1.0), right.dv.get(var, 1.0), 1.0
        ), False

    def join_estimate(
        self, left: RelEstimate, right: RelEstimate
    ) -> RelEstimate:
        """Fold one equi-join into the running relation estimate.  A
        leaf ⋈ leaf step on exactly one shared variable is EXACT (degree
        products); everything else uses the independence model."""
        shared = [v for v in left.dv if v in right.dv]
        rows = None
        if len(shared) == 1 and left.plan is not None and right.plan is not None:
            exact = self.exact_join_rows(left.plan, right.plan, shared[0])
            if exact is not None:
                rows = float(exact)
        if rows is None:
            rows = left.rows * right.rows
            for v in shared:
                rows /= max(left.dv[v], right.dv[v], 1.0)
        dv: Dict[str, float] = {}
        for v, d in left.dv.items():
            dv[v] = min(d, right.dv[v]) if v in right.dv else d
        for v, d in right.dv.items():
            dv.setdefault(v, d)
        rows = max(rows, 0.0)
        for v in dv:
            dv[v] = max(min(dv[v], rows), 1.0 if rows else 0.0)
        return RelEstimate(rows, dv)


def estimator_for(db) -> Optional[CardinalityEstimator]:
    """The backend's live estimator, rebuilt whenever `delta_version`
    moved — statistics invalidate exactly like result caches.  None for
    backends without host index segments (the pure host algebra needs
    no planning)."""
    if (
        getattr(db, "fin", None) is None
        and getattr(db, "host_bucket_segments", None) is None
    ):
        return None
    est = getattr(db, "_planner_estimator", None)
    version = getattr(db, "delta_version", None)
    if est is None or est.version != version or est.db is not db:
        est = CardinalityEstimator(db)
        db._planner_estimator = est
    return est
