"""Cost-based whole-plan query planner (ISSUE 8 / ROADMAP item).

Turns a conjunction into a COSTED whole-plan program before anything is
dispatched: join order from a Selinger-style DP over the wildcard-index
degree statistics (search.py / stats.py), per-step pricing against the
kernel byte models (cost.py / kernels/budget.py), and an estimated
initial capacity per intermediate — replacing the greedy smallest-first
`order_plans` and the blind `initial_result_capacity` seed, so most
queries settle in retry round 0 (every avoided retry tier is a fresh
XLA compile saved).

Consumers: `query/fused.py FusedExecutor._exec_job` and
`parallel/fused_sharded.py ShardedFusedExecutor._exec_job` call
`plan_conjunction` behind `DasConfig.use_planner` (env DAS_TPU_PLANNER;
"auto" = on — the planner is pure host arithmetic).  The tree executor's
ordered-conjunction leaves (query/tree.py conj) ride the same executor
hook.  Count batches keep their structural ordering (`_count_order`
exists to SHARE compiles across miner lanes; per-lane planning would
fragment them).

Observability: `PLANNER_COUNTS` (keys declared in ops/counters.py
PLANNER_KEYS, daslint DL008) tracks planned-vs-greedy traffic, retry
rounds, and summed estimated-vs-actual join rows;
`DistributedAtomSpace.explain(query)` renders one query's costed plan
(and, with execute=True, the actual per-stage rows next to the
estimates); the service facade folds `snapshot()` into
`coalescer_stats()["planner"]` so estimator drift is visible in
production.

Correctness envelope: the planner chooses among orders the executors
already accept — answers are bit-identical to the legacy path for every
order (the reseed quirk re-answers on the exact variant exactly as
before), and capacity seeds only move the STARTING rung of the existing
overflow-retry ladder.  A planner bug can cost time, never answers.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from das_tpu.ops.counters import PLANNER_KEYS

#: planner telemetry; keys DECLARED in ops/counters.py (PLANNER_KEYS)
#: and pinned by daslint rule DL008 — the dict is built from the
#: registry so the two cannot drift (the DL004 idiom).
PLANNER_COUNTS: Dict[str, int] = {k: 0 for k in PLANNER_KEYS}


def reset_planner_counts() -> None:
    for k in PLANNER_COUNTS:
        PLANNER_COUNTS[k] = 0


def enabled(config=None) -> bool:
    """Resolve planner routing.  Env DAS_TPU_PLANNER beats the config so
    a deployment (or the bench A/B) can flip the path without code
    changes — the DAS_TPU_PALLAS idiom."""
    mode = os.environ.get("DAS_TPU_PLANNER")
    if mode is None and config is not None:
        mode = getattr(config, "use_planner", "auto")
    mode = str("auto" if mode is None else mode).lower()
    if mode in ("off", "0", "false"):
        return False
    return True  # "auto"/"on": pure host arithmetic, on everywhere


def snapshot() -> Dict[str, float]:
    """Counter snapshot plus the estimator-error ratio operators watch:
    actual/estimated summed join rows of settled planned programs (1.0 =
    the statistics still describe the data; >>1 = skew has outgrown the
    uniformity assumption and capacity seeds are starting to retry)."""
    out = dict(PLANNER_COUNTS)
    est = out.get("est_rows", 0)
    out["actual_vs_est_ratio"] = (
        round(out.get("actual_rows", 0) / est, 4) if est else None
    )
    return out


def record_planned(planned) -> None:
    """Executor-hook accounting: one planner-driven conjunction plus the
    search method that produced its order.  Lives HERE, not in
    plan_conjunction, so explain() (which also plans) never inflates the
    planned/method decomposition — dp + greedy_tail + ref_order always
    sums to `planned`.  The explicit literal dispatch (instead of
    `PLANNER_COUNTS[planned.method]`) keeps every counting site a
    declared-key literal daslint DL008 can pin."""
    PLANNER_COUNTS["planned"] += 1
    method = planned.method
    if method == "dp":
        PLANNER_COUNTS["dp"] += 1
    elif method == "greedy_tail":
        PLANNER_COUNTS["greedy_tail"] += 1
    else:
        PLANNER_COUNTS["ref_order"] += 1


def observe_settle(planned, actual_join_rows, rounds: int,
                   shards: int = 1) -> None:
    """Fold one settled planner-driven job into the telemetry: retry
    rounds actually paid and estimated-vs-actual join output rows (the
    estimator-error signal).  Called from the executors' settle halves.
    The sharded executor's per-join actuals are WORST-SHARD totals, so
    its estimates are scaled to the even-split per-shard expectation —
    a ratio drifting past the 2x skew headroom is exactly the signal
    that hub keys are concentrating on one shard."""
    if rounds <= 1:
        PLANNER_COUNTS["round0"] += 1
    else:
        PLANNER_COUNTS["retries"] += rounds - 1
    est = sum(-(-int(r) // max(shards, 1)) for r in planned.est_join_rows)
    act = sum(int(r) for r in actual_join_rows)
    PLANNER_COUNTS["est_rows"] += est
    PLANNER_COUNTS["actual_rows"] += act
    from das_tpu import obs

    if obs.enabled():
        # est-vs-actual PER SETTLED JOB on the trace (ISSUE 12): the
        # aggregate ratio above smooths exactly the per-query outliers
        # the closeout run needs to see next to their dispatch spans
        obs.event(
            "planner.observe", est_rows=est, actual_rows=act,
            per_step_est=list(planned.est_join_rows),
            per_step_actual=[int(r) for r in actual_join_rows],
            retry_rounds=rounds - 1,
        )


# re-exports: the public planner surface
from das_tpu.planner.search import (  # noqa: E402
    PlannedProgram,
    PlannedTree,
    plan_conjunction,
    plan_tree,
)
from das_tpu.planner.stats import (  # noqa: E402
    CardinalityEstimator,
    estimator_for,
)


def _term_brief(plan) -> Dict:
    """Human-readable one-liner for explain output."""
    return {
        "arity": plan.arity,
        "type_id": plan.type_id,
        "ctype": plan.ctype,
        "fixed": list(plan.fixed),
        "vars": list(plan.var_names),
        "negated": plan.negated,
    }


#: sentinel: "no precomputed plan — run plan_conjunction here" (None is
#: a legitimate computed outcome, the planner's decline)
_UNPLANNED = object()


def _compile_report(digest: str, site_hint: Optional[str] = None) -> Dict:
    """The explain(compile=True) block: ledger rows for the executed
    program's signature digest (compile wall, cost/memory analysis,
    calibration ratio), falling back to the site's rows when the digest
    has no entry (e.g. the program compiled before the ledger was
    enabled).  `enabled` False with empty rows tells the operator WHY
    nothing is there."""
    from das_tpu.obs import proflog

    rows = proflog.rows(digest=digest)
    if not rows and site_hint is not None:
        rows = proflog.rows(site=site_hint)
    return {
        "enabled": proflog.enabled(),
        "digest": digest,
        "rows": rows,
    }


def _explain_plans(db, plans, execute: bool, sharded: bool,
                   planned=_UNPLANNED, compile_report: bool = False) -> Dict:
    if planned is _UNPLANNED:
        PLANNER_COUNTS["explain"] += 1
        n_shards = 1
        if sharded:
            n_shards = int(db.mesh.devices.size)
        planned = plan_conjunction(db, list(plans), n_shards=n_shards)
    out: Dict = {
        "route": (
            planned.route if planned is not None
            else ("sharded" if sharded else "fused")
        ),
        "planner_enabled": enabled(getattr(db, "config", None)),
        "planned": planned is not None,
    }
    if planned is not None:
        out.update(
            method=planned.method,
            cost_bytes=planned.cost,
            order=[_term_brief(plans[i]) for i in planned.order],
            est_term_rows=list(planned.est_term_rows),
            est_join_rows=list(planned.est_join_rows),
            join_cap_seeds=list(planned.join_cap_seeds),
            # leading positives fused into one k-way intersection step
            # (0 = binary chain); est_join_rows/join_cap_seeds then
            # lead with the multiway step's output figures
            multiway=planned.multiway,
        )
    if not execute:
        return out
    # run the job through the executor's real dispatch/settle halves so
    # "actual" reflects the exact program production would run (route,
    # caps, learned-capacity merge included)
    if sharded:
        from das_tpu.parallel.fused_sharded import get_sharded_executor

        ex = get_sharded_executor(db)
    else:
        from das_tpu.query.fused import get_executor

        ex = get_executor(db)
    job = ex._exec_job(list(plans), False)
    if job is None:
        out["actual"] = None  # executor declined: staged/host path answers
        if compile_report:
            out["compile"] = None
        return out
    import jax

    from das_tpu.query.fused import FETCH_COUNTS

    while True:
        dev = job.dispatch()
        FETCH_COUNTS["n"] += 1  # one settle transfer per round (DL013)
        if job.settle(jax.device_get(dev), dev):
            break
    result = job.result
    out["actual"] = {
        "count": None if result is None else result.count,
        "term_rows": list(getattr(job, "last_ranges", ()) or ()),
        "join_rows": list(getattr(job, "last_join_rows", ()) or ()),
        "retry_rounds": max(0, getattr(job, "rounds", 1) - 1),
        "reseed_fallback": bool(getattr(result, "reseed_needed", False)),
    }
    if compile_report:
        # the dispatched program's ledger record (ISSUE 14): the final
        # plan_sig is the signature the settled round compiled under —
        # the same digest the builders keyed instrument() with
        from das_tpu.obs import proflog

        out["compile"] = _compile_report(
            proflog.sig_digest(job.plan_sig(), False),
            site_hint="sharded" if sharded else "fused",
        )
    return out


def _explain_tree_fused(db, fusable, execute: bool, sharded: bool,
                        compile_report: bool = False) -> Dict:
    """Render the whole-tree fused plan (ISSUE 10): per-site costed
    conjunction plans, the union/anti placement the one program
    hard-codes, and per-branch estimated rows — with execute=True, the
    actual per-site rows, retry rounds and the final count out of the
    SINGLE dispatched program."""
    PLANNER_COUNTS["explain"] += 1
    pos_sites, neg_plans, _const = fusable
    n_shards = int(db.mesh.devices.size) if sharded else 1
    pt = plan_tree(db, pos_sites, neg_plans, n_shards=n_shards)
    # render per-site detail from the plans plan_tree ALREADY computed —
    # one explain call plans each site exactly once and bumps the
    # explain counter exactly once
    site_plans = (
        pt.site_plans if pt is not None else tuple(None for _ in pos_sites)
    )
    out: Dict = {
        "route": (
            pt.route if pt is not None
            else ("sharded_tree_fused" if sharded else "fused_tree")
        ),
        "planned": pt is not None,
        "tree_fused": True,
        "planner_enabled": enabled(getattr(db, "config", None)),
        "sites": [
            _explain_plans(db, site, False, sharded, planned=sp)
            for site, sp in zip(pos_sites, site_plans)
        ],
        "neg_site": (
            _explain_plans(
                db, neg_plans, False, sharded,
                planned=pt.neg_plan if pt is not None else None,
            )
            if neg_plans else None
        ),
    }
    if pt is not None:
        out.update(
            cost_bytes=pt.cost,
            est_site_rows=list(pt.est_site_rows),
            est_union_rows=pt.est_union_rows,
            # placement: the union (concat + dedup) runs after ALL
            # positive sites; the anti (difference) after the union
            union_after=pt.union_after,
            anti_after_union=pt.anti_after_union,
        )
    if not execute:
        return out
    if sharded:
        from das_tpu.parallel.fused_sharded import get_sharded_executor

        ex = get_sharded_executor(db)
    else:
        from das_tpu.query.fused import get_executor

        ex = get_executor(db)
    job = ex.execute_tree(pos_sites, neg_plans)
    if job is None or job.result is None:
        out["actual"] = None  # declined: the tree executor answers
        if compile_report:
            out["compile"] = None
        return out
    if compile_report:
        from das_tpu.obs import proflog

        out["compile"] = _compile_report(
            proflog.sig_digest(job.tree_sig(), False),
            site_hint="sharded_tree" if sharded else "fused_tree",
        )
    out["actual"] = {
        "count": job.result.count,
        # the mesh union dedups SHARD-LOCALLY (cross-shard duplicate
        # answers die in the host set at materialization — the
        # ShardedTreeOps rule), so the replicated count UPPER-BOUNDS
        # the distinct answer count on the sharded route; single-device
        # counts are exact post-dedup
        "count_is_upper_bound": sharded,
        "matched_any": job.matched_any,
        "retry_rounds": max(0, job.rounds - 1),
        "programs": job.rounds,
        "sites": [
            {
                "count": j.result.count,
                "term_rows": list(j.last_ranges or ()),
                "join_rows": list(j.last_join_rows or ()),
            }
            for j in job.site_jobs
        ],
        "neg_site": (
            {
                "count": job.neg_job.result.count,
                "term_rows": list(job.neg_job.last_ranges or ()),
                "join_rows": list(job.neg_job.last_join_rows or ()),
            }
            if job.neg_job is not None else None
        ),
    }
    return out


def explain(db, query, execute: bool = False,
            compile: bool = False) -> Dict:
    """The observability surface behind `DistributedAtomSpace.explain`:
    what the planner decided for `query` — chosen order, route,
    estimated rows, capacity seeds — and, with execute=True, the actual
    per-stage rows and retry rounds next to the estimates.  An
    Or/negation tree in the fusable subset reports the WHOLE-TREE fused
    plan (site order, union/anti placement, per-branch est rows —
    _explain_tree_fused); other tree composites report one entry per
    ordered-conjunction site (query/tree.py conj_sites); queries
    outside the compiled language report route "host".

    With compile=True (ISSUE 14; implies execute — the rows describe
    the program the executor actually dispatched) each entry gains a
    `compile` block: the program ledger's record for the executed
    signature — compile wall seconds, cost_analysis flops /
    bytes-accessed, memory_analysis byte columns and the byte-model
    calibration ratio (das_tpu/obs/proflog.py; empty rows with
    enabled=False when DAS_TPU_PROFLOG is off)."""
    from das_tpu.query import compiler as qc

    execute = execute or compile
    plans = qc.plan_query(db, query)
    if plans is qc.EMPTY_PLAN:
        return {"route": "fused", "planned": False, "empty": True}
    sharded = hasattr(db, "query_sharded")
    if plans is not None:
        return _explain_plans(
            db, plans, execute, sharded, compile_report=compile
        )
    from das_tpu.query.plan import NotCompilable, build_plan
    from das_tpu.query.tree import (
        conj_sites,
        tree_fusion_enabled,
        tree_fusion_sites,
    )

    try:
        node = build_plan(db, query)
    except NotCompilable:
        return {"route": "host", "planned": False}
    fusable = tree_fusion_sites(node)
    if fusable is not None and tree_fusion_enabled(
        getattr(db, "config", None)
    ):
        return _explain_tree_fused(
            db, fusable, execute, sharded, compile_report=compile
        )
    sites = conj_sites(node)
    return {
        "route": "tree",
        "planned": bool(sites),
        "sites": [
            _explain_plans(
                db, site, execute, sharded, compile_report=compile
            )
            for site in sites
        ],
    }
