"""Cost model for whole-plan pricing (search.py consumes this).

Each candidate join step is priced as BYTES MOVED, the unit the rest of
the stack already reasons in (kernels/budget.py byte models):

  * the estimated materialized output — rows × int32 row width — which
    TrieJax identifies as the term that dominates real join cost
    (intermediate blow-up, not per-tuple CPU);
  * the kernel byte model of the step at the capacity the estimate
    implies: `budget.join_plan` / `index_join_plan` / `probe_plan`
    return the resident + streamed-block footprint for the
    single-block / grid-chunked layouts, and a step the byte planner
    would kick to the LOWERED bodies pays a penalty factor — lowered
    sort-merge materializes full sort/offset vectors in HBM instead of
    streaming VMEM blocks, and on hardware that is the measured gap the
    kernels exist to close.

The model is deliberately coarse — it must only ORDER plans correctly,
not predict milliseconds — and every constant is a power of two so unit
tests can pin exact costs.
"""

from __future__ import annotations

from das_tpu.kernels import budget

#: int32 columns everywhere
ROW_BYTES = 4

#: headroom multiplier between an estimated row count and the capacity
#: the plan seeds for it: one doubling absorbs the estimator's
#: uniformity error on mildly skewed data while keeping the buffers an
#: order of magnitude under the blind initial_result_capacity seed for
#: serving-shaped queries
CAP_MARGIN = 2

#: pricing penalty for a step whose byte plan falls off the kernel
#: routes (budget.ROUTE_LOWERED): the lowered sort-merge pays full-table
#: sorts and scatter materialization in HBM
LOWERED_PENALTY = 4

#: flat per-stage charge (bytes-equivalent): every extra stage is more
#: traced program, more retry surface, and one more stats slot — breaks
#: cost ties toward shorter chains
STAGE_OVERHEAD = 1 << 12


def pow2_at_least(n: int, lo: int = 64) -> int:
    c = lo
    while c < n:
        c *= 2
    return c


def cap_for(est_rows: float, max_capacity: int, exact: bool = False) -> int:
    """Initial capacity for an estimated intermediate: margin, power of
    two, clamped to the configured ceiling (an over-clamped cap just
    re-enters the existing overflow-retry ladder).  `exact` drops the
    margin — a degree-product figure is a hard bound on what the
    overflow stats can report, so padding past its power-of-two rung
    only buys bigger buffers."""
    want = int(est_rows) + 1 if exact else int(est_rows * CAP_MARGIN) + 1
    return min(pow2_at_least(max(64, want)), max(int(max_capacity), 64))


def term_cost(rows: int, width: int) -> float:
    """Materializing one probed term table."""
    return float(rows) * (width or 1) * ROW_BYTES + STAGE_OVERHEAD


def multiway_step_cost(
    left_rows: float,
    left_width: int,
    tails,
    cap_rows: float,
    out_width: int,
    max_capacity: int,
) -> float:
    """Price one k-way multiway intersection step (kernels/multiway.py):
    the byte-model footprint at the capacity the estimate implies plus
    ONE estimated materialized output — where the equivalent binary
    chain pays k-1 join stages and k-2 materialized INTERMEDIATES
    (TrieJax's deleted-intermediate term; search.py compares the two
    sums to route the star prefix).  `tails` is a sequence of
    (rows, width) for the non-first clauses; the kernel pads them to a
    common width, which the byte model prices."""
    cap = cap_for(cap_rows, max_capacity)
    kpad = max([w for _r, w in tails] + [1])
    plan = budget.multiway_plan(
        int(min(left_rows, 2**31 - 1)), max(left_width, 1),
        tuple((int(min(r, 2**31 - 1)), kpad) for r, _w in tails),
        max(out_width, 1), cap,
    )
    stage = float(plan.resident_bytes + plan.block_bytes)
    if plan.route == budget.ROUTE_LOWERED:
        stage *= LOWERED_PENALTY
    return stage + cap_rows * out_width * ROW_BYTES + STAGE_OVERHEAD


def join_step_cost(
    left_rows: float,
    left_width: int,
    right_rows: float,
    right_width: int,
    n_pairs: int,
    cap_rows: float,
    out_width: int,
    max_capacity: int,
) -> float:
    """Price one binary join: the byte-model footprint of the step at
    the capacity the estimate implies, plus the estimated materialized
    window, with the lowered-route penalty when the combined buffers
    overflow every kernel layout.  `cap_rows` is the capacity-relevant
    row estimate (index-join candidate counts included — see
    stats.pair_join_rows), i.e. the buffer the step actually writes."""
    cap = cap_for(cap_rows, max_capacity)
    plan = budget.join_plan(
        int(min(left_rows, 2**31 - 1)), max(left_width, 1),
        int(min(right_rows, 2**31 - 1)), max(right_width, 1),
        max(n_pairs, 1), max(out_width, 1), cap,
    )
    stage = float(plan.resident_bytes + plan.block_bytes)
    if plan.route == budget.ROUTE_LOWERED:
        stage *= LOWERED_PENALTY
    return stage + cap_rows * out_width * ROW_BYTES + STAGE_OVERHEAD
