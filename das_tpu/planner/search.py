"""Join-order search: costed whole-plan programs (`PlannedProgram`).

Selinger-style dynamic programming over CONNECTED subsets of the
positive terms, left-deep chains only (the executors fold left-deep),
up to ``DAS_TPU_PLANNER_DP_MAX`` clauses (default 8: 2^8 subsets × 8
extensions is microseconds of host arithmetic); wider conjunctions fall
back to greedy smallest-ESTIMATED-OUTPUT-first — still a strict upgrade
over the legacy smallest-term-first, which ignores join selectivity
entirely.

One ordering rule is inherited unchanged from `order_plans`
(query/fused.py): when the positive terms are CONNECTED in reference
order and at least one is grounded, the reference order is kept — the
compiled program is then the reference fold itself, its in-program
reseed flag is authoritative, and a zero-count answer needs no
exact-variant re-run.  The planner still prices that order and seeds
its capacities; it just refuses to trade the reseed authority away for
an estimated win on queries whose intermediates are small by
construction (they are grounded).  Reordering stays bit-identical
either way — the executors' reseed fallback re-answers any order the
quirk could bite — this rule is about not PAYING that fallback.

Negated terms filter at the end regardless of order, exactly like the
legacy ordering.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from das_tpu.planner import cost as pcost
from das_tpu.planner.stats import RelEstimate, estimator_for
from das_tpu.query.fused import reference_order_authoritative

#: exact-DP clause ceiling (env DAS_TPU_PLANNER_DP_MAX); beyond it the
#: greedy-by-estimated-output tail orders the conjunction
DEFAULT_DP_MAX = 8

#: "auto" multiway routing needs at least this many fused clauses — a
#: 2-clause "star" is just the binary join with no intermediate to
#: delete, so auto keeps the chain (and its index-join option); "on"
#: routes any eligible prefix >= 2 (what the differential tests force)
MULTIWAY_AUTO_MIN_K = 3


def dp_max() -> int:
    raw = os.environ.get("DAS_TPU_PLANNER_DP_MAX")
    if not raw:
        return DEFAULT_DP_MAX
    try:
        return max(int(raw), 2)
    except ValueError:
        return DEFAULT_DP_MAX


def multiway_mode(config=None) -> str:
    """Resolve k-way multiway kernel routing: "auto" (cost-based),
    "on" (every eligible star prefix), "off".  Env DAS_TPU_MULTIWAY
    beats the config — the DAS_TPU_PALLAS idiom, so the bench A/B can
    flip arms without code changes."""
    mode = os.environ.get("DAS_TPU_MULTIWAY")
    if mode is None and config is not None:
        mode = getattr(config, "use_multiway", "auto")
    mode = str("auto" if mode is None else mode).lower()
    if mode in ("on", "1", "true"):
        return "on"
    if mode in ("off", "0", "false"):
        return "off"
    return "auto"


@dataclass(frozen=True)
class PlannedProgram:
    """One costed whole-plan decision, fixed BEFORE anything dispatches.

    order          — permutation into the caller's plan list (positives
                     in chosen join order, then negatives)
    est_term_rows  — exact per-term candidate rows, in `order`
    est_join_rows  — estimated output rows per STEP: with multiway the
                     first entry is the k-way output, then one entry
                     per tail binary join; pure chains have one entry
                     per join (the executors' stats report the same
                     layout, so est-vs-actual compares like with like)
    join_cap_seeds — initial capacity per step buffer (margin + pow2),
                     replacing the blind initial_result_capacity seed;
                     same layout as est_join_rows
    route          — the answer route this plan expects to take; always
                     a member of ops/counters.py ROUTE_KEYS (daslint
                     DL008 pins this)
    method         — "dp" / "greedy_tail" / "ref_order" (PLANNER_KEYS)
    cost           — the model's bytes-moved figure for the whole chain
    multiway       — number of LEADING positives fused into one k-way
                     intersection step (kernels/multiway.py); 0 = pure
                     binary chain.  The first `multiway` terms of
                     `order` form a star on one shared variable.
    """

    order: Tuple[int, ...]
    est_term_rows: Tuple[int, ...]
    est_join_rows: Tuple[int, ...]
    join_cap_seeds: Tuple[int, ...]
    route: str
    method: str
    cost: float
    multiway: int = 0


def _shares_var(a, b) -> bool:
    return bool(set(a.var_names) & set(b.var_names))


def _connected(plans: List) -> bool:
    """All positive terms form one variable-connected component."""
    if len(plans) <= 1:
        return True
    seen = {0}
    grew = True
    while grew:
        grew = False
        for i, p in enumerate(plans):
            if i in seen:
                continue
            if any(_shares_var(p, plans[j]) for j in seen):
                seen.add(i)
                grew = True
    return len(seen) == len(plans)


def _index_join_eligible(plan) -> bool:
    """Mirror of query/fused.py plan_index_joins' right-side test: an
    ordered whole-type probe (no grounding, no template key, no repeated
    variables, positive) — the executor will probe the posting index
    instead of materializing the table, and the join CAPACITY then
    scales with the FIRST shared variable's candidate count."""
    return (
        not plan.negated
        and not plan.eq_pairs
        and not plan.fixed
        and plan.ctype is None
        and plan.type_id is not None
    )


def _join_step(est, acc, right, right_plan):
    """One left-deep join step: (folded RelEstimate, capacity-relevant
    rows, shared-var count, exact?).  For an index-join-eligible right
    side the capacity model is the single-variable candidate count
    (stats.pair_join_rows), never below the final match estimate.
    `exact` marks a capacity figure derived from the degree dot product
    — a hard bound on what the overflow stats can report, so the seed
    needs no estimate-error margin."""
    shared = [v for v in acc.dv if v in right.dv]
    out = est.join_estimate(acc, right)
    cap_rows = out.rows
    exact = (
        len(shared) == 1
        and acc.plan is not None and right.plan is not None
        and est.exact_join_rows(acc.plan, right.plan, shared[0]) is not None
    )
    if shared and _index_join_eligible(right_plan):
        pr, p_exact = est.pair_join_rows(acc, right, shared[0])
        if pr >= cap_rows:
            cap_rows, exact = pr, p_exact
    return out, cap_rows, len(shared), exact


def _chain_estimates(est, terms: List, order: Tuple[int, ...]):
    """(est_join_rows, join_cap_seeds, cost, step_costs) of one
    left-deep order.  est_join_rows are the CAPACITY-relevant per-join
    rows — the number the executors' overflow stats report (candidate
    counts for index joins, match counts for materialized joins) — so
    est-vs-actual telemetry compares like with like.  `step_costs` is
    the per-join breakdown (term costs excluded) the multiway router
    compares its one intersection step against."""
    rels = [est.term_estimate(terms[i]) for i in order]
    acc = rels[0]
    widths = [len(terms[i].var_names) for i in order]
    width = widths[0]
    total = pcost.term_cost(int(acc.rows), width)
    join_rows: List[int] = []
    max_cap = _max_capacity(est.db)
    caps: List[int] = []
    step_costs: List[float] = []
    for n in range(1, len(order)):
        right = rels[n]
        out, cap_rows, n_pairs, exact = _join_step(
            est, acc, right, terms[order[n]]
        )
        out_width = width + sum(
            1 for v in terms[order[n]].var_names if v not in acc.dv
        )
        total += pcost.term_cost(int(right.rows), widths[n])
        step = pcost.join_step_cost(
            acc.rows, width, right.rows, widths[n],
            n_pairs, cap_rows, out_width, max_cap,
        )
        total += step
        step_costs.append(step)
        join_rows.append(int(cap_rows))
        caps.append(pcost.cap_for(cap_rows, max_cap, exact=exact))
        acc = out
        width = out_width
    return tuple(join_rows), tuple(caps), total, step_costs


def _multiway_prefix(terms: List, order: Tuple[int, ...]):
    """(m, v): the longest prefix of the ordered positives forming a
    STAR on one shared variable — every clause after the first shares
    EXACTLY {v} with the variables accumulated so far (its remaining
    variables are fresh).  That is the shape the k-way kernel grounds
    in one pass: tail rows pair freely within a v group, so the slot
    layout is a pure mixed-radix product and no cross-tail
    verification beyond v is needed.  m == 0 when even the first join
    is not a single-variable step."""
    if len(order) < 2:
        return 0, None
    seen = set(terms[order[0]].var_names)
    shared0 = set(terms[order[1]].var_names) & seen
    if len(shared0) != 1:
        return 0, None
    v = next(iter(shared0))
    m = 1
    for idx in order[1:]:
        t = terms[idx]
        if (set(t.var_names) & seen) != {v}:
            break
        seen |= set(t.var_names)
        m += 1
    return (m if m >= 2 else 0), v


def _max_capacity(db) -> int:
    return int(getattr(
        getattr(db, "config", None), "max_result_capacity", 1 << 24
    ))


def _star_chain_seeds(est, terms, order, join_rows, caps, max_cap):
    """Chain-route seed reuse of the EXACT k-way statistic (ISSUE 10
    satellite / ROADMAP multiway remainder): when the chain is chosen
    over the multiway kernel — mode off, auto declined the cost race,
    or the prefix infeasible — its DEEPER star-prefix intermediates
    still ride the independence model, which errs low exactly on skew
    (the guaranteed retry tier the multiway route exists to delete).
    But the intermediate after folding prefix clauses 0..t+1 IS the
    (t+2)-way star join, whose exact size `stats.multiway_rows` already
    computes: reuse it for the capacity seed, margin-free, so the chain
    settles in round 0 on the same skew shapes.

    The statistic covers INDEX-JOIN steps too: a star step shares
    exactly ONE variable, so the posting-index candidate count — Σ over
    accumulator rows of the right term's degree at the probed position
    — telescopes to Σ_v Π_j deg_j(v) over the intersected supports,
    which is multiway_rows verbatim (no remaining shared columns exist
    to verify candidates away).  The capacity model and the match count
    coincide on stars, so the seed is exact on both routes."""
    m, v = _multiway_prefix(terms, order)
    if m < 3:
        return join_rows, caps  # the first join is already exact (dot)
    join_rows, caps = list(join_rows), list(caps)
    for t in range(1, m - 1):
        prefix = [terms[order[j]] for j in range(t + 2)]
        rows, exact = est.multiway_rows(prefix, v)
        if exact:
            join_rows[t] = int(rows)
            caps[t] = pcost.cap_for(rows, max_cap, exact=True)
    return tuple(join_rows), tuple(caps)


def _dp_order(est, terms: List) -> Tuple[int, ...]:
    """Best left-deep order over connected subsets (exact within the
    model).  States key on frozensets of term indices; transitions only
    extend by variable-connected terms, so cross products never enter a
    plan for a connected conjunction."""
    n = len(terms)
    rels = [est.term_estimate(t) for t in terms]
    widths = [len(t.var_names) for t in terms]
    max_cap = _max_capacity(est.db)
    # state -> (cost, order, RelEstimate, width)
    best: Dict[frozenset, Tuple[float, Tuple[int, ...], RelEstimate, int]] = {}
    for i in range(n):
        best[frozenset((i,))] = (
            pcost.term_cost(int(rels[i].rows), widths[i]),
            (i,), rels[i], widths[i],
        )
    for size in range(1, n):
        for state, (c, order, acc, width) in list(best.items()):
            if len(state) != size:
                continue
            for j in range(n):
                if j in state:
                    continue
                if not any(_shares_var(terms[j], terms[i]) for i in state):
                    continue
                out, cap_rows, n_pairs, _exact = _join_step(
                    est, acc, rels[j], terms[j]
                )
                out_width = width + sum(
                    1 for v in terms[j].var_names if v not in acc.dv
                )
                c2 = c + pcost.term_cost(int(rels[j].rows), widths[j])
                c2 += pcost.join_step_cost(
                    acc.rows, width, rels[j].rows, widths[j],
                    n_pairs, cap_rows, out_width, max_cap,
                )
                key = state | {j}
                cur = best.get(key)
                if cur is None or c2 < cur[0]:
                    best[key] = (c2, order + (j,), out, out_width)
    return best[frozenset(range(n))][1]


def _greedy_order(est, terms: List) -> Tuple[int, ...]:
    """Greedy tail for conjunctions past the DP ceiling: start from the
    smallest term, always extend with the connected term minimizing the
    estimated join OUTPUT (selectivity-aware, unlike the legacy
    smallest-term-first)."""
    n = len(terms)
    rels = [est.term_estimate(t) for t in terms]
    start = min(range(n), key=lambda i: rels[i].rows)
    order = [start]
    acc = rels[start]
    remaining = set(range(n)) - {start}
    while remaining:
        connected = [
            j for j in remaining
            if any(_shares_var(terms[j], terms[i]) for i in order)
        ] or list(remaining)
        j = min(
            connected,
            key=lambda j: _join_step(est, acc, rels[j], terms[j])[1],
        )
        acc = _join_step(est, acc, rels[j], terms[j])[0]
        order.append(j)
        remaining.remove(j)
    return tuple(order)


def plan_conjunction(db, plans, *, n_shards: int = 1) -> Optional[PlannedProgram]:
    """Turn a conjunction into a costed whole-plan program, or None when
    the planner declines (no estimator surface, disconnected positives)
    — the caller falls back to the legacy heuristics, answer-identical.

    `n_shards > 1` scales the capacity seeds to PER-SHARD buffers (the
    sharded executor's join_caps unit), with the same 2x skew headroom
    its probe capacities use.

    Pure planning — no counters here: explain() calls this too, and the
    planned/method telemetry must decompose EXECUTOR traffic only (the
    hooks count via planner.record_planned)."""
    if not plans or not isinstance(plans, (list, tuple)):
        return None
    est = estimator_for(db)
    if est is None:
        return None
    pos_idx = [i for i, p in enumerate(plans) if not p.negated]
    neg_idx = [i for i, p in enumerate(plans) if p.negated]
    if not pos_idx:
        return None
    positives = [plans[i] for i in pos_idx]
    if not _connected(positives):
        return None  # cross products: legacy ordering owns the rare case

    # reference-order authority rule — ONE shared predicate with
    # order_plans (see module docstring)
    if reference_order_authoritative(positives):
        order_pos: Tuple[int, ...] = tuple(range(len(positives)))
        method = "ref_order"
    elif len(positives) <= dp_max():
        order_pos = _dp_order(est, positives)
        method = "dp"
    else:
        order_pos = _greedy_order(est, positives)
        method = "greedy_tail"

    join_rows, caps, total, step_costs = _chain_estimates(
        est, positives, order_pos
    )

    # -- multiway routing: fuse a star prefix into one k-way step ------
    # (kernels/multiway.py).  The chain's independence model can only
    # seed the FIRST intermediate exactly (pairwise degree dots); the
    # k-way step's ONE output buffer seeds from the exact intersection
    # product (stats.multiway_rows), so the skew shapes whose deeper
    # intermediates under-seed and pay retry tiers settle in round 0.
    mw = 0
    config = getattr(db, "config", None)
    mode = multiway_mode(config)
    max_cap = _max_capacity(db)
    if mode != "off" and len(positives) >= 2:
        m, v = _multiway_prefix(positives, order_pos)
        if m >= 2:
            prefix = [positives[order_pos[j]] for j in range(m)]
            # every prefix clause materializes as a term table: a clause
            # whose candidate set exceeds the capacity ceiling would
            # make the executor decline the whole job — keep the chain
            # (whose index-join route never materializes it) instead
            feasible = all(
                pcost.pow2_at_least(est.rows(p)) <= max_cap
                for p in prefix
            )
            if feasible:
                mw_rows, mw_exact = est.multiway_rows(prefix, v)
                width0 = len(prefix[0].var_names)
                out_width = len(
                    set().union(*(set(p.var_names) for p in prefix))
                )
                mw_cost = pcost.multiway_step_cost(
                    est.rows(prefix[0]), width0,
                    [(est.rows(p), len(p.var_names)) for p in prefix[1:]],
                    mw_rows, out_width, max_cap,
                )
                if mode == "on" or (
                    m >= MULTIWAY_AUTO_MIN_K
                    and mw_cost < sum(step_costs[: m - 1])
                ):
                    mw = m
                    mw_cap = pcost.cap_for(mw_rows, max_cap, exact=mw_exact)
                    total = total - sum(step_costs[: m - 1]) + mw_cost
                    join_rows = (int(mw_rows),) + join_rows[m - 1:]
                    caps = (mw_cap,) + caps[m - 1:]

    if mw == 0 and len(positives) >= 3:
        # chain route chosen (or forced) over multiway: the deeper
        # star-prefix intermediates reuse the exact k-way statistic
        # instead of the independence model (see _star_chain_seeds)
        join_rows, caps = _star_chain_seeds(
            est, positives, order_pos, join_rows, caps, max_cap
        )

    if n_shards > 1:
        caps = tuple(
            pcost.pow2_at_least(max(64, 2 * (-(-c // n_shards))))
            for c in caps
        )
    order = tuple(pos_idx[i] for i in order_pos) + tuple(neg_idx)
    term_rows = tuple(
        est.rows(plans[i]) for i in order
    )
    from das_tpu import kernels

    kernel = kernels.enabled(config)
    if n_shards > 1:
        if mw:
            route = "sharded_multiway"
        elif kernel:
            route = "sharded_kernel"
        else:
            route = "sharded"
    else:
        if mw:
            route = "fused_multiway"
        elif kernel:
            route = "fused_kernel"
        else:
            route = "fused"
    return PlannedProgram(
        order=order,
        est_term_rows=term_rows,
        est_join_rows=join_rows,
        join_cap_seeds=caps,
        route=route,
        method=method,
        cost=float(total),
        multiway=mw,
    )


# ---------------------------------------------------------------------------
# whole-tree planning (ISSUE 10): one costed program for an Or/Not tree
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PlannedTree:
    """One costed whole-TREE decision (fused Or/negation execution,
    query/tree.py tree_fusion_sites): per-site conjunction plans plus
    the union/anti placement the fused program hard-codes.

    site_plans     — one Optional[PlannedProgram] per positive Or branch
                     (None = the per-site planner declined; the executor
                     falls back to its legacy ordering for that site —
                     the tree still fuses)
    neg_plan       — plan of the joint negative conjunction, when the Or
                     carries syntactic Not children (de-Morgan branch)
    est_site_rows  — estimated final rows per positive site, in site
                     order (the union's concat inputs)
    est_union_rows — estimated union size (sum of sites — the dedup can
                     only shrink it, so this bounds the union buffer)
    union_after    — index into the site list after which the in-program
                     union (concat + dedup) runs; always len(site_plans)
                     (every positive site feeds it) — recorded so
                     explain() renders the placement explicitly
    anti_after_union — the anti-join (negation difference) runs AFTER
                     the union dedup, against the joint-negative table
    route          — "fused_tree" / "sharded_tree_fused" (ROUTE_KEYS,
                     daslint DL008)
    cost           — summed site costs + the union's modeled bytes
    """

    site_plans: Tuple[Optional[PlannedProgram], ...]
    neg_plan: Optional[PlannedProgram]
    est_site_rows: Tuple[int, ...]
    est_union_rows: int
    union_after: int
    anti_after_union: bool
    route: str
    cost: float


def _site_out_rows(db, plans, planned) -> int:
    """Estimated FINAL rows of one conjunction site: the last join's
    estimate when planned, else the largest positive term's exact count
    (the fallback executor's capacity logic never sees an estimate)."""
    if planned is not None and planned.est_join_rows:
        return int(planned.est_join_rows[-1])
    if planned is not None:
        return int(planned.est_term_rows[0])
    est = estimator_for(db)
    pos = [p for p in plans if not p.negated]
    if est is None or not pos:
        return 0
    return max(est.rows(p) for p in pos)


def plan_tree(db, pos_sites, neg_plans=None, *, n_shards: int = 1):
    """Cost and order a whole Or/negation plan tree (ISSUE 10): one
    PlannedProgram per conjunction site (plan_conjunction — Selinger
    order + capacity seeds, counts nothing), the union buffer estimate,
    and the union/anti placement.  Returns None when there is nothing
    to plan (no sites) — the caller keeps the tree executor.

    Pure planning, like plan_conjunction: explain() calls this too, so
    no counters fire here (the executors' tree jobs count per site via
    the ordinary record_planned hook)."""
    if not pos_sites and not neg_plans:
        return None
    site_plans = tuple(
        plan_conjunction(db, list(site), n_shards=n_shards)
        for site in pos_sites
    )
    neg_plan = (
        plan_conjunction(db, list(neg_plans), n_shards=n_shards)
        if neg_plans else None
    )
    site_rows = tuple(
        _site_out_rows(db, site, planned)
        for site, planned in zip(pos_sites, site_plans)
    )
    union_rows = int(sum(site_rows))
    out_width = max(
        (len({v for p in site if not p.negated for v in p.var_names})
         for site in pos_sites),
        default=1,
    )
    cost = sum(p.cost for p in site_plans if p is not None)
    if neg_plan is not None:
        cost += neg_plan.cost
    # the union's modeled bytes: one concat + dedup pass over the
    # summed site windows (sort-dominated, priced as materialization)
    cost += float(union_rows) * max(out_width, 1) * pcost.ROW_BYTES
    route = "sharded_tree_fused" if n_shards > 1 else "fused_tree"
    return PlannedTree(
        site_plans=site_plans,
        neg_plan=neg_plan,
        est_site_rows=site_rows,
        est_union_rows=union_rows,
        union_after=len(site_plans),
        anti_after_union=neg_plans is not None and bool(neg_plans),
        route=route,
        cost=float(cost),
    )
