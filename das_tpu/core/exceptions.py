"""Framework exceptions (parity with /root/reference/das/exceptions.py:3-22)."""


class DasError(Exception):
    pass


class MettaLexerError(DasError):
    pass


class MettaSyntaxError(DasError):
    pass


class AtomeseLexerError(DasError):
    pass


class AtomeseSyntaxError(DasError):
    pass


class UndefinedSymbolError(DasError):
    def __init__(self, symbols):
        self.symbols = symbols
        super().__init__(f"Undefined symbols: {symbols}")


class InvalidHandleError(DasError):
    pass


class CapacityOverflowError(DasError):
    """A fixed-capacity device buffer overflowed; caller should retry with a
    larger capacity (see das_tpu.ops capacities)."""


class CoalescerSaturatedError(DasError):
    """The serving coalescer's submit queue hit its backpressure bound
    (DasConfig.coalesce_queue_max, service/coalesce.py): the request was
    rejected instead of growing host memory without limit; retry later."""
