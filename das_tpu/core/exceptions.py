"""Framework exceptions (parity with /root/reference/das/exceptions.py:3-22)."""


class DasError(Exception):
    pass


class MettaLexerError(DasError):
    pass


class MettaSyntaxError(DasError):
    pass


class AtomeseLexerError(DasError):
    pass


class AtomeseSyntaxError(DasError):
    pass


class UndefinedSymbolError(DasError):
    def __init__(self, symbols):
        self.symbols = symbols
        super().__init__(f"Undefined symbols: {symbols}")


class InvalidHandleError(DasError):
    pass


class CapacityOverflowError(DasError):
    """A fixed-capacity device buffer overflowed; caller should retry with a
    larger capacity (see das_tpu.ops capacities)."""


class CoalescerSaturatedError(DasError):
    """The serving coalescer's submit queue hit its backpressure bound
    (DasConfig.coalesce_queue_max, service/coalesce.py): the request was
    rejected instead of growing host memory without limit; retry later."""


class InjectedFault(DasError):
    """A deterministic injected failure (das_tpu/fault maybe_fail):
    raised at a declared FAULT_SITES seam by an armed DAS_TPU_FAULT
    schedule — typed so chaos runs can tell injection from real bugs,
    retryable so it exercises the same recovery machinery a transient
    transport failure would."""

    def __init__(self, site: str, call: int, retryable: bool = True):
        self.site = site
        self.call = call
        self.retryable = retryable
        super().__init__(f"injected fault at site '{site}' (call {call})")


class DasDeadlineError(DasError):
    """A query exceeded its deadline (DasConfig.query_deadline_ms, env
    DAS_TPU_DEADLINE_MS): expired by the coalescer worker while queued/
    grouped, abandoned host-side at settle, or timed out at the bounded
    RPC wait (service/server.py) — no RPC thread blocks forever.
    Retryable: the answer was never computed, only not delivered in
    time."""

    def __init__(self, msg: str = "query deadline exceeded",
                 deadline_ms: float = 0.0):
        self.deadline_ms = deadline_ms
        super().__init__(msg)


class SnapshotCorruptError(DasError):
    """A persisted snapshot generation (or its write-ahead log) failed
    verification (das_tpu/storage/durable.py): a section's CRC did not
    match its manifest digest, the manifest itself is torn/absent, or
    WAL replay broke the delta_version continuity check.  Restore
    NEVER serves unverified bytes — it falls back to the newest valid
    prior generation, and raises this typed error only when no valid
    generation exists at all."""


class BreakerOpenError(DasError):
    """The tenant's serving circuit breaker is open (degraded mode,
    das_tpu/fault CircuitBreaker + service/coalesce.py): cache-hit
    answers are still served, but this query needed a fresh device
    dispatch and was rejected retryable.  `retry_after_ms` hints when
    the next half-open probe may restore service."""

    def __init__(self, msg: str = "circuit breaker open; retry later",
                 retry_after_ms: float = None):
        self.retry_after_ms = retry_after_ms
        super().__init__(msg)
