"""Parsed-expression record.

One `Expression` carries a fully hashed MeTTa/Atomese expression on its way
from a parser into the columnar store.  Field semantics match the reference
record (/root/reference/das/expression.py:6-56): `composite_type` is the
nested type-signature list (e.g. ``[Similarity_h, Concept_h, Concept_h]``,
with sub-lists for nested sub-expressions), `elements` the target handles,
`hash_code` the atom's own handle.

The reference's `to_dict()` emitted a MongoDB document (key_0/key_1 vs a
`keys` list split by arity).  The TPU build stores atoms columnar — see
`das_tpu.storage.atom_table` — but `to_dict()` is kept for API-parity
surfaces (`get_atom_as_dict`) and checkpoint metadata.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, List, Optional


@dataclass
class Expression:
    toplevel: bool = False
    ordered: bool = True
    terminal_name: Optional[str] = None
    typedef_name: Optional[str] = None
    typedef_name_hash: Optional[str] = None
    symbol_name: Optional[str] = None
    named_type: Optional[str] = None
    named_type_hash: Optional[str] = None
    composite_type: Optional[List[Any]] = None
    composite_type_hash: Optional[str] = None
    elements: Optional[List[str]] = None
    hash_code: Optional[str] = None

    def __hash__(self):
        return hash(self.hash_code)

    @property
    def is_terminal(self) -> bool:
        return self.terminal_name is not None

    @property
    def is_typedef(self) -> bool:
        return self.typedef_name is not None

    @property
    def arity(self) -> int:
        return len(self.elements) if self.elements else 0

    def to_dict(self) -> dict:
        assert self.ordered
        answer = {
            "_id": self.hash_code,
            "composite_type_hash": self.composite_type_hash,
        }
        if self.typedef_name is not None:
            answer["named_type"] = self.typedef_name
            answer["named_type_hash"] = self.typedef_name_hash
        elif self.terminal_name is not None:
            answer["name"] = self.terminal_name
            answer["named_type"] = self.named_type
        else:
            answer["is_toplevel"] = self.toplevel
            answer["composite_type"] = self.composite_type
            answer["named_type"] = self.named_type
            answer["named_type_hash"] = self.named_type_hash
            arity = len(self.elements)
            assert arity > 0
            if arity > 2:
                answer["keys"] = self.elements
            else:
                answer["key_0"] = self.elements[0]
                if arity > 1:
                    answer["key_1"] = self.elements[1]
        return answer

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=False, indent=4)
