"""One typed configuration object.

Replaces the reference's three config mechanisms (env vars, module-level
flag constants, argparse — SURVEY.md §5) with a single dataclass.  Env vars
are still honored as *overrides* so container deployments keep working.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional, Tuple


@dataclass
class DasConfig:
    # --- storage / backend selection -------------------------------------
    backend: str = "tensor"          # "memory" | "tensor" | "sharded"
    platform: Optional[str] = None   # None = jax default; "cpu" to force host
    # checkpoint dir auto-loaded at construction — the TPU-native analogue
    # of the reference's env-var Mongo/Redis endpoints: a bare
    # `DistributedAtomSpace()` (reference scripts/benchmark.py:203) attaches
    # to this persisted store instead of a database server
    checkpoint_path: Optional[str] = None

    # --- mesh / sharding --------------------------------------------------
    mesh_shape: Optional[Tuple[int, ...]] = None  # None = all local devices
    mesh_axis_names: Tuple[str, ...] = ("shards",)

    # --- query engine -----------------------------------------------------
    no_overload: bool = False  # forbid two vars sharing a value in ordered asn
    # capacity (rows) for padded device result buffers; doubled on overflow
    initial_result_capacity: int = 1 << 14
    max_result_capacity: int = 1 << 24
    # incremental commits: total delta atoms held as an LSM overlay before
    # the store is fully re-finalized (storage/tensor_db.py refresh)
    delta_merge_threshold: int = 1 << 16
    # Pallas fused probe→gather→join kernels (das_tpu/kernels/):
    # "auto" = on for TPU, off elsewhere; "on" forces them (off-TPU they
    # run in interpret mode — answer-identical, used by the differential
    # suite and the bench A/B); "off" forces the lowered op chains.
    # Env DAS_TPU_PALLAS overrides (see das_tpu/kernels/__init__.py).
    use_pallas_kernels: str = "auto"
    # sharded backend: where unordered/negated/nested query trees run —
    # "mesh" (default: the tree evaluator with row-sharded composite
    # tables, parallel/sharded_tree.py), "tensor" (legacy single-device
    # tree over a replicated store copy), or "host"
    sharded_tree_fallback: str = "mesh"

    # --- serving edge -----------------------------------------------------
    # widest batch one coalescer drain may form (service/coalesce.py); the
    # served path's throughput knob — BENCH_r05 showed per-query cost
    # halving as concurrency doubles, so deployments need to tune this
    coalesce_max_batch: int = 256
    # coalescer execution pipelining (service/coalesce.py): how many
    # dispatched-but-unsettled batches may be in flight at once.  Depth 2
    # lets batch N+1's device program execute while batch N's host
    # settle/materialization runs; 1 restores strictly serial batches.
    pipeline_depth: int = 2
    # device-resident query result cache (query/fused.py ResultCache):
    # max cached results per executor, keyed by plan shape + grounded
    # values and guarded by the backend's incremental-commit counter
    # (storage/delta.py delta_version) so commits invalidate stale
    # entries.  0 disables.  Consulted by the serving/batched paths —
    # repeated hot queries skip the device entirely.
    result_cache_size: int = 256

    # --- ingest -----------------------------------------------------------
    pattern_black_list: List[str] = field(default_factory=list)
    ingest_chunk_size: int = 10_000_000
    use_native_ingest: bool = True   # C++ fast path when the .so is present

    # --- observability ----------------------------------------------------
    log_file: str = "/tmp/das_tpu.log"
    log_level: str = "INFO"

    @staticmethod
    def from_env(**overrides) -> "DasConfig":
        cfg = DasConfig(**overrides)
        backend = os.environ.get("DAS_TPU_BACKEND")
        if backend:
            cfg.backend = backend
        platform = os.environ.get("DAS_TPU_PLATFORM")
        if platform:
            cfg.platform = platform
        checkpoint = os.environ.get("DAS_TPU_CHECKPOINT")
        if checkpoint:
            cfg.checkpoint_path = checkpoint
        pallas = os.environ.get("DAS_TPU_PALLAS")
        if pallas:
            cfg.use_pallas_kernels = pallas
        max_batch = os.environ.get("DAS_TPU_COALESCE_MAX_BATCH")
        if max_batch:
            cfg.coalesce_max_batch = int(max_batch)
        depth = os.environ.get("DAS_TPU_PIPELINE_DEPTH")
        if depth:
            cfg.pipeline_depth = int(depth)
        cache = os.environ.get("DAS_TPU_RESULT_CACHE")
        if cache:
            cfg.result_cache_size = int(cache)
        return cfg
