"""One typed configuration object.

Replaces the reference's three config mechanisms (env vars, module-level
flag constants, argparse — SURVEY.md §5) with a single dataclass.  Env vars
are still honored as *overrides* so container deployments keep working.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: THE declared set of DAS_TPU_* environment flags, mapping each name to
#: (DasConfig field or None for module-local flags, one-line description).
#: daslint rule DL003 (das_tpu/analysis) pins this registry against the
#: code in both directions — an `os.environ` read of an undeclared name
#: fails lint, and so does a registered name nothing reads — and
#: scripts/gen_env_table.py renders it into ARCHITECTURE.md §11 so the
#: operator docs cannot drift from the code either.  Module-local flags
#: (field None) are debug/bring-up switches read at their point of use;
#: anything a deployment should tune belongs on DasConfig.
ENV_REGISTRY: Dict[str, Tuple[Optional[str], str]] = {
    "DAS_TPU_BACKEND": (
        "backend", "storage backend: memory / tensor / sharded"),
    "DAS_TPU_PLATFORM": (
        "platform", "force a jax platform (e.g. cpu) for the store"),
    "DAS_TPU_CHECKPOINT": (
        "checkpoint_path",
        "checkpoint dir auto-loaded by a bare DistributedAtomSpace()"),
    "DAS_TPU_PALLAS": (
        "use_pallas_kernels",
        "kernel routing: auto (TPU-only) / on / off "
        "(das_tpu/kernels/__init__.py enabled())"),
    "DAS_TPU_PLANNER": (
        "use_planner",
        "cost-based query planner: auto (on) / on / off "
        "(das_tpu/planner/__init__.py enabled())"),
    "DAS_TPU_PLANNER_DP_MAX": (
        None,
        "clause ceiling for the planner's exact DP join-order search; "
        "larger conjunctions order greedily (das_tpu/planner/search.py; "
        "default 8)"),
    "DAS_TPU_MULTIWAY": (
        "use_multiway",
        "k-way multiway join kernel routing: auto (cost-based, star "
        "prefixes of >=3 clauses) / on (every eligible prefix) / off "
        "(das_tpu/planner/search.py multiway_mode())"),
    "DAS_TPU_TREE_FUSION": (
        "use_tree_fusion",
        "whole-tree fused execution of Or/negation plan trees: auto "
        "(on) / on / off (das_tpu/query/tree.py tree_fusion_enabled())"),
    "DAS_TPU_COALESCE_MAX_BATCH": (
        "coalesce_max_batch",
        "widest batch one coalescer drain may form (service/coalesce.py)"),
    "DAS_TPU_PIPELINE_DEPTH": (
        "pipeline_depth",
        "floor of the in-flight dispatch window; 1 = serial (no "
        "adaptation)"),
    "DAS_TPU_PIPELINE_DEPTH_MAX": (
        "pipeline_depth_max",
        "ceiling of the RTT-adaptive in-flight window "
        "(service/coalesce.py sizes it as ceil(rtt/dispatch_cost))"),
    "DAS_TPU_COALESCE_QUEUE_MAX": (
        "coalesce_queue_max",
        "coalescer submit-queue backpressure bound; past it submits "
        "are rejected (CoalescerSaturatedError); 0 = unbounded"),
    "DAS_TPU_RESULT_CACHE": (
        "result_cache_size",
        "delta-versioned result cache entries per executor; 0 disables"),
    "DAS_TPU_DEADLINE_MS": (
        "query_deadline_ms",
        "per-query serving deadline in ms: queued/grouped entries past "
        "it expire with DasDeadlineError and RPC waits are bounded "
        "(service/coalesce.py, service/server.py); 0 = off"),
    "DAS_TPU_BREAKER_THRESHOLD": (
        "breaker_failure_threshold",
        "consecutive retryable settle failures that trip a tenant's "
        "serving circuit breaker to degraded mode (das_tpu/fault "
        "CircuitBreaker); 0 disables the breaker"),
    "DAS_TPU_BREAKER_COOLDOWN_MS": (
        "breaker_cooldown_ms",
        "open-breaker cooldown before a half-open probe may restore "
        "full service (das_tpu/fault CircuitBreaker)"),
    "DAS_TPU_FAULT": (
        None,
        "deterministic fault-injection spec, e.g. "
        "seed=7;sites=settle_fetch,commit_apply;rate=0.25;max=4 "
        "(das_tpu/fault; unset = off, no-allocation fast path)"),
    "DAS_TPU_SNAPSHOT_DIR": (
        "snapshot_dir",
        "dasdur snapshot root (storage/durable.py): crash-consistent "
        "generational snapshots + write-ahead delta log auto-attach; a "
        "bare DistributedAtomSpace() restores the newest valid "
        "generation + WAL replay; unset = no durability"),
    "DAS_TPU_WAL": (
        "wal",
        "write-ahead delta log mode: auto (armed whenever a snapshot "
        "root is attached) / off (snapshots only — commits after the "
        "last snapshot are lost on crash) (storage/durable.py "
        "wal_enabled)"),
    "DAS_TPU_SNAPSHOT_KEEP": (
        "snapshot_keep",
        "completed snapshot generations retained after each new "
        "snapshot (storage/durable.py prune_generations; default 2)"),
    "DAS_TPU_VMEM_BUDGET": (
        None,
        "kernel VMEM byte budget for the bytes planner "
        "(kernels/budget.py; default 8 MiB = half-core VMEM)"),
    "DAS_TPU_PALLAS_INTERPRET": (
        None,
        "=1 forces the true Pallas interpreter off-TPU instead of the "
        "direct ref-discharge (kernels/common.py; ~2-5 s compile/site)"),
    "DAS_TPU_XLA_CACHE": (
        None,
        "persistent XLA compile cache dir (das_tpu/__init__.py, "
        "CapStore placement in query/fused.py); =0 disables"),
    "DAS_TPU_COALESCE": (
        None, "=0 disables serving-edge query coalescing "
              "(service/server.py)"),
    "DAS_TPU_STAR": (
        None, "=0 disables the star-count degree-product fast path "
              "(query/starcount.py)"),
    "DAS_TPU_STAR_FOLD": (
        None, "star-count fold placement: host (default) / device "
              "(query/starcount.py)"),
    "DAS_TPU_HOST_COUNT": (
        None, "=0 disables the host-side count shortcut in the fused "
              "executor (query/fused.py)"),
    "DAS_TPU_LOOP_BARRIER": (
        None, "=1 inserts a debug barrier between fused-loop stages "
              "(query/fused.py)"),
    "DAS_TPU_COLUMNAR": (
        None, "=0 disables the columnar ingest fast path "
              "(ingest/pipeline.py)"),
    "DAS_TPU_NO_NATIVE": (
        None, "set to skip the C++ native ingest .so (ingest/native.py)"),
    "DAS_TPU_NATIVE_LIB": (
        None, "override path of the native ingest .so (ingest/native.py)"),
    "DAS_TPU_FINALIZE_VERBOSE": (
        None, "set to log per-phase columnar finalize timings "
              "(storage/columnar.py)"),
    "DAS_TPU_TEST_PLATFORM": (
        None, "test-suite jax platform override (tests/conftest.py; "
              "default cpu with an 8-device virtual mesh)"),
    "DAS_TPU_TRACE": (
        None, "=1/on enables the structured trace recorder + metric "
              "layer (das_tpu/obs; default off = no-allocation no-op)"),
    "DAS_TPU_PROFLOG": (
        None, "=1/on enables the program ledger — per-signature XLA "
              "compile wall time, cost/memory analysis, byte-model "
              "calibration (das_tpu/obs/proflog.py; default off = "
              "identity fast path, programs run exactly un-instrumented)"),
    "DAS_TPU_TRACE_RING": (
        None, "span ring-buffer capacity of the trace recorder "
              "(das_tpu/obs/recorder.py; default 65536, oldest drop)"),
    "DAS_TPU_TRACE_JAX": (
        None, "=1 wraps the dispatch/settle halves in jax.profiler "
              "TraceAnnotation scopes (das_tpu/obs/jaxprof.py) so host "
              "spans line up with the XLA device timeline"),
    "DAS_TPU_TRACE_DIR": (
        "profiler_trace_dir",
        "jax.profiler start_trace output dir (obs/jaxprof.py "
        "maybe_start_trace; unset = no device trace)"),
    "DAS_TPU_METRICS_PORT": (
        None, "Prometheus text-exposition HTTP port on the service "
              "(service/server.py GET /metrics); unset/0 = off; setting "
              "it implies DAS_TPU_TRACE=1 unless that is explicitly 0"),
}

#: registry names whose readers live outside das_tpu/ (DL003 skips its
#: "declared but never read" leg for these)
ENV_DECLARED_EXTERNAL: Tuple[str, ...] = ("DAS_TPU_TEST_PLATFORM",)


@dataclass
class DasConfig:
    # --- storage / backend selection -------------------------------------
    backend: str = "tensor"          # "memory" | "tensor" | "sharded"
    platform: Optional[str] = None   # None = jax default; "cpu" to force host
    # checkpoint dir auto-loaded at construction — the TPU-native analogue
    # of the reference's env-var Mongo/Redis endpoints: a bare
    # `DistributedAtomSpace()` (reference scripts/benchmark.py:203) attaches
    # to this persisted store instead of a database server
    checkpoint_path: Optional[str] = None
    # dasdur durability root (ISSUE 15, storage/durable.py): when set, a
    # bare DistributedAtomSpace() RESTORES the newest valid snapshot
    # generation + WAL replay (seconds instead of minutes for a replica
    # cold start), and live commits append fsynced write-ahead records —
    # a crash loses nothing past the last completed fsync.  None = no
    # durability (the pre-dasdur behavior exactly).
    snapshot_dir: Optional[str] = None
    # write-ahead delta log mode: "auto" arms the WAL whenever a
    # snapshot root is attached; "off" keeps snapshots only (commits
    # after the last snapshot are lost on crash)
    wal: str = "auto"
    # completed snapshot generations kept after each new snapshot
    # (older ones — and their WALs — are pruned)
    snapshot_keep: int = 2

    # --- mesh / sharding --------------------------------------------------
    mesh_shape: Optional[Tuple[int, ...]] = None  # None = all local devices
    mesh_axis_names: Tuple[str, ...] = ("shards",)

    # --- query engine -----------------------------------------------------
    no_overload: bool = False  # forbid two vars sharing a value in ordered asn
    # capacity (rows) for padded device result buffers; doubled on overflow
    initial_result_capacity: int = 1 << 14
    max_result_capacity: int = 1 << 24
    # incremental commits: total delta atoms held as an LSM overlay before
    # the store is fully re-finalized (storage/tensor_db.py refresh)
    delta_merge_threshold: int = 1 << 16
    # Pallas fused probe→gather→join kernels (das_tpu/kernels/):
    # "auto" = on for TPU, off elsewhere; "on" forces them (off-TPU they
    # run in interpret mode — answer-identical, used by the differential
    # suite and the bench A/B); "off" forces the lowered op chains.
    # Env DAS_TPU_PALLAS overrides (see das_tpu/kernels/__init__.py).
    use_pallas_kernels: str = "auto"
    # cost-based whole-plan query planner (das_tpu/planner/): cardinality
    # estimates from the wildcard-index degree statistics pick join
    # order, expected route, and the initial capacity of every
    # intermediate BEFORE anything is dispatched — replacing the
    # greedy smallest-first ordering and the blind
    # initial_result_capacity seed so most queries settle in retry
    # round 0.  "auto" = on (the planner is pure host arithmetic);
    # "off" restores the legacy heuristics (the bench A/B flips this).
    # Env DAS_TPU_PLANNER overrides (see das_tpu/planner/__init__.py).
    use_planner: str = "auto"
    # worst-case-optimal k-way multiway join kernel (das_tpu/kernels/
    # multiway.py): when the planner finds a star prefix — consecutive
    # clauses all sharing exactly ONE variable — it can ground them in
    # one leapfrog-intersection pass instead of a binary-join chain
    # with materialized intermediates.  "auto" = cost-based (prefixes
    # of >=3 clauses whose modeled bytes beat the chain); "on" routes
    # every eligible prefix (>=2 clauses — what the differential tests
    # force); "off" restores the pure binary chain.  Routed by the
    # planner only (use_planner off disables it too).  Env
    # DAS_TPU_MULTIWAY overrides (see das_tpu/planner/search.py).
    use_multiway: str = "auto"
    # whole-tree fused execution (ISSUE 10): an Or/negation plan tree
    # whose every node is an ordered conjunction over one shared
    # variable universe compiles to ONE planner-costed program — every
    # conjunction site plus the in-program union (concat + dedup) and
    # negation (anti-join) settle in a single dispatch/transfer, where
    # the tree executor pays one dispatch/settle round trip per site.
    # "auto" = on (answers are bit-identical to the tree executor —
    # ineligible shapes fall back to it); "off" restores per-site tree
    # execution (the bench A/B flips this).  Env DAS_TPU_TREE_FUSION
    # overrides (see das_tpu/query/tree.py tree_fusion_enabled()).
    use_tree_fusion: str = "auto"
    # sharded backend: where unordered/negated/nested query trees run —
    # "mesh" (default: the tree evaluator with row-sharded composite
    # tables, parallel/sharded_tree.py), "tensor" (legacy single-device
    # tree over a replicated store copy), or "host"
    sharded_tree_fallback: str = "mesh"

    # --- serving edge -----------------------------------------------------
    # widest batch one coalescer drain may form (service/coalesce.py); the
    # served path's throughput knob — BENCH_r05 showed per-query cost
    # halving as concurrency doubles, so deployments need to tune this
    coalesce_max_batch: int = 256
    # coalescer execution pipelining (service/coalesce.py): the FLOOR of
    # the in-flight dispatch window.  Depth 2 lets batch N+1's device
    # program execute while batch N's host settle/materialization runs;
    # 1 restores strictly serial batches (and disables adaptation).
    pipeline_depth: int = 2
    # ceiling of the RTT-adaptive window: the worker sizes the window to
    # ceil(settle_rtt / dispatch_cost) from its own EWMAs — on a
    # tunneled TPU (~100 ms settle vs ~ms dispatch) it deepens toward
    # this bound; on local dispatch the ratio stays near 1 and the
    # pipeline_depth floor holds
    pipeline_depth_max: int = 8
    # backpressure bound on the coalescer submit queue: past it,
    # submit() rejects with CoalescerSaturatedError instead of letting
    # an open-loop client population grow host memory without limit.
    # 0 = unbounded (the pre-bound behavior).
    coalesce_queue_max: int = 8192
    # per-query serving deadline (ms): the coalescer worker expires
    # queued/grouped entries past it with a typed DasDeadlineError,
    # settle abandons expired futures host-side, and the RPC wait in
    # service/server.py is bounded — no RPC thread ever blocks forever.
    # 0 = off (the pre-deadline behavior exactly).
    query_deadline_ms: int = 0
    # per-tenant serving circuit breaker (das_tpu/fault CircuitBreaker,
    # driven by service/coalesce.py): this many CONSECUTIVE
    # retryable-class settle failures (or saturation rejections) trip
    # the tenant to degraded mode — speculation off, window at its
    # floor, cache-hit answers still served, fresh dispatches rejected
    # retryable with a retry-after hint.  0 disables the breaker.
    breaker_failure_threshold: int = 8
    # how long an OPEN breaker waits before granting ONE half-open
    # probe; the probe's success restores full service, its failure
    # restarts the cooldown
    breaker_cooldown_ms: int = 250
    # device-resident query result cache (query/fused.py ResultCache):
    # max cached results per executor, keyed by plan shape + grounded
    # values and guarded by the backend's incremental-commit counter
    # (storage/delta.py delta_version) so commits invalidate stale
    # entries.  0 disables.  Consulted by the serving/batched paths —
    # repeated hot queries skip the device entirely.
    result_cache_size: int = 256

    # --- ingest -----------------------------------------------------------
    pattern_black_list: List[str] = field(default_factory=list)
    ingest_chunk_size: int = 10_000_000
    use_native_ingest: bool = True   # C++ fast path when the .so is present

    # --- observability ----------------------------------------------------
    log_file: str = "/tmp/das_tpu.log"
    log_level: str = "INFO"
    # jax.profiler start_trace output directory (env DAS_TPU_TRACE_DIR):
    # when set (and the obs layer is on), serve()/dump_trace start a
    # device trace here so the hardware run can correlate host spans
    # (das_tpu/obs) with the XLA device timeline in Perfetto.  None =
    # no device trace (the default; host-side tracing is independent).
    profiler_trace_dir: Optional[str] = None

    @staticmethod
    def from_env(**overrides) -> "DasConfig":
        cfg = DasConfig(**overrides)
        backend = os.environ.get("DAS_TPU_BACKEND")
        if backend:
            cfg.backend = backend
        platform = os.environ.get("DAS_TPU_PLATFORM")
        if platform:
            cfg.platform = platform
        checkpoint = os.environ.get("DAS_TPU_CHECKPOINT")
        if checkpoint:
            cfg.checkpoint_path = checkpoint
        snapshot_dir = os.environ.get("DAS_TPU_SNAPSHOT_DIR")
        if snapshot_dir:
            cfg.snapshot_dir = snapshot_dir
        wal = os.environ.get("DAS_TPU_WAL")
        if wal:
            cfg.wal = wal
        snapshot_keep = os.environ.get("DAS_TPU_SNAPSHOT_KEEP")
        if snapshot_keep:
            cfg.snapshot_keep = int(snapshot_keep)
        pallas = os.environ.get("DAS_TPU_PALLAS")
        if pallas:
            cfg.use_pallas_kernels = pallas
        planner = os.environ.get("DAS_TPU_PLANNER")
        if planner:
            cfg.use_planner = planner
        multiway = os.environ.get("DAS_TPU_MULTIWAY")
        if multiway:
            cfg.use_multiway = multiway
        tree_fusion = os.environ.get("DAS_TPU_TREE_FUSION")
        if tree_fusion:
            cfg.use_tree_fusion = tree_fusion
        max_batch = os.environ.get("DAS_TPU_COALESCE_MAX_BATCH")
        if max_batch:
            cfg.coalesce_max_batch = int(max_batch)
        depth = os.environ.get("DAS_TPU_PIPELINE_DEPTH")
        if depth:
            cfg.pipeline_depth = int(depth)
        depth_max = os.environ.get("DAS_TPU_PIPELINE_DEPTH_MAX")
        if depth_max:
            cfg.pipeline_depth_max = int(depth_max)
        queue_max = os.environ.get("DAS_TPU_COALESCE_QUEUE_MAX")
        if queue_max:
            cfg.coalesce_queue_max = int(queue_max)
        cache = os.environ.get("DAS_TPU_RESULT_CACHE")
        if cache:
            cfg.result_cache_size = int(cache)
        deadline = os.environ.get("DAS_TPU_DEADLINE_MS")
        if deadline:
            cfg.query_deadline_ms = int(deadline)
        breaker_threshold = os.environ.get("DAS_TPU_BREAKER_THRESHOLD")
        if breaker_threshold:
            cfg.breaker_failure_threshold = int(breaker_threshold)
        breaker_cooldown = os.environ.get("DAS_TPU_BREAKER_COOLDOWN_MS")
        if breaker_cooldown:
            cfg.breaker_cooldown_ms = int(breaker_cooldown)
        trace_dir = os.environ.get("DAS_TPU_TRACE_DIR")
        if trace_dir:
            cfg.profiler_trace_dir = trace_dir
        return cfg
