"""Content-addressed atom identity.

Every atom (typed node or variable-arity link) is identified by an md5 hex
digest, byte-for-byte compatible with the reference hasher
(/root/reference/das/expression_hasher.py:4-35):

  * ``named_type_hash(t)   = md5(t)``
  * ``terminal_hash(t, n)  = md5(t + " " + n)``
  * ``expression_hash(th, targets) = composite_hash([th, *targets])``
  * ``composite_hash([x])  = x``  (singleton collapse)
  * ``composite_hash(xs)   = md5(" ".join(xs))``

TPU-first design: hex strings never reach the device.  Each 128-bit digest is
truncated to a signed int64 (first 8 bytes, big-endian) which is the *device
handle* used in every HBM-resident table.  The full hex digest survives only
in host-side dictionaries at the API boundary, so result sets can be reported
with reference-identical handles.  At 2^64 key space, the collision
probability for a 10^9-atom KB is ~2.7e-2 ppm (birthday bound) — and the
host-side hex map detects any collision at ingest time.
"""

from __future__ import annotations

from hashlib import md5
from typing import Any, Iterable, List, Sequence, Union

import numpy as np

COMPOUND_SEPARATOR = " "

# Signed-int64 device handle for the wildcard '*' sentinel is never needed:
# wildcards are compile-time structure, not data.  Still, reserve a sentinel
# for "empty slot" in device hash tables / padded target columns.
EMPTY_I64 = np.int64(-(2**63))  # never produced by digest truncation (see below)
I64_PAD_MAX = 2**63 - 1  # capacity-pad sentinel; also excluded from key range


def compute_hash(text: str) -> str:
    """md5 hex digest of utf-8 text (reference `_compute_hash`)."""
    return md5(text.encode("utf-8")).hexdigest()


def named_type_hash(name: str) -> str:
    return compute_hash(name)


def terminal_hash(named_type: str, terminal_name: str) -> str:
    return compute_hash(named_type + COMPOUND_SEPARATOR + terminal_name)


def composite_hash(hash_base: Union[str, List[str]]) -> str:
    if isinstance(hash_base, str):
        return hash_base
    if isinstance(hash_base, list):
        if len(hash_base) == 1:
            return hash_base[0]
        return compute_hash(COMPOUND_SEPARATOR.join(hash_base))
    raise ValueError(
        f"Invalid base to compute composite hash: {type(hash_base)}: {hash_base}"
    )


def expression_hash(type_hash: str, elements: Sequence[str]) -> str:
    return composite_hash([type_hash, *elements])


class ExpressionHasher:
    """Namespace-compatible facade mirroring the reference class."""

    compound_separator = COMPOUND_SEPARATOR
    _compute_hash = staticmethod(compute_hash)
    named_type_hash = staticmethod(named_type_hash)
    terminal_hash = staticmethod(terminal_hash)
    composite_hash = staticmethod(composite_hash)
    expression_hash = staticmethod(expression_hash)


class StringExpressionHasher:
    """Debug variant producing READABLE handles instead of digests
    (reference expression_hasher.py:38-60: `<Concept: human>` — the handle
    style the reference StubDB exposes).  Never used on the device path."""

    @staticmethod
    def _compute_hash(text: str) -> str:
        return str(text)

    @staticmethod
    def named_type_hash(name: str) -> str:
        return f"<Type: {name}>"

    @staticmethod
    def terminal_hash(named_type: str, terminal_name: str) -> str:
        return f"<{named_type}: {terminal_name}>"

    @staticmethod
    def expression_hash(named_type_hash: str, elements: List[str]) -> str:
        return f"<{named_type_hash}: {elements}>"

    @staticmethod
    def composite_hash(hash_list: List[str]) -> str:
        if len(hash_list) == 1:
            return hash_list[0]
        return f"{hash_list}"


# ---------------------------------------------------------------------------
# Device handles: 64-bit truncation
# ---------------------------------------------------------------------------

def hex_to_i64(hex_digest: str) -> np.int64:
    """First 8 bytes of the digest as a signed big-endian int64.

    Two sentinel values are excluded from the real-key range so that no
    digest can collide with a table sentinel:

      * EMPTY_I64 (int64 min) — the "empty slot" marker — remaps to min+1;
      * int64 max — the capacity-pad marker used by the tensor store's
        padded buckets (storage/tensor_db.py) — remaps to max-1.
    """
    v = int(hex_digest[:16], 16)
    if v >= 2**63:
        v -= 2**64
    if v == int(EMPTY_I64):
        v += 1
    elif v == I64_PAD_MAX:
        v -= 1
    return np.int64(v)


def hex_to_i64_bulk(hex_digests) -> np.ndarray:
    """Vectorized `hex_to_i64` over a sequence of hex digests.

    Columnizing a multi-million-link bucket (storage/atom_table.py
    build_bucket) calls this once per bucket instead of the scalar
    function per link — the ASCII→nibble decode runs as 16 numpy vector
    ops.  Bit-exact with the scalar version incl. the sentinel remap."""
    m = len(hex_digests)
    if m == 0:
        return np.empty(0, dtype=np.int64)
    # dtype "S16" ascii-encodes and truncates each digest to its first 16
    # chars — exactly the 8 bytes the scalar version parses
    u = np.array(hex_digests, dtype="S16").view(np.uint8).reshape(m, 16)
    is_hex = (
        ((u >= 48) & (u <= 57)) | ((u >= 97) & (u <= 102)) | ((u >= 65) & (u <= 70))
    )
    if not is_hex.all():
        # non-hex char or a digest shorter than 16 chars (NUL padding from
        # the "S16" cast) — take the scalar path, which parses (or raises)
        # exactly like int(x, 16)
        return np.array([hex_to_i64(h) for h in hex_digests], dtype=np.int64)
    nib = np.where(
        u >= 97, u - 87, np.where(u >= 65, u - 55, u - 48)
    ).astype(np.uint64)
    val = np.zeros(m, dtype=np.uint64)
    for k in range(16):
        val = (val << np.uint64(4)) | nib[:, k]
    out = val.view(np.int64).copy()  # two's complement == the v-2**64 branch
    out[out == EMPTY_I64] += 1
    out[out == I64_PAD_MAX] -= 1
    return out


def i64_hash_str(text: str) -> np.int64:
    return hex_to_i64(compute_hash(text))


def hex_list_to_i64(hex_digests: Iterable[str]) -> np.ndarray:
    return np.array([hex_to_i64(h) for h in hex_digests], dtype=np.int64)


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Cheap 64-bit finalizer used to derive secondary probe offsets for
    open-addressing tables on device.  Operates on uint64 views."""
    x = x.astype(np.uint64, copy=True)
    x += np.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    x = x ^ (x >> np.uint64(31))
    return x
