"""Shared constants and naming schema.

Mirrors the semantic constants of the reference
(/root/reference/das/database/db_interface.py:4-5,
 /root/reference/das/database/mongo_schema.py:3-18,
 /root/reference/das/database/key_value_schema.py:3-11) without the
Mongo/Redis specifics: in the TPU build these names survive only as logical
field names of the columnar store and checkpoint layout.
"""

WILDCARD = "*"

# Link types whose targets form a multiset rather than a tuple.  Targets of
# unordered links are canonically sorted at ingest (as the reference does in
# redis_mongo_db.py:249-250) so any permutation hashes identically.
UNORDERED_LINK_TYPES = ["Similarity", "Set"]

TYPEDEF_MARK = ":"
BASIC_TYPE = "Type"


class AtomKinds:
    NODE = 0
    LINK = 1
    TYPEDEF = 2


class TableNames:
    """Logical table names of the columnar store (checkpoint keys)."""

    NODES = "nodes"
    ATOM_TYPES = "atom_types"
    LINKS = "links"            # bucketed by arity: links/arity_{a}
    OUTGOING = "outgoing_set"
    INCOMING = "incoming_set"
    PATTERNS = "patterns"
    TEMPLATES = "templates"
    NAMES = "names"


class FieldNames:
    ID_HASH = "_id"
    TYPE = "composite_type_hash"
    TYPE_NAME = "named_type"
    TYPE_NAME_HASH = "named_type_hash"
    COMPOSITE_TYPE = "composite_type"
    NODE_NAME = "name"
    KEY_PREFIX = "key"
    KEYS = "keys"
