"""Kernel 3: worst-case-optimal k-way join — leapfrog intersection over
one shared variable, all clauses grounded in a single pass.

A k-clause star conjunction (every tail clause sharing exactly ONE
variable v with the accumulated bindings) executes on the lowered path
as a CHAIN of binary sort-probe joins (ops/join.py _join_tables_impl),
each materializing a capacity-sized intermediate in HBM — the
capacity-retry ladder exists precisely because those intermediates blow
up on skew-heavy shapes, and the PR-8 planner can only seed the FIRST
join exactly (pairwise degree dot products); deeper intermediates ride
the independence model, which errs low exactly on skew.  TrieJax
(arXiv:1905.08021) shows Leapfrog-Triejoin-style multiway intersection
maps onto sorted arrays + binary-search ladders — the machinery these
kernels already have — and "Query Processing on Tensor Computation
Runtimes" (arXiv:2203.01877) argues this class of join belongs on the
accelerator as batched gathers.

This kernel grounds ALL k clauses at once:

  * each tail clause's term table sorts by its v column in-kernel (the
    join prologue idiom, `_mix_columns` + argsort — the SAME injective
    single-column mix the binary chain uses, so enumeration order and
    collision behavior match the chain bit-for-bit);
  * every clause-0 row seeks into every tail with the unrolled
    binary-search ladder (`unrolled_search` lower/upper bound) — the
    data-parallel form of leapfrog's seek-max/advance loop: a v value
    survives iff EVERY tail's window is non-empty, and the per-row
    match count is the product of window widths;
  * output slots resolve (left row, tail offsets) by one upper-bound
    ladder over the combined-count offsets vector plus a mixed-radix
    decomposition (last tail fastest) — exactly the lexicographic
    (l0, o1, .., oT) layout the left-deep binary chain materializes,
    so the emitted rows are POSITIONALLY identical to the chain's
    settled output (tests/test_zmultiway.py pins this);
  * NO intermediate tables exist: the one output buffer is the final
    join, seeded margin-free by the planner's exact k-way degree
    product (planner/stats.py multiway_rows) — zero capacity-retry
    rounds on the shapes where the chain's independence-seeded
    intermediates pay retry tiers.

The kernel also emits the PARTIAL pair totals (prefix products summed,
`tot_ref[t]` = the t-th binary intermediate's would-be size) so the
fused program can reproduce the reference's empty-accumulator reseed
verdict without ever materializing those intermediates.

Tail tables arrive CONCATENATED into one width-padded buffer with
static row segments (`segs`), so the kernel body has a fixed signature
for any k — the byte model (budget.multiway_plan) prices the padded
buffer, and daslint DL005 pins the refs against KERNEL_BUFFERS like
every other body.  Single-block vs grid-chunked is the bytes planner's
trace-time pick; off-TPU both bodies discharge to ordinary XLA ops
(kernels/common.py), with the tiled prologue hoisted once per launch
(`hoisted`)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from das_tpu.ops.join import _mix_columns
from das_tpu.ops.join import _SENTINEL_L as _SL
from das_tpu.ops.join import _SENTINEL_R as _SR

from das_tpu.kernels import budget
from das_tpu.kernels.common import (
    hoisted,
    run_grid_kernel,
    run_kernel,
    select_columns,
    unrolled_search,
)
from das_tpu.kernels.join import _window_iota

# python literals: pallas_call rejects jnp-array constants captured by a
# kernel body; identical values to the binary chain's sentinels so the
# enumeration (and its astronomically-unlikely collision behavior)
# matches the chain exactly
_SENTINEL_L = int(_SL)
_SENTINEL_R = int(_SR)


def _mw_prologue(lv_ref, lm_ref, tv_ref, tm_ref, segs, vcol0, n_left):
    """Per-launch scalar/vector prologue: mix + sort every tail segment
    by its v column, seek the clause-0 keys into each (lower/upper
    ladder), fold the per-row window widths into the combined count,
    the prefix-partial totals, and the slot offsets vector.  Shared by
    the single-block and tiled bodies (and hoisted across grid steps
    under the off-TPU discharge) so every layout agrees by
    construction."""
    lv, lm = lv_ref[:], lm_ref[:].astype(bool)
    key_l = _mix_columns(lv, (vcol0,), lm, _SENTINEL_L)
    tails = []
    run = None
    partials = []
    for off, rows, vcol, _extras in segs:
        tv = tv_ref[off:off + rows, :]
        tm = tm_ref[off:off + rows].astype(bool)
        key_t = _mix_columns(tv, (vcol,), tm, _SENTINEL_R)
        order = jnp.argsort(key_t).astype(jnp.int32)
        key_sorted = jnp.take(key_t, order)
        lo = unrolled_search(key_sorted, key_l, "left")
        hi = unrolled_search(key_sorted, key_l, "right")
        cnt = (hi - lo).astype(jnp.int64)
        run = cnt if run is None else run * cnt
        partials.append(jnp.sum(run))
        tails.append((tv, tm, order, lo, cnt))
    offsets = (
        jax.lax.associative_scan(jnp.add, run) if n_left > 1 else run
    )
    return lv, lm, tails, partials, run, offsets


def _mw_window(base, chunk, pro, segs, vcol0, n_left):
    """Verify-and-emit for output slots [base, base+chunk): resolve each
    slot to (left row, per-tail sorted-window offsets) — upper-bound
    ladder over the combined offsets, then mixed-radix decomposition
    with the LAST tail fastest, i.e. the left-deep chain's lexicographic
    pair layout — gather, verify the v column exactly per tail (the mix
    is a route, never trusted), and emit the concatenated row."""
    lv, lm, tails, partials, run, offsets = pro
    total = partials[-1]
    j = _window_iota(base, chunk)
    li = unrolled_search(offsets, j, "right")
    li_safe = jnp.clip(li, 0, max(n_left - 1, 0))
    rem = j - jnp.take(offsets - run, li_safe)
    ris = [None] * len(segs)
    for t in range(len(segs) - 1, -1, -1):
        _tv, _tm, order, lo, cnt = tails[t]
        c_safe = jnp.maximum(jnp.take(cnt, li_safe), 1)
        o = rem % c_safe
        rem = rem // c_safe
        ri_sorted = (
            jnp.take(lo, li_safe).astype(jnp.int64) + o
        ).astype(jnp.int32)
        rows_t = segs[t][1]
        ris[t] = jnp.take(order, jnp.clip(ri_sorted, 0, max(rows_t - 1, 0)))
    out_valid = (j < total) & jnp.take(lm, li_safe)
    lvv = jnp.take(lv[:, vcol0], li_safe)
    parts = [jnp.take(lv, li_safe, axis=0)]
    for t, (_off, _rows, vcol, extras) in enumerate(segs):
        tv, tm, _order, _lo, _cnt = tails[t]
        rt = ris[t]
        out_valid = out_valid & jnp.take(tm, rt) & (
            jnp.take(tv[:, vcol], rt) == lvv
        )
        if extras:
            parts.append(select_columns(jnp.take(tv, rt, axis=0), extras))
    out = jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
    return jnp.where(out_valid[:, None], out, jnp.int32(0)), out_valid


def _multiway_kernel_body(segs, vcol0, capacity, n_left):
    def kernel(lv_ref, lm_ref, tv_ref, tm_ref, out_ref, ov_ref, tot_ref):
        pro = _mw_prologue(
            lv_ref, lm_ref, tv_ref, tm_ref, segs, vcol0, n_left
        )
        out, out_valid = _mw_window(0, capacity, pro, segs, vcol0, n_left)
        out_ref[:, :] = out
        ov_ref[:] = out_valid.astype(jnp.int32)
        tot_ref[:] = jnp.stack(pro[3])

    return kernel


def _tiled_multiway_body(segs, vcol0, chunk, n_left):
    """Grid-chunked k-way intersection: step g owns output slots
    [g*chunk, (g+1)*chunk).  All tables and the per-tail sort/offset
    vectors stay resident (the planner only picks this route when they
    fit); the prologue re-runs per step under pallas (carried-scratch
    hoisting is the standing real-TPU follow-up, ARCHITECTURE §9) and is
    hoisted once per launch under the off-TPU discharge; the partial
    totals ride the carried [T]-element block (same values every
    step)."""

    def kernel(g, lv_ref, lm_ref, tv_ref, tm_ref, out_ref, ov_ref,
               tot_ref, *, memo=None):
        pro = hoisted(memo, "prologue", lambda: _mw_prologue(
            lv_ref, lm_ref, tv_ref, tm_ref, segs, vcol0, n_left
        ))
        out, out_valid = _mw_window(
            g * chunk, chunk, pro, segs, vcol0, n_left
        )
        out_ref[:, :] = out
        ov_ref[:] = out_valid.astype(jnp.int32)
        tot_ref[:] = jnp.stack(pro[3])

    return kernel


def multiway_join_impl(
    left_vals, left_valid, tails, vcol0, tail_meta, capacity: int,
    *, interpret: bool,
):
    """Traceable k-way star join.  `tails` is a sequence of (vals, mask)
    term tables; `tail_meta[t] = (vcol, extra_cols)` gives each tail's
    shared-variable column and the columns it contributes to the output
    (its variables not already bound — the planner guarantees the star
    shape, so that is every non-v column).  Returns
    (out_vals[cap, k_out] int32, out_valid[cap] bool, totals[T] int64)
    where totals[t] is the EXACT pair count of the t-th would-be binary
    intermediate (totals[-1] = the final join size, the capacity-retry
    figure) — the same numbers the chain's per-join stats report,
    without the intermediates existing.

    Tail tables concatenate into one width-padded buffer with static
    row segments so the kernel signature is k-independent (DL005 pins
    it); single-block vs grid-chunked is the bytes planner's trace-time
    pick (budget.multiway_plan)."""
    tail_meta = tuple((int(v), tuple(e)) for v, e in tail_meta)
    n_left, k_left = left_vals.shape
    kpad = max(tv.shape[1] for tv, _ in tails)
    segs = []
    parts_v, parts_m = [], []
    off = 0
    for (tv, tm), (vcol, extras) in zip(tails, tail_meta):
        rows = tv.shape[0]
        if tv.shape[1] < kpad:
            tv = jnp.pad(tv, ((0, 0), (0, kpad - tv.shape[1])))
        parts_v.append(tv)
        parts_m.append(tm.astype(jnp.int32))
        segs.append((off, rows, vcol, extras))
        off += rows
    segs = tuple(segs)
    tv_all = jnp.concatenate(parts_v, axis=0)
    tm_all = jnp.concatenate(parts_m, axis=0)
    k_out = k_left + sum(len(e) for _v, e in tail_meta)
    plan = budget.multiway_plan(
        n_left, k_left,
        tuple((s[1], kpad) for s in segs), k_out, capacity,
    )
    inputs = (left_vals, left_valid.astype(jnp.int32), tv_all, tm_all)
    n_tails = len(segs)
    if plan.tiled:
        chunk = plan.chunk_rows
        padded = -(-capacity // chunk) * chunk
        out, ov, tot = run_grid_kernel(
            _tiled_multiway_body(segs, vcol0, chunk, n_left),
            padded // chunk,
            (
                ((padded, k_out), jnp.int32),
                ((padded,), jnp.int32),
                ((n_tails,), jnp.int64),
            ),
            (chunk, chunk, None),
            inputs, interpret,
        )
        # pad slots sit beyond every total: plain slices suffice
        out, ov = out[:capacity], ov[:capacity]
    else:
        # a ROUTE_LOWERED verdict is the PLANNER's signal not to route
        # this step (planner/search.py declines multiway); invoked
        # anyway, the single-block body runs — always safe off-TPU
        # (direct discharge), an explicit over-budget Mosaic compile on
        # hardware rather than a silent re-route (the _run_pair_kernel
        # contract)
        out, ov, tot = run_kernel(
            _multiway_kernel_body(segs, vcol0, capacity, n_left),
            (
                ((capacity, k_out), jnp.int32),
                ((capacity,), jnp.int32),
                ((n_tails,), jnp.int64),
            ),
            inputs, interpret,
        )
    return out, ov.astype(bool), tot
