"""Bytes-based kernel eligibility planner (single-block → grid-chunked →
lowered).

The PR-1..3 gate was `fits()`: every dimension (table rows, buffer
capacity) independently compared against a single row bound
(KERNEL_MAX_ROWS = 2^18).  That gate was wrong twice over:

  * too strict — a >2^18-row posting table with a small probe window
    fits VMEM comfortably (rows are ~16 B each only if ALL of them are
    resident; the probe touches a cap-sized window), yet fits() kicked
    exactly the FlyBase-scale whole-table terms the kernels were built
    for back to the lowered op chains;
  * too loose — dimensions were checked independently, but a kernel
    holds its buffers CONCURRENTLY: inside shard_map the gathered left
    side is S×cap rows next to the per-shard right table and the output
    block, and each piece passing the per-dimension bound says nothing
    about the sum.

This module replaces it with an explicit byte model.  Each kernel stage
(probe, join, index join, anti join) describes its VMEM-resident set and
its per-row streamed cost; the planner sums the COMBINED footprint and
picks a route:

  ROUTE_SINGLE  — everything fits one VMEM block: the PR-1 whole-block
                  kernels run unchanged.
  ROUTE_TILED   — the capacity-scaled buffers overflow the budget but
                  the irreducible resident set (binary-search ladder
                  inputs for probes; both key columns + the offsets
                  vector for joins) fits: the grid-chunked kernel
                  variants stream chunk_rows-sized blocks per grid step
                  (probe.py / join.py tiled bodies, common.py
                  run_grid_kernel).
  ROUTE_LOWERED — even the tiled resident set overflows (e.g. a
                  sort-merge join whose BOTH tables exceed VMEM — the
                  index-join form exists precisely so the big side never
                  materializes), or the off-TPU compile guard trips.

The budget is env-configurable (DAS_TPU_VMEM_BUDGET, bytes) and
defaults to half of a TPU core's ~16 MB VMEM — the other half is
headroom for Mosaic's own scratch, double-buffering of the streamed
blocks, and model error (the byte model is deliberately coarse: it
counts declared buffers, not compiler temporaries).

Routes are re-derived per capacity-retry round at every call site
(fused dispatch, sharded dispatch, count-batch make_sig, staged
probe/join loops) and INSIDE the kernel impls at trace time from the
actual traced shapes — one model, two consumers, so the executor's
route telemetry and the traced program always agree for a given shape.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

ROUTE_SINGLE = "single"
ROUTE_TILED = "tiled"
ROUTE_LOWERED = "lowered"

#: Buffer manifest: every kernel body's ordered `*_ref` parameters, i.e.
#: the buffers the byte models below must price.  daslint rule DL005
#: (das_tpu/analysis) pins these tuples against the actual nested
#: `kernel` signatures in probe.py / join.py, so adding a Ref to a body
#: (a scratch table, an extra output block) without touching THIS file —
#: where the per-row arithmetic lives — fails lint instead of becoming a
#: latent VMEM OOM at the first Mosaic compile on hardware.  Keyed
#: `<module stem>.<factory>`; scalar/prologue refs (probe key, fvals,
#: type key) ride the models' constant terms, table refs the resident
#: terms, window refs the per_row terms:
#:   probe._kernel_body:  keys/perm (12 B/key) + targets (4 B×arity) are
#:     resident_single; vals+mask+count ride per_row = 4*arity + 4*k_out
#:     + 12 with the gathered window; the tiled body streams the same
#:     refs per chunk (probe_plan).
#:   join bodies: lv/lm + rv/rm + the in-kernel sort/offsets vectors are
#:     the resident term (4*k+28 / 4*k+24 per row); out/ov/tot ride
#:     per_row (join_plan).  The index-join bodies swap rv/rm for the
#:     keys/perm/targets posting index, ladder-addressed like the probe
#:     (index_join_plan).  The anti body is all-resident, nothing
#:     capacity-scaled (anti_join_plan).
KERNEL_BUFFERS = {
    "probe._kernel_body": (
        "key_ref", "fvals_ref", "keys_ref", "perm_ref", "targets_ref",
        "vals_ref", "mask_ref", "cnt_ref",
    ),
    "probe._tiled_body": (
        "key_ref", "fvals_ref", "keys_ref", "perm_ref", "targets_ref",
        "vals_ref", "mask_ref", "cnt_ref",
    ),
    "join._join_kernel_body": (
        "lv_ref", "lm_ref", "rv_ref", "rm_ref",
        "out_ref", "ov_ref", "tot_ref",
    ),
    "join._tiled_join_body": (
        "lv_ref", "lm_ref", "rv_ref", "rm_ref",
        "out_ref", "ov_ref", "tot_ref",
    ),
    "join._index_join_kernel_body": (
        "tk_ref", "lv_ref", "lm_ref", "keys_ref", "perm_ref",
        "targets_ref", "out_ref", "ov_ref", "tot_ref",
    ),
    "join._tiled_index_join_body": (
        "tk_ref", "lv_ref", "lm_ref", "keys_ref", "perm_ref",
        "targets_ref", "out_ref", "ov_ref", "tot_ref",
    ),
    "join._anti_kernel_body": (
        "lv_ref", "lm_ref", "rv_ref", "rm_ref", "keep_ref",
    ),
    #: k-way star join (kernels/multiway.py): the clause-0 table plus
    #: ONE width-padded concatenation of every tail table (static row
    #: segments keep the signature k-independent), all resident with
    #: their per-tail sort/ladder vectors (multiway_plan); out/ov ride
    #: per_row, the [T] partial-totals vector is constant-sized.
    "multiway._multiway_kernel_body": (
        "lv_ref", "lm_ref", "tv_ref", "tm_ref",
        "out_ref", "ov_ref", "tot_ref",
    ),
    "multiway._tiled_multiway_body": (
        "lv_ref", "lm_ref", "tv_ref", "tm_ref",
        "out_ref", "ov_ref", "tot_ref",
    ),
}

#: default VMEM byte budget for ONE kernel's combined buffers: half of
#: the ~16 MB/core VMEM (see module docstring for what the other half
#: buys).  Override with DAS_TPU_VMEM_BUDGET (bytes).
DEFAULT_VMEM_BUDGET = 8 * 1024 * 1024

#: per-grid-step streamed blocks target at most this fraction of the
#: budget, leaving the rest to the resident set + double buffering
_BLOCK_FRACTION = 4

#: rows axis granularity of a grid chunk: the TPU vector unit tiles
#: (8, 128) for 32-bit types, and a chunk-blocked output's MINOR axis is
#: the row index for the 1-D mask/count blocks — so chunk_rows must be a
#: multiple of the 128-lane minor axis (which also covers the 8-sublane
#: second-minor for the 2-D value blocks).  ARCHITECTURE §9 real-TPU
#: item 3, enforced at every emission site by daslint DL011.
LANE_ROWS = 128

#: floor for the chunk size: below this the grid bookkeeping dominates
#: the streamed work (and off-TPU every step is a separate trace of the
#: kernel body, so tiny chunks explode compile time).  8 lane rows —
#: exactly one (8,128) tile of a 1-D block, keeping the floor itself
#: lane-aligned.
MIN_CHUNK_ROWS = 1024

#: ceiling on grid steps: cdiv(capacity, chunk) past this falls back to
#: the lowered ops — off-TPU each step re-traces the body (compile
#: size), on TPU a deeper grid than this means the capacity itself is
#: far past serving scale
MAX_GRID_STEPS = 256

#: off-TPU (direct discharge / interpreter) there is no VMEM to budget —
#: this bounds XLA compile/runtime cost of the unrolled search ladders
#: (same role as the old KERNEL_MAX_ROWS_INTERPRET)
INTERPRET_MAX_ROWS = 1 << 22


def vmem_budget() -> int:
    """Configured VMEM byte budget (env DAS_TPU_VMEM_BUDGET beats the
    default, same override idiom as DAS_TPU_PALLAS).  Read per call so a
    test or bench A/B can flip routes without reloads; the planner is
    pure python, so the read is noise."""
    raw = os.environ.get("DAS_TPU_VMEM_BUDGET")
    if not raw:
        return DEFAULT_VMEM_BUDGET
    try:
        return max(int(raw), 1)
    except ValueError:
        return DEFAULT_VMEM_BUDGET


@dataclass(frozen=True)
class StagePlan:
    """One kernel stage's routing verdict.

    chunk_rows is the grid step size for ROUTE_TILED (0 otherwise);
    resident_bytes / block_bytes record the model's two components so
    telemetry (bench tiled A/B) can show WHY a route was picked."""

    route: str
    chunk_rows: int
    resident_bytes: int
    block_bytes: int

    @property
    def kernel(self) -> bool:
        return self.route != ROUTE_LOWERED

    @property
    def tiled(self) -> bool:
        return self.route == ROUTE_TILED


def _interpret_mode() -> bool:
    # lazy: das_tpu.kernels imports this module at the end of its own
    # init, so a top-level package import here would be circular
    from das_tpu.kernels import interpret_mode

    return interpret_mode()


def _lane_floor(n: int) -> int:
    """Largest multiple of the 128-lane tiling at or below n (0 when n
    is below one lane row — callers floor at MIN_CHUNK_ROWS)."""
    return (int(n) // LANE_ROWS) * LANE_ROWS


def _lane_ceil(n: int) -> int:
    """Smallest multiple of the 128-lane tiling at or above n."""
    return -(-int(n) // LANE_ROWS) * LANE_ROWS


def chunk_rows_for(row_bytes: int, capacity: int, budget: int) -> int:
    """Grid step size: the largest LANE-ALIGNED chunk (multiple of the
    (8,128) tiling's 128-row minor axis — ARCHITECTURE §9 item 3,
    pinned by daslint DL011) whose streamed block stays under
    budget/_BLOCK_FRACTION, floored at MIN_CHUNK_ROWS and never larger
    than the window itself rounded UP to a lane multiple — a window at
    or below the chunk is a one-step grid, not a reason to grow the
    block, and the callers' pad-to-chunk-multiple slicing keeps the pad
    rows beyond every count either way."""
    cap_aligned = _lane_ceil(max(int(capacity), 1))
    chunk = _lane_floor(budget // _BLOCK_FRACTION // max(row_bytes, 1))
    chunk = max(chunk, MIN_CHUNK_ROWS)
    return min(chunk, cap_aligned)


def _interpret_guard(*dims) -> bool:
    """True when the off-TPU compile-cost bound rejects these row counts
    (same role as the old KERNEL_MAX_ROWS_INTERPRET: the unrolled search
    ladders and per-chunk traces are XLA compile time on CPU)."""
    return _interpret_mode() and any(
        int(d) > INTERPRET_MAX_ROWS for d in dims
    )


def _plan(resident: int, per_row: int, capacity: int, *dims) -> StagePlan:
    """Shared route pick: resident bytes + capacity×per_row vs budget.

    dims are every row count the kernel's unrolled search ladders or
    gathers address — bounded off-TPU by the compile guard only (on TPU
    the ladder is O(log n) scalar work; the bytes model owns the rest)."""
    capacity = max(int(capacity), 0)
    if _interpret_guard(*dims, capacity):
        return StagePlan(ROUTE_LOWERED, 0, resident, per_row * capacity)
    budget = vmem_budget()
    single = resident + per_row * capacity
    if single <= budget:
        return StagePlan(ROUTE_SINGLE, 0, resident, single - resident)
    if resident > budget:
        return StagePlan(ROUTE_LOWERED, 0, resident, per_row * capacity)
    # the chunk is sized against the HEADROOM the resident set leaves, so
    # a near-budget resident table still tiles with a smaller block
    # rather than losing the kernel route outright
    chunk = chunk_rows_for(per_row, capacity, budget - resident)
    if resident + per_row * chunk > budget:
        return StagePlan(ROUTE_LOWERED, 0, resident, per_row * chunk)
    if -(-capacity // chunk) > MAX_GRID_STEPS:
        return StagePlan(ROUTE_LOWERED, 0, resident, per_row * chunk)
    return StagePlan(ROUTE_TILED, chunk, resident, per_row * chunk)


def probe_plan(
    n_keys: int, n_rows: int, arity: int, k_out: int, capacity: int
) -> StagePlan:
    """Kernel 1 (probe→gather→term table).

    Single-block holds the sorted posting keys (int64) + permutation
    (int32) + the target table (int32×arity) + the cap-sized window
    (gathered rows, emitted vals, mask, indices).  Tiled keeps NOTHING
    table-sized logically resident — the binary-search ladder reads
    O(log n) elements and each grid step streams one chunk_rows-sized
    permutation/target block plus its output slice (the
    dtype×arity×chunk_rows accounting from ARCHITECTURE §9) — so a
    FlyBase-scale whole-table term routes tiled even at a tiny window
    (a one-step grid) instead of falling back to the lowered chain."""
    capacity = max(int(capacity), 0)
    per_row = 4 * arity + 4 * k_out + 12  # gathered row + vals + mask/idx
    if _interpret_guard(n_keys, n_rows, capacity):
        return StagePlan(ROUTE_LOWERED, 0, 0, per_row * capacity)
    budget = vmem_budget()
    resident_single = 12 * int(n_keys) + 4 * int(n_rows) * arity
    single = resident_single + per_row * capacity
    if single <= budget:
        return StagePlan(
            ROUTE_SINGLE, 0, resident_single, single - resident_single
        )
    # tiled: the table stays off the resident set (streamed per step —
    # the remaining real-TPU work is staging those reads through explicit
    # DMA; see ARCHITECTURE §9), so only the per-step window is budgeted
    chunk = chunk_rows_for(per_row, capacity, budget)
    if per_row * chunk > budget or -(-capacity // max(chunk, 1)) > MAX_GRID_STEPS:
        return StagePlan(ROUTE_LOWERED, 0, 0, per_row * chunk)
    return StagePlan(ROUTE_TILED, chunk, 0, per_row * chunk)


def join_plan(
    n_left: int, k_left: int, n_right: int, k_right: int,
    n_pairs: int, k_out: int, capacity: int,
) -> StagePlan:
    """Kernel 2 (sort-probe + pair materialization).

    BOTH tables plus the sort/offsets vectors are irreducibly resident —
    every output slot may address any left/right row, and the offsets
    vector is what the per-slot upper-bound ladder searches.  Only the
    output window (pair gathers + emitted rows) tiles.  A join whose
    resident set alone overflows is lowered: that shape is what the
    index-join form (right side never materialized) exists for."""
    resident = (
        int(n_left) * (4 * k_left + 28)    # lv + lm + key_l + offsets/lo
        + int(n_right) * (4 * k_right + 24)  # rv + rm + key_r + order/sorted
    )
    per_row = 4 * k_out + 4 * k_left + 4 * k_right + 16
    return _plan(resident, per_row, capacity, n_left, n_right)


def index_join_plan(
    n_left: int, k_left: int, n_keys: int, n_rows: int, arity: int,
    k_out: int, capacity: int,
) -> StagePlan:
    """Index-join variant: the right side is the (type<<32|target)
    posting index, probed — never materialized, never sorted.  Resident:
    the left table + its probe/offsets vectors; the index itself is
    ladder-addressed like the probe kernel's keys.  The capacity window
    (perm/target gathers + emitted rows) tiles."""
    resident = int(n_left) * (4 * k_left + 28)
    per_row = 4 * k_out + 4 * arity + 16
    return _plan(resident, per_row, capacity, n_left, n_keys, n_rows)


def multiway_plan(
    n_left: int, k_left: int, tails, k_out: int, capacity: int
) -> StagePlan:
    """Kernel 3 (k-way leapfrog intersection, kernels/multiway.py).

    The clause-0 table AND every tail table are irreducibly resident —
    each output slot may address any row of any clause, and the per-tail
    offsets/count vectors are what the slot-resolution ladders search.
    `tails` is a sequence of (rows, padded_width) — the byte model
    prices the PADDED concatenated buffer the kernel actually holds.
    Per left row the kernel also carries the mixed key plus one
    lo/count pair per tail.  Only the output window (per-tail row
    gathers + emitted rows) tiles."""
    tails = tuple((int(r), int(w)) for r, w in tails)
    n_tails = max(len(tails), 1)
    resident = int(n_left) * (4 * k_left + 12 + 20 * n_tails)
    for rows, width in tails:
        resident += rows * (4 * width + 24)  # tv + tm + key + order/sorted
    per_row = 4 * k_out + sum(4 * w for _r, w in tails) + 24
    return _plan(
        resident, per_row, capacity, n_left, *(r for r, _w in tails)
    )


def anti_join_plan(
    n_left: int, k_left: int, n_right: int, k_right: int
) -> StagePlan:
    """Anti join (searchsorted membership): both key columns resident,
    output is one bool per left row — nothing capacity-scaled, so the
    route is single-block or lowered, never tiled."""
    resident = (
        int(n_left) * (4 * k_left + 20)
        + int(n_right) * (4 * k_right + 20)
    )
    return _plan(resident, 0, 0, n_left, n_right)


def combine(*plans: StagePlan) -> str:
    """Program-level route from per-stage plans: lowered if ANY stage is
    lowered (the program traces every stage — a single over-budget stage
    must kick the whole program to the lowered bodies, matching the old
    all-or-nothing use_kernels contract), tiled if any survivor tiles."""
    route = ROUTE_SINGLE
    for p in plans:
        if p.route == ROUTE_LOWERED:
            return ROUTE_LOWERED
        if p.route == ROUTE_TILED:
            route = ROUTE_TILED
    return route
