"""Shared in-kernel primitives for the Pallas query kernels.

Everything here runs INSIDE a kernel body on VMEM-resident values, so the
building blocks avoid ops the Mosaic vocabulary treats as opaque where a
compare/select formulation exists: binary search is a statically-unrolled
log₂ ladder of vectorized gathers (the TrieJax probe shape), not
`jnp.searchsorted` (whose 'sort' lowering would re-sort the query side
in-kernel).

`run_kernel` is the single launch point.  On TPU it is a plain
`pl.pallas_call`.  Off-TPU the body executes by DIRECT DISCHARGE — the
refs become thin functional wrappers over jnp arrays and the body runs as
ordinary traced ops.  This is semantically the Pallas interpreter for our
kernels (single program, no grid, every output written exactly once) but
skips the interpreter's grid-emulation machinery, which costs ~2-5 s of
XLA compile PER CALL SITE on CPU (measured jax 0.4.37) — prohibitive for
a differential suite that compiles dozens of kernel shapes.  Set
DAS_TPU_PALLAS_INTERPRET=1 to force the real `interpret=True` path
(tests/test_zkernels.py exercises it on a fixed shape so the actual
pallas_call lowering stays covered)."""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def unrolled_search(keys, queries, side: str):
    """Vectorized binary search of `queries` into sorted `keys`.

    side='left'  → first index with keys[i] >= q (lower bound),
    side='right' → first index with keys[i] >  q (upper bound);
    exactly `jnp.searchsorted` semantics.  The ladder is statically
    unrolled to ⌈log₂(n)⌉+1 steps, each one clipped gather + compare +
    select across all query lanes — no data-dependent trip counts, no
    per-query scan."""
    n = keys.shape[0]
    if n == 0:
        return jnp.zeros(jnp.shape(queries), jnp.int32)
    lo = jnp.zeros(jnp.shape(queries), jnp.int32)
    hi = jnp.full(jnp.shape(queries), n, jnp.int32)
    for _ in range(max(1, int(n).bit_length())):
        mid = (lo + hi) // 2
        v = jnp.take(keys, jnp.clip(mid, 0, n - 1))
        go_right = (v < queries) if side == "left" else (v <= queries)
        open_ = lo < hi
        lo = jnp.where(open_ & go_right, mid + 1, lo)
        hi = jnp.where(open_ & ~go_right, mid, hi)
    return lo


def select_columns(rows, cols):
    """rows[:, cols] for a STATIC column tuple as stacked single-column
    slices — static strided slices instead of a gather along the lane
    axis (which Mosaic cannot tile)."""
    return jnp.stack([rows[:, c] for c in cols], axis=1)


class _Ref:
    """Functional stand-in for a `pl.Ref` during direct discharge."""

    __slots__ = ("val",)

    def __init__(self, val):
        self.val = val

    def __getitem__(self, idx):
        return self.val[idx]

    def __setitem__(self, idx, v):
        self.val = self.val.at[idx].set(v)


def force_pallas_interpret() -> bool:
    return os.environ.get("DAS_TPU_PALLAS_INTERPRET", "0") == "1"


def run_kernel(body, out_shapes, inputs, interpret: bool):
    """Launch one kernel body: `pl.pallas_call` on TPU (or under
    DAS_TPU_PALLAS_INTERPRET=1), direct ref-discharge otherwise.  Valid
    because our kernels are single-program, grid-free, non-aliasing, and
    write every output exactly once — the discharge is then literally the
    interpreter's semantics without its per-call-site compile cost."""
    if not interpret or force_pallas_interpret():
        return pl.pallas_call(
            body,
            out_shape=tuple(
                jax.ShapeDtypeStruct(s, d) for s, d in out_shapes
            ),
            interpret=interpret,
        )(*inputs)
    outs = tuple(_Ref(jnp.zeros(s, d)) for s, d in out_shapes)
    body(*(_Ref(x) for x in inputs), *outs)
    return tuple(o.val for o in outs)
