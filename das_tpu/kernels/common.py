"""Shared in-kernel primitives for the Pallas query kernels.

Everything here runs INSIDE a kernel body on VMEM-resident values, so the
building blocks avoid ops the Mosaic vocabulary treats as opaque where a
compare/select formulation exists: binary search is a statically-unrolled
log₂ ladder of vectorized gathers (the TrieJax probe shape), not
`jnp.searchsorted` (whose 'sort' lowering would re-sort the query side
in-kernel).

`run_kernel` (single-block) and `run_grid_kernel` (grid-chunked, the
bytes planner's tiled route) are the two launch points.  On TPU they are
plain `pl.pallas_call`s — the grid form with chunk-blocked output
BlockSpecs and carried (constant-index) accumulator blocks.  Off-TPU the
bodies execute by DIRECT DISCHARGE — the refs become thin functional
wrappers over jnp arrays and the body runs as ordinary traced ops, with
the grid emulated as a python loop (blocked outputs concatenate, carried
refs persist across steps).  This is semantically the Pallas interpreter
for our kernels (sequential grid, non-aliasing, every output block
written by exactly one step — carried blocks by every step) but skips
the interpreter's machinery, which costs ~2-5 s of XLA compile PER CALL
SITE on CPU (measured jax 0.4.37) — prohibitive for a differential suite
that compiles dozens of kernel shapes.  Set DAS_TPU_PALLAS_INTERPRET=1
to force the real `interpret=True` path (tests/test_zkernels.py and
tests/test_ztiled.py each exercise it on fixed shapes so the actual
pallas_call lowering — grid and BlockSpecs included — stays covered)."""

from __future__ import annotations

import inspect
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from das_tpu.obs import proflog


def unrolled_search(keys, queries, side: str):
    """Vectorized binary search of `queries` into sorted `keys`.

    side='left'  → first index with keys[i] >= q (lower bound),
    side='right' → first index with keys[i] >  q (upper bound);
    exactly `jnp.searchsorted` semantics.  The ladder is statically
    unrolled to ⌈log₂(n)⌉+1 steps, each one clipped gather + compare +
    select across all query lanes — no data-dependent trip counts, no
    per-query scan."""
    n = keys.shape[0]
    if n == 0:
        return jnp.zeros(jnp.shape(queries), jnp.int32)
    lo = jnp.zeros(jnp.shape(queries), jnp.int32)
    hi = jnp.full(jnp.shape(queries), n, jnp.int32)
    for _ in range(max(1, int(n).bit_length())):
        mid = (lo + hi) // 2
        v = jnp.take(keys, jnp.clip(mid, 0, n - 1))
        go_right = (v < queries) if side == "left" else (v <= queries)
        open_ = lo < hi
        lo = jnp.where(open_ & go_right, mid + 1, lo)
        hi = jnp.where(open_ & ~go_right, mid, hi)
    return lo


def select_columns(rows, cols):
    """rows[:, cols] for a STATIC column tuple as stacked single-column
    slices — static strided slices instead of a gather along the lane
    axis (which Mosaic cannot tile)."""
    return jnp.stack([rows[:, c] for c in cols], axis=1)


def hoisted(memo, key, fn):
    """Per-LAUNCH hoisting hook for grid-body prologues.

    Under the python-loop discharge, `run_grid_kernel` hands every step
    of a memo-accepting body the SAME dict — the first step computes the
    prologue (sort + search ladders + offsets, identical every step
    because grid bodies never write their inputs) and later steps reuse
    the traced values, so an off-TPU g-step launch traces ONE prologue
    instead of g (PR 4 recorded the per-chunk re-run honestly as
    slower-than-lowered on CPU; this deletes it).  Under pallas `memo`
    is None and fn() runs inline — the body is traced once with a
    symbolic program id, so nothing is lost (the on-HARDWARE per-step
    re-execution is the carried-scratch follow-up, ARCHITECTURE §9)."""
    if memo is None:
        return fn()
    if key not in memo:
        memo[key] = fn()
    return memo[key]


class _Ref:
    """Functional stand-in for a `pl.Ref` during direct discharge."""

    __slots__ = ("val",)

    def __init__(self, val):
        self.val = val

    def __getitem__(self, idx):
        return self.val[idx]

    def __setitem__(self, idx, v):
        self.val = self.val.at[idx].set(v)


def force_pallas_interpret() -> bool:
    return os.environ.get("DAS_TPU_PALLAS_INTERPRET", "0") == "1"


def run_kernel(body, out_shapes, inputs, interpret: bool):
    """Launch one kernel body: `pl.pallas_call` on TPU (or under
    DAS_TPU_PALLAS_INTERPRET=1), direct ref-discharge otherwise.  Valid
    because our kernels are single-program, grid-free, non-aliasing, and
    write every output exactly once — the discharge is then literally the
    interpreter's semantics without its per-call-site compile cost."""
    t0 = proflog.launch_mark()
    if not interpret or force_pallas_interpret():
        out = pl.pallas_call(
            body,
            out_shape=tuple(
                jax.ShapeDtypeStruct(s, d) for s, d in out_shapes
            ),
            interpret=interpret,
        )(*inputs)
        proflog.record_launch("kernel", body, out_shapes, t0, pallas=True)
        return out
    outs = tuple(_Ref(jnp.zeros(s, d)) for s, d in out_shapes)
    body(*(_Ref(x) for x in inputs), *outs)
    proflog.record_launch("kernel", body, out_shapes, t0, pallas=False)
    return tuple(o.val for o in outs)


def run_grid_kernel(body, grid: int, out_shapes, out_chunks, inputs,
                    interpret: bool):
    """Launch one GRID-CHUNKED kernel (the budget planner's tiled route).

    `body(step, *in_refs, *out_refs)`: step is the grid index (python int
    under discharge, `pl.program_id(0)` under pallas — bodies must stay
    conditional-free and index arithmetically, which all of ours do).
    Inputs arrive as FULL refs every step (the streamed window is gathered
    in-body by dynamic index — on a real TPU the remaining Mosaic work is
    staging those reads through explicit DMA; ARCHITECTURE §9 carries the
    caveat).  `out_chunks[i]` is the per-step block row count for an
    output blocked along axis 0, or None for a CARRIED output: one block
    revisited by every step (Pallas keeps a same-index output block
    resident across sequential grid steps — the running-count
    accumulator rides there).

    Every blocked output's axis 0 must be grid*chunk exactly — callers
    pad the window to a chunk multiple and slice the result back, so
    neither launch path needs partial-block semantics.

    Off-TPU the grid is discharged as a python loop: blocked outputs
    collect per-step blocks, carried refs persist across iterations —
    the sequential-grid semantics without the interpreter's per-call-site
    compile cost (same contract as run_kernel's discharge)."""
    t0 = proflog.launch_mark()
    if not interpret or force_pallas_interpret():
        def _const(nd):
            return lambda g: (0,) * nd

        def _chunked(nd):
            return lambda g: (g,) + (0,) * (nd - 1)

        in_specs = [
            pl.BlockSpec(tuple(x.shape), _const(x.ndim)) for x in inputs
        ]
        out_specs = tuple(
            pl.BlockSpec(tuple(s), _const(len(s))) if c is None
            else pl.BlockSpec((c,) + tuple(s[1:]), _chunked(len(s)))
            for (s, _d), c in zip(out_shapes, out_chunks)
        )
        out = pl.pallas_call(
            lambda *refs: body(pl.program_id(0), *refs),
            grid=(grid,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=tuple(
                jax.ShapeDtypeStruct(s, d) for s, d in out_shapes
            ),
            interpret=interpret,
        )(*inputs)
        proflog.record_launch(
            "kernel_grid", body, out_shapes, t0, pallas=True
        )
        return out

    in_refs = tuple(_Ref(x) for x in inputs)
    # one shared memo per LAUNCH for bodies that accept it: the
    # step-invariant prologue (see `hoisted`) computes once and is
    # reused across the python-loop grid steps
    memo = (
        {} if "memo" in inspect.signature(body).parameters else None
    )
    carried = {
        i: _Ref(jnp.zeros(s, d))
        for i, ((s, d), c) in enumerate(zip(out_shapes, out_chunks))
        if c is None
    }
    blocks = {i: [] for i, c in enumerate(out_chunks) if c is not None}
    for g in range(grid):
        out_refs = []
        for i, ((s, d), c) in enumerate(zip(out_shapes, out_chunks)):
            if c is None:
                out_refs.append(carried[i])
            else:
                out_refs.append(_Ref(jnp.zeros((c,) + tuple(s[1:]), d)))
        if memo is None:
            body(g, *in_refs, *out_refs)
        else:
            body(g, *in_refs, *out_refs, memo=memo)
        for i in blocks:
            blocks[i].append(out_refs[i].val)
    out = tuple(
        carried[i].val if c is None else jnp.concatenate(blocks[i], axis=0)
        for i, c in enumerate(out_chunks)
    )
    proflog.record_launch("kernel_grid", body, out_shapes, t0, pallas=False)
    return out
