"""Kernel 2: fused hash-join inner loop (sort-probe + pair
materialization under capacity), plus the anti-join membership kernel.

The lowered join (`ops/join.py _join_tables_impl`) composes a key mix,
an argsort, two `searchsorted`s, a cumsum, a scatter+cummax segment
expansion and three gathers — each a separate XLA op whose
capacity-sized intermediates live in HBM.  Here the whole inner loop is
ONE `pl.pallas_call`: keys are mixed in registers, the left column
sort-probes the right side with the in-kernel binary-search ladder, and
each output slot resolves its (left row, right row) pair with an
upper-bound search over the running offsets — the cummax-over-scatter
trick is unnecessary when the offsets vector is VMEM-resident.

The posting-index variant (`index_join_impl`, mirroring
ops/join.py _index_join_impl) probes the prebuilt (type<<32|target)
positional index instead of a materialized right table, so whole-type
terms join without sorting or materializing the big side.

Each variant has a single-block layout (PR 1) and a GRID-CHUNKED layout,
picked at trace time by the bytes planner (kernels/budget.py): the
chunked bodies grid over OUTPUT SLOTS in fixed-row chunks with the
offsets vector (and for the sort-merge form both tables) resident, each
step resolving its chunk's pair bases with the same upper-bound ladder
and emitting one output block; the exact pair total rides a carried
one-element block.  Slot formulas are shared with the single-block
bodies (`_expand_window` / `_emit_pairs`), so the concatenated chunks
are bit-identical to the whole block — pinned by tests/test_ztiled.py.

The anti join (`anti_join_impl`, mirroring ops/join.py _anti_join_impl)
is a small single-block kernel: both key columns mix in registers, the
right side sorts in-kernel, and a membership ladder invalidates matched
left rows — nothing capacity-scaled, so the planner only ever picks
single-block or lowered for it.

All bodies compute the exact pair `total` so the host's
capacity-overflow retry contract is unchanged."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

# the kernel and lowered joins must mix IDENTICALLY — the differential
# suite pins whole-output identity — so the mix and its sentinels are
# imported, not copied (plain jnp code, traceable inside a kernel body)
from das_tpu.ops.join import _mix_columns
from das_tpu.ops.join import _SENTINEL_L as _SL
from das_tpu.ops.join import _SENTINEL_R as _SR

from das_tpu.kernels import budget
from das_tpu.kernels.common import (
    hoisted,
    run_grid_kernel,
    run_kernel,
    select_columns,
    unrolled_search,
)

# as python literals: pallas_call rejects jnp-array constants captured by
# a kernel body ("captures constants ... pass them as inputs")
_SENTINEL_L = int(_SL)
_SENTINEL_R = int(_SR)


def _window_iota(base, chunk):
    """Output-slot indices [base, base+chunk) as int64 (2-D iota then
    squeeze: TPU rejects 1-D iota).  base is a python int under grid
    discharge, a traced scalar under pallas."""
    return (
        jnp.asarray(base).astype(jnp.int64)
        + jax.lax.broadcasted_iota(jnp.int64, (chunk, 1), 0)[:, 0]
    )


def _scan_offsets(cnt):
    """Inclusive prefix sum of the per-left-row pair counts — split out
    so the tiled bodies can hoist it with their prologue (one scan per
    launch under the off-TPU discharge, not one per chunk)."""
    return jax.lax.associative_scan(jnp.add, cnt) if cnt.shape[0] > 1 else cnt


def _expand_window(j, lo, cnt, n_left, offsets=None):
    """Slot→(left row, right offset) resolution for slot indices `j`:
    slot j belongs to left row li = upper_bound(offsets, j); its right
    index is lo[li] + (j - prev[li]).  Identical pair layout to the
    lowered scatter+cummax expansion (tests pin positional equality) —
    and shared between the single-block (j = whole window) and tiled
    (j = one chunk) bodies, so the layouts agree by construction.
    `offsets` may be precomputed (the tiled bodies hoist the scan)."""
    if offsets is None:
        offsets = _scan_offsets(cnt)
    total = offsets[-1]
    li = unrolled_search(offsets, j, "right")
    li_safe = jnp.clip(li, 0, max(n_left - 1, 0))
    prev = jnp.take(offsets - cnt, li_safe)
    ri_sorted = (jnp.take(lo, li_safe).astype(jnp.int64)
                 + (j - prev)).astype(jnp.int32)
    return total, li_safe, ri_sorted


def _expand_pairs(lo, cnt, capacity, n_left):
    """Whole-window expansion (single-block bodies)."""
    j = _window_iota(0, capacity)
    total, li_safe, ri_sorted = _expand_window(j, lo, cnt, n_left)
    return j, total, li_safe, ri_sorted


def _join_prologue(lv_ref, lm_ref, rv_ref, rm_ref, pairs):
    """Key mix + in-kernel sort-probe of the right side: the per-step
    scalar/vector prologue shared by the single-block and tiled
    sort-merge bodies."""
    lcols = tuple(lc for lc, _ in pairs)
    rcols = tuple(rc for _, rc in pairs)
    lv, lm = lv_ref[:], lm_ref[:].astype(bool)
    rv, rm = rv_ref[:], rm_ref[:].astype(bool)
    key_l = _mix_columns(lv, lcols, lm, _SENTINEL_L)
    key_r = _mix_columns(rv, rcols, rm, _SENTINEL_R)
    order = jnp.argsort(key_r).astype(jnp.int32)
    key_r_sorted = jnp.take(key_r, order)
    lo = unrolled_search(key_r_sorted, key_l, "left")
    hi = unrolled_search(key_r_sorted, key_l, "right")
    cnt = (hi - lo).astype(jnp.int64)
    return lv, lm, rv, rm, order, lo, cnt


def _emit_pairs(j, total, li_safe, ri, lv, lm, rv, rm, pairs, right_extra):
    """Verify + gather one window of materialized pairs (shared emit of
    the single-block and tiled sort-merge bodies)."""
    out_valid = j < total
    for lc, rc in pairs:
        out_valid = out_valid & (
            jnp.take(lv[:, lc], li_safe) == jnp.take(rv[:, rc], ri)
        )
    out_valid = out_valid & jnp.take(lm, li_safe) & jnp.take(rm, ri)
    parts = [jnp.take(lv, li_safe, axis=0)]
    if right_extra:
        parts.append(select_columns(jnp.take(rv, ri, axis=0), right_extra))
    out = jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
    return jnp.where(out_valid[:, None], out, jnp.int32(0)), out_valid


def _join_kernel_body(pairs, right_extra, capacity, n_left, n_right):
    def kernel(lv_ref, lm_ref, rv_ref, rm_ref, out_ref, ov_ref, tot_ref):
        lv, lm, rv, rm, order, lo, cnt = _join_prologue(
            lv_ref, lm_ref, rv_ref, rm_ref, pairs
        )
        j, total, li_safe, ri_sorted = _expand_pairs(lo, cnt, capacity, n_left)
        ri = jnp.take(order, jnp.clip(ri_sorted, 0, max(n_right - 1, 0)))
        out, out_valid = _emit_pairs(
            j, total, li_safe, ri, lv, lm, rv, rm, pairs, right_extra
        )
        out_ref[:, :] = out
        ov_ref[:] = out_valid.astype(jnp.int32)
        tot_ref[0] = total

    return kernel


def _tiled_join_body(pairs, right_extra, chunk, n_left, n_right):
    """Grid-chunked sort-merge join: step g owns output slots
    [g*chunk, (g+1)*chunk).  Both tables and the offsets vector stay
    resident (the planner only picks this route when they fit); under
    pallas the prologue re-runs per step (sort + ladders — hoisting it
    into carried scratch is a real-TPU tuning follow-up, ARCHITECTURE
    §9), while the off-TPU python-loop discharge hoists it ONCE per
    launch (`hoisted` + run_grid_kernel's per-launch memo — PR 4
    recorded the per-chunk re-run as slower-than-lowered on CPU); each
    step emits one output block; the exact total rides the carried
    one-element block."""

    def kernel(g, lv_ref, lm_ref, rv_ref, rm_ref, out_ref, ov_ref,
               tot_ref, *, memo=None):
        def prologue():
            pro = _join_prologue(lv_ref, lm_ref, rv_ref, rm_ref, pairs)
            return pro + (_scan_offsets(pro[6]),)

        lv, lm, rv, rm, order, lo, cnt, offsets = hoisted(
            memo, "prologue", prologue
        )
        j = _window_iota(g * chunk, chunk)
        total, li_safe, ri_sorted = _expand_window(
            j, lo, cnt, n_left, offsets
        )
        ri = jnp.take(order, jnp.clip(ri_sorted, 0, max(n_right - 1, 0)))
        out, out_valid = _emit_pairs(
            j, total, li_safe, ri, lv, lm, rv, rm, pairs, right_extra
        )
        out_ref[:, :] = out
        ov_ref[:] = out_valid.astype(jnp.int32)
        tot_ref[0] = total

    return kernel


def _run_pair_kernel(single_body, tiled_body, plan, capacity, k_out,
                     inputs, interpret):
    """Launch a pair-materializing kernel on the planner's route: the
    single-block body whole, or the tiled body over a chunk-padded
    window (outputs sliced back to `capacity` — pad slots sit beyond
    every total, so plain slices suffice).  A ROUTE_LOWERED verdict is
    the CALLER's fallback signal (every call site gates on plan.kernel
    before reaching an impl); invoked anyway, the impl runs the
    single-block body — always safe off-TPU (direct discharge), an
    explicit over-budget Mosaic compile on hardware rather than a
    silent re-route that would falsify the dispatch counters."""
    if plan.tiled:
        chunk = plan.chunk_rows
        padded = -(-capacity // chunk) * chunk
        out, ov, tot = run_grid_kernel(
            tiled_body, padded // chunk,
            (
                ((padded, k_out), jnp.int32),
                ((padded,), jnp.int32),
                ((1,), jnp.int64),
            ),
            (chunk, chunk, None),
            inputs, interpret,
        )
        return out[:capacity], ov[:capacity], tot
    out, ov, tot = run_kernel(
        single_body,
        (
            ((capacity, k_out), jnp.int32),
            ((capacity,), jnp.int32),
            ((1,), jnp.int64),
        ),
        inputs, interpret,
    )
    return out, ov, tot


def join_tables_impl(
    left_vals, left_valid, right_vals, right_valid,
    pairs, right_extra, capacity: int, *, interpret: bool,
):
    """Traceable fused equi-join.  Contract identical to
    ops/join.py:_join_tables_impl: (out_vals[cap, kL+E] int32,
    out_valid[cap] bool, total int64).  Single-block vs grid-chunked is
    the bytes planner's trace-time pick."""
    pairs, right_extra = tuple(pairs), tuple(right_extra)
    k_out = left_vals.shape[1] + len(right_extra)
    n_left, n_right = left_vals.shape[0], right_vals.shape[0]
    plan = budget.join_plan(
        n_left, left_vals.shape[1], n_right, right_vals.shape[1],
        len(pairs), k_out, capacity,
    )
    inputs = (
        left_vals, left_valid.astype(jnp.int32),
        right_vals, right_valid.astype(jnp.int32),
    )
    out, ov, tot = _run_pair_kernel(
        _join_kernel_body(pairs, right_extra, capacity, n_left, n_right),
        _tiled_join_body(pairs, right_extra, plan.chunk_rows, n_left, n_right)
        if plan.tiled else None,
        plan, capacity, k_out, inputs, interpret,
    )
    return out, ov.astype(bool), tot[0]


def _index_join_window(
    g_base, chunk, tk_ref, lv_ref, lm_ref, keys_ref, perm_ref, targets_ref,
    pairs, right_var_cols, right_extra, n_left, n_keys, n_rows, memo=None,
):
    """Shared probe + window emit of the index-join bodies (single-block:
    one window covering the capacity; tiled: one chunk per grid step,
    with the probe/offsets prologue hoisted once per launch under the
    off-TPU discharge via `memo` — see common.py hoisted)."""
    def prologue():
        lc0, _rc0 = pairs[0]
        lv, lm = lv_ref[:], lm_ref[:].astype(bool)
        type_key = tk_ref[0]
        probe = jnp.where(
            lm, (type_key << 32) | lv[:, lc0].astype(jnp.int64),
            jnp.int64(-1),
        )
        keys = keys_ref[:]
        lo = unrolled_search(keys, probe, "left")
        hi = unrolled_search(keys, probe, "right")
        cnt = jnp.where(lm, hi - lo, 0).astype(jnp.int64)
        return lv, lm, lo, cnt, _scan_offsets(cnt)

    lv, lm, lo, cnt, offsets = hoisted(memo, "prologue", prologue)
    j = _window_iota(g_base, chunk)
    total, li_safe, ri_sorted = _expand_window(j, lo, cnt, n_left, offsets)
    local = jnp.take(perm_ref[:], jnp.clip(ri_sorted, 0, n_keys - 1))
    row_t = jnp.take(targets_ref[:], jnp.clip(local, 0, n_rows - 1), axis=0)

    out_valid = (j < total) & jnp.take(lm, li_safe)
    for lc, rc in pairs[1:]:
        out_valid = out_valid & (
            row_t[:, right_var_cols[rc]] == jnp.take(lv[:, lc], li_safe)
        )
    parts = [jnp.take(lv, li_safe, axis=0)]
    if right_extra:
        parts.append(select_columns(
            row_t, tuple(right_var_cols[rc] for rc in right_extra)
        ))
    out = jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
    return jnp.where(out_valid[:, None], out, jnp.int32(0)), out_valid, total


def _index_join_kernel_body(
    pairs, right_var_cols, right_extra, capacity, n_left, n_keys, n_rows,
):
    def kernel(tk_ref, lv_ref, lm_ref, keys_ref, perm_ref, targets_ref,
               out_ref, ov_ref, tot_ref):
        out, out_valid, total = _index_join_window(
            0, capacity, tk_ref, lv_ref, lm_ref, keys_ref, perm_ref,
            targets_ref, pairs, right_var_cols, right_extra,
            n_left, n_keys, n_rows,
        )
        out_ref[:, :] = out
        ov_ref[:] = out_valid.astype(jnp.int32)
        tot_ref[0] = total

    return kernel


def _tiled_index_join_body(
    pairs, right_var_cols, right_extra, chunk, n_left, n_keys, n_rows,
):
    """Grid-chunked index join: output slots chunked exactly like the
    sort-merge form; the posting index is ladder-probed per step and the
    perm/target gathers touch only the step's chunk of pair bases."""

    def kernel(g, tk_ref, lv_ref, lm_ref, keys_ref, perm_ref, targets_ref,
               out_ref, ov_ref, tot_ref, *, memo=None):
        out, out_valid, total = _index_join_window(
            g * chunk, chunk, tk_ref, lv_ref, lm_ref, keys_ref, perm_ref,
            targets_ref, pairs, right_var_cols, right_extra,
            n_left, n_keys, n_rows, memo=memo,
        )
        out_ref[:, :] = out
        ov_ref[:] = out_valid.astype(jnp.int32)
        tot_ref[0] = total

    return kernel


def index_join_impl(
    left_vals, left_valid, keys_sorted, perm, targets, type_key,
    pairs, right_var_cols, right_extra, capacity: int, *, interpret: bool,
):
    """Traceable fused index join (contract of
    ops/join.py:_index_join_impl): the right side is the whole-type term,
    probed through the prebuilt positional posting index — never
    materialized, never sorted.  Single-block vs grid-chunked is the
    bytes planner's trace-time pick — this is the FlyBase-scale route,
    where the index dwarfs VMEM but the join output does not."""
    pairs = tuple(pairs)
    right_var_cols = tuple(right_var_cols)
    right_extra = tuple(right_extra)
    k_out = left_vals.shape[1] + len(right_extra)
    n_left, n_keys, n_rows = (
        left_vals.shape[0], keys_sorted.shape[0], targets.shape[0],
    )
    plan = budget.index_join_plan(
        n_left, left_vals.shape[1], n_keys, n_rows, targets.shape[1],
        k_out, capacity,
    )
    tk = jnp.reshape(jnp.asarray(type_key, jnp.int64), (1,))
    inputs = (
        tk, left_vals, left_valid.astype(jnp.int32), keys_sorted, perm,
        targets,
    )
    out, ov, tot = _run_pair_kernel(
        _index_join_kernel_body(
            pairs, right_var_cols, right_extra, capacity,
            n_left, n_keys, n_rows,
        ),
        _tiled_index_join_body(
            pairs, right_var_cols, right_extra, plan.chunk_rows,
            n_left, n_keys, n_rows,
        ) if plan.tiled else None,
        plan, capacity, k_out, inputs, interpret,
    )
    return out, ov.astype(bool), tot[0]


def _anti_kernel_body(pairs):
    lcols = tuple(lc for lc, _ in pairs)
    rcols = tuple(rc for _, rc in pairs)

    def kernel(lv_ref, lm_ref, rv_ref, rm_ref, keep_ref):
        lv, lm = lv_ref[:], lm_ref[:].astype(bool)
        rv, rm = rv_ref[:], rm_ref[:].astype(bool)
        key_l = _mix_columns(lv, lcols, lm, _SENTINEL_L)
        key_r = _mix_columns(rv, rcols, rm, _SENTINEL_R)
        key_r_sorted = jnp.sort(key_r)
        lo = unrolled_search(key_r_sorted, key_l, "left")
        hi = unrolled_search(key_r_sorted, key_l, "right")
        keep_ref[:] = (lm & ~(hi > lo)).astype(jnp.int32)

    return kernel


def anti_join_impl(
    left_vals, left_valid, right_vals, right_valid, pairs, *, interpret: bool,
):
    """Traceable fused anti join (contract of
    ops/join.py:_anti_join_impl): returns the filtered left validity
    mask.  Single-block only — the output is one bool per left row, so
    there is nothing capacity-scaled to tile; the planner gates
    eligibility (anti_join_plan) at the call sites."""
    body = _anti_kernel_body(tuple(pairs))
    (keep,) = run_kernel(
        body,
        (((left_vals.shape[0],), jnp.int32),),
        (
            left_vals, left_valid.astype(jnp.int32),
            right_vals, right_valid.astype(jnp.int32),
        ),
        interpret,
    )
    return keep.astype(bool)


@partial(jax.jit, static_argnames=(
    "pairs", "right_extra", "capacity", "interpret", "vmem_budget"))
def join_tables_jit(
    left_vals, left_valid, right_vals, right_valid,
    *, pairs, right_extra, capacity, interpret, vmem_budget=0,
):
    """Single-dispatch wrapper for the staged pipeline.  `vmem_budget`
    is static cache-key salt only (see probe_term_table_jit): a budget
    change must retrace, not replay the old layout."""
    return join_tables_impl(
        left_vals, left_valid, right_vals, right_valid,
        pairs, right_extra, capacity, interpret=interpret,
    )


@partial(jax.jit, static_argnames=("pairs", "interpret"))
def anti_join_jit(
    left_vals, left_valid, right_vals, right_valid, *, pairs, interpret,
):
    """Single-dispatch wrapper for the staged pipeline's negation filter."""
    return anti_join_impl(
        left_vals, left_valid, right_vals, right_valid, pairs,
        interpret=interpret,
    )
