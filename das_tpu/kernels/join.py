"""Kernel 2: fused hash-join inner loop (sort-probe + pair
materialization under capacity).

The lowered join (`ops/join.py _join_tables_impl`) composes a key mix,
an argsort, two `searchsorted`s, a cumsum, a scatter+cummax segment
expansion and three gathers — each a separate XLA op whose
capacity-sized intermediates live in HBM.  Here the whole inner loop is
ONE `pl.pallas_call`: keys are mixed in registers, the left column
sort-probes the right side with the in-kernel binary-search ladder, and
each output slot resolves its (left row, right row) pair with an
upper-bound search over the running offsets — the cummax-over-scatter
trick is unnecessary when the offsets vector is VMEM-resident.

The posting-index variant (`index_join_impl`, mirroring
ops/join.py _index_join_impl) probes the prebuilt (type<<32|target)
positional index instead of a materialized right table, so whole-type
terms join without sorting or materializing the big side.

Both bodies compute the exact pair `total` so the host's
capacity-overflow retry contract is unchanged."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

# the kernel and lowered joins must mix IDENTICALLY — the differential
# suite pins whole-output identity — so the mix and its sentinels are
# imported, not copied (plain jnp code, traceable inside a kernel body)
from das_tpu.ops.join import _mix_columns
from das_tpu.ops.join import _SENTINEL_L as _SL
from das_tpu.ops.join import _SENTINEL_R as _SR

from das_tpu.kernels.common import run_kernel, select_columns, unrolled_search

# as python literals: pallas_call rejects jnp-array constants captured by
# a kernel body ("captures constants ... pass them as inputs")
_SENTINEL_L = int(_SL)
_SENTINEL_R = int(_SR)


def _expand_pairs(lo, cnt, capacity, n_left):
    """Slot→(left row, right offset) resolution: slot j belongs to left
    row li = upper_bound(offsets, j); its right index is lo[li] + (j -
    prev[li]).  Identical pair layout to the lowered scatter+cummax
    expansion (tests pin positional equality)."""
    offsets = jax.lax.associative_scan(jnp.add, cnt) if cnt.shape[0] > 1 else cnt
    total = offsets[-1]
    j = jax.lax.broadcasted_iota(jnp.int32, (capacity, 1), 0)[:, 0].astype(jnp.int64)
    li = unrolled_search(offsets, j, "right")
    li_safe = jnp.clip(li, 0, max(n_left - 1, 0))
    prev = jnp.take(offsets - cnt, li_safe)
    ri_sorted = (jnp.take(lo, li_safe).astype(jnp.int64)
                 + (j - prev)).astype(jnp.int32)
    return j, total, li_safe, ri_sorted


def _join_kernel_body(pairs, right_extra, capacity, n_left, n_right):
    lcols = tuple(lc for lc, _ in pairs)
    rcols = tuple(rc for _, rc in pairs)

    def kernel(lv_ref, lm_ref, rv_ref, rm_ref, out_ref, ov_ref, tot_ref):
        lv, lm = lv_ref[:], lm_ref[:].astype(bool)
        rv, rm = rv_ref[:], rm_ref[:].astype(bool)
        key_l = _mix_columns(lv, lcols, lm, _SENTINEL_L)
        key_r = _mix_columns(rv, rcols, rm, _SENTINEL_R)
        order = jnp.argsort(key_r).astype(jnp.int32)
        key_r_sorted = jnp.take(key_r, order)
        lo = unrolled_search(key_r_sorted, key_l, "left")
        hi = unrolled_search(key_r_sorted, key_l, "right")
        cnt = (hi - lo).astype(jnp.int64)
        j, total, li_safe, ri_sorted = _expand_pairs(lo, cnt, capacity, n_left)
        ri = jnp.take(order, jnp.clip(ri_sorted, 0, max(n_right - 1, 0)))

        out_valid = j < total
        for lc, rc in pairs:
            out_valid = out_valid & (
                jnp.take(lv[:, lc], li_safe) == jnp.take(rv[:, rc], ri)
            )
        out_valid = out_valid & jnp.take(lm, li_safe) & jnp.take(rm, ri)

        parts = [jnp.take(lv, li_safe, axis=0)]
        if right_extra:
            parts.append(select_columns(jnp.take(rv, ri, axis=0), right_extra))
        out = jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
        out_ref[:, :] = jnp.where(out_valid[:, None], out, jnp.int32(0))
        ov_ref[:] = out_valid.astype(jnp.int32)
        tot_ref[0] = total

    return kernel


def join_tables_impl(
    left_vals, left_valid, right_vals, right_valid,
    pairs, right_extra, capacity: int, *, interpret: bool,
):
    """Traceable fused equi-join.  Contract identical to
    ops/join.py:_join_tables_impl: (out_vals[cap, kL+E] int32,
    out_valid[cap] bool, total int64)."""
    k_out = left_vals.shape[1] + len(right_extra)
    body = _join_kernel_body(
        tuple(pairs), tuple(right_extra), capacity,
        left_vals.shape[0], right_vals.shape[0],
    )
    out, ov, tot = run_kernel(
        body,
        (
            ((capacity, k_out), jnp.int32),
            ((capacity,), jnp.int32),
            ((1,), jnp.int64),
        ),
        (
            left_vals, left_valid.astype(jnp.int32),
            right_vals, right_valid.astype(jnp.int32),
        ),
        interpret,
    )
    return out, ov.astype(bool), tot[0]


def _index_join_kernel_body(
    pairs, right_var_cols, right_extra, capacity, n_left, n_keys, n_rows,
):
    lc0, _rc0 = pairs[0]

    def kernel(tk_ref, lv_ref, lm_ref, keys_ref, perm_ref, targets_ref,
               out_ref, ov_ref, tot_ref):
        lv, lm = lv_ref[:], lm_ref[:].astype(bool)
        type_key = tk_ref[0]
        probe = jnp.where(
            lm, (type_key << 32) | lv[:, lc0].astype(jnp.int64), jnp.int64(-1)
        )
        keys = keys_ref[:]
        lo = unrolled_search(keys, probe, "left")
        hi = unrolled_search(keys, probe, "right")
        cnt = jnp.where(lm, hi - lo, 0).astype(jnp.int64)
        j, total, li_safe, ri_sorted = _expand_pairs(lo, cnt, capacity, n_left)
        local = jnp.take(perm_ref[:], jnp.clip(ri_sorted, 0, n_keys - 1))
        row_t = jnp.take(targets_ref[:], jnp.clip(local, 0, n_rows - 1), axis=0)

        out_valid = (j < total) & jnp.take(lm, li_safe)
        for lc, rc in pairs[1:]:
            out_valid = out_valid & (
                row_t[:, right_var_cols[rc]] == jnp.take(lv[:, lc], li_safe)
            )
        parts = [jnp.take(lv, li_safe, axis=0)]
        if right_extra:
            parts.append(select_columns(
                row_t, tuple(right_var_cols[rc] for rc in right_extra)
            ))
        out = jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
        out_ref[:, :] = jnp.where(out_valid[:, None], out, jnp.int32(0))
        ov_ref[:] = out_valid.astype(jnp.int32)
        tot_ref[0] = total

    return kernel


def index_join_impl(
    left_vals, left_valid, keys_sorted, perm, targets, type_key,
    pairs, right_var_cols, right_extra, capacity: int, *, interpret: bool,
):
    """Traceable fused index join (contract of
    ops/join.py:_index_join_impl): the right side is the whole-type term,
    probed through the prebuilt positional posting index — never
    materialized, never sorted."""
    k_out = left_vals.shape[1] + len(right_extra)
    body = _index_join_kernel_body(
        tuple(pairs), tuple(right_var_cols), tuple(right_extra), capacity,
        left_vals.shape[0], keys_sorted.shape[0], targets.shape[0],
    )
    tk = jnp.reshape(jnp.asarray(type_key, jnp.int64), (1,))
    out, ov, tot = run_kernel(
        body,
        (
            ((capacity, k_out), jnp.int32),
            ((capacity,), jnp.int32),
            ((1,), jnp.int64),
        ),
        (tk, left_vals, left_valid.astype(jnp.int32), keys_sorted, perm, targets),
        interpret,
    )
    return out, ov.astype(bool), tot[0]


@partial(jax.jit, static_argnames=("pairs", "right_extra", "capacity", "interpret"))
def join_tables_jit(
    left_vals, left_valid, right_vals, right_valid,
    *, pairs, right_extra, capacity, interpret,
):
    """Single-dispatch wrapper for the staged pipeline."""
    return join_tables_impl(
        left_vals, left_valid, right_vals, right_valid,
        pairs, right_extra, capacity, interpret=interpret,
    )
