"""Pallas fused query kernels (probe→gather→join) and their routing.

The round-5 VERDICT's depth item: the query pipeline's hot ops were all
generic XLA primitives, and each conjunctive term still lowered to a
chain of separate ops (`searchsorted` ×2, clip, gather, mask, then the
join's sort/searchsorted cascade), every stage round-tripping its
cap-sized intermediates through HBM.  This package fuses the two hot
chains into single Pallas kernels (TrieJax, arXiv:1905.08021; tensor-
runtime query processing, arXiv:2203.01877):

  * kernels/probe.py — Kernel 1: posting-key binary search + permutation
    window gather + target-column gather + positional verification +
    term-table emit, one VMEM-resident pass (replaces
    ops/posting.py:range_probe → verify_positions →
    ops/join.py:build_term_table);
  * kernels/join.py  — Kernel 2: the hash-join inner loop — sort-probe of
    the left key column against the right + pair materialization under a
    static capacity (replaces ops/join.py:_join_tables_impl and its
    posting-index variant _index_join_impl) — plus the anti-join
    membership kernel (replaces _anti_join_impl, ROUTE_COUNTS
    `anti_kernel`).

Eligibility and layout come from the BYTES planner (kernels/budget.py):
per-stage VMEM byte models pick single-block → grid-chunked → lowered
against a configurable budget (env DAS_TPU_VMEM_BUDGET), re-derived per
capacity-retry round.  The grid-chunked layouts (this PR) stream the
capacity window in fixed-row chunks, so shapes past the old
single-block row bound (2^18 — exactly the FlyBase-scale whole-table
terms) stay on the kernel route instead of falling back to the lowered
op chains.

Routing: `DasConfig.use_pallas_kernels` ("auto" | "on" | "off", env
override DAS_TPU_PALLAS).  "auto" = on for TPU (compiled Mosaic kernels),
off elsewhere; an explicit "on" off-TPU executes the SAME kernel bodies
in interpret mode — by direct ref-discharge to ordinary XLA ops
(kernels/common.py run_kernel / run_grid_kernel; DAS_TPU_PALLAS_INTERPRET=1
forces the full Pallas interpreter) — answer-identical and
tier-1-testable under JAX_PLATFORMS=cpu (the differential suites in
tests/test_zkernels.py and tests/test_ztiled.py and the bench A/Bs all
run that way).  Off-TPU execution is a correctness vehicle, not a fast
path, which is why "auto" does not enable it suite-wide on CPU.  The
sharded mesh programs route their shard-LOCAL probe/join bodies through
the same kernels (parallel/fused_sharded.py, ShardedPlanSig.use_kernels;
collectives stay lowered), and the vmapped count-batch groups route
through FusedPlanSig.use_kernels (query/fused.py count_batch) — see
ARCHITECTURE.md §9.
"""

from __future__ import annotations

import os
from functools import lru_cache

from das_tpu.ops.counters import DISPATCH_KEYS

__all__ = [
    "DISPATCH_COUNTS",
    "anti_join",
    "anti_join_impl",
    "budget",
    "enabled",
    "index_join_impl",
    "interpret_mode",
    "join_tables",
    "join_tables_impl",
    "multiway_join_impl",
    "probe_term_table",
    "probe_term_table_impl",
    "record_dispatch",
    "reset_dispatch_counts",
    "route_label",
]

#: host-side launches of compiled device programs, by path.  "lowered" =
#: one generic jitted op (ops/posting.py, ops/join.py wrappers), "kernel"
#: = one fused Pallas call, "fused" = one whole-plan single-dispatch
#: program (query/fused.py), "sharded" = one whole-plan shard_map mesh
#: program (parallel/fused_sharded.py), "count" = one vmapped count-batch
#: group program (query/fused.py count_batch); the *_kernel variants
#: count the subset whose bodies routed through the Pallas kernels, and
#: the *_tiled variants the further subset whose planner verdict was the
#: GRID-CHUNKED layout (kernels/budget.py) — so a byte-model regression
#: that silently re-routes eligible large shapes to the lowered chains
#: (or quietly de-tiles them) breaks a pinned count, not just a perf
#: number.  The dispatch-count regression tests pin the per-query totals
#: so a refactor can't silently re-fragment the pipeline.  Keys are
#: DECLARED in das_tpu/ops/counters.py — the one registry daslint rule
#: DL004 pins every counting literal against — and the dict is built
#: from it so dict and registry cannot drift.
DISPATCH_COUNTS = {k: 0 for k in DISPATCH_KEYS}


def record_dispatch(kind: str, n: int = 1) -> None:
    DISPATCH_COUNTS[kind] = DISPATCH_COUNTS.get(kind, 0) + n
    from das_tpu import obs

    if obs.enabled():
        # the obs metric layer's one aggregate dispatch tick — every
        # device-program enqueue funnels through here, so the Prometheus
        # surface gets a total without a counter per DISPATCH_KEYS route
        obs.counter("exec.dispatches").inc(n)


def reset_dispatch_counts() -> None:
    for k in DISPATCH_COUNTS:
        DISPATCH_COUNTS[k] = 0


@lru_cache(maxsize=1)
def _platform() -> str:
    import jax

    return jax.devices()[0].platform


def interpret_mode() -> bool:
    """True off-TPU: the kernel bodies discharge to plain XLA ops — same
    answers, no Mosaic compile (kernels/common.py run_kernel)."""
    return _platform() != "tpu"


def enabled(config=None) -> bool:
    """Resolve kernel routing.  Env DAS_TPU_PALLAS beats the config so a
    deployment (or a bench A/B) can flip the path without code changes."""
    mode = os.environ.get("DAS_TPU_PALLAS")
    if mode is None and config is not None:
        mode = getattr(config, "use_pallas_kernels", "auto")
    mode = str("auto" if mode is None else mode).lower()
    if mode in ("on", "1", "true"):
        return True
    if mode in ("off", "0", "false"):
        return False
    # auto: compiled kernels on TPU; off elsewhere (explicit "on" runs
    # them through the interpreter — see module docstring)
    return _platform() == "tpu"


def route_label(config=None) -> str:
    """Bench/telemetry label for the active kernel route."""
    if not enabled(config):
        return "off"
    return "pallas-interpret" if interpret_mode() else "pallas"


# -- jitted single-dispatch wrappers (staged-path entry points) -----------
#
# The *_impl functions trace INSIDE a caller's program (query/fused.py
# build_fused) and are not counted; these wrappers are the staged
# pipeline's per-stage launches, so each counts exactly one dispatch
# ("kernel", plus "kernel_tiled" when the planner picked the
# grid-chunked layout for the shape — recomputed here from the same
# byte model the traced body consults, so counter and program agree).


def probe_term_table(
    sorted_keys, perm, targets, probe_key, fixed_vals, capacity: int,
    *, var_cols, eq_pairs, extra_fixed,
):
    """One fused probe→gather→term-table dispatch.  Returns
    (vals[cap, k] int32, mask[cap] bool, range_count) device arrays."""
    from das_tpu.kernels.probe import probe_term_table_jit

    record_dispatch("kernel")
    if budget.probe_plan(
        sorted_keys.shape[0], targets.shape[0], targets.shape[1],
        len(var_cols), capacity,
    ).tiled:
        record_dispatch("kernel_tiled")
    return probe_term_table_jit(
        sorted_keys, perm, targets, probe_key, fixed_vals,
        capacity=capacity, var_cols=tuple(var_cols),
        eq_pairs=tuple(eq_pairs), extra_fixed=tuple(extra_fixed),
        interpret=interpret_mode(), vmem_budget=budget.vmem_budget(),
    )


def join_tables(
    left_vals, left_valid, right_vals, right_valid,
    pairs, right_extra, capacity: int,
):
    """One fused equi-join dispatch (pair materialization under capacity).
    Returns (out_vals, out_valid bool, total int64) device arrays."""
    from das_tpu.kernels.join import join_tables_jit

    record_dispatch("kernel")
    if budget.join_plan(
        left_vals.shape[0], left_vals.shape[1],
        right_vals.shape[0], right_vals.shape[1],
        len(pairs), left_vals.shape[1] + len(right_extra), capacity,
    ).tiled:
        record_dispatch("kernel_tiled")
    return join_tables_jit(
        left_vals, left_valid, right_vals, right_valid,
        pairs=tuple(pairs), right_extra=tuple(right_extra),
        capacity=capacity, interpret=interpret_mode(),
        vmem_budget=budget.vmem_budget(),
    )


def anti_join(left_vals, left_valid, right_vals, right_valid, pairs):
    """One fused anti-join dispatch (negation membership filter).
    Returns the filtered left validity mask (bool device array)."""
    from das_tpu.kernels.join import anti_join_jit

    record_dispatch("kernel")
    return anti_join_jit(
        left_vals, left_valid, right_vals, right_valid,
        pairs=tuple(pairs), interpret=interpret_mode(),
    )


# imported LAST: budget's lazy helpers import back from this package at
# call time (interpret_mode), and probe/join import budget at module load
from das_tpu.kernels import budget  # noqa: E402
from das_tpu.kernels.probe import probe_term_table_impl  # noqa: E402
from das_tpu.kernels.join import (  # noqa: E402
    anti_join_impl,
    index_join_impl,
    join_tables_impl,
)
from das_tpu.kernels.multiway import multiway_join_impl  # noqa: E402
