"""Pallas fused query kernels (probe→gather→join) and their routing.

The round-5 VERDICT's depth item: the query pipeline's hot ops were all
generic XLA primitives, and each conjunctive term still lowered to a
chain of separate ops (`searchsorted` ×2, clip, gather, mask, then the
join's sort/searchsorted cascade), every stage round-tripping its
cap-sized intermediates through HBM.  This package fuses the two hot
chains into single Pallas kernels (TrieJax, arXiv:1905.08021; tensor-
runtime query processing, arXiv:2203.01877):

  * kernels/probe.py — Kernel 1: posting-key binary search + permutation
    window gather + target-column gather + positional verification +
    term-table emit, one VMEM-resident pass (replaces
    ops/posting.py:range_probe → verify_positions →
    ops/join.py:build_term_table);
  * kernels/join.py  — Kernel 2: the hash-join inner loop — sort-probe of
    the left key column against the right + pair materialization under a
    static capacity (replaces ops/join.py:_join_tables_impl and its
    posting-index variant _index_join_impl).

Routing: `DasConfig.use_pallas_kernels` ("auto" | "on" | "off", env
override DAS_TPU_PALLAS).  "auto" = on for TPU (compiled Mosaic kernels),
off elsewhere; an explicit "on" off-TPU executes the SAME kernel bodies
in interpret mode — by direct ref-discharge to ordinary XLA ops
(kernels/common.py run_kernel; DAS_TPU_PALLAS_INTERPRET=1 forces the full
Pallas interpreter) — answer-identical and tier-1-testable under
JAX_PLATFORMS=cpu (the differential suite in tests/test_zkernels.py and
the bench A/B both run that way).  Off-TPU execution is a correctness
vehicle, not a fast path, which is why "auto" does not enable it
suite-wide on CPU.  The sharded mesh programs route their shard-LOCAL
probe/join bodies through the same kernels (parallel/fused_sharded.py,
ShardedPlanSig.use_kernels; collectives stay lowered), and the vmapped
count-batch groups route through FusedPlanSig.use_kernels
(query/fused.py count_batch) — see ARCHITECTURE.md §9.
"""

from __future__ import annotations

import os
from functools import lru_cache

from das_tpu.kernels.probe import probe_term_table_impl
from das_tpu.kernels.join import index_join_impl, join_tables_impl

__all__ = [
    "DISPATCH_COUNTS",
    "enabled",
    "index_join_impl",
    "interpret_mode",
    "join_tables",
    "join_tables_impl",
    "probe_term_table",
    "probe_term_table_impl",
    "record_dispatch",
    "reset_dispatch_counts",
    "route_label",
]

#: host-side launches of compiled device programs, by path.  "lowered" =
#: one generic jitted op (ops/posting.py, ops/join.py wrappers), "kernel"
#: = one fused Pallas call, "fused" = one whole-plan single-dispatch
#: program (query/fused.py), "sharded" = one whole-plan shard_map mesh
#: program (parallel/fused_sharded.py), "count" = one vmapped count-batch
#: group program (query/fused.py count_batch); the *_kernel variants
#: count the subset whose bodies routed through the Pallas kernels.  The
#: dispatch-count regression tests pin the per-query totals so a refactor
#: can't silently re-fragment the pipeline.
DISPATCH_COUNTS = {
    "lowered": 0, "kernel": 0, "fused": 0, "fused_kernel": 0,
    "sharded": 0, "sharded_kernel": 0, "count": 0, "count_kernel": 0,
}


def record_dispatch(kind: str, n: int = 1) -> None:
    DISPATCH_COUNTS[kind] = DISPATCH_COUNTS.get(kind, 0) + n


def reset_dispatch_counts() -> None:
    for k in DISPATCH_COUNTS:
        DISPATCH_COUNTS[k] = 0


@lru_cache(maxsize=1)
def _platform() -> str:
    import jax

    return jax.devices()[0].platform


def interpret_mode() -> bool:
    """True off-TPU: the kernel bodies discharge to plain XLA ops — same
    answers, no Mosaic compile (kernels/common.py run_kernel)."""
    return _platform() != "tpu"


def enabled(config=None) -> bool:
    """Resolve kernel routing.  Env DAS_TPU_PALLAS beats the config so a
    deployment (or a bench A/B) can flip the path without code changes."""
    mode = os.environ.get("DAS_TPU_PALLAS")
    if mode is None and config is not None:
        mode = getattr(config, "use_pallas_kernels", "auto")
    mode = str("auto" if mode is None else mode).lower()
    if mode in ("on", "1", "true"):
        return True
    if mode in ("off", "0", "false"):
        return False
    # auto: compiled kernels on TPU; off elsewhere (explicit "on" runs
    # them through the interpreter — see module docstring)
    return _platform() == "tpu"


def route_label(config=None) -> str:
    """Bench/telemetry label for the active kernel route."""
    if not enabled(config):
        return "off"
    return "pallas-interpret" if interpret_mode() else "pallas"


#: largest single dimension (table rows or buffer capacity) the
#: single-block kernels accept ON TPU.  The current kernels hold the
#: whole posting window / binding table in one VMEM block (~16 MB/core):
#: int64 keys + perm + arity-2 targets is ~16 B/row, so 2^18 rows leaves
#: headroom for outputs and scratch.  Shapes past the bound stay on the
#: lowered ops (FlyBase-scale whole-table terms are exactly the case) —
#: lifting it needs the grid-chunked kernel evolution (ARCHITECTURE §9).
KERNEL_MAX_ROWS = 1 << 18

#: off-TPU (direct discharge) there is no VMEM block to fit — the bound
#: only guards XLA compile/runtime cost of the unrolled ladders, so the
#: bench A/B can keep the kernel route engaged at bio/flybase scale
KERNEL_MAX_ROWS_INTERPRET = 1 << 22


def fits(*sizes) -> bool:
    """True when every given dimension is kernel-eligible on the active
    backend."""
    bound = KERNEL_MAX_ROWS_INTERPRET if interpret_mode() else KERNEL_MAX_ROWS
    return all(int(s) <= bound for s in sizes)


# -- jitted single-dispatch wrappers (staged-path entry points) -----------
#
# The *_impl functions trace INSIDE a caller's program (query/fused.py
# build_fused) and are not counted; these wrappers are the staged
# pipeline's per-stage launches, so each counts exactly one dispatch.


def probe_term_table(
    sorted_keys, perm, targets, probe_key, fixed_vals, capacity: int,
    *, var_cols, eq_pairs, extra_fixed,
):
    """One fused probe→gather→term-table dispatch.  Returns
    (vals[cap, k] int32, mask[cap] bool, range_count) device arrays."""
    from das_tpu.kernels.probe import probe_term_table_jit

    record_dispatch("kernel")
    return probe_term_table_jit(
        sorted_keys, perm, targets, probe_key, fixed_vals,
        capacity=capacity, var_cols=tuple(var_cols),
        eq_pairs=tuple(eq_pairs), extra_fixed=tuple(extra_fixed),
        interpret=interpret_mode(),
    )


def join_tables(
    left_vals, left_valid, right_vals, right_valid,
    pairs, right_extra, capacity: int,
):
    """One fused equi-join dispatch (pair materialization under capacity).
    Returns (out_vals, out_valid bool, total int64) device arrays."""
    from das_tpu.kernels.join import join_tables_jit

    record_dispatch("kernel")
    return join_tables_jit(
        left_vals, left_valid, right_vals, right_valid,
        pairs=tuple(pairs), right_extra=tuple(right_extra),
        capacity=capacity, interpret=interpret_mode(),
    )
