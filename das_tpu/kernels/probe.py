"""Kernel 1: fused probe → gather → term-table build.

The lowered path answers one conjunctive term with a chain of generic XLA
ops — `searchsorted` ×2 on the posting keys, clip, permutation gather,
target-row gather, per-position verification masks, column select, mask
broadcast (ops/posting.py range_probe → verify_positions →
ops/join.py build_term_table) — each materializing a capacity-sized
intermediate in HBM.  Here the whole chain is ONE `pl.pallas_call`: the
binary search runs in-kernel over the sorted posting keys, the matched
permutation window streams through VMEM, target columns are gathered and
verified in registers, and only the padded term table + validity mask +
exact range count are written out.

Two layouts, picked by the bytes planner (kernels/budget.py) at trace
time from the actual shapes:

  * single-block (`_kernel_body`) — the PR-1 whole-block kernel, for
    shapes whose combined footprint fits the VMEM budget;
  * grid-chunked (`_tiled_body`) — grids over the posting window in
    fixed-row chunks: the binary-search ladder is the scalar prologue of
    every step, each step streams one chunk_rows-sized permutation/
    target block and emits its verified output slice, and the exact
    range count rides a carried one-element block.  Per-row formulas are
    IDENTICAL to the single-block body (row index = lo + global offset),
    so the concatenated chunks are bit-identical to the whole block —
    what tests/test_ztiled.py pins differentially.

Off-TPU both bodies discharge to ordinary XLA ops (kernels/common.py
run_kernel / run_grid_kernel): answer-identical to the lowered chain,
which is what tests/test_zkernels.py and tests/test_ztiled.py pin."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from das_tpu.kernels import budget
from das_tpu.kernels.common import (
    hoisted,
    run_grid_kernel,
    run_kernel,
    select_columns,
    unrolled_search,
)
from das_tpu.ops.posting import INVALID_ROW

# as a python literal: pallas_call rejects jnp-array constants captured by
# a kernel body ("captures constants ... pass them as inputs")
_INVALID_ROW = int(INVALID_ROW)


def _emit_window(base, chunk, lo, count, fvals_ref, perm_ref, targets_ref,
                 var_cols, eq_pairs, extra_fixed, n_keys, n_rows):
    """Verify-and-emit for window rows [base, base+chunk): the shared
    per-row pipeline of the single-block and tiled bodies — one source of
    truth so the tiled chunks concatenate bit-identically."""
    offs = (
        jnp.asarray(base).astype(jnp.int32)
        + jax.lax.broadcasted_iota(jnp.int32, (chunk, 1), 0)[:, 0]
    )
    valid = offs < count
    idx = jnp.clip(lo + offs, 0, n_keys - 1)
    local = jnp.where(valid, jnp.take(perm_ref[:], idx),
                      jnp.int32(_INVALID_ROW))
    safe = jnp.clip(local, 0, n_rows - 1)
    rows = jnp.take(targets_ref[:], safe, axis=0)
    mask = valid
    for i, pos in enumerate(extra_fixed):
        mask = mask & (rows[:, pos] == fvals_ref[i])
    for p1, p2 in eq_pairs:
        mask = mask & (rows[:, p1] == rows[:, p2])
    vals = select_columns(rows, var_cols)
    return jnp.where(mask[:, None], vals, jnp.int32(0)), mask


def _kernel_body(capacity, var_cols, eq_pairs, extra_fixed, n_keys, n_rows):
    def kernel(key_ref, fvals_ref, keys_ref, perm_ref, targets_ref,
               vals_ref, mask_ref, cnt_ref):
        keys = keys_ref[:]
        key = key_ref[0]
        lo = unrolled_search(keys, key, "left")
        hi = unrolled_search(keys, key, "right")
        count = (hi - lo).astype(jnp.int32)
        vals, mask = _emit_window(
            jnp.int32(0), capacity, lo, count, fvals_ref, perm_ref,
            targets_ref, var_cols, eq_pairs, extra_fixed, n_keys, n_rows,
        )
        vals_ref[:, :] = vals
        mask_ref[:] = mask.astype(jnp.int32)
        cnt_ref[0] = count

    return kernel


def _tiled_body(chunk, var_cols, eq_pairs, extra_fixed, n_keys, n_rows):
    """Grid-chunked probe: step g owns window rows [g*chunk, (g+1)*chunk).
    Under pallas the ladder re-runs as each step's scalar prologue
    (O(log n) compare/select work — cheaper than carrying lo/hi through
    scratch); the off-TPU discharge hoists it once per launch (`hoisted`
    + run_grid_kernel's per-launch memo).  The range count is written to
    the carried one-element block every step (same value each time — the
    'running count' is exact from step 0)."""

    def kernel(g, key_ref, fvals_ref, keys_ref, perm_ref, targets_ref,
               vals_ref, mask_ref, cnt_ref, *, memo=None):
        def prologue():
            keys = keys_ref[:]
            key = key_ref[0]
            lo = unrolled_search(keys, key, "left")
            hi = unrolled_search(keys, key, "right")
            return lo, (hi - lo).astype(jnp.int32)

        lo, count = hoisted(memo, "prologue", prologue)
        vals, mask = _emit_window(
            g * chunk, chunk, lo, count, fvals_ref, perm_ref, targets_ref,
            var_cols, eq_pairs, extra_fixed, n_keys, n_rows,
        )
        vals_ref[:, :] = vals
        mask_ref[:] = mask.astype(jnp.int32)
        cnt_ref[0] = count

    return kernel


def probe_term_table_impl(
    sorted_keys, perm, targets, probe_key, fixed_vals, capacity: int,
    *, var_cols, eq_pairs, extra_fixed, interpret: bool,
):
    """Traceable core (used both standalone and inside the fused
    whole-plan program).  Returns (vals[cap, k] int32, mask[cap] bool,
    range_count int32) — the exact contract of the lowered
    range_probe/verify/build_term_table chain.  The single-block vs
    grid-chunked layout is picked here, at trace time, by the bytes
    planner — callers only decided kernel-vs-lowered."""
    probe_key = jnp.reshape(
        jnp.asarray(probe_key, dtype=sorted_keys.dtype), (1,)
    )
    fvals = jnp.asarray(fixed_vals, dtype=jnp.int32)
    if fvals.shape[0] == 0:  # zero-length SMEM blocks don't exist
        fvals = jnp.zeros((1,), dtype=jnp.int32)
    var_cols, eq_pairs, extra_fixed = (
        tuple(var_cols), tuple(eq_pairs), tuple(extra_fixed)
    )
    n_keys, n_rows = sorted_keys.shape[0], targets.shape[0]
    plan = budget.probe_plan(
        n_keys, n_rows, targets.shape[1], len(var_cols), capacity
    )
    inputs = (probe_key, fvals, sorted_keys, perm, targets)
    if plan.tiled:
        chunk = plan.chunk_rows
        padded = -(-capacity // chunk) * chunk
        body = _tiled_body(
            chunk, var_cols, eq_pairs, extra_fixed, n_keys, n_rows,
        )
        vals, mask, cnt = run_grid_kernel(
            body, padded // chunk,
            (
                ((padded, len(var_cols)), jnp.int32),
                ((padded,), jnp.int32),
                ((1,), jnp.int32),
            ),
            (chunk, chunk, None),
            inputs, interpret,
        )
        # the pad rows are beyond every count: plain slices, no masking
        vals, mask = vals[:capacity], mask[:capacity]
    else:
        body = _kernel_body(
            capacity, var_cols, eq_pairs, extra_fixed, n_keys, n_rows,
        )
        vals, mask, cnt = run_kernel(
            body,
            (
                ((capacity, len(var_cols)), jnp.int32),
                ((capacity,), jnp.int32),
                ((1,), jnp.int32),
            ),
            inputs, interpret,
        )
    return vals, mask.astype(bool), cnt[0]


@partial(jax.jit, static_argnames=(
    "capacity", "var_cols", "eq_pairs", "extra_fixed", "interpret",
    "vmem_budget"))
def probe_term_table_jit(
    sorted_keys, perm, targets, probe_key, fixed_vals,
    *, capacity, var_cols, eq_pairs, extra_fixed, interpret,
    vmem_budget=0,
):
    """Single-dispatch wrapper for the staged pipeline (one compiled
    program per term shape; capacity is part of the cache key, exactly
    like the lowered ops).  `vmem_budget` is the caller's
    budget.vmem_budget() snapshot: unused in the body (the impl re-reads
    the same env at trace time) but STATIC, so a budget change retraces
    warm shapes instead of replaying an executable whose layout the old
    budget picked."""
    return probe_term_table_impl(
        sorted_keys, perm, targets, probe_key, fixed_vals, capacity,
        var_cols=var_cols, eq_pairs=eq_pairs, extra_fixed=extra_fixed,
        interpret=interpret,
    )
