"""Kernel 1: fused probe → gather → term-table build.

The lowered path answers one conjunctive term with a chain of generic XLA
ops — `searchsorted` ×2 on the posting keys, clip, permutation gather,
target-row gather, per-position verification masks, column select, mask
broadcast (ops/posting.py range_probe → verify_positions →
ops/join.py build_term_table) — each materializing a capacity-sized
intermediate in HBM.  Here the whole chain is ONE `pl.pallas_call`: the
binary search runs in-kernel over the sorted posting keys, the matched
permutation window streams through VMEM, target columns are gathered and
verified in registers, and only the padded term table + validity mask +
exact range count are written out.

Off-TPU the body discharges to ordinary XLA ops (kernels/common.py
run_kernel): answer-identical to the lowered chain, which is what
tests/test_zkernels.py pins differentially."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from das_tpu.kernels.common import run_kernel, select_columns, unrolled_search
from das_tpu.ops.posting import INVALID_ROW

# as a python literal: pallas_call rejects jnp-array constants captured by
# a kernel body ("captures constants ... pass them as inputs")
_INVALID_ROW = int(INVALID_ROW)


def _kernel_body(capacity, var_cols, eq_pairs, extra_fixed, n_keys, n_rows):
    def kernel(key_ref, fvals_ref, keys_ref, perm_ref, targets_ref,
               vals_ref, mask_ref, cnt_ref):
        keys = keys_ref[:]
        key = key_ref[0]
        lo = unrolled_search(keys, key, "left")
        hi = unrolled_search(keys, key, "right")
        count = (hi - lo).astype(jnp.int32)
        offs = jax.lax.broadcasted_iota(jnp.int32, (capacity, 1), 0)[:, 0]
        valid = offs < count
        idx = jnp.clip(lo + offs, 0, n_keys - 1)
        local = jnp.where(valid, jnp.take(perm_ref[:], idx),
                          jnp.int32(_INVALID_ROW))
        safe = jnp.clip(local, 0, n_rows - 1)
        rows = jnp.take(targets_ref[:], safe, axis=0)
        mask = valid
        for i, pos in enumerate(extra_fixed):
            mask = mask & (rows[:, pos] == fvals_ref[i])
        for p1, p2 in eq_pairs:
            mask = mask & (rows[:, p1] == rows[:, p2])
        vals = select_columns(rows, var_cols)
        vals_ref[:, :] = jnp.where(mask[:, None], vals, jnp.int32(0))
        mask_ref[:] = mask.astype(jnp.int32)
        cnt_ref[0] = count

    return kernel


def probe_term_table_impl(
    sorted_keys, perm, targets, probe_key, fixed_vals, capacity: int,
    *, var_cols, eq_pairs, extra_fixed, interpret: bool,
):
    """Traceable core (used both standalone and inside the fused
    whole-plan program).  Returns (vals[cap, k] int32, mask[cap] bool,
    range_count int32) — the exact contract of the lowered
    range_probe/verify/build_term_table chain."""
    probe_key = jnp.reshape(
        jnp.asarray(probe_key, dtype=sorted_keys.dtype), (1,)
    )
    fvals = jnp.asarray(fixed_vals, dtype=jnp.int32)
    if fvals.shape[0] == 0:  # zero-length SMEM blocks don't exist
        fvals = jnp.zeros((1,), dtype=jnp.int32)
    body = _kernel_body(
        capacity, tuple(var_cols), tuple(eq_pairs), tuple(extra_fixed),
        sorted_keys.shape[0], targets.shape[0],
    )
    vals, mask, cnt = run_kernel(
        body,
        (
            ((capacity, len(var_cols)), jnp.int32),
            ((capacity,), jnp.int32),
            ((1,), jnp.int32),
        ),
        (probe_key, fvals, sorted_keys, perm, targets),
        interpret,
    )
    return vals, mask.astype(bool), cnt[0]


@partial(jax.jit, static_argnames=(
    "capacity", "var_cols", "eq_pairs", "extra_fixed", "interpret"))
def probe_term_table_jit(
    sorted_keys, perm, targets, probe_key, fixed_vals,
    *, capacity, var_cols, eq_pairs, extra_fixed, interpret,
):
    """Single-dispatch wrapper for the staged pipeline (one compiled
    program per term shape; capacity is part of the cache key, exactly
    like the lowered ops)."""
    return probe_term_table_impl(
        sorted_keys, perm, targets, probe_key, fixed_vals, capacity,
        var_cols=var_cols, eq_pairs=eq_pairs, extra_fixed=extra_fixed,
        interpret=interpret,
    )
