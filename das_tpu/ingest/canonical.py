"""Canonical (fast-path) knowledge-base loader.

Loads normalized one-expression-per-line MeTTa files (the format produced
by automated converters — see the assumptions documented at
/root/reference/das/distributed_atom_space.py:366-402) without the general
tokenizer: a three-state line scanner (types → terminals → expressions)
plus a single-pass char-level expression parser that computes all hashes
inline (role of /root/reference/das/canonical_parser.py:242-365).

Canonical-format specifics:
  * typedef lines   ``(: Name Type)`` then ``(: "terminal name" Type)``
  * expression terminals are written ``"Type name"`` (type prefix inside
    the quotes) so terminal hashes need no symbol-table lookup;
  * flat type hierarchy; no forward references.

Unlike the reference (which re-scans MongoDB afterwards to emit four
kv-files, external-sorts them with sort(1) and SADDs Redis), results land
directly in `AtomSpaceData`; all indexes are derived tensors built by
`finalize()`.  A C++ implementation of this scanner (native/) is used
automatically when built — see das_tpu/ingest/native.py.
"""

from __future__ import annotations

import re
from typing import List, Optional

from das_tpu.core.expression import Expression
from das_tpu.core.hashing import ExpressionHasher
from das_tpu.core.schema import BASIC_TYPE, TYPEDEF_MARK
from das_tpu.storage.atom_table import AtomSpaceData

_ASCII_WS = " \t\r\n\f\v"
_ASCII_WS_RE = re.compile(f"[{re.escape(_ASCII_WS)}]+")


class CanonicalParseError(Exception):
    """Base for canonical-loader failures — one contract whether the
    Python scanner or the native C++ scanner (ingest/native.py) ran."""


class CanonicalFormatError(CanonicalParseError):
    def __init__(self, lineno: int, line: str, reason: str):
        super().__init__(f"line {lineno}: {reason}: {line!r}")


class CanonicalLoader:
    _S_TYPES, _S_TERMINALS, _S_EXPRESSIONS = range(3)

    def __init__(self, data: Optional[AtomSpaceData] = None):
        self.data = data if data is not None else AtomSpaceData()
        self._mark_hash = ExpressionHasher.named_type_hash(TYPEDEF_MARK)
        self._base_hash = ExpressionHasher.named_type_hash(BASIC_TYPE)
        self._state = self._S_TYPES

    # -- records -----------------------------------------------------------

    def _typedef(self, name: str, stype: str) -> None:
        t = self.data.table
        stype_hash = t.get_named_type_hash(stype)
        name_hash = t.get_named_type_hash(name)
        t.named_types[name] = stype
        t.parent_type[name_hash] = stype_hash
        composite = [self._mark_hash, stype_hash, self._base_hash]
        expr = Expression(
            toplevel=True,
            typedef_name=name,
            typedef_name_hash=name_hash,
            named_type=TYPEDEF_MARK,
            named_type_hash=self._mark_hash,
            composite_type=composite,
            composite_type_hash=ExpressionHasher.composite_hash(list(composite)),
            elements=[name_hash, stype_hash],
        )
        expr.hash_code = ExpressionHasher.expression_hash(
            self._mark_hash, expr.elements
        )
        t.symbol_hash[name] = expr.hash_code
        self.data.add_typedef(expr)

    def _terminal(self, name: str, stype: str) -> None:
        t = self.data.table
        stype_hash = t.get_named_type_hash(stype)
        # record the terminal's type like the MeTTa parser does on a
        # `(: "name" Type)` declaration: a LATER transaction referencing
        # this terminal by bare name must resolve (last declaration wins)
        t.named_types[name] = stype
        expr = Expression(
            terminal_name=name,
            named_type=stype,
            named_type_hash=stype_hash,
            composite_type=[stype_hash],
            composite_type_hash=stype_hash,
            hash_code=t.get_terminal_hash(stype, name),
        )
        self.data.add_terminal(expr)

    def _emit_link(self, named_type, elements, composite_type, composite_type_hash, toplevel) -> str:
        named_type_hash = self.data.table.get_named_type_hash(named_type)
        hash_code = ExpressionHasher.expression_hash(named_type_hash, elements)
        self.data.add_link(
            Expression(
                toplevel=toplevel,
                named_type=named_type,
                named_type_hash=named_type_hash,
                composite_type=composite_type,
                composite_type_hash=composite_type_hash,
                elements=list(elements),
                hash_code=hash_code,
            )
        )
        return hash_code

    # -- the char-level expression scanner ---------------------------------

    def parse_expression_line(self, line: str, lineno: int = 0) -> None:
        """One canonical expression: heads are bare symbols, targets are
        quoted ``"Type name"`` terminals or nested expressions."""
        # each open frame: [head_symbol, elements, composite_type, ct_hashes]
        frames: List[list] = []
        i, n = 0, len(line)
        token: List[str] = []
        result_emitted = False

        def close_token():
            if token:
                sym = "".join(token)
                token.clear()
                if not frames or frames[-1][0] is not None:
                    raise CanonicalFormatError(
                        lineno, line, f"unexpected symbol {sym!r}"
                    )
                frames[-1][0] = sym

        while i < n:
            c = line[i]
            if c == "(":
                close_token()
                frames.append([None, [], [], []])
            elif c == ")":
                close_token()
                if not frames:
                    raise CanonicalFormatError(lineno, line, "unbalanced ')'")
                head, elements, ctypes, cthashes = frames.pop()
                if head is None:
                    raise CanonicalFormatError(lineno, line, "headless expression")
                head_hash = self.data.table.get_named_type_hash(head)
                composite_type = [head_hash, *ctypes]
                composite_type_hash = ExpressionHasher.composite_hash(
                    [head_hash, *cthashes]
                )
                toplevel = not frames
                h = self._emit_link(
                    head, elements, composite_type, composite_type_hash, toplevel
                )
                if frames:
                    frames[-1][1].append(h)
                    frames[-1][2].append(composite_type)
                    frames[-1][3].append(composite_type_hash)
                else:
                    result_emitted = True
            elif c == '"':
                j = i + 1
                while j < n and not (line[j] == '"' and line[j - 1] != "\\"):
                    j += 1
                if j >= n:
                    raise CanonicalFormatError(lineno, line, "unterminated string")
                body = line[i + 1 : j]
                parts = body.split(" ", 1)
                if len(parts) != 2 or not frames:
                    raise CanonicalFormatError(
                        lineno, line, f"bad canonical terminal {body!r}"
                    )
                stype, name = parts
                stype_hash = self.data.table.get_named_type_hash(stype)
                frames[-1][1].append(
                    self.data.table.get_terminal_hash(stype, name)
                )
                frames[-1][2].append(stype_hash)
                frames[-1][3].append(stype_hash)
                i = j
            elif c == " ":
                close_token()
            else:
                token.append(c)
            i += 1
        if frames or not result_emitted:
            raise CanonicalFormatError(lineno, line, "unbalanced expression")

    # -- the line-state machine --------------------------------------------

    def parse_lines(self, lines) -> None:
        # per-file state reset (reference canonical_parser.py:324 sets
        # READING_TYPES at the top of every parse(); the canonical-format
        # contract is per-file — distributed_atom_space.py:372-375)
        self._state = self._S_TYPES
        for lineno, raw in enumerate(lines, 1):
            # ASCII whitespace only: matches both the native C++ scanner
            # and the reference's char-level parser (canonical_parser.py
            # :242-305 compares against literal ' '), so a name containing
            # a Unicode space byte sequence hashes identically everywhere
            line = raw.strip(_ASCII_WS)
            if not line:
                continue
            parts = [p for p in _ASCII_WS_RE.split(line) if p]
            if self._state == self._S_TYPES:
                if parts[0] != "(:":
                    raise CanonicalFormatError(lineno, line, "expected typedef")
                if len(parts) < 2:
                    raise CanonicalFormatError(lineno, line, "bad typedef")
                if parts[1].startswith('"'):
                    self._state = self._S_TERMINALS
                else:
                    if len(parts) != 3:
                        raise CanonicalFormatError(lineno, line, "bad typedef")
                    self._typedef(parts[1], parts[-1].rstrip(")"))
                    continue
            if self._state == self._S_TERMINALS:
                if parts[0] == "(:":
                    name = " ".join(parts[1:-1]).strip('"')
                    self._terminal(name, parts[-1].rstrip(")"))
                    continue
                self._state = self._S_EXPRESSIONS
            if self._state == self._S_EXPRESSIONS:
                if parts[0] == "(:" or not (
                    line.startswith("(") and line.endswith(")")
                ):
                    raise CanonicalFormatError(lineno, line, "bad expression line")
                self.parse_expression_line(line, lineno)

    def parse_file(self, path: str) -> None:
        with open(path, "r") as fh:
            self.parse_lines(fh)

    def parse_text(self, text: str) -> None:
        self.parse_lines(text.splitlines())


def load_canonical_file(path: str, data: Optional[AtomSpaceData] = None) -> AtomSpaceData:
    loader = CanonicalLoader(data)
    loader.parse_file(path)
    return loader.data


def load_canonical_text(text: str, data: Optional[AtomSpaceData] = None) -> AtomSpaceData:
    loader = CanonicalLoader(data)
    loader.parse_text(text)
    return loader.data
